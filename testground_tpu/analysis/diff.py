"""Differential run analysis: the RunDiff document builder.

Two finished tasks' journals + jsonl streams load into ONE structured
document with two kinds of comparison, matched to what each number IS:

- **Deterministic counters compared exactly.** The sim is a
  seed-deterministic program: message-flow totals, fault counters,
  latency histograms (sim-time, not wall), SLO breach records and the
  traffic matrix must be IDENTICAL between two runs of the same
  composition + seed. A mismatch there is a correctness finding —
  never noise, never a tolerance band.
- **Throughput/wall judged statistically.** Chunk dispatch walls are
  host wall-clock on a noisy box (ROADMAP notes ±40% on the serving
  container), so single-number ratios lie. Verdicts come from the
  per-chunk rate samples already streamed into ``sim_perf.jsonl``:
  median ratio for effect size + a hand-rolled two-sided Mann-Whitney U
  (rank test — no distribution assumption, robust to the fat-tailed
  stalls a shared box produces) for significance, warmup chunks
  excluded exactly as the ledger's ``steady_*`` window excludes them.
  Each judged row carries its verdict
  (``improved|regressed|unchanged|inconclusive``), sample counts and
  p-value, so a reader can audit the call.

This module is stdlib-only (see the package docstring) and is the ONE
comparison codepath: ``Engine.diff_tasks`` / ``GET /diff`` / ``tg
diff`` build full RunDiff documents here, and ``tg perf --compare``
(``sim.perf.perf_compare``) delegates to :func:`ledger_scalars` /
:func:`perf_compare` below.
"""

from __future__ import annotations

import hashlib
import json
import math
from typing import Any, Iterable

__all__ = [
    "DIFF_PLANES",
    "build_run_diff",
    "extract_ledger_metrics",
    "fmt_rate",
    "judge_samples",
    "ledger_scalars",
    "mann_whitney_u",
    "num",
    "perf_compare",
    "task_snapshot",
    "validate_planes",
]

# one name per source surface: counters = journal flow totals (+ the
# telemetry stream's mirror), perf = sim_perf.jsonl chunk samples +
# ledger scalars, latency = sim.latency percentiles (sim-time),
# phases = sim.phases static cost rows, slo = journal rule verdicts,
# netmatrix = sim.net_matrix totals + cells
DIFF_PLANES = ("counters", "perf", "latency", "phases", "slo", "netmatrix")


# --------------------------------------------------------------- shared
# numeric hygiene + rate formatting: canonical implementations live here
# (stdlib-only) and sim/perf.py re-exports them, so ledger consumers and
# the diff engine format identically without analysis importing jax.


def num(v, default=None):
    """A finite number, or ``default`` — perf/stats payloads are decoded
    JSON from possibly foreign writers, so a null/NaN/string field must
    degrade gracefully, never TypeError. Shared by every ledger consumer
    (``runners/pretty.py`` tables, the Prometheus exposition)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return default
    if not math.isfinite(v):
        return default
    return v


def fmt_rate(v, missing: str = "?") -> str:
    """A rate with a G/M/k suffix (``?`` for absent/non-finite) — the one
    formatter behind the ``tg perf`` table, ``--compare`` lines and the
    ``tg diff`` throughput rows."""
    n = num(v)
    if n is None:
        return missing
    for div, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= div:
            return f"{n / div:.2f}{suffix}"
    return f"{n:.1f}"


# ---------------------------------------------------------- statistics


def mann_whitney_u(xs: Iterable, ys: Iterable) -> tuple[float, float]:
    """Two-sided Mann-Whitney U test via the normal approximation with
    tie correction and continuity correction. Returns ``(U₁, p)`` where
    U₁ is the statistic for ``xs``.

    Hand-rolled on purpose: scipy is not a dependency of this repo, the
    sample sizes here (chunks per run, typically 8-500) are square in
    the approximation's comfort zone, and a rank test needs no
    distribution assumption — exactly right for fat-tailed shared-box
    dispatch walls. Degenerate inputs (empty side, all values tied)
    return p=1.0: no evidence of a shift, never a crash.
    """
    xs = [float(v) for v in xs]
    ys = [float(v) for v in ys]
    n1, n2 = len(xs), len(ys)
    if n1 == 0 or n2 == 0:
        return 0.0, 1.0
    pooled = sorted(
        [(v, 0) for v in xs] + [(v, 1) for v in ys], key=lambda t: t[0]
    )
    n = n1 + n2
    ranks = [0.0] * n
    tie_term = 0.0
    i = 0
    while i < n:
        j = i
        while j + 1 < n and pooled[j + 1][0] == pooled[i][0]:
            j += 1
        avg_rank = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[k] = avg_rank
        t = j - i + 1
        tie_term += t * t * t - t
        i = j + 1
    r1 = sum(r for r, (_, side) in zip(ranks, pooled) if side == 0)
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mean_u = n1 * n2 / 2.0
    var_u = 0.0
    if n > 1:
        var_u = (n1 * n2 / 12.0) * ((n + 1) - tie_term / (n * (n - 1)))
    if var_u <= 0.0:  # every value tied: no evidence either way
        return u1, 1.0
    # continuity correction toward the mean
    cc = 0.5 if u1 != mean_u else 0.0
    z = (abs(u1 - mean_u) - cc) / math.sqrt(var_u)
    p = math.erfc(max(z, 0.0) / math.sqrt(2.0))
    return u1, min(1.0, max(0.0, p))


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def judge_samples(
    a_samples: Iterable,
    b_samples: Iterable,
    *,
    alpha: float = 0.01,
    min_samples: int = 4,
    rel_epsilon: float = 0.10,
    higher_is_better: bool = True,
) -> dict:
    """Noise-aware verdict for one metric: B (candidate) vs A
    (baseline), judged from per-chunk samples.

    The defaults are deliberately conservative for the serving box's
    documented ±40% wall-clock noise (ROADMAP): a verdict needs BOTH
    rank-test significance at alpha=0.01 AND a ≥10% median shift, so
    two back-to-back identical runs judge clean while a real slowdown
    (p orders of magnitude below alpha) still flags. See PERF.md
    "Noise-aware comparison".

    Returns ``{verdict, n_a, n_b, median_a, median_b, ratio, p_value,
    reason}`` where verdict is one of:

    - ``improved`` / ``regressed`` — the shift is statistically
      significant (Mann-Whitney p < alpha) AND practically meaningful
      (median ratio outside ±rel_epsilon);
    - ``unchanged`` — no meaningful shift (either not significant and
      medians within the band, or significant but negligible effect);
    - ``inconclusive`` — too few samples to test, or an observed median
      shift the rank test cannot confirm at this noise level — the
      honest answer on a ±40% box, and what a gating consumer must
      treat as "do not block, do journal".
    """
    xs = [v for v in (num(s) for s in a_samples) if v is not None]
    ys = [v for v in (num(s) for s in b_samples) if v is not None]
    row: dict[str, Any] = {"n_a": len(xs), "n_b": len(ys)}
    if len(xs) < min_samples or len(ys) < min_samples:
        row.update(
            verdict="inconclusive",
            reason=(
                f"too few samples (n_a={len(xs)}, n_b={len(ys)}, "
                f"need {min_samples})"
            ),
        )
        if xs:
            row["median_a"] = _median(xs)
        if ys:
            row["median_b"] = _median(ys)
        return row
    med_a, med_b = _median(xs), _median(ys)
    row["median_a"], row["median_b"] = med_a, med_b
    ratio = med_b / med_a if med_a else math.inf
    row["ratio"] = round(ratio, 6) if math.isfinite(ratio) else None
    _, p = mann_whitney_u(xs, ys)
    row["p_value"] = round(p, 6)
    shifted = not (1.0 - rel_epsilon <= ratio <= 1.0 + rel_epsilon)
    significant = p < alpha
    if significant and shifted:
        better = ratio > 1.0
        if not higher_is_better:
            better = not better
        row["verdict"] = "improved" if better else "regressed"
        row["reason"] = (
            f"median ratio x{ratio:.3f}, p={p:.4g} < {alpha:g}"
        )
    elif shifted:
        row["verdict"] = "inconclusive"
        row["reason"] = (
            f"median ratio x{ratio:.3f} but p={p:.4g} >= {alpha:g} "
            "(shift not separable from noise)"
        )
    else:
        row["verdict"] = "unchanged"
        row["reason"] = f"median ratio x{ratio:.3f}, p={p:.4g}"
    return row


# -------------------------------------------------- ledger scalar diff
# (the `tg perf --compare` core, shared with the RunDiff perf plane)


def extract_ledger_metrics(obj: dict) -> dict:
    """Pull the comparable numbers out of any ledger-bearing shape:

    - a ``tg perf --json`` payload (``{"perf": {...}, "sim": {...}}``)
    - a journal ``sim`` block (``{"perf": {...}, "wall_secs": ...}``)
    - a bare ledger block (``{"compile": ..., "execute": ...}``)
    - a ``bench.py`` / BENCH_rNN.json line
      (``{"metric": "sim_peer_ticks_per_sec", "value": ..., "perf": ...}``)
    - the bench-trajectory wrapper the driver records (``{"tail":
      "<log>\\n{bench json line}"}``) — the embedded line is unwrapped

    Returns ``{peer_ticks_per_sec?, compile_secs?, lower_secs?,
    xla_compile_secs?, wall_secs?, ticks?}`` — only what the shape holds.
    """
    out: dict[str, float] = {}
    if not isinstance(obj, dict):
        return out
    if (
        isinstance(obj.get("tail"), str)
        and "metric" not in obj
        and "perf" not in obj
        and "sim" not in obj
    ):
        for line in reversed(obj["tail"].splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                return extract_ledger_metrics(json.loads(line))
            except ValueError:
                continue
        return out
    perf = obj
    if isinstance(obj.get("perf"), dict):
        perf = obj["perf"]
    elif isinstance(obj.get("sim"), dict):
        perf = obj["sim"].get("perf", {})
    sim = obj.get("sim") if isinstance(obj.get("sim"), dict) else obj
    # the module-level finite coercion — json.loads admits NaN/Infinity
    # literals, and a hand-edited baseline must not print 'xnan' ratios
    ex = perf.get("execute") if isinstance(perf.get("execute"), dict) else {}
    co = perf.get("compile") if isinstance(perf.get("compile"), dict) else {}
    for key, src in (
        ("peer_ticks_per_sec", ex.get("steady_peer_ticks_per_sec")),
        ("peer_ticks_per_sec", ex.get("peer_ticks_per_sec")),
        ("wall_secs", ex.get("wall_secs")),
        ("ticks", ex.get("ticks")),
        ("lower_secs", co.get("lower_secs")),
        ("xla_compile_secs", co.get("compile_secs")),
    ):
        v = num(src)
        if v is not None and key not in out:
            out[key] = v
    # bench.py headline line (BENCH_rNN.json)
    if obj.get("metric") == "sim_peer_ticks_per_sec":
        v = num(obj.get("value"))
        if v is not None:
            out.setdefault("peer_ticks_per_sec", v)
        v = num(obj.get("compile_secs"))
        if v is not None:
            out.setdefault("compile_secs", v)
    # journal sim block fields
    if isinstance(sim, dict):
        for key, name in (("wall_secs", "wall_secs"), ("ticks", "ticks")):
            v = num(sim.get(key))
            if v is not None:
                out.setdefault(name, v)
        v = num(sim.get("compile_secs"))
        if v is not None:
            out.setdefault("compile_secs", v)
    return out


def ledger_scalars(current: dict, baseline: dict) -> list[dict]:
    """The comparable ledger scalars between two ledger-bearing dicts:
    ``[{metric, current, baseline, ratio}]`` (ratio = current/baseline).
    Summary numbers, one per run — informational effect sizes with no
    per-chunk samples behind them, so NO verdict is attached here (the
    RunDiff perf plane judges the sampled metrics; ``perf_compare``
    prints these as-is)."""
    cur, base = extract_ledger_metrics(current), extract_ledger_metrics(baseline)
    rows: list[dict] = []
    c, b = cur.get("peer_ticks_per_sec"), base.get("peer_ticks_per_sec")
    if c and b:
        rows.append(
            {
                "metric": "peer_ticks_per_sec",
                "current": c,
                "baseline": b,
                "ratio": c / b,
            }
        )
    c, b = cur.get("compile_secs"), base.get("compile_secs")
    if c is None:
        c = (cur.get("lower_secs") or 0) + (cur.get("xla_compile_secs") or 0) or None
    if b is None:
        b = (base.get("lower_secs") or 0) + (base.get("xla_compile_secs") or 0) or None
    if c and b:
        rows.append(
            {"metric": "compile_secs", "current": c, "baseline": b, "ratio": c / b}
        )
    c, b = cur.get("wall_secs"), base.get("wall_secs")
    if c and b:
        rows.append(
            {"metric": "wall_secs", "current": c, "baseline": b, "ratio": c / b}
        )
    return rows


def perf_compare(
    current: dict, baseline: dict, label: str = "baseline"
) -> list[str]:
    """Human-readable throughput deltas between two ledger-bearing
    dicts — the ``tg perf --compare`` body. Returns one line per
    comparable metric; a single explanatory line when nothing overlaps
    (never raises on shape mismatches — review-time tooling must not
    crash on a hand-edited baseline)."""
    lines: list[str] = []
    for row in ledger_scalars(current, baseline):
        c, b, ratio = row["current"], row["baseline"], row["ratio"]
        if row["metric"] == "peer_ticks_per_sec":
            lines.append(
                f"peer·ticks/s  {fmt_rate(c)} vs {fmt_rate(b)} {label} "
                f"(x{ratio:.3f})"
            )
        elif row["metric"] == "compile_secs":
            lines.append(
                f"compile       {c:.2f}s vs {b:.2f}s {label} (x{ratio:.3f})"
            )
        elif row["metric"] == "wall_secs":
            lines.append(
                f"wall          {c:.2f}s vs {b:.2f}s {label} (x{ratio:.3f})"
            )
    if not lines:
        lines.append(
            f"no comparable throughput fields between this task and {label} "
            "(expected a perf ledger, a journal sim block, or a bench.py "
            "JSON line)"
        )
    return lines


# ----------------------------------------------------------- snapshots


def _dict(v) -> dict:
    return v if isinstance(v, dict) else {}


def task_snapshot(task: dict, perf_rows: list[dict] | None = None) -> dict:
    """Normalize one task (its ``to_dict`` shape) + its swept
    ``sim_perf.jsonl`` rows into the snapshot :func:`build_run_diff`
    consumes. Defensive throughout: a half-archived or foreign task
    yields a sparse snapshot, never an exception — missing planes are
    reported as absent by the diff, not crashed on."""
    task = _dict(task)
    result = _dict(task.get("result"))
    journal = _dict(result.get("journal"))
    states = task.get("states") or []
    state = ""
    if isinstance(states, list) and states:
        state = str(_dict(states[-1]).get("state") or "")
    return {
        "task_id": str(task.get("id") or ""),
        "plan": str(task.get("plan") or ""),
        "case": str(task.get("case") or ""),
        "state": state,
        "outcome": str(task.get("outcome") or ""),
        "error": str(task.get("error") or ""),
        "sim": _dict(journal.get("sim")),
        "telemetry": _dict(journal.get("telemetry")),
        "slo": _dict(journal.get("slo")),
        "composition": _dict(task.get("composition")),
        "perf_rows": [r for r in (perf_rows or []) if isinstance(r, dict)],
    }


def validate_planes(planes) -> tuple[str, ...]:
    """Normalize a plane selection (``None``/empty → all) and raise
    ``ValueError`` naming the known planes on an unknown one — the 400
    the daemon route and the CLI surface."""
    if not planes:
        return DIFF_PLANES
    if isinstance(planes, str):
        planes = [p for p in planes.split(",") if p.strip()]
    out = []
    for p in planes:
        p = str(p).strip()
        if p not in DIFF_PLANES:
            raise ValueError(
                f"unknown diff plane {p!r} (known: {', '.join(DIFF_PLANES)})"
            )
        if p not in out:
            out.append(p)
    return tuple(out) or DIFF_PLANES


# ------------------------------------------------- setup identity


def _scrub_setup(obj):
    """The composition minus everything that does not shape results:
    display metadata and build artifact paths (two identical
    submissions build to cache-keyed — but potentially distinct —
    artifact paths). What remains IS the determinism identity: same
    scrubbed composition ⇒ the runs are identically seeded and every
    deterministic counter must match exactly."""
    if isinstance(obj, dict):
        return {
            k: _scrub_setup(v)
            for k, v in sorted(obj.items())
            if k not in ("metadata", "artifact")
        }
    if isinstance(obj, list):
        return [_scrub_setup(v) for v in obj]
    return obj


def _setup_diff_paths(a, b, prefix="", out=None, limit=16) -> list[str]:
    """Dotted paths where two scrubbed setups differ (bounded)."""
    if out is None:
        out = []
    if len(out) >= limit:
        return out
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            _setup_diff_paths(
                a.get(k), b.get(k), f"{prefix}.{k}" if prefix else str(k), out, limit
            )
        return out
    if isinstance(a, list) and isinstance(b, list) and len(a) == len(b):
        for i, (va, vb) in enumerate(zip(a, b)):
            _setup_diff_paths(va, vb, f"{prefix}[{i}]", out, limit)
        return out
    if a != b and len(out) < limit:
        out.append(prefix or "<root>")
    return out


# ------------------------------------------------- exact-counter planes

# the journal sim block's deterministic counters: seed-determined
# program outputs, never wall-clock (wall_secs/compile_secs live in the
# perf plane's scalar view)
SIM_COUNTER_KEYS = (
    "ticks",
    "tick_ms",
    "processes",
    "devices",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_delivered",
    "msgs_dropped",
    "msgs_rejected",
    "msgs_in_flight",
    "msgs_fault_dropped",
    "faults_crashed",
    "faults_restarted",
    "latency_clamped",
    "bw_queue_dropped",
    "bw_rate_change_backlogged",
    "pub_dropped",
    "carry_bytes",
)

TELEMETRY_TOTAL_KEYS = (
    "delivered",
    "sent",
    "enqueued",
    "dropped",
    "rejected",
    "in_flight",
    "fault_dropped",
)


def _digest(v) -> dict:
    """Bounded stand-in for a large exact-compared object (the traffic
    matrix): cell count + sum + a content hash, so the row stays
    renderable while equality is still judged on the full object."""
    blob = json.dumps(v, sort_keys=True, default=str)
    total = 0

    def _sum(o):
        nonlocal total
        if isinstance(o, (int, float)) and not isinstance(o, bool):
            total += o
        elif isinstance(o, list):
            for x in o:
                _sum(x)

    _sum(v)
    return {
        "sum": total,
        "sha1": hashlib.sha1(blob.encode()).hexdigest()[:10],
    }


def _counter_rows(pairs: list[tuple[str, Any, Any]], digest_over=64) -> list[dict]:
    rows = []
    for name, va, vb in pairs:
        if va is None and vb is None:
            continue
        equal = va == vb
        if isinstance(va, list) and len(json.dumps(va, default=str)) > digest_over:
            va = _digest(va)
        if isinstance(vb, list) and len(json.dumps(vb, default=str)) > digest_over:
            vb = _digest(vb)
        rows.append({"name": name, "a": va, "b": vb, "equal": equal})
    return rows


def _flatten_numeric(prefix: str, obj, skip=()) -> list[tuple[str, Any]]:
    """Dotted (name, value) leaves of a journal sub-block, skipping
    key names in ``skip`` (the wall-clock fields of otherwise
    deterministic blocks)."""
    out: list[tuple[str, Any]] = []
    if isinstance(obj, dict):
        for k in sorted(obj):
            if k in skip:
                continue
            out.extend(_flatten_numeric(f"{prefix}.{k}", obj[k], skip))
    elif isinstance(obj, (int, float, str, bool)) or obj is None:
        out.append((prefix, obj))
    elif isinstance(obj, list):
        out.append((prefix, obj))
    return out


def _plane_counters(a: dict, b: dict) -> dict:
    sim_a, sim_b = _dict(a.get("sim")), _dict(b.get("sim"))
    tel_a = _dict(_dict(a.get("telemetry")).get("totals"))
    tel_b = _dict(_dict(b.get("telemetry")).get("totals"))
    if not sim_a and not sim_b and not tel_a and not tel_b:
        return {"absent": "neither run journaled a sim block"}
    pairs: list[tuple[str, Any, Any]] = []
    for k in SIM_COUNTER_KEYS:
        pairs.append((f"sim.{k}", sim_a.get(k), sim_b.get(k)))
    # the telemetry stream's cumulative mirror (present only when the
    # per-tick block was compiled in) — pinned separately so a stream/
    # journal divergence shows up as ITS own row
    for k in TELEMETRY_TOTAL_KEYS:
        pairs.append((f"telemetry.totals.{k}", tel_a.get(k), tel_b.get(k)))
    rows = _counter_rows(pairs)
    return {
        "compared": len(rows),
        "mismatched": sum(1 for r in rows if not r["equal"]),
        "rows": rows,
    }


def _plane_latency(a: dict, b: dict) -> dict:
    lat_a = _dict(_dict(a.get("sim")).get("latency"))
    lat_b = _dict(_dict(b.get("sim")).get("latency"))
    if not lat_a and not lat_b:
        return {"absent": "no latency block (telemetry off in both runs)"}
    # per-receiver-group {count, p50/p95/p99_ms}: SIM-time quantities
    # derived from deterministic device-side histograms — exact compare
    # is correct even though the unit is "ms"
    names = sorted(set(lat_a) | set(lat_b))
    pairs = []
    for g in names:
        ga, gb = _dict(lat_a.get(g)), _dict(lat_b.get(g))
        for k in sorted(set(ga) | set(gb)):
            pairs.append((f"latency.{g}.{k}", ga.get(k), gb.get(k)))
    rows = _counter_rows(pairs)
    return {
        "compared": len(rows),
        "mismatched": sum(1 for r in rows if not r["equal"]),
        "rows": rows,
    }


def _plane_slo(a: dict, b: dict) -> dict:
    slo_a, slo_b = _dict(a.get("slo")), _dict(b.get("slo"))
    if not slo_a and not slo_b:
        return {"absent": "no SLO rules armed in either run"}
    pairs: list[tuple[str, Any, Any]] = [
        ("slo.breaches", slo_a.get("breaches"), slo_b.get("breaches"))
    ]
    rules_a = {
        str(r.get("name")): r for r in slo_a.get("rules") or [] if isinstance(r, dict)
    }
    rules_b = {
        str(r.get("name")): r for r in slo_b.get("rules") or [] if isinstance(r, dict)
    }
    for name in sorted(set(rules_a) | set(rules_b)):
        ra, rb = _dict(rules_a.get(name)), _dict(rules_b.get(name))
        # breach counts/ticks/worst observations are sim-domain and
        # deterministic; rule shape (metric/op/threshold/severity) is
        # config — both compare exactly
        for k in (
            "metric",
            "op",
            "threshold",
            "window_ticks",
            "severity",
            "breaches",
            "first_tick",
            "last_tick",
            "worst",
            "last_observed",
        ):
            pairs.append((f"slo.{name}.{k}", ra.get(k), rb.get(k)))
    rows = _counter_rows(pairs)
    return {
        "compared": len(rows),
        "mismatched": sum(1 for r in rows if not r["equal"]),
        "rows": rows,
    }


def _plane_netmatrix(a: dict, b: dict) -> dict:
    nm_a = _dict(_dict(a.get("sim")).get("net_matrix"))
    nm_b = _dict(_dict(b.get("sim")).get("net_matrix"))
    if not nm_a and not nm_b:
        return {"absent": "no traffic matrix (netmatrix off in both runs)"}
    tot_a, tot_b = _dict(nm_a.get("totals")), _dict(nm_b.get("totals"))
    pairs: list[tuple[str, Any, Any]] = []
    for k in sorted(set(tot_a) | set(tot_b)):
        pairs.append((f"net_matrix.totals.{k}", tot_a.get(k), tot_b.get(k)))
    for k in ("labels", "bytes_total", "mismatches", "matrix"):
        pairs.append((f"net_matrix.{k}", nm_a.get(k), nm_b.get(k)))
    rows = _counter_rows(pairs)
    return {
        "compared": len(rows),
        "mismatched": sum(1 for r in rows if not r["equal"]),
        "rows": rows,
    }


def _plane_phases(a: dict, b: dict) -> dict:
    ph_a = _dict(_dict(a.get("sim")).get("phases"))
    ph_b = _dict(_dict(b.get("sim")).get("phases"))
    if not ph_a and not ph_b:
        return {"absent": "no phase ledger (phases off in both runs)"}
    # static XLA cost rows are build-deterministic; measured_ms/
    # measured_reps are wall-clock calibration — excluded from the
    # exact plane (they would need per-rep samples to judge honestly)
    noisy = ("measured_ms", "measured_reps")

    def rows_by_phase(block):
        return {
            str(r.get("phase")): r
            for r in block.get("rows") or []
            if isinstance(r, dict)
        }

    pa, pb = rows_by_phase(ph_a), rows_by_phase(ph_b)
    pairs: list[tuple[str, Any, Any]] = []
    for phase in sorted(set(pa) | set(pb)):
        ra, rb = _dict(pa.get(phase)), _dict(pb.get(phase))
        for k in sorted((set(ra) | set(rb)) - {"phase", *noisy}):
            pairs.append((f"phases.{phase}.{k}", ra.get(k), rb.get(k)))
    res_a = dict(_flatten_numeric("phases.residual", _dict(ph_a.get("residual"))))
    res_b = dict(_flatten_numeric("phases.residual", _dict(ph_b.get("residual"))))
    for name in sorted(set(res_a) | set(res_b)):
        pairs.append((name, res_a.get(name), res_b.get(name)))
    rows = _counter_rows(pairs)
    return {
        "compared": len(rows),
        "mismatched": sum(1 for r in rows if not r["equal"]),
        "rows": rows,
    }


# ------------------------------------------------------ perf plane


def _steady_samples(snapshot: dict, key: str) -> list[float]:
    """Per-chunk ``key`` samples from the swept sim_perf.jsonl rows,
    warmup dispatches excluded — the same window the ledger's
    ``steady_*`` summary uses (warmup count recovered from the journal:
    chunks − steady_chunks; 1 when the journal doesn't say)."""
    perf = _dict(_dict(snapshot.get("sim")).get("perf"))
    ex = _dict(perf.get("execute"))
    warmup = 1
    chunks, steady = num(ex.get("chunks")), num(ex.get("steady_chunks"))
    if chunks is not None and steady is not None:
        warmup = max(0, int(chunks) - int(steady))
    out: list[float] = []
    for row in snapshot.get("perf_rows") or []:
        if not isinstance(row, dict) or row.get("stream") not in (None, "perf"):
            continue
        idx = num(row.get("chunk"))
        v = num(row.get(key))
        if idx is None or v is None or int(idx) < warmup:
            continue
        out.append(float(v))
    return out


def _plane_perf(a: dict, b: dict) -> dict:
    out: dict[str, Any] = {}
    metrics: list[dict] = []
    # judged rows: per-chunk samples through the rank test. ticks/s is
    # the primary rate (higher better); the dispatch wall is its time-
    # domain view (lower better) — same ranks, so consistent verdicts
    for metric, key, higher in (
        ("chunk_ticks_per_sec", "ticks_per_sec", True),
        ("chunk_peer_ticks_per_sec", "peer_ticks_per_sec", True),
        ("chunk_wall_secs", "wall_secs", False),
    ):
        xs = _steady_samples(a, key)
        ys = _steady_samples(b, key)
        if not xs and not ys:
            continue
        row = judge_samples(xs, ys, higher_is_better=higher)
        row["metric"] = metric
        metrics.append(row)
    if metrics:
        out["metrics"] = metrics
    else:
        out["absent"] = (
            "no per-chunk perf samples in either run "
            "(sim_perf.jsonl missing or empty)"
        )
    # one-number ledger summaries: the same extraction `tg perf
    # --compare` prints — effect sizes only, no verdict (n=1)
    scalars = [
        {
            "metric": r["metric"],
            "a": r["baseline"],
            "b": r["current"],
            "ratio": round(r["ratio"], 6),
        }
        for r in ledger_scalars(
            {"sim": _dict(b.get("sim"))}, {"sim": _dict(a.get("sim"))}
        )
    ]
    if scalars:
        out["scalars"] = scalars
    return out


# ------------------------------------------------------ the document


def _run_ident(snapshot: dict) -> dict:
    sim = _dict(snapshot.get("sim"))
    rc = _dict(
        _dict(_dict(snapshot.get("composition")).get("global")).get("run_config")
    )
    ident = {
        "task_id": snapshot.get("task_id"),
        "plan": snapshot.get("plan"),
        "case": snapshot.get("case"),
        "state": snapshot.get("state"),
        "outcome": snapshot.get("outcome"),
        "seed": rc.get("seed", 0),
    }
    if num(sim.get("ticks")) is not None:
        ident["ticks"] = sim.get("ticks")
    if num(sim.get("wall_secs")) is not None:
        ident["wall_secs"] = sim.get("wall_secs")
    return ident


def build_run_diff(a: dict, b: dict, planes=None) -> dict:
    """Assemble the RunDiff document from two :func:`task_snapshot`
    results. Pure host-side arithmetic; never raises on sparse or
    corrupt snapshots (absent planes are reported, not crashed on).

    Document contract (docs/OBSERVABILITY.md "Run diff"): ``a`` is the
    baseline, ``b`` the candidate. ``setup.identical`` records whether
    the scrubbed compositions match — when True, every exact-plane
    mismatch lands in ``findings`` with severity ``correctness``; when
    False the mismatched rows are still reported but stay informational
    (different setups legitimately count differently). ``verdict`` is
    the roll-up: ``findings`` > ``mixed`` > ``regressed`` > ``improved``
    > ``clean``.
    """
    planes = validate_planes(planes)
    a, b = _dict(a), _dict(b)
    setup_a, setup_b = _scrub_setup(a.get("composition")), _scrub_setup(
        b.get("composition")
    )
    have_setups = bool(setup_a) and bool(setup_b)
    identical = have_setups and setup_a == setup_b
    setup: dict[str, Any] = {"identical": identical}
    if have_setups and not identical:
        setup["diffs"] = _setup_diff_paths(setup_a, setup_b)
    elif not have_setups:
        setup["note"] = "composition missing on one side; assuming different"
    doc: dict[str, Any] = {
        "a": _run_ident(a),
        "b": _run_ident(b),
        "planes": list(planes),
        "setup": setup,
    }
    builders = {
        "counters": _plane_counters,
        "perf": _plane_perf,
        "latency": _plane_latency,
        "phases": _plane_phases,
        "slo": _plane_slo,
        "netmatrix": _plane_netmatrix,
    }
    findings: list[dict] = []
    for plane in planes:
        try:
            block = builders[plane](a, b)
        except Exception as exc:  # noqa: BLE001 — analysis never crashes
            block = {"absent": f"plane failed to build: {exc}"}
        doc[plane] = block
        if plane == "perf":
            continue
        for row in block.get("rows") or []:
            if row["equal"]:
                continue
            if identical:
                # same scrubbed composition + seed ⇒ the program is
                # deterministic ⇒ this is a correctness finding
                findings.append(
                    {
                        "plane": plane,
                        "name": row["name"],
                        "a": row["a"],
                        "b": row["b"],
                        "severity": "correctness",
                    }
                )
    doc["findings"] = findings
    regressed: list[str] = []
    improved: list[str] = []
    for row in _dict(doc.get("perf")).get("metrics") or []:
        if row.get("verdict") == "regressed":
            regressed.append(row["metric"])
        elif row.get("verdict") == "improved":
            improved.append(row["metric"])
    doc["regressed"] = regressed
    doc["improved"] = improved
    if findings:
        doc["verdict"] = "findings"
    elif regressed and improved:
        doc["verdict"] = "mixed"
    elif regressed:
        doc["verdict"] = "regressed"
    elif improved:
        doc["verdict"] = "improved"
    else:
        doc["verdict"] = "clean"
    return doc
