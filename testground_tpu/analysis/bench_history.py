"""Banked bench history + the regression sentinel verdicts.

``bench.py --bank`` appends ONE env-fingerprinted row per bench run to
an append-only ``BENCH_HISTORY.jsonl`` (committed at the repo root, the
machine-readable successor to the hand-curated BENCH_r*.json prose
trajectory — ROADMAP item 5's "banked verdicts"). Rows group by
:func:`history_key` — (workload, rung, backend, device kind,
transport, mesh layout) — so numbers from different machines, scales,
or shardings never gate each other.

``tools/bench_regression.py`` turns the bank into a CI gate via
:func:`sentinel_report`: the newest row per key against the median of
its banked predecessors, with a deliberately GENEROUS tolerance
(default 2.5×) because the serving box is ±40% noisy and a single
bench run is one sample — only a slowdown no plausible noise explains
fails the build. Anything slower-but-within-bound is journaled as
``inconclusive`` and passes; see PERF.md "Noise-aware comparison".

stdlib-only at module scope (the package rule); jax/git are probed
lazily and best-effort inside :func:`env_fingerprint`.
"""

from __future__ import annotations

import json
import os
from typing import Any

from .diff import num

__all__ = [
    "HISTORY_FILE",
    "bank_row",
    "env_fingerprint",
    "history_key",
    "load_history",
    "sentinel_report",
]

HISTORY_FILE = "BENCH_HISTORY.jsonl"


def env_fingerprint() -> dict:
    """Where this number was measured: backend, device kind and count,
    jax version, host cpu count, platform, and the git sha of the tree
    that produced it. Every probe is best-effort — a fingerprint field
    missing (no git, no devices) must never fail a bench run."""
    import platform

    fp: dict[str, Any] = {
        "python": platform.python_version(),
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
    }
    try:
        import jax

        fp["jax"] = jax.__version__
        devs = jax.devices()
        fp["backend"] = jax.default_backend()
        fp["devices"] = len(devs)
        if devs:
            fp["device_kind"] = str(devs[0].device_kind)
    except Exception:  # noqa: BLE001 — fingerprint is descriptive only
        pass
    try:
        import subprocess

        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            capture_output=True,
            text=True,
            timeout=10,
        )
        if sha.returncode == 0 and sha.stdout.strip():
            fp["git_sha"] = sha.stdout.strip()
    except Exception:  # noqa: BLE001
        pass
    return fp


def history_key(row: dict) -> tuple:
    """The comparison group a banked row belongs to. Rows only gate
    rows measured at the same workload + rung on the same kind of
    hardware, transport, and mesh layout — a TPU number never judges a
    CPU number, and a 4-shard rung never judges an unmeshed one (a
    sharded program is a different machine, not noise). Pre-mesh rows
    carry no ``mesh`` field and default to the unmeshed group."""
    fp = row.get("fingerprint") if isinstance(row.get("fingerprint"), dict) else {}
    return (
        str(row.get("workload") or ""),
        int(num(row.get("instances"), 0)),
        str(fp.get("backend") or ""),
        str(fp.get("device_kind") or ""),
        str(row.get("transport") or ""),
        str(row.get("mesh") or ""),
    )


def bank_row(path: str, row: dict) -> dict:
    """Append one row to the bank (append-only by construction: the
    file is opened in ``a`` mode and rows are never rewritten). Returns
    the row as written."""
    row = dict(row)
    with open(path, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return row


def load_history(path: str) -> list[dict]:
    """Every parseable row, in file (= append) order. Corrupt lines are
    skipped — a half-written row from a crashed bench must not brick
    the sentinel."""
    rows: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def sentinel_report(
    rows: list[dict], tolerance: float = 2.5, rel_epsilon: float = 0.05
) -> dict:
    """Per-key verdicts over a loaded history. For each key group the
    NEWEST row is judged against the median headline value of its
    predecessors (median, not last: a one-off noisy bank must not move
    the baseline much):

    - ``regressed``   — newest < baseline/tolerance: slower than even
      the generous noise bound explains ⇒ the gate fails;
    - ``inconclusive`` — slower than the epsilon band but within the
      noise bound, or no predecessor to judge against ⇒ passes, but
      the row is journaled for a human;
    - ``improved`` / ``ok`` — faster than the band / within it.

    Returns ``{keys: [{key fields, verdict, value, baseline?, ratio?,
    samples, reason}], regressions: N, inconclusive: N}``.
    """
    tolerance = max(1.0, float(tolerance))
    groups: dict[tuple, list[dict]] = {}
    for row in rows:
        if num(row.get("value")) is None:
            continue
        groups.setdefault(history_key(row), []).append(row)
    out: dict[str, Any] = {"keys": [], "regressions": 0, "inconclusive": 0}
    for key in sorted(groups):
        series = groups[key]
        newest = series[-1]
        value = float(num(newest.get("value")))
        entry: dict[str, Any] = {
            "workload": key[0],
            "instances": key[1],
            "backend": key[2],
            "device_kind": key[3],
            "transport": key[4],
            "mesh": key[5],
            "value": value,
            "samples": len(series),
            "ts": newest.get("ts"),
        }
        prior = [float(num(r.get("value"))) for r in series[:-1]]
        if not prior:
            entry["verdict"] = "inconclusive"
            entry["reason"] = "no banked baseline yet (first row for this key)"
            out["inconclusive"] += 1
        else:
            baseline = _median(prior)
            entry["baseline"] = baseline
            ratio = value / baseline if baseline else float("inf")
            entry["ratio"] = round(ratio, 4)
            if ratio < 1.0 / tolerance:
                entry["verdict"] = "regressed"
                entry["reason"] = (
                    f"x{ratio:.3f} of the banked baseline — beyond the "
                    f"{tolerance:g}x noise bound"
                )
                out["regressions"] += 1
            elif ratio < 1.0 - rel_epsilon:
                entry["verdict"] = "inconclusive"
                entry["reason"] = (
                    f"x{ratio:.3f} slower, but within the {tolerance:g}x "
                    "noise bound — journaled, not gated"
                )
                out["inconclusive"] += 1
            elif ratio > 1.0 + rel_epsilon:
                entry["verdict"] = "improved"
                entry["reason"] = f"x{ratio:.3f} of the banked baseline"
            else:
                entry["verdict"] = "ok"
                entry["reason"] = f"x{ratio:.3f} of the banked baseline"
        out["keys"].append(entry)
    return out
