"""Cross-run analysis plane (docs/OBSERVABILITY.md "Run diff / bench
sentinel").

Everything under this package is deliberately import-light (stdlib
only, no jax/numpy at module scope): the diff engine runs against
ARCHIVED tasks — a `tg diff` of two finished runs, or the CI bench
sentinel over BENCH_HISTORY.jsonl — where paying a device-backend
import for pure host-side arithmetic would be wasted startup.

- :mod:`testground_tpu.analysis.diff` — the RunDiff document builder:
  deterministic counters compared exactly, throughput judged from
  per-chunk samples with noise-robust statistics (median ratio +
  Mann-Whitney U). Backend of ``tg diff`` / ``GET /diff`` and the one
  comparison codepath behind ``tg perf --compare``.
- :mod:`testground_tpu.analysis.bench_history` — the append-only
  env-fingerprinted bench bank (``bench.py --bank``) and the regression
  sentinel verdicts (``tools/bench_regression.py``).
"""
