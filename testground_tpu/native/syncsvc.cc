// tg-syncsvc — native sync service for the local:exec runner.
//
// The runtime analog of the reference's sync-service container (Go +
// Redis, pkg/runner/local_common.go:77-104): a single-threaded poll()
// event loop serving the framework's newline-delimited-JSON protocol
// (testground_tpu/sync/server.py is the behavioral spec):
//
//   request:  {"id": N, "op": <op>, ...args}\n
//   reply:    {"id": N, ...result}\n             exactly one, except
//   subscribe streams {"id": N, "entry": <raw>, "seq": i} frames.
//
// Ops: signal_entry(state), counter(state), barrier(state, target[,
// timeout]), signal_and_wait(state, target[, timeout]),
// publish(topic, payload), subscribe(topic).
//
// Design notes:
// - publish payloads are NEVER parsed: the raw JSON value text is stored
//   and echoed verbatim into subscribe frames, so arbitrary payloads
//   round-trip without a full JSON implementation;
// - one thread, no locks: barrier waiters and topic subscribers are
//   parked records flushed when counters/topics advance — the C++ twin
//   of the Python server's per-request threads without the threads;
// - stdout handshake: "LISTENING <port>" once bound (the runner reads
//   this to learn an ephemeral port).
//
// Build: g++ -O2 -std=c++17 -o tg-syncsvc syncsvc.cc
// (testground_tpu/native/syncsvc.py wraps build + spawn + lifecycle).

#include <arpa/inet.h>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

double now_secs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// ---------------------------------------------------------------- JSON bits
// Minimal field extraction over one request line. Values are returned as
// raw JSON text; strings additionally unescape via json_unescape.

// Skip a JSON value starting at i; returns one-past-end, or npos on error.
size_t skip_value(const std::string& s, size_t i) {
  while (i < s.size() && isspace((unsigned char)s[i])) i++;
  if (i >= s.size()) return std::string::npos;
  char c = s[i];
  if (c == '"') {
    for (i++; i < s.size(); i++) {
      if (s[i] == '\\') { i++; continue; }
      if (s[i] == '"') return i + 1;
    }
    return std::string::npos;
  }
  if (c == '{' || c == '[') {
    char open = c, close = (c == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    for (; i < s.size(); i++) {
      char d = s[i];
      if (in_str) {
        if (d == '\\') { i++; continue; }
        if (d == '"') in_str = false;
      } else if (d == '"') {
        in_str = true;
      } else if (d == open) {
        depth++;
      } else if (d == close) {
        depth--;
        if (depth == 0) return i + 1;
      }
    }
    return std::string::npos;
  }
  // number / true / false / null
  size_t j = i;
  while (j < s.size() && (isalnum((unsigned char)s[j]) || s[j] == '-' ||
                          s[j] == '+' || s[j] == '.'))
    j++;
  return j == i ? std::string::npos : j;
}

// Raw JSON text of top-level field `key`, or empty if absent.
std::string find_field(const std::string& line, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  size_t i = 0;
  bool in_str = false;
  int depth = 0;
  for (; i < line.size(); i++) {
    char c = line[i];
    if (in_str) {
      if (c == '\\') { i++; continue; }
      if (c == '"') in_str = false;
      continue;
    }
    if (c == '{' || c == '[') { depth++; continue; }
    if (c == '}' || c == ']') { depth--; continue; }
    if (c == '"') {
      if (depth == 1 && line.compare(i, pat.size(), pat) == 0) {
        size_t j = i + pat.size();
        while (j < line.size() && isspace((unsigned char)line[j])) j++;
        if (j < line.size() && line[j] == ':') {
          size_t start = j + 1;
          while (start < line.size() && isspace((unsigned char)line[start]))
            start++;
          size_t end = skip_value(line, start);
          if (end == std::string::npos) return "";
          return line.substr(start, end - start);
        }
      }
      in_str = true;
    }
  }
  return "";
}

void utf8_append(std::string& out, unsigned cp) {
  if (cp < 0x80) {
    out += char(cp);
  } else if (cp < 0x800) {
    out += char(0xC0 | (cp >> 6));
    out += char(0x80 | (cp & 0x3F));
  } else {
    out += char(0xE0 | (cp >> 12));
    out += char(0x80 | ((cp >> 6) & 0x3F));
    out += char(0x80 | (cp & 0x3F));
  }
}

// Decode a raw JSON string token ("...") to its value; empty on error.
std::string json_unescape(const std::string& raw) {
  if (raw.size() < 2 || raw.front() != '"' || raw.back() != '"') return "";
  std::string out;
  out.reserve(raw.size());
  for (size_t i = 1; i + 1 < raw.size(); i++) {
    char c = raw[i];
    if (c != '\\') { out += c; continue; }
    if (++i + 1 > raw.size()) break;
    switch (raw[i]) {
      case 'n': out += '\n'; break;
      case 't': out += '\t'; break;
      case 'r': out += '\r'; break;
      case 'b': out += '\b'; break;
      case 'f': out += '\f'; break;
      case 'u': {
        if (i + 4 < raw.size()) {
          unsigned cp = (unsigned)strtoul(raw.substr(i + 1, 4).c_str(),
                                          nullptr, 16);
          utf8_append(out, cp);
          i += 4;
        }
        break;
      }
      default: out += raw[i];
    }
  }
  return out;
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

long field_long(const std::string& line, const std::string& key, long dflt) {
  std::string raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  return strtol(raw.c_str(), nullptr, 10);
}

double field_double(const std::string& line, const std::string& key,
                    double dflt) {
  std::string raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  return strtod(raw.c_str(), nullptr);
}

// ------------------------------------------------------------------- state

struct Conn {
  int fd;
  std::string rbuf;
  std::string wbuf;  // unsent reply bytes; drained on POLLOUT
};

// A reply backlog beyond this marks the client dead (it stopped reading);
// dropping it beats stalling the loop for everyone else.
constexpr size_t kMaxWbuf = 16 << 20;

struct Waiter {           // a parked barrier / signal_and_wait
  int fd;
  long id;
  std::string state;
  long target;
  long seq;               // -1 for plain barrier; echoed for signal_and_wait
  double deadline;        // 0 = none
};

struct Sub {
  int fd;
  long id;
  size_t cursor;
};

struct Topic {
  std::vector<std::string> entries;  // raw JSON payloads, verbatim
  std::vector<Sub> subs;
};

std::unordered_map<int, Conn> conns;
std::unordered_map<std::string, long> counters;
std::vector<Waiter> waiters;
std::unordered_map<std::string, Topic> topics;

std::vector<int> dead_conns;  // drop after the current dispatch completes

// Try to drain a connection's write buffer; non-blocking, never stalls
// the event loop (one wedged reader must not freeze every barrier).
void flush_wbuf(Conn& c) {
  while (!c.wbuf.empty()) {
    ssize_t n = send(c.fd, c.wbuf.data(), c.wbuf.size(),
                     MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n > 0) {
      c.wbuf.erase(0, (size_t)n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    dead_conns.push_back(c.fd);  // peer gone
    return;
  }
}

void send_line(int fd, const std::string& line) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  Conn& c = it->second;
  c.wbuf += line;
  c.wbuf += '\n';
  if (c.wbuf.size() > kMaxWbuf) {
    dead_conns.push_back(fd);
    return;
  }
  flush_wbuf(c);
}

void reply_err(int fd, long id, const std::string& msg) {
  char buf[64];
  snprintf(buf, sizeof buf, "{\"id\": %ld, \"error\": \"", id);
  send_line(fd, std::string(buf) + json_escape(msg) + "\"}");
}

void flush_waiters(const std::string& state) {
  long count = counters[state];
  for (size_t i = 0; i < waiters.size();) {
    Waiter& w = waiters[i];
    if (w.state == state && count >= w.target) {
      char buf[128];
      if (w.seq >= 0)
        snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld, \"ok\": true}",
                 w.id, w.seq);
      else
        snprintf(buf, sizeof buf, "{\"id\": %ld, \"ok\": true}", w.id);
      send_line(w.fd, buf);
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
}

void flush_subs(const std::string& topic_name) {
  Topic& t = topics[topic_name];
  for (Sub& sub : t.subs) {
    while (sub.cursor < t.entries.size()) {
      char head[64];
      snprintf(head, sizeof head, "{\"id\": %ld, \"entry\": ", sub.id);
      sub.cursor++;
      char tail[32];
      snprintf(tail, sizeof tail, ", \"seq\": %zu}", sub.cursor);
      send_line(sub.fd, std::string(head) + t.entries[sub.cursor - 1] + tail);
    }
  }
}

void expire_waiters();  // defined below; used for zero-timeout barriers

void handle_line(int fd, const std::string& line) {
  long id = field_long(line, "id", -1);
  std::string op = json_unescape(find_field(line, "op"));
  if (op.empty()) {
    reply_err(fd, -1, "malformed request");
    return;
  }
  char buf[160];
  if (op == "signal_entry") {
    std::string state = json_unescape(find_field(line, "state"));
    long seq = ++counters[state];
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld}", id, seq);
    send_line(fd, buf);
    flush_waiters(state);
  } else if (op == "counter") {
    std::string state = json_unescape(find_field(line, "state"));
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"count\": %ld}", id,
             counters[state]);
    send_line(fd, buf);
  } else if (op == "barrier" || op == "signal_and_wait") {
    std::string state = json_unescape(find_field(line, "state"));
    long target = field_long(line, "target", 0);
    // absent/null timeout = wait forever; an EXPLICIT 0 is an immediate
    // non-blocking check (the Python spec server's wait_for(timeout=0))
    double timeout = field_double(line, "timeout", -1.0);
    long seq = -1;
    if (op == "signal_and_wait") seq = ++counters[state];
    Waiter w{fd, id, state, target, seq,
             timeout >= 0 ? now_secs() + timeout : 0.0};
    waiters.push_back(w);
    flush_waiters(state);  // may satisfy immediately (incl. this one)
    if (timeout == 0.0) expire_waiters();  // unmet zero-timeout fails now
  } else if (op == "publish") {
    std::string topic = json_unescape(find_field(line, "topic"));
    std::string payload = find_field(line, "payload");
    if (payload.empty()) payload = "null";
    Topic& t = topics[topic];
    t.entries.push_back(payload);
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %zu}", id,
             t.entries.size());
    send_line(fd, buf);
    flush_subs(topic);
  } else if (op == "subscribe") {
    std::string topic = json_unescape(find_field(line, "topic"));
    topics[topic].subs.push_back(Sub{fd, id, 0});
    flush_subs(topic);
  } else {
    reply_err(fd, id, "unknown op '" + op + "'");
  }
}

void drop_conn(int fd) {
  close(fd);
  conns.erase(fd);
  for (size_t i = 0; i < waiters.size();) {
    if (waiters[i].fd == fd) {
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
  for (auto& kv : topics) {
    auto& subs = kv.second.subs;
    for (size_t i = 0; i < subs.size();) {
      if (subs[i].fd == fd) {
        subs[i] = subs.back();
        subs.pop_back();
      } else {
        i++;
      }
    }
  }
}

void expire_waiters() {
  double now = now_secs();
  for (size_t i = 0; i < waiters.size();) {
    if (waiters[i].deadline > 0 && now >= waiters[i].deadline) {
      reply_err(waiters[i].fd, waiters[i].id,
                "barrier timed out: " + waiters[i].state);
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
}

volatile sig_atomic_t stop_flag = 0;
void on_term(int) { stop_flag = 1; }

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  for (int i = 1; i + 1 < argc; i += 2)
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);

  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0 || listen(lfd, 512) != 0) {
    perror("tg-syncsvc: bind/listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (sockaddr*)&addr, &alen);
  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::vector<pollfd> pfds;
  char rbuf[65536];
  while (!stop_flag) {
    pfds.clear();
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& kv : conns)
      pfds.push_back(
          {kv.first,
           (short)(POLLIN | (kv.second.wbuf.empty() ? 0 : POLLOUT)), 0});

    // poll timeout tracks the nearest barrier deadline
    int tmo = -1;
    double now = now_secs();
    for (const Waiter& w : waiters)
      if (w.deadline > 0) {
        int ms = (int)((w.deadline - now) * 1000) + 1;
        if (ms < 0) ms = 0;
        if (tmo < 0 || ms < tmo) tmo = ms;
      }
    int rc = poll(pfds.data(), pfds.size(), tmo);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    expire_waiters();
    for (const pollfd& p : pfds) {
      if (p.fd != lfd && (p.revents & POLLOUT)) {
        auto it = conns.find(p.fd);
        if (it != conns.end()) flush_wbuf(it->second);
      }
      if (!(p.revents & (POLLIN | POLLHUP | POLLERR))) continue;
      if (p.fd == lfd) {
        int cfd = accept(lfd, nullptr, nullptr);
        if (cfd >= 0) {
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          conns[cfd] = Conn{cfd, std::string()};
        }
        continue;
      }
      auto it = conns.find(p.fd);
      if (it == conns.end()) continue;
      ssize_t n = recv(p.fd, rbuf, sizeof rbuf, 0);
      if (n <= 0) {
        drop_conn(p.fd);
        continue;
      }
      it->second.rbuf.append(rbuf, (size_t)n);
      std::string& b = it->second.rbuf;
      size_t start = 0, nl;
      while ((nl = b.find('\n', start)) != std::string::npos) {
        std::string line = b.substr(start, nl - start);
        start = nl + 1;
        if (!line.empty()) handle_line(p.fd, line);
        if (conns.find(p.fd) == conns.end()) break;  // dropped mid-batch
      }
      if (conns.find(p.fd) != conns.end()) b.erase(0, start);
    }
    // reap connections whose peer vanished or stopped reading
    for (int fd : dead_conns)
      if (conns.count(fd)) drop_conn(fd);
    dead_conns.clear();
  }
  for (auto& kv : conns) close(kv.first);
  close(lfd);
  return 0;
}
