// tg-syncsvc — native sync service for the local:exec runner.
//
// The runtime analog of the reference's sync-service container (Go +
// Redis, pkg/runner/local_common.go:77-104): sharded epoll event loops
// serving the framework's newline-delimited-JSON protocol
// (testground_tpu/sync/server.py is the behavioral spec):
//
//   request:  {"id": N, "op": <op>, ...args}\n
//   reply:    {"id": N, ...result}\n             exactly one, except
//   subscribe streams {"id": N, "entry": <raw>, "seq": i} frames.
//
// Ops: signal_entry(state[, token]), counter(state), barrier(state,
// target[, timeout]), signal_and_wait(state, target[, timeout][,
// token]), publish(topic, payload[, token]), subscribe(topic), plus the
// liveness/identity plane (docs/CROSSHOST.md, spec'd by server.py):
// ping (pong + boot id), hello (instance identity; abnormal disconnect
// publishes an eviction event to its events_topic), bye (clean close),
// sync_stats (the wire-versioned stats plane, v2: v1 occupancy fields
// conns/waiters/subs/boot plus counter-level per-op/conn-churn/barrier-
// lifecycle/pubsub/dedup blocks — docs/INSTANCE_PROTOCOL.md §4.2; this
// server stays at counter level, histograms are python-server-only).
// `token` is an idempotency key: re-sent mutations from a reconnecting
// client answer with the original seq instead of mutating twice.
//
// Architecture (the 10k fan-in rewrite, docs/CROSSHOST.md "Server
// architecture"). The r1 bench measured the previous single-poll()
// design serializing at 10k clients — every wake rescanned a 10k-entry
// pollfd array and every signal rescanned the whole flat waiter list
// (O(W²) under a width-W barrier storm). Now:
//
// - --shards N event-loop THREADS (default auto: min(4, cores)), one
//   epoll set per shard; the listener is registered EPOLLEXCLUSIVE in
//   every set so the kernel fans accepted connections out across
//   shards. Connections are owned by their accepting shard; all
//   coordination state (counters/topics/waiters/tokens/stats) is
//   shared under one mutex taken ONCE PER DRAIN, not per op.
// - each wake DRAINS every ready connection first (no lock), then
//   applies the whole batch of decoded ops in one locked pass, then
//   runs ONE coalesced release pass: waiters are indexed per state
//   with a min-target watermark, so a signal storm costs O(1) per
//   signal until a barrier is actually satisfiable, and a satisfied
//   barrier fans out all W replies in one sweep (batched release).
// - the request hot path is allocation-free: fields are parsed as
//   string_views over the connection's read buffer, and replies are
//   appended straight into a flat per-connection write buffer flushed
//   once per drain — many frames, one send().
// - a reader whose write-buffer backlog trips --max-wbuf (default
//   16 MiB) has stopped reading and is shed (slow-reader backpressure)
//   rather than wedging memory or fairness for other peers. Cross-
//   shard replies (barrier releases, pubsub fanout) ride per-shard
//   inboxes + an eventfd wake, tagged with a connection generation so
//   a recycled fd never receives a dead peer's frames.
// - publish payloads are NEVER parsed: the raw JSON value text is
//   stored and echoed verbatim into subscribe frames;
// - stdout handshake: "LISTENING <port>" once bound (the runner reads
//   this to learn an ephemeral port);
// - --host picks the bind address (default loopback; 0.0.0.0 makes the
//   service a network citizen other hosts can dial); --idle-timeout S
//   evicts connections that sent nothing (not even a heartbeat ping)
//   for S seconds, so a SIGSTOPped or half-open peer releases its
//   parked waiters instead of leaking occupancy forever.
//
// Build: g++ -O2 -std=c++17 -pthread -o tg-syncsvc syncsvc.cc
// (testground_tpu/native/syncsvc.py wraps build + spawn + lifecycle).

#include <arpa/inet.h>
#include <atomic>
#include <climits>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <string_view>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

double now_secs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// ---------------------------------------------------------------- JSON bits
// Minimal zero-copy field extraction over one request line: values come
// back as string_views into the line (raw JSON text); strings unescape
// through a caller-provided scratch only when they actually contain
// escapes. The hot ops never allocate.

using sv = std::string_view;

// Skip a JSON value starting at i; returns one-past-end, or npos on error.
size_t skip_value(sv s, size_t i) {
  while (i < s.size() && isspace((unsigned char)s[i])) i++;
  if (i >= s.size()) return sv::npos;
  char c = s[i];
  if (c == '"') {
    for (i++; i < s.size(); i++) {
      if (s[i] == '\\') { i++; continue; }
      if (s[i] == '"') return i + 1;
    }
    return sv::npos;
  }
  if (c == '{' || c == '[') {
    char open = c, close = (c == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    for (; i < s.size(); i++) {
      char d = s[i];
      if (in_str) {
        if (d == '\\') { i++; continue; }
        if (d == '"') in_str = false;
      } else if (d == '"') {
        in_str = true;
      } else if (d == open) {
        depth++;
      } else if (d == close) {
        depth--;
        if (depth == 0) return i + 1;
      }
    }
    return sv::npos;
  }
  // number / true / false / null
  size_t j = i;
  while (j < s.size() && (isalnum((unsigned char)s[j]) || s[j] == '-' ||
                          s[j] == '+' || s[j] == '.'))
    j++;
  return j == i ? sv::npos : j;
}

// Raw JSON text of top-level field `key`, or empty if absent.
sv find_field(sv line, sv key) {
  size_t i = 0;
  bool in_str = false;
  int depth = 0;
  for (; i < line.size(); i++) {
    char c = line[i];
    if (in_str) {
      if (c == '\\') { i++; continue; }
      if (c == '"') in_str = false;
      continue;
    }
    if (c == '{' || c == '[') { depth++; continue; }
    if (c == '}' || c == ']') { depth--; continue; }
    if (c == '"') {
      if (depth == 1 && i + key.size() + 2 <= line.size() &&
          line[i + key.size() + 1] == '"' &&
          line.compare(i + 1, key.size(), key) == 0) {
        size_t j = i + key.size() + 2;
        while (j < line.size() && isspace((unsigned char)line[j])) j++;
        if (j < line.size() && line[j] == ':') {
          size_t start = j + 1;
          while (start < line.size() && isspace((unsigned char)line[start]))
            start++;
          size_t end = skip_value(line, start);
          if (end == sv::npos) return sv{};
          return line.substr(start, end - start);
        }
      }
      in_str = true;
    }
  }
  return sv{};
}

void utf8_append(std::string& out, unsigned cp) {
  if (cp < 0x80) {
    out += char(cp);
  } else if (cp < 0x800) {
    out += char(0xC0 | (cp >> 6));
    out += char(0x80 | (cp & 0x3F));
  } else {
    out += char(0xE0 | (cp >> 12));
    out += char(0x80 | ((cp >> 6) & 0x3F));
    out += char(0x80 | (cp & 0x3F));
  }
}

// Decode a raw JSON string token ("...") to its value. Escape-free
// strings (every state/topic the SDK generates) come back as a view
// into the input; only escaped ones round-trip through `scratch`.
sv json_unescape(sv raw, std::string& scratch) {
  if (raw.size() < 2 || raw.front() != '"' || raw.back() != '"') return sv{};
  sv body = raw.substr(1, raw.size() - 2);
  if (body.find('\\') == sv::npos) return body;  // the no-alloc fast path
  scratch.clear();
  scratch.reserve(body.size());
  for (size_t i = 0; i < body.size(); i++) {
    char c = body[i];
    if (c != '\\') { scratch += c; continue; }
    if (++i >= body.size()) break;
    switch (body[i]) {
      case 'n': scratch += '\n'; break;
      case 't': scratch += '\t'; break;
      case 'r': scratch += '\r'; break;
      case 'b': scratch += '\b'; break;
      case 'f': scratch += '\f'; break;
      case 'u': {
        if (i + 4 < body.size()) {
          unsigned cp = (unsigned)strtoul(
              std::string(body.substr(i + 1, 4)).c_str(), nullptr, 16);
          utf8_append(scratch, cp);
          i += 4;
        }
        break;
      }
      default: scratch += body[i];
    }
  }
  return sv(scratch);
}

std::string json_escape(sv s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

long field_long(sv line, sv key, long dflt) {
  sv raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  char buf[32];
  size_t n = raw.size() < sizeof buf - 1 ? raw.size() : sizeof buf - 1;
  memcpy(buf, raw.data(), n);
  buf[n] = 0;
  return strtol(buf, nullptr, 10);
}

double field_double(sv line, sv key, double dflt) {
  sv raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  char buf[40];
  size_t n = raw.size() < sizeof buf - 1 ? raw.size() : sizeof buf - 1;
  memcpy(buf, raw.data(), n);
  buf[n] = 0;
  return strtod(buf, nullptr);
}

// ------------------------------------------------------------------- state

// Outbound reply routed to another shard's conn, generation-tagged so a
// recycled fd never sees a dead peer's frames.
struct Msg {
  int fd;
  uint64_t gen;
  std::string line;  // '\n'-terminated
};

struct Conn {
  int fd = -1;
  uint64_t gen = 0;
  double last_active = 0.0;  // last byte read (idle-sweep clock)
  bool hello = false;        // identity registered
  bool clean = false;        // said bye — no eviction event
  bool dead = false;         // marked for drop at end of this drain
  bool dropped = false;      // drop_conn ran; map entry erased post-flush
  bool dirty = false;        // has unflushed output this drain
  bool want_write = false;   // EPOLLOUT armed
  std::string rbuf;
  // flat outbound buffer: replies append at the tail, the flush sends
  // the [whead, size) suffix in ONE syscall; cleared (capacity kept)
  // once fully drained
  std::string wbuf;
  size_t whead = 0;
  std::string events_topic;
  std::string group;
  long instance = -1;
};

struct Shard {
  int id = 0;
  int ep = -1;
  int evfd = -1;
  std::unordered_map<int, Conn> conns;
  std::mutex inbox_mu;
  std::vector<Msg> inbox;
  // drain-cycle scratch (loop thread only)
  std::vector<Conn*> dirty;
  std::vector<int> dead;
  long accepted = 0;  // accepts this drain, folded into stats in bulk
};

int g_nshards = 1;
std::deque<Shard> g_shards;  // deque: Shard holds a mutex (non-movable)
thread_local Shard* t_shard = nullptr;
thread_local std::vector<std::vector<Msg>>* t_outbound = nullptr;
thread_local std::unordered_set<std::string>* t_touched_states = nullptr;
thread_local std::unordered_set<std::string>* t_touched_topics = nullptr;

// A reply backlog beyond this marks the client dead (it stopped
// reading); shedding it beats stalling or ballooning for everyone else.
size_t g_max_wbuf = 16 << 20;

std::atomic<uint64_t> g_gen{1};
std::atomic<long> g_conn_count{0};

struct Waiter {  // a parked barrier / signal_and_wait (record, no thread)
  int fd;
  uint64_t gen;
  int shard;
  long id;
  long target;
  long seq;        // -1 for plain barrier; echoed for signal_and_wait
  double deadline; // 0 = none
};

// Per-state waiter index with a min-target watermark: a signal on an
// armed state is O(1) until some waiter is actually satisfiable; the
// release pass then fans out every satisfied waiter in one sweep.
struct StateWaiters {
  std::vector<Waiter> v;
  long min_target = LONG_MAX;
};

struct Sub {
  int fd;
  uint64_t gen;
  int shard;
  long id;
  size_t cursor;
};

struct Topic {
  std::vector<std::string> entries;  // raw JSON payloads, verbatim
  std::vector<Sub> subs;
};

// ---- everything below is guarded by g_mu (taken once per drain) ----
std::mutex g_mu;
std::unordered_map<std::string, long> counters;
std::unordered_map<std::string, StateWaiters> waiters_by_state;
size_t g_waiter_count = 0;
double g_next_deadline = 0.0;  // earliest parked deadline; 0 = none
std::unordered_map<std::string, Topic> topics;
// idempotency tokens (key: state/topic + '\x1f' + token → original seq),
// FIFO-bounded: only a reconnecting client's unacked window (seconds of
// traffic) ever needs a token, so capping at kMaxTokens bounds memory
// over week-long runs without risking a realistic double-apply.
constexpr size_t kMaxTokens = 65536;
struct TokenMap {
  std::unordered_map<std::string, long> map;
  std::deque<std::string> order;
  long* find(const std::string& key) {
    auto it = map.find(key);
    return it == map.end() ? nullptr : &it->second;
  }
  void put(const std::string& key, long seq) {
    if (map.emplace(key, seq).second) {
      order.push_back(key);
      while (order.size() > kMaxTokens) {
        map.erase(order.front());
        order.pop_front();
      }
    }
  }
};
TokenMap sig_tokens;
TokenMap pub_tokens;
std::string boot_id;       // changes every server start (restart detector)
double idle_timeout = 0.0;  // seconds; 0 = sweep disabled
double evict_grace = 2.0;   // reconnect window before eviction publishes

// reusable lookup keys for view-keyed map access (C++17 unordered maps
// cannot look up by string_view; assigning into a retained-capacity
// string costs a memcpy, not an allocation)
thread_local std::string t_key1, t_key2, t_scratch1, t_scratch2;

std::string& keyed(std::string& slot, sv view) {
  slot.assign(view.data(), view.size());
  return slot;
}

// ------------------------------------------------ sync-stats plane (v2)
// Counter-level mirror of the Python server's stats plane
// (testground_tpu/sync/stats.py; wire parity pinned by
// tests/test_sync_stats.py). Histograms and barrier-episode timing are
// python-server-only richness — this server stays at counters, which
// cost one increment on already-dispatched paths. --stats 0 disables
// the plane (sync_stats answers the v1 occupancy shape), which exists
// for the fan-in bench's instrumented-vs-uninstrumented A/B.
bool stats_on = true;
double stats_start = 0.0;
struct SyncStatsCounters {
  // per-op dispatch counters (counted BEFORE the reply is built, so a
  // sync_stats reply includes itself — the conservation contract)
  long signal_entry = 0, counter = 0, barrier = 0, signal_and_wait = 0,
       publish = 0, subscribe = 0, ping = 0, hello = 0, bye = 0,
       sync_stats = 0;
  // connection churn
  long accepts = 0, closes = 0, evictions = 0;
  size_t conns_hwm = 0;
  // barrier lifecycle (per-waiter)
  long bar_parked = 0, bar_released = 0, bar_timed_out = 0,
       bar_canceled = 0;
  // pubsub
  long published = 0;
  size_t depth_hwm = 0, subs_open = 0, subs_hwm = 0;
  // idempotency dedup
  long dedup_signal = 0, dedup_publish = 0;
};
SyncStatsCounters g_stats;

std::string sync_stats_v2_tail() {
  // the v2 extension blocks appended after the v1 fields; pubsub
  // topic/entry gauges count NON-EMPTY topics so both backends agree
  // (this map grows an empty record on subscribe, the Python dict
  // does not)
  size_t nonempty = 0, entries = 0;
  for (const auto& kv : topics)
    if (!kv.second.entries.empty()) {
      nonempty++;
      entries += kv.second.entries.size();
    }
  const SyncStatsCounters& g = g_stats;
  char buf[1536];
  snprintf(
      buf, sizeof buf,
      ", \"v\": 2, \"uptime_secs\": %.3f"
      ", \"ops\": {\"signal_entry\": %ld, \"counter\": %ld, \"barrier\": "
      "%ld, \"signal_and_wait\": %ld, \"publish\": %ld, \"subscribe\": "
      "%ld, \"ping\": %ld, \"hello\": %ld, \"bye\": %ld, \"sync_stats\": "
      "%ld}"
      ", \"conn\": {\"accepts\": %ld, \"closes\": %ld, \"evictions\": "
      "%ld, \"hwm\": %zu}"
      ", \"barriers\": {\"parked\": %ld, \"released\": %ld, "
      "\"timed_out\": %ld, \"canceled\": %ld}"
      ", \"pubsub\": {\"published\": %ld, \"topics\": %zu, \"entries\": "
      "%zu, \"depth_hwm\": %zu, \"subs_hwm\": %zu}"
      ", \"dedup\": {\"signal_hits\": %ld, \"publish_hits\": %ld}",
      now_secs() - stats_start, g.signal_entry, g.counter, g.barrier,
      g.signal_and_wait, g.publish, g.subscribe, g.ping, g.hello, g.bye,
      g.sync_stats, g.accepts, g.closes, g.evictions, g.conns_hwm,
      g.bar_parked, g.bar_released, g.bar_timed_out, g.bar_canceled,
      g.published, nonempty, entries, g.depth_hwm, g.subs_hwm,
      g.dedup_signal, g.dedup_publish);
  return std::string(buf);
}

// live connection count per hello'd identity, plus evictions waiting out
// their grace window (canceled when the identity reconnects in time)
std::unordered_map<std::string, int> live_ids;
struct PendingEvict {
  std::string key;
  double due;
  std::string topic;
  std::string payload;
};
std::vector<PendingEvict> pending_evictions;

volatile sig_atomic_t stop_flag = 0;  // set by SIGTERM/SIGINT

// --------------------------------------------------------------- outbound

// Append one frame to a local conn's flat write buffer; sheds the peer
// if its backlog trips the bound (it stopped reading).
void out_append(Conn& c, const char* data, size_t n) {
  if (c.dead) return;
  c.wbuf.append(data, n);
  if (c.wbuf.size() - c.whead > g_max_wbuf) {
    if (stats_on) g_stats.evictions++;
    c.dead = true;
    t_shard->dead.push_back(c.fd);
    return;
  }
  if (!c.dirty) {
    c.dirty = true;
    t_shard->dirty.push_back(&c);
  }
}

void out_append(Conn& c, sv s) { out_append(c, s.data(), s.size()); }

// Route a reply to whichever shard owns the conn (generation-checked).
void route_line(int fd, uint64_t gen, int shard, std::string&& line) {
  if (shard == t_shard->id) {
    auto it = t_shard->conns.find(fd);
    if (it != t_shard->conns.end() && it->second.gen == gen)
      out_append(it->second, line.data(), line.size());
  } else {
    (*t_outbound)[shard].push_back(Msg{fd, gen, std::move(line)});
  }
}

void reply_err(Conn& c, long id, sv msg) {
  char buf[64];
  int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"error\": \"", id);
  out_append(c, buf, (size_t)n);
  std::string esc = json_escape(msg);
  out_append(c, esc.data(), esc.size());
  out_append(c, "\"}\n", 3);
}

// Try to drain a connection's write buffer; non-blocking, never stalls
// the loop. Marks the conn dead on a hard error.
void flush_conn(Conn& c) {
  while (c.whead < c.wbuf.size()) {
    ssize_t w = send(c.fd, c.wbuf.data() + c.whead,
                     c.wbuf.size() - c.whead, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) break;
      c.dead = true;
      t_shard->dead.push_back(c.fd);
      return;
    }
    c.whead += (size_t)w;
  }
  if (c.whead >= c.wbuf.size()) {
    c.whead = 0;
    if (c.wbuf.capacity() > (256 << 10)) {
      std::string().swap(c.wbuf);  // a fanout spike must not pin memory
    } else {
      c.wbuf.clear();
    }
  }
  bool need_write = c.whead < c.wbuf.size();
  if (need_write != c.want_write) {
    c.want_write = need_write;
    struct epoll_event ev{};
    ev.events = EPOLLIN | (need_write ? EPOLLOUT : 0);
    ev.data.ptr = &c;
    epoll_ctl(t_shard->ep, EPOLL_CTL_MOD, c.fd, &ev);
  }
}

// ----------------------------------------------------- coalesced release

// Release every satisfiable waiter of one state in a single sweep
// (BATCHED barrier release: one state transition fans out W replies
// through the per-conn/per-shard outbound buffers instead of W
// independent write paths). Called from the per-drain release pass.
void release_state(const std::string& state) {
  auto it = waiters_by_state.find(state);
  if (it == waiters_by_state.end()) return;
  StateWaiters& sw = it->second;
  long count = counters[state];
  if (count < sw.min_target) return;  // the O(1) watermark skip
  long new_min = LONG_MAX;
  size_t kept = 0;
  for (size_t i = 0; i < sw.v.size(); i++) {
    Waiter& w = sw.v[i];
    if (count >= w.target) {
      char buf[128];
      int n;
      if (w.seq >= 0)
        n = snprintf(buf, sizeof buf,
                     "{\"id\": %ld, \"seq\": %ld, \"ok\": true}\n", w.id,
                     w.seq);
      else
        n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"ok\": true}\n",
                     w.id);
      if (stats_on) g_stats.bar_released++;
      g_waiter_count--;
      route_line(w.fd, w.gen, w.shard, std::string(buf, (size_t)n));
    } else {
      if (w.target < new_min) new_min = w.target;
      sw.v[kept++] = w;
    }
  }
  sw.v.resize(kept);
  sw.min_target = new_min;
  if (sw.v.empty()) waiters_by_state.erase(it);
}

// Stream every undelivered entry of one topic to each subscriber, one
// pass, frames batched into the per-conn outbound buffers.
void fanout_topic(const std::string& topic_name) {
  auto it = topics.find(topic_name);
  if (it == topics.end()) return;
  Topic& t = it->second;
  if (t.subs.empty() || t.entries.empty()) return;
  for (Sub& sub : t.subs) {
    while (sub.cursor < t.entries.size()) {
      char head[64];
      int hn = snprintf(head, sizeof head, "{\"id\": %ld, \"entry\": ",
                        sub.id);
      sub.cursor++;
      char tail[40];
      int tn = snprintf(tail, sizeof tail, ", \"seq\": %zu}\n", sub.cursor);
      const std::string& entry = t.entries[sub.cursor - 1];
      if (sub.shard == t_shard->id) {
        auto cit = t_shard->conns.find(sub.fd);
        if (cit != t_shard->conns.end() && cit->second.gen == sub.gen) {
          Conn& c = cit->second;
          out_append(c, head, (size_t)hn);
          out_append(c, entry.data(), entry.size());
          out_append(c, tail, (size_t)tn);
        }
      } else {
        std::string line;
        line.reserve(hn + entry.size() + tn);
        line.append(head, (size_t)hn);
        line += entry;
        line.append(tail, (size_t)tn);
        (*t_outbound)[sub.shard].push_back(
            Msg{sub.fd, sub.gen, std::move(line)});
      }
    }
  }
}

void expire_waiters(double now) {
  if (g_next_deadline <= 0 || now < g_next_deadline) return;
  double next = 0.0;
  for (auto it = waiters_by_state.begin(); it != waiters_by_state.end();) {
    StateWaiters& sw = it->second;
    long new_min = LONG_MAX;
    size_t kept = 0;
    for (size_t i = 0; i < sw.v.size(); i++) {
      Waiter& w = sw.v[i];
      if (w.deadline > 0 && now >= w.deadline) {
        if (stats_on) g_stats.bar_timed_out++;
        g_waiter_count--;
        char buf[96];
        int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"error\": \"",
                         w.id);
        route_line(w.fd, w.gen, w.shard,
                   std::string(buf, (size_t)n) +
                       json_escape("barrier timed out: " + it->first) +
                       "\"}\n");
      } else {
        if (w.deadline > 0 && (next == 0.0 || w.deadline < next))
          next = w.deadline;
        if (w.target < new_min) new_min = w.target;
        sw.v[kept++] = w;
      }
    }
    sw.v.resize(kept);
    sw.min_target = new_min;
    if (sw.v.empty())
      it = waiters_by_state.erase(it);
    else
      ++it;
  }
  g_next_deadline = next;
}

// Signal with optional idempotency token: a re-sent request (reconnect
// replay) answers with the original seq instead of double-counting.
long signal_with_token(sv state, sv token) {
  if (!token.empty()) {
    std::string& key = keyed(t_key2, state);
    key += '\x1f';
    key.append(token.data(), token.size());
    if (long* prev = sig_tokens.find(key)) {
      if (stats_on) g_stats.dedup_signal++;
      return *prev;
    }
    long seq = ++counters[keyed(t_key1, state)];
    sig_tokens.put(key, seq);
    return seq;
  }
  return ++counters[keyed(t_key1, state)];
}

// Append a server-generated entry (eviction events) to a topic.
void publish_entry(const std::string& topic, const std::string& payload) {
  Topic& t = topics[topic];
  t.entries.push_back(payload);
  if (stats_on) {
    g_stats.published++;
    if (t.entries.size() > g_stats.depth_hwm)
      g_stats.depth_hwm = t.entries.size();
  }
  t_touched_topics->insert(topic);
}

std::string ident_key(const Conn& c) {
  return c.events_topic + '\x1f' + c.group + '\x1f' +
         std::to_string(c.instance);
}

// ---------------------------------------------------------------- dispatch

void count_op_slow(sv op) {
  SyncStatsCounters& g = g_stats;
  if (op == "counter") g.counter++;
  else if (op == "barrier") g.barrier++;
  else if (op == "signal_and_wait") g.signal_and_wait++;
  else if (op == "publish") g.publish++;
  else if (op == "subscribe") g.subscribe++;
  else if (op == "ping") g.ping++;
  else if (op == "hello") g.hello++;
  else if (op == "bye") g.bye++;
  else if (op == "sync_stats") g.sync_stats++;
}

void handle_line(Conn& conn, sv line) {
  long id = field_long(line, "id", -1);
  sv op = json_unescape(find_field(line, "op"), t_scratch1);
  char buf[160];
  if (op == "signal_entry") {  // THE hot op: fully allocation-free
    if (stats_on) g_stats.signal_entry++;
    sv state = json_unescape(find_field(line, "state"), t_scratch1);
    sv token = json_unescape(find_field(line, "token"), t_scratch2);
    long seq = signal_with_token(state, token);
    int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld}\n", id,
                     seq);
    out_append(conn, buf, (size_t)n);
    // a signal can only release someone if anyone is parked at all —
    // the flood fast path skips the touched-set entirely
    if (g_waiter_count)
      t_touched_states->emplace(state.data(), state.size());
    return;
  }
  if (op.empty()) {
    reply_err(conn, -1, "malformed request");
    return;
  }
  if (stats_on) count_op_slow(op);
  if (op == "ping") {
    int n = snprintf(buf, sizeof buf,
                     "{\"id\": %ld, \"pong\": true, \"boot\": \"%s\"}\n",
                     id, boot_id.c_str());
    out_append(conn, buf, (size_t)n);
  } else if (op == "hello") {
    if (conn.hello) {  // re-hello on the same conn: retag the identity
      auto lit = live_ids.find(ident_key(conn));
      if (lit != live_ids.end() && --lit->second <= 0) live_ids.erase(lit);
    }
    conn.hello = true;
    sv et = json_unescape(find_field(line, "events_topic"), t_scratch1);
    conn.events_topic.assign(et.data(), et.size());
    sv grp = json_unescape(find_field(line, "group"), t_scratch1);
    conn.group.assign(grp.data(), grp.size());
    conn.instance = field_long(line, "instance", -1);
    live_ids[ident_key(conn)]++;
    int n = snprintf(buf, sizeof buf,
                     "{\"id\": %ld, \"ok\": true, \"boot\": \"%s\"}\n", id,
                     boot_id.c_str());
    out_append(conn, buf, (size_t)n);
  } else if (op == "bye") {
    conn.clean = true;
    int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"ok\": true}\n", id);
    out_append(conn, buf, (size_t)n);
  } else if (op == "sync_stats") {
    size_t nsubs = 0;
    for (const auto& kv : topics) nsubs += kv.second.subs.size();
    int n = snprintf(buf, sizeof buf,
                     "{\"id\": %ld, \"conns\": %ld, \"waiters\": %zu, "
                     "\"subs\": %zu, \"boot\": \"%s\"",
                     id, g_conn_count.load(), g_waiter_count, nsubs,
                     boot_id.c_str());
    std::string r(buf, (size_t)n);
    if (stats_on) r += sync_stats_v2_tail();
    r += "}\n";
    out_append(conn, r.data(), r.size());
  } else if (op == "counter") {
    sv state = json_unescape(find_field(line, "state"), t_scratch1);
    int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"count\": %ld}\n",
                     id, counters[keyed(t_key1, state)]);
    out_append(conn, buf, (size_t)n);
  } else if (op == "barrier" || op == "signal_and_wait") {
    // `op` may itself be a view into t_scratch1 (escape-containing op
    // name); latch the distinction BEFORE state unescaping clobbers it
    bool is_saw = (op == "signal_and_wait");
    sv state = json_unescape(find_field(line, "state"), t_scratch1);
    long target = field_long(line, "target", 0);
    // absent/null timeout = wait forever; an EXPLICIT 0 is an immediate
    // non-blocking check (the Python spec server's semantics): unmet
    // after this drain's release pass → timed out
    double timeout = field_double(line, "timeout", -1.0);
    long seq = -1;
    if (is_saw)
      seq = signal_with_token(
          state, json_unescape(find_field(line, "token"), t_scratch2));
    double deadline = timeout >= 0 ? now_secs() + timeout : 0.0;
    if (stats_on) g_stats.bar_parked++;
    StateWaiters& sw = waiters_by_state[keyed(t_key1, state)];
    if (target < sw.min_target) sw.min_target = target;
    sw.v.push_back(
        Waiter{conn.fd, conn.gen, t_shard->id, id, target, seq, deadline});
    g_waiter_count++;
    if (timeout >= 0 &&
        (g_next_deadline == 0.0 || deadline < g_next_deadline))
      g_next_deadline = deadline;
    t_touched_states->emplace(state.data(), state.size());
  } else if (op == "publish") {
    sv topic = json_unescape(find_field(line, "topic"), t_scratch1);
    sv payload = find_field(line, "payload");
    if (payload.empty()) payload = "null";
    sv token = json_unescape(find_field(line, "token"), t_scratch2);
    long seq;
    long* prev = nullptr;
    if (!token.empty()) {
      std::string& tkey = keyed(t_key2, topic);
      tkey += '\x1f';
      tkey.append(token.data(), token.size());
      prev = pub_tokens.find(tkey);
    }
    if (prev) {  // replayed publish
      if (stats_on) g_stats.dedup_publish++;
      seq = *prev;
    } else {
      Topic& t = topics[keyed(t_key1, topic)];
      t.entries.emplace_back(payload.data(), payload.size());
      seq = (long)t.entries.size();
      if (!token.empty()) pub_tokens.put(t_key2, seq);
      if (stats_on) {
        g_stats.published++;
        if (t.entries.size() > g_stats.depth_hwm)
          g_stats.depth_hwm = t.entries.size();
      }
    }
    int n = snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld}\n", id,
                     seq);
    out_append(conn, buf, (size_t)n);
    t_touched_topics->emplace(topic.data(), topic.size());
  } else if (op == "subscribe") {
    sv topic = json_unescape(find_field(line, "topic"), t_scratch1);
    topics[keyed(t_key1, topic)].subs.push_back(
        Sub{conn.fd, conn.gen, t_shard->id, id, 0});
    if (stats_on && ++g_stats.subs_open > g_stats.subs_hwm)
      g_stats.subs_hwm = g_stats.subs_open;
    t_touched_topics->emplace(topic.data(), topic.size());
  } else {
    reply_err(conn, id, "unknown op '" + std::string(op) + "'");
  }
}

// --------------------------------------------------------------- teardown

void drop_conn(Conn& c) {
  // salvage identity before erasing: an abnormal disconnect of a
  // hello'd instance SCHEDULES an eviction event AFTER its occupancy
  // (parked waiters, subscriptions) is released — published only if no
  // connection with the same identity is back within evict_grace (a
  // client dropping its socket to reconnect is not dead)
  if (c.hello) {
    std::string key = ident_key(c);
    auto lit = live_ids.find(key);
    int remaining = 0;
    if (lit != live_ids.end() && --lit->second <= 0) {
      live_ids.erase(lit);
    } else if (lit != live_ids.end()) {
      remaining = lit->second;
    }
    if (!c.clean && !c.events_topic.empty() && !stop_flag &&
        remaining == 0) {
      pending_evictions.push_back(PendingEvict{
          key, now_secs() + evict_grace, c.events_topic,
          std::string("{\"type\": \"evicted\", \"group\": \"") +
              json_escape(c.group) + "\", \"instance\": " +
              std::to_string(c.instance) +
              ", \"error\": \"connection lost (killed, partitioned, or "
              "idle-evicted)\"}"});
    }
  }
  if (stats_on) g_stats.closes++;
  g_conn_count--;
  // purge parked waiters and subscriptions (by fd + generation)
  for (auto it = waiters_by_state.begin(); it != waiters_by_state.end();) {
    StateWaiters& sw = it->second;
    long new_min = LONG_MAX;
    size_t kept = 0;
    for (size_t i = 0; i < sw.v.size(); i++) {
      Waiter& w = sw.v[i];
      if (w.fd == c.fd && w.gen == c.gen) {
        if (stats_on) g_stats.bar_canceled++;  // conn lost mid-barrier
        g_waiter_count--;
      } else {
        if (w.target < new_min) new_min = w.target;
        sw.v[kept++] = w;
      }
    }
    sw.v.resize(kept);
    sw.min_target = new_min;
    if (sw.v.empty())
      it = waiters_by_state.erase(it);
    else
      ++it;
  }
  for (auto& kv : topics) {
    auto& subs = kv.second.subs;
    for (size_t i = 0; i < subs.size();) {
      if (subs[i].fd == c.fd && subs[i].gen == c.gen) {
        if (stats_on && g_stats.subs_open > 0) g_stats.subs_open--;
        subs[i] = subs.back();
        subs.pop_back();
      } else {
        i++;
      }
    }
  }
  close(c.fd);  // also removes it from the shard's epoll set
}

// Publish due evictions whose identity never came back; an identity
// that reconnected inside its grace window is silently canceled.
void flush_evictions() {
  if (pending_evictions.empty()) return;
  double now = now_secs();
  for (size_t i = 0; i < pending_evictions.size();) {
    PendingEvict& pe = pending_evictions[i];
    if (live_ids.count(pe.key)) {  // came back — cancel
      pe = pending_evictions.back();
      pending_evictions.pop_back();
    } else if (now >= pe.due) {
      publish_entry(pe.topic, pe.payload);
      pending_evictions[i] = pending_evictions.back();
      pending_evictions.pop_back();
    } else {
      i++;
    }
  }
}

// Mark this shard's connections silent past the idle window dead: a
// heartbeating client is never idle, so only dead/partitioned peers
// (whose kernel may keep the socket ESTABLISHED forever) trip this.
void sweep_idle(double now) {
  if (idle_timeout <= 0) return;
  for (auto& kv : t_shard->conns)
    if (!kv.second.dead && now - kv.second.last_active > idle_timeout) {
      if (stats_on) g_stats.evictions++;
      kv.second.dead = true;
      t_shard->dead.push_back(kv.first);
    }
}

void on_term(int) { stop_flag = 1; }

// ------------------------------------------------------------- shard loop

int g_listen_fd = -1;
// epoll data.ptr tags for the two non-conn fds in each shard's set
void* const kTagListener = nullptr;
char g_evfd_tag;  // address used as the eventfd tag

void shard_loop(Shard* shard) {
  t_shard = shard;
  std::vector<std::vector<Msg>> outbound(g_nshards);
  t_outbound = &outbound;
  std::unordered_set<std::string> touched_states, touched_topics;
  t_touched_states = &touched_states;
  t_touched_topics = &touched_topics;

  {  // listener shared across shards: the kernel picks ONE shard per
     // pending connection (accept fan-out)
    struct epoll_event ev{};
    ev.events = EPOLLIN | EPOLLEXCLUSIVE;
    ev.data.ptr = kTagListener;
    epoll_ctl(shard->ep, EPOLL_CTL_ADD, g_listen_fd, &ev);
  }
  {
    struct epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = &g_evfd_tag;
    epoll_ctl(shard->ep, EPOLL_CTL_ADD, shard->evfd, &ev);
  }

  constexpr int kMaxEvents = 1024;
  std::vector<struct epoll_event> evs(kMaxEvents);
  char rbuf[65536];
  std::vector<Conn*> batch;  // conns with complete lines this drain

  while (!stop_flag) {
    // ---- timeout: nearest barrier deadline / idle sweep / evictions
    int tmo = -1;
    {
      std::lock_guard<std::mutex> lk(g_mu);
      if (g_next_deadline > 0) {
        int ms = (int)((g_next_deadline - now_secs()) * 1000) + 1;
        if (ms < 0) ms = 0;
        tmo = ms;
      }
      if (!pending_evictions.empty() && (tmo < 0 || tmo > 100)) tmo = 100;
    }
    if (idle_timeout > 0) {
      int sweep_ms = (int)(idle_timeout * 250);  // idle_timeout / 4
      if (sweep_ms < 100) sweep_ms = 100;
      if (tmo < 0 || sweep_ms < tmo) tmo = sweep_ms;
    }
    if (!shard->dead.empty()) tmo = 0;
    int rc = epoll_wait(shard->ep, evs.data(), kMaxEvents, tmo);
    if (rc < 0 && errno != EINTR) break;
    if (stop_flag) break;
    double now = now_secs();

    // ---- phase A (no lock): accept + read; batch conns with lines
    batch.clear();
    for (int i = 0; i < rc; i++) {
      void* tag = evs[i].data.ptr;
      uint32_t e = evs[i].events;
      if (tag == kTagListener) {
        while (true) {
          int cfd = accept4(g_listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          auto [it, fresh] = shard->conns.try_emplace(cfd);
          Conn& c = it->second;
          c = Conn{};
          c.fd = cfd;
          c.gen = g_gen.fetch_add(1);
          c.last_active = now;
          g_conn_count++;
          shard->accepted++;
          struct epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = &c;
          epoll_ctl(shard->ep, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      if (tag == &g_evfd_tag) {
        uint64_t v;
        while (read(shard->evfd, &v, sizeof v) > 0) {
        }
        continue;
      }
      Conn& c = *static_cast<Conn*>(tag);
      if (c.dead) continue;
      if (e & EPOLLOUT) flush_conn(c);
      if (c.dead || !(e & (EPOLLIN | EPOLLHUP | EPOLLERR))) continue;
      ssize_t n = recv(c.fd, rbuf, sizeof rbuf, 0);
      if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                     errno != EINTR)) {
        // EOF/reset: any already-received complete lines (e.g. a "bye"
        // right before close) still dispatch below, THEN the drop runs
        c.dead = true;
        shard->dead.push_back(c.fd);
        if (!c.rbuf.empty() && c.rbuf.find('\n') != std::string::npos)
          batch.push_back(&c);
      } else if (n > 0) {
        c.last_active = now;
        c.rbuf.append(rbuf, (size_t)n);
        if (memchr(c.rbuf.data(), '\n', c.rbuf.size()))
          batch.push_back(&c);
      }
    }

    // inbox: replies routed here by other shards
    std::vector<Msg> incoming;
    if (g_nshards > 1) {
      std::lock_guard<std::mutex> lk(shard->inbox_mu);
      incoming.swap(shard->inbox);
    }

    // ---- phase B (one lock): apply the whole batch + coalesced passes
    {
      std::lock_guard<std::mutex> lk(g_mu);
      if (shard->accepted) {
        if (stats_on) {
          g_stats.accepts += shard->accepted;
          long live = g_conn_count.load();
          if ((size_t)live > g_stats.conns_hwm)
            g_stats.conns_hwm = (size_t)live;
        }
        shard->accepted = 0;
      }
      for (Msg& m : incoming) {
        auto it = shard->conns.find(m.fd);
        if (it != shard->conns.end() && it->second.gen == m.gen)
          out_append(it->second, m.line.data(), m.line.size());
      }
      for (Conn* cp : batch) {
        Conn& c = *cp;
        sv rest(c.rbuf);
        size_t consumed = 0;
        while (true) {
          size_t nl = rest.find('\n');
          if (nl == sv::npos) break;
          sv line = rest.substr(0, nl);
          rest.remove_prefix(nl + 1);
          consumed += nl + 1;
          // a shed conn (write-bound tripped) stops dispatching; an
          // EOF'd conn still drains its final lines (e.g. bye)
          if (!line.empty() &&
              !(c.dead && c.wbuf.size() - c.whead > g_max_wbuf))
            handle_line(c, line);
        }
        c.rbuf.erase(0, consumed);
      }
      sweep_idle(now);
      // mark-drop only: the map entry (and thus every Conn* in this
      // drain's dirty list and epoll events) stays valid until the
      // post-flush erase below
      for (int fd : shard->dead) {
        auto it = shard->conns.find(fd);
        if (it == shard->conns.end() || it->second.dropped) continue;
        it->second.dropped = true;
        drop_conn(it->second);
      }
      flush_evictions();
      // release BEFORE expire: a zero-timeout barrier that is already
      // satisfiable must release this drain, not time out (the Python
      // spec's wait_for(timeout=0) checks the predicate first)
      for (const std::string& s : touched_states) release_state(s);
      touched_states.clear();
      for (const std::string& t : touched_topics) fanout_topic(t);
      touched_topics.clear();
      expire_waiters(now);
    }

    // ---- phase C (no lock): deliver cross-shard replies, flush dirty
    for (int s = 0; s < g_nshards; s++) {
      if (outbound[s].empty()) continue;
      {
        std::lock_guard<std::mutex> lk(g_shards[s].inbox_mu);
        for (Msg& m : outbound[s])
          g_shards[s].inbox.push_back(std::move(m));
      }
      uint64_t one = 1;
      ssize_t wr = write(g_shards[s].evfd, &one, sizeof one);
      (void)wr;
      outbound[s].clear();
    }
    for (Conn* cp : shard->dirty) {
      cp->dirty = false;
      if (!cp->dead) flush_conn(*cp);
    }
    shard->dirty.clear();
    // erase dropped conns now that no Conn* from this drain remains
    // live; conns that died DURING the flush above (not yet dropped)
    // stay queued for the next drain's mark-drop
    size_t keep = 0;
    for (int fd : shard->dead) {
      auto it = shard->conns.find(fd);
      if (it == shard->conns.end()) continue;
      if (it->second.dropped)
        shard->conns.erase(it);
      else
        shard->dead[keep++] = fd;
    }
    shard->dead.resize(keep);
  }
  // shutdown: drop this shard's conns (no eviction events: stop_flag)
  {
    std::lock_guard<std::mutex> lk(g_mu);
    for (auto& kv : shard->conns) drop_conn(kv.second);
    shard->conns.clear();
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  int shards = 0;  // 0 = auto
  const char* host = "127.0.0.1";
  for (int i = 1; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--host") == 0) host = argv[i + 1];
    if (strcmp(argv[i], "--idle-timeout") == 0)
      idle_timeout = atof(argv[i + 1]);
    if (strcmp(argv[i], "--evict-grace") == 0)
      evict_grace = atof(argv[i + 1]);
    // --stats 0 answers sync_stats with the v1 occupancy shape and
    // skips the counters (the fan-in bench's A/B knob)
    if (strcmp(argv[i], "--stats") == 0) stats_on = atoi(argv[i + 1]) != 0;
    if (strcmp(argv[i], "--shards") == 0) shards = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--max-wbuf") == 0)
      g_max_wbuf = (size_t)atol(argv[i + 1]);
  }
  stats_start = now_secs();
  if (shards <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    shards = (int)(hw < 1 ? 1 : (hw > 4 ? 4 : hw));
  }
  g_nshards = shards;

  {  // boot id: distinguishes restarts for reconnecting clients
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    char buf[48];
    snprintf(buf, sizeof buf, "%lx-%lx-%x", (unsigned long)ts.tv_sec,
             (unsigned long)ts.tv_nsec, (unsigned)getpid());
    boot_id = buf;
  }

  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (strcmp(host, "localhost") == 0) host = "127.0.0.1";
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    fprintf(stderr, "tg-syncsvc: bad --host %s (want an IPv4 address)\n",
            host);
    return 1;
  }
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(lfd, 1024) != 0) {
    perror("tg-syncsvc: bind/listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (sockaddr*)&addr, &alen);
  g_listen_fd = lfd;

  g_shards.resize(shards);
  for (int i = 0; i < shards; i++) {
    g_shards[i].id = i;
    g_shards[i].ep = epoll_create1(0);
    g_shards[i].evfd = eventfd(0, EFD_NONBLOCK);
    if (g_shards[i].ep < 0 || g_shards[i].evfd < 0) {
      perror("tg-syncsvc: epoll/eventfd");
      return 1;
    }
  }

  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::vector<std::thread> threads;
  for (int i = 1; i < shards; i++)
    threads.emplace_back(shard_loop, &g_shards[i]);
  shard_loop(&g_shards[0]);  // shard 0 runs on the main thread
  stop_flag = 1;
  // wake the other shards so their epoll_wait returns promptly
  for (int i = 1; i < shards; i++) {
    uint64_t one64 = 1;
    ssize_t wr = write(g_shards[i].evfd, &one64, sizeof one64);
    (void)wr;
  }
  for (auto& t : threads) t.join();
  close(lfd);
  return 0;
}
