// tg-syncsvc — native sync service for the local:exec runner.
//
// The runtime analog of the reference's sync-service container (Go +
// Redis, pkg/runner/local_common.go:77-104): a single-threaded poll()
// event loop serving the framework's newline-delimited-JSON protocol
// (testground_tpu/sync/server.py is the behavioral spec):
//
//   request:  {"id": N, "op": <op>, ...args}\n
//   reply:    {"id": N, ...result}\n             exactly one, except
//   subscribe streams {"id": N, "entry": <raw>, "seq": i} frames.
//
// Ops: signal_entry(state[, token]), counter(state), barrier(state,
// target[, timeout]), signal_and_wait(state, target[, timeout][,
// token]), publish(topic, payload[, token]), subscribe(topic), plus the
// liveness/identity plane (docs/CROSSHOST.md, spec'd by server.py):
// ping (pong + boot id), hello (instance identity; abnormal disconnect
// publishes an eviction event to its events_topic), bye (clean close),
// sync_stats (the wire-versioned stats plane, v2: v1 occupancy fields
// conns/waiters/subs/boot plus counter-level per-op/conn-churn/barrier-
// lifecycle/pubsub/dedup blocks — docs/INSTANCE_PROTOCOL.md §4.2; this
// server stays at counter level, histograms are python-server-only).
// `token` is an idempotency key: re-sent mutations from a reconnecting
// client answer with the original seq instead of mutating twice.
//
// Design notes:
// - publish payloads are NEVER parsed: the raw JSON value text is stored
//   and echoed verbatim into subscribe frames, so arbitrary payloads
//   round-trip without a full JSON implementation;
// - one thread, no locks: barrier waiters and topic subscribers are
//   parked records flushed when counters/topics advance — the C++ twin
//   of the Python server's per-request threads without the threads;
// - stdout handshake: "LISTENING <port>" once bound (the runner reads
//   this to learn an ephemeral port);
// - --host picks the bind address (default loopback; 0.0.0.0 makes the
//   service a network citizen other hosts can dial — the
//   cluster_k8s.go:302 analog); --idle-timeout S evicts connections
//   that sent nothing (not even a heartbeat ping) for S seconds, so a
//   SIGSTOPped or half-open peer releases its parked waiters instead of
//   leaking occupancy forever.
//
// Build: g++ -O2 -std=c++17 -o tg-syncsvc syncsvc.cc
// (testground_tpu/native/syncsvc.py wraps build + spawn + lifecycle).

#include <arpa/inet.h>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

double now_secs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// ---------------------------------------------------------------- JSON bits
// Minimal field extraction over one request line. Values are returned as
// raw JSON text; strings additionally unescape via json_unescape.

// Skip a JSON value starting at i; returns one-past-end, or npos on error.
size_t skip_value(const std::string& s, size_t i) {
  while (i < s.size() && isspace((unsigned char)s[i])) i++;
  if (i >= s.size()) return std::string::npos;
  char c = s[i];
  if (c == '"') {
    for (i++; i < s.size(); i++) {
      if (s[i] == '\\') { i++; continue; }
      if (s[i] == '"') return i + 1;
    }
    return std::string::npos;
  }
  if (c == '{' || c == '[') {
    char open = c, close = (c == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    for (; i < s.size(); i++) {
      char d = s[i];
      if (in_str) {
        if (d == '\\') { i++; continue; }
        if (d == '"') in_str = false;
      } else if (d == '"') {
        in_str = true;
      } else if (d == open) {
        depth++;
      } else if (d == close) {
        depth--;
        if (depth == 0) return i + 1;
      }
    }
    return std::string::npos;
  }
  // number / true / false / null
  size_t j = i;
  while (j < s.size() && (isalnum((unsigned char)s[j]) || s[j] == '-' ||
                          s[j] == '+' || s[j] == '.'))
    j++;
  return j == i ? std::string::npos : j;
}

// Raw JSON text of top-level field `key`, or empty if absent.
std::string find_field(const std::string& line, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  size_t i = 0;
  bool in_str = false;
  int depth = 0;
  for (; i < line.size(); i++) {
    char c = line[i];
    if (in_str) {
      if (c == '\\') { i++; continue; }
      if (c == '"') in_str = false;
      continue;
    }
    if (c == '{' || c == '[') { depth++; continue; }
    if (c == '}' || c == ']') { depth--; continue; }
    if (c == '"') {
      if (depth == 1 && line.compare(i, pat.size(), pat) == 0) {
        size_t j = i + pat.size();
        while (j < line.size() && isspace((unsigned char)line[j])) j++;
        if (j < line.size() && line[j] == ':') {
          size_t start = j + 1;
          while (start < line.size() && isspace((unsigned char)line[start]))
            start++;
          size_t end = skip_value(line, start);
          if (end == std::string::npos) return "";
          return line.substr(start, end - start);
        }
      }
      in_str = true;
    }
  }
  return "";
}

void utf8_append(std::string& out, unsigned cp) {
  if (cp < 0x80) {
    out += char(cp);
  } else if (cp < 0x800) {
    out += char(0xC0 | (cp >> 6));
    out += char(0x80 | (cp & 0x3F));
  } else {
    out += char(0xE0 | (cp >> 12));
    out += char(0x80 | ((cp >> 6) & 0x3F));
    out += char(0x80 | (cp & 0x3F));
  }
}

// Decode a raw JSON string token ("...") to its value; empty on error.
std::string json_unescape(const std::string& raw) {
  if (raw.size() < 2 || raw.front() != '"' || raw.back() != '"') return "";
  std::string out;
  out.reserve(raw.size());
  for (size_t i = 1; i + 1 < raw.size(); i++) {
    char c = raw[i];
    if (c != '\\') { out += c; continue; }
    if (++i + 1 > raw.size()) break;
    switch (raw[i]) {
      case 'n': out += '\n'; break;
      case 't': out += '\t'; break;
      case 'r': out += '\r'; break;
      case 'b': out += '\b'; break;
      case 'f': out += '\f'; break;
      case 'u': {
        if (i + 4 < raw.size()) {
          unsigned cp = (unsigned)strtoul(raw.substr(i + 1, 4).c_str(),
                                          nullptr, 16);
          utf8_append(out, cp);
          i += 4;
        }
        break;
      }
      default: out += raw[i];
    }
  }
  return out;
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

long field_long(const std::string& line, const std::string& key, long dflt) {
  std::string raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  return strtol(raw.c_str(), nullptr, 10);
}

double field_double(const std::string& line, const std::string& key,
                    double dflt) {
  std::string raw = find_field(line, key);
  if (raw.empty() || raw == "null") return dflt;
  return strtod(raw.c_str(), nullptr);
}

// ------------------------------------------------------------------- state

struct Conn {
  int fd;
  std::string rbuf;
  std::string wbuf;  // unsent reply bytes; drained on POLLOUT
  double last_active = 0.0;  // last byte read (idle-sweep clock)
  bool hello = false;        // identity registered
  bool clean = false;        // said bye — no eviction event
  std::string events_topic;
  std::string group;
  long instance = -1;
};

// A reply backlog beyond this marks the client dead (it stopped reading);
// dropping it beats stalling the loop for everyone else.
constexpr size_t kMaxWbuf = 16 << 20;

struct Waiter {           // a parked barrier / signal_and_wait
  int fd;
  long id;
  std::string state;
  long target;
  long seq;               // -1 for plain barrier; echoed for signal_and_wait
  double deadline;        // 0 = none
};

struct Sub {
  int fd;
  long id;
  size_t cursor;
};

struct Topic {
  std::vector<std::string> entries;  // raw JSON payloads, verbatim
  std::vector<Sub> subs;
};

std::unordered_map<int, Conn> conns;
std::unordered_map<std::string, long> counters;
std::vector<Waiter> waiters;
std::unordered_map<std::string, Topic> topics;
// idempotency tokens (key: state/topic + '\x1f' + token → original seq),
// FIFO-bounded: only a reconnecting client's unacked window (seconds of
// traffic) ever needs a token, so capping at kMaxTokens bounds memory
// over week-long runs without risking a realistic double-apply.
constexpr size_t kMaxTokens = 65536;
struct TokenMap {
  std::unordered_map<std::string, long> map;
  std::deque<std::string> order;
  long* find(const std::string& key) {
    auto it = map.find(key);
    return it == map.end() ? nullptr : &it->second;
  }
  void put(const std::string& key, long seq) {
    if (map.emplace(key, seq).second) {
      order.push_back(key);
      while (order.size() > kMaxTokens) {
        map.erase(order.front());
        order.pop_front();
      }
    }
  }
};
TokenMap sig_tokens;
TokenMap pub_tokens;
std::string boot_id;       // changes every server start (restart detector)
double idle_timeout = 0.0;  // seconds; 0 = sweep disabled
double evict_grace = 2.0;   // reconnect window before eviction publishes

// ------------------------------------------------ sync-stats plane (v2)
// Counter-level mirror of the Python server's stats plane
// (testground_tpu/sync/stats.py; wire parity pinned by
// tests/test_sync_stats.py). Histograms and barrier-episode timing are
// python-server-only richness — this server stays at counters, which
// cost one increment on already-dispatched paths. --stats 0 disables
// the plane (sync_stats answers the v1 occupancy shape), which exists
// for the fan-in bench's instrumented-vs-uninstrumented A/B.
bool stats_on = true;
double stats_start = 0.0;
struct SyncStatsCounters {
  // per-op dispatch counters (counted BEFORE the reply is built, so a
  // sync_stats reply includes itself — the conservation contract)
  long signal_entry = 0, counter = 0, barrier = 0, signal_and_wait = 0,
       publish = 0, subscribe = 0, ping = 0, hello = 0, bye = 0,
       sync_stats = 0;
  // connection churn
  long accepts = 0, closes = 0, evictions = 0;
  size_t conns_hwm = 0;
  // barrier lifecycle (per-waiter)
  long bar_parked = 0, bar_released = 0, bar_timed_out = 0,
       bar_canceled = 0;
  // pubsub
  long published = 0;
  size_t depth_hwm = 0, subs_open = 0, subs_hwm = 0;
  // idempotency dedup
  long dedup_signal = 0, dedup_publish = 0;
};
SyncStatsCounters g_stats;

std::string sync_stats_v2_tail() {
  // the v2 extension blocks appended after the v1 fields; pubsub
  // topic/entry gauges count NON-EMPTY topics so both backends agree
  // (this map grows an empty record on subscribe, the Python dict
  // does not)
  size_t nonempty = 0, entries = 0;
  for (const auto& kv : topics)
    if (!kv.second.entries.empty()) {
      nonempty++;
      entries += kv.second.entries.size();
    }
  const SyncStatsCounters& g = g_stats;
  char buf[1536];
  snprintf(
      buf, sizeof buf,
      ", \"v\": 2, \"uptime_secs\": %.3f"
      ", \"ops\": {\"signal_entry\": %ld, \"counter\": %ld, \"barrier\": "
      "%ld, \"signal_and_wait\": %ld, \"publish\": %ld, \"subscribe\": "
      "%ld, \"ping\": %ld, \"hello\": %ld, \"bye\": %ld, \"sync_stats\": "
      "%ld}"
      ", \"conn\": {\"accepts\": %ld, \"closes\": %ld, \"evictions\": "
      "%ld, \"hwm\": %zu}"
      ", \"barriers\": {\"parked\": %ld, \"released\": %ld, "
      "\"timed_out\": %ld, \"canceled\": %ld}"
      ", \"pubsub\": {\"published\": %ld, \"topics\": %zu, \"entries\": "
      "%zu, \"depth_hwm\": %zu, \"subs_hwm\": %zu}"
      ", \"dedup\": {\"signal_hits\": %ld, \"publish_hits\": %ld}",
      now_secs() - stats_start, g.signal_entry, g.counter, g.barrier,
      g.signal_and_wait, g.publish, g.subscribe, g.ping, g.hello, g.bye,
      g.sync_stats, g.accepts, g.closes, g.evictions, g.conns_hwm,
      g.bar_parked, g.bar_released, g.bar_timed_out, g.bar_canceled,
      g.published, nonempty, entries, g.depth_hwm, g.subs_hwm,
      g.dedup_signal, g.dedup_publish);
  return std::string(buf);
}

void count_op(const std::string& op) {
  if (!stats_on) return;
  SyncStatsCounters& g = g_stats;
  if (op == "signal_entry") g.signal_entry++;
  else if (op == "counter") g.counter++;
  else if (op == "barrier") g.barrier++;
  else if (op == "signal_and_wait") g.signal_and_wait++;
  else if (op == "publish") g.publish++;
  else if (op == "subscribe") g.subscribe++;
  else if (op == "ping") g.ping++;
  else if (op == "hello") g.hello++;
  else if (op == "bye") g.bye++;
  else if (op == "sync_stats") g.sync_stats++;
}

// live connection count per hello'd identity, plus evictions waiting out
// their grace window (canceled when the identity reconnects in time)
std::unordered_map<std::string, int> live_ids;
struct PendingEvict {
  std::string key;
  double due;
  std::string topic;
  std::string payload;
};
std::vector<PendingEvict> pending_evictions;

std::vector<int> dead_conns;  // drop after the current dispatch completes

// Try to drain a connection's write buffer; non-blocking, never stalls
// the event loop (one wedged reader must not freeze every barrier).
void flush_wbuf(Conn& c) {
  while (!c.wbuf.empty()) {
    ssize_t n = send(c.fd, c.wbuf.data(), c.wbuf.size(),
                     MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n > 0) {
      c.wbuf.erase(0, (size_t)n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    dead_conns.push_back(c.fd);  // peer gone
    return;
  }
}

void send_line(int fd, const std::string& line) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  Conn& c = it->second;
  c.wbuf += line;
  c.wbuf += '\n';
  if (c.wbuf.size() > kMaxWbuf) {
    dead_conns.push_back(fd);
    return;
  }
  flush_wbuf(c);
}

void reply_err(int fd, long id, const std::string& msg) {
  char buf[64];
  snprintf(buf, sizeof buf, "{\"id\": %ld, \"error\": \"", id);
  send_line(fd, std::string(buf) + json_escape(msg) + "\"}");
}

void flush_waiters(const std::string& state) {
  long count = counters[state];
  for (size_t i = 0; i < waiters.size();) {
    Waiter& w = waiters[i];
    if (w.state == state && count >= w.target) {
      char buf[128];
      if (w.seq >= 0)
        snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld, \"ok\": true}",
                 w.id, w.seq);
      else
        snprintf(buf, sizeof buf, "{\"id\": %ld, \"ok\": true}", w.id);
      if (stats_on) g_stats.bar_released++;
      send_line(w.fd, buf);
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
}

void flush_subs(const std::string& topic_name) {
  Topic& t = topics[topic_name];
  for (Sub& sub : t.subs) {
    while (sub.cursor < t.entries.size()) {
      char head[64];
      snprintf(head, sizeof head, "{\"id\": %ld, \"entry\": ", sub.id);
      sub.cursor++;
      char tail[32];
      snprintf(tail, sizeof tail, ", \"seq\": %zu}", sub.cursor);
      send_line(sub.fd, std::string(head) + t.entries[sub.cursor - 1] + tail);
    }
  }
}

void expire_waiters();  // defined below; used for zero-timeout barriers

// Signal with optional idempotency token: a re-sent request (reconnect
// replay) answers with the original seq instead of double-counting.
long signal_with_token(const std::string& state, const std::string& token) {
  if (!token.empty()) {
    std::string key = state + '\x1f' + token;
    if (long* prev = sig_tokens.find(key)) {
      if (stats_on) g_stats.dedup_signal++;
      return *prev;
    }
    long seq = ++counters[state];
    sig_tokens.put(key, seq);
    return seq;
  }
  return ++counters[state];
}

// Append a server-generated entry (eviction events) to a topic.
void publish_entry(const std::string& topic, const std::string& payload) {
  Topic& t = topics[topic];
  t.entries.push_back(payload);
  if (stats_on) {
    g_stats.published++;
    if (t.entries.size() > g_stats.depth_hwm)
      g_stats.depth_hwm = t.entries.size();
  }
  flush_subs(topic);
}

std::string ident_key(const Conn& c) {
  return c.events_topic + '\x1f' + c.group + '\x1f' +
         std::to_string(c.instance);
}

void handle_line(int fd, const std::string& line) {
  long id = field_long(line, "id", -1);
  std::string op = json_unescape(find_field(line, "op"));
  if (op.empty()) {
    reply_err(fd, -1, "malformed request");
    return;
  }
  count_op(op);
  char buf[160];
  if (op == "signal_entry") {
    std::string state = json_unescape(find_field(line, "state"));
    std::string token = json_unescape(find_field(line, "token"));
    long seq = signal_with_token(state, token);
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld}", id, seq);
    send_line(fd, buf);
    flush_waiters(state);
  } else if (op == "ping") {
    send_line(fd, "{\"id\": " + std::to_string(id) +
                      ", \"pong\": true, \"boot\": \"" + boot_id + "\"}");
  } else if (op == "hello") {
    auto it = conns.find(fd);
    if (it != conns.end()) {
      Conn& c = it->second;
      if (c.hello) {  // re-hello on the same conn: retag the identity
        auto lit = live_ids.find(ident_key(c));
        if (lit != live_ids.end() && --lit->second <= 0) live_ids.erase(lit);
      }
      c.hello = true;
      c.events_topic = json_unescape(find_field(line, "events_topic"));
      c.group = json_unescape(find_field(line, "group"));
      c.instance = field_long(line, "instance", -1);
      live_ids[ident_key(c)]++;
    }
    send_line(fd, "{\"id\": " + std::to_string(id) +
                      ", \"ok\": true, \"boot\": \"" + boot_id + "\"}");
  } else if (op == "bye") {
    auto it = conns.find(fd);
    if (it != conns.end()) it->second.clean = true;
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"ok\": true}", id);
    send_line(fd, buf);
  } else if (op == "sync_stats") {
    size_t nsubs = 0;
    for (const auto& kv : topics) nsubs += kv.second.subs.size();
    snprintf(buf, sizeof buf,
             "{\"id\": %ld, \"conns\": %zu, \"waiters\": %zu, \"subs\": %zu, "
             "\"boot\": \"%s\"",
             id, conns.size(), waiters.size(), nsubs, boot_id.c_str());
    std::string r(buf);
    if (stats_on) r += sync_stats_v2_tail();
    send_line(fd, r + "}");
  } else if (op == "counter") {
    std::string state = json_unescape(find_field(line, "state"));
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"count\": %ld}", id,
             counters[state]);
    send_line(fd, buf);
  } else if (op == "barrier" || op == "signal_and_wait") {
    std::string state = json_unescape(find_field(line, "state"));
    long target = field_long(line, "target", 0);
    // absent/null timeout = wait forever; an EXPLICIT 0 is an immediate
    // non-blocking check (the Python spec server's wait_for(timeout=0))
    double timeout = field_double(line, "timeout", -1.0);
    long seq = -1;
    if (op == "signal_and_wait")
      seq = signal_with_token(state, json_unescape(find_field(line, "token")));
    Waiter w{fd, id, state, target, seq,
             timeout >= 0 ? now_secs() + timeout : 0.0};
    if (stats_on) g_stats.bar_parked++;
    waiters.push_back(w);
    flush_waiters(state);  // may satisfy immediately (incl. this one)
    if (timeout == 0.0) expire_waiters();  // unmet zero-timeout fails now
  } else if (op == "publish") {
    std::string topic = json_unescape(find_field(line, "topic"));
    std::string payload = find_field(line, "payload");
    if (payload.empty()) payload = "null";
    std::string token = json_unescape(find_field(line, "token"));
    long seq;
    long* prev =
        token.empty() ? nullptr : pub_tokens.find(topic + '\x1f' + token);
    if (prev) {  // replayed publish
      if (stats_on) g_stats.dedup_publish++;
      seq = *prev;
    } else {
      Topic& t = topics[topic];
      t.entries.push_back(payload);
      seq = (long)t.entries.size();
      if (!token.empty()) pub_tokens.put(topic + '\x1f' + token, seq);
      if (stats_on) {
        g_stats.published++;
        if (t.entries.size() > g_stats.depth_hwm)
          g_stats.depth_hwm = t.entries.size();
      }
    }
    snprintf(buf, sizeof buf, "{\"id\": %ld, \"seq\": %ld}", id, seq);
    send_line(fd, buf);
    flush_subs(topic);
  } else if (op == "subscribe") {
    std::string topic = json_unescape(find_field(line, "topic"));
    topics[topic].subs.push_back(Sub{fd, id, 0});
    if (stats_on && ++g_stats.subs_open > g_stats.subs_hwm)
      g_stats.subs_hwm = g_stats.subs_open;
    flush_subs(topic);
  } else {
    reply_err(fd, id, "unknown op '" + op + "'");
  }
}

volatile sig_atomic_t stop_flag = 0;  // set by SIGTERM/SIGINT

void drop_conn(int fd) {
  // salvage identity before erasing: an abnormal disconnect of a
  // hello'd instance SCHEDULES an eviction event AFTER its occupancy
  // (parked waiters, subscriptions) is released — published only if no
  // connection with the same identity is back within evict_grace (a
  // client dropping its socket to reconnect is not dead)
  auto it = conns.find(fd);
  if (it != conns.end()) {
    Conn& c = it->second;
    if (c.hello) {
      std::string key = ident_key(c);
      auto lit = live_ids.find(key);
      int remaining = 0;
      if (lit != live_ids.end() && --lit->second <= 0) {
        live_ids.erase(lit);
      } else if (lit != live_ids.end()) {
        remaining = lit->second;
      }
      if (!c.clean && !c.events_topic.empty() && !stop_flag &&
          remaining == 0) {
        pending_evictions.push_back(PendingEvict{
            key, now_secs() + evict_grace, c.events_topic,
            std::string("{\"type\": \"evicted\", \"group\": \"") +
                json_escape(c.group) + "\", \"instance\": " +
                std::to_string(c.instance) +
                ", \"error\": \"connection lost (killed, partitioned, or "
                "idle-evicted)\"}"});
      }
    }
  }
  close(fd);
  if (stats_on && conns.count(fd)) g_stats.closes++;
  conns.erase(fd);
  for (size_t i = 0; i < waiters.size();) {
    if (waiters[i].fd == fd) {
      if (stats_on) g_stats.bar_canceled++;  // conn lost mid-barrier
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
  for (auto& kv : topics) {
    auto& subs = kv.second.subs;
    for (size_t i = 0; i < subs.size();) {
      if (subs[i].fd == fd) {
        if (stats_on && g_stats.subs_open > 0) g_stats.subs_open--;
        subs[i] = subs.back();
        subs.pop_back();
      } else {
        i++;
      }
    }
  }
}

// Publish due evictions whose identity never came back; an identity
// that reconnected inside its grace window is silently canceled.
void flush_evictions() {
  if (pending_evictions.empty()) return;
  double now = now_secs();
  for (size_t i = 0; i < pending_evictions.size();) {
    PendingEvict& pe = pending_evictions[i];
    if (live_ids.count(pe.key)) {  // came back — cancel
      pe = pending_evictions.back();
      pending_evictions.pop_back();
    } else if (now >= pe.due) {
      publish_entry(pe.topic, pe.payload);
      pending_evictions[i] = pending_evictions.back();
      pending_evictions.pop_back();
    } else {
      i++;
    }
  }
}

// Mark connections silent past the idle window dead: a heartbeating
// client is never idle, so only dead/partitioned peers (whose kernel
// may keep the socket ESTABLISHED forever) trip this. Deferred via
// dead_conns — dropping mid-cycle would let accept() reuse an fd that
// stale pfds entries still reference.
void sweep_idle() {
  if (idle_timeout <= 0) return;
  double now = now_secs();
  for (const auto& kv : conns)
    if (now - kv.second.last_active > idle_timeout) {
      if (stats_on) g_stats.evictions++;
      dead_conns.push_back(kv.first);
    }
}

void expire_waiters() {
  double now = now_secs();
  for (size_t i = 0; i < waiters.size();) {
    if (waiters[i].deadline > 0 && now >= waiters[i].deadline) {
      if (stats_on) g_stats.bar_timed_out++;
      reply_err(waiters[i].fd, waiters[i].id,
                "barrier timed out: " + waiters[i].state);
      waiters[i] = waiters.back();
      waiters.pop_back();
    } else {
      i++;
    }
  }
}

// declared above drop_conn; shutdown disconnects are not evictions
void on_term(int) { stop_flag = 1; }

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  const char* host = "127.0.0.1";
  for (int i = 1; i + 1 < argc; i += 2) {
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--host") == 0) host = argv[i + 1];
    if (strcmp(argv[i], "--idle-timeout") == 0)
      idle_timeout = atof(argv[i + 1]);
    if (strcmp(argv[i], "--evict-grace") == 0)
      evict_grace = atof(argv[i + 1]);
    // --stats 0 answers sync_stats with the v1 occupancy shape and
    // skips the counters (the fan-in bench's A/B knob)
    if (strcmp(argv[i], "--stats") == 0) stats_on = atoi(argv[i + 1]) != 0;
  }
  stats_start = now_secs();

  {  // boot id: distinguishes restarts for reconnecting clients
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    char buf[48];
    snprintf(buf, sizeof buf, "%lx-%lx-%x", (unsigned long)ts.tv_sec,
             (unsigned long)ts.tv_nsec, (unsigned)getpid());
    boot_id = buf;
  }

  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (strcmp(host, "localhost") == 0) host = "127.0.0.1";
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    fprintf(stderr, "tg-syncsvc: bad --host %s (want an IPv4 address)\n",
            host);
    return 1;
  }
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(lfd, 1024) != 0) {
    perror("tg-syncsvc: bind/listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (sockaddr*)&addr, &alen);
  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::vector<pollfd> pfds;
  char rbuf[65536];
  while (!stop_flag) {
    pfds.clear();
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& kv : conns)
      pfds.push_back(
          {kv.first,
           (short)(POLLIN | (kv.second.wbuf.empty() ? 0 : POLLOUT)), 0});

    // poll timeout tracks the nearest barrier deadline (and the idle
    // sweep cadence when eviction is enabled)
    int tmo = -1;
    double now = now_secs();
    for (const Waiter& w : waiters)
      if (w.deadline > 0) {
        int ms = (int)((w.deadline - now) * 1000) + 1;
        if (ms < 0) ms = 0;
        if (tmo < 0 || ms < tmo) tmo = ms;
      }
    if (idle_timeout > 0) {
      int sweep_ms = (int)(idle_timeout * 250);  // idle_timeout / 4
      if (sweep_ms < 100) sweep_ms = 100;
      if (tmo < 0 || sweep_ms < tmo) tmo = sweep_ms;
    }
    if (!pending_evictions.empty() && (tmo < 0 || tmo > 100)) tmo = 100;
    int rc = poll(pfds.data(), pfds.size(), tmo);
    if (rc < 0) {
      if (errno == EINTR) continue;
      break;
    }
    expire_waiters();
    flush_evictions();
    for (const pollfd& p : pfds) {
      if (p.fd != lfd && (p.revents & POLLOUT)) {
        auto it = conns.find(p.fd);
        if (it != conns.end()) flush_wbuf(it->second);
      }
      if (!(p.revents & (POLLIN | POLLHUP | POLLERR))) continue;
      if (p.fd == lfd) {
        int cfd = accept(lfd, nullptr, nullptr);
        if (cfd >= 0) {
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn c;
          c.fd = cfd;
          c.last_active = now_secs();
          conns[cfd] = std::move(c);
          if (stats_on) {
            g_stats.accepts++;
            if (conns.size() > g_stats.conns_hwm)
              g_stats.conns_hwm = conns.size();
          }
        }
        continue;
      }
      auto it = conns.find(p.fd);
      if (it == conns.end()) continue;
      ssize_t n = recv(p.fd, rbuf, sizeof rbuf, 0);
      if (n <= 0) {
        drop_conn(p.fd);
        continue;
      }
      it->second.last_active = now_secs();
      it->second.rbuf.append(rbuf, (size_t)n);
      std::string& b = it->second.rbuf;
      size_t start = 0, nl;
      while ((nl = b.find('\n', start)) != std::string::npos) {
        std::string line = b.substr(start, nl - start);
        start = nl + 1;
        if (!line.empty()) handle_line(p.fd, line);
        if (conns.find(p.fd) == conns.end()) break;  // dropped mid-batch
      }
      if (conns.find(p.fd) != conns.end()) b.erase(0, start);
    }
    // reap connections whose peer vanished, stopped reading, or idled
    // out — the ONE place conns are dropped, after dispatch, so no
    // stale pfds entry can touch a reused fd this cycle
    sweep_idle();
    for (int fd : dead_conns)
      if (conns.count(fd)) drop_conn(fd);
    dead_conns.clear();
  }
  for (auto& kv : conns) close(kv.first);
  close(lfd);
  return 0;
}
