"""Native runtime components (C++), built on demand with the system
toolchain and cached under ``$TESTGROUND_HOME/work/bin``."""

from .syncsvc import (
    NativeSyncService,
    build_fanin_driver,
    build_syncsvc,
    native_available,
)

__all__ = [
    "NativeSyncService",
    "build_fanin_driver",
    "build_syncsvc",
    "native_available",
]
