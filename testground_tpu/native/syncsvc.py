"""Build + lifecycle wrapper for the native sync service (syncsvc.cc).

The local:exec runner's per-run sync infrastructure can be served by the
C++ event-loop server instead of the in-process Python one — the native
analog of the reference deploying its Go sync-service container
(``pkg/runner/local_common.go:77-104``). The binary is compiled once from
the packaged source with the system ``g++`` and cached by source hash in
``$TESTGROUND_HOME/work/bin``; hosts without a toolchain silently fall
back to the Python server (runner config ``sync_service = "auto"``).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import signal
import subprocess
import uuid

from testground_tpu.logging_ import S

__all__ = [
    "NativeSyncService",
    "build_syncsvc",
    "build_fanin_driver",
    "native_available",
]

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "syncsvc.cc")
_DRIVER_SRC = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fanin_driver.cc"
)


def native_available() -> bool:
    return shutil.which("g++") is not None and os.path.isfile(_SRC)


def _build_native(src: str, name: str, bin_dir: str) -> str:
    """Compile (or reuse) a native binary; returns its path. The binary
    name embeds the source hash, so edits rebuild and stale caches never
    serve."""
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    os.makedirs(bin_dir, exist_ok=True)
    out = os.path.join(bin_dir, f"{name}-{digest}")
    if os.path.isfile(out):
        return out
    # unique per builder — including threads within one engine process
    # (DEFAULT_WORKERS=2 can race here on a cold cache)
    tmp = f"{out}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-pthread", "-o", tmp, src],
        check=True,
        capture_output=True,
        text=True,
    )
    os.replace(tmp, out)  # atomic install; last writer wins with same bits
    S().debug("built native binary: %s", out)
    return out


def build_syncsvc(bin_dir: str) -> str:
    """Compile (or reuse) the sync-server binary; returns its path."""
    return _build_native(_SRC, "tg-syncsvc", bin_dir)


def build_fanin_driver(bin_dir: str) -> str:
    """Compile (or reuse) the fan-in bench's mini-client fleet driver
    (``fanin_driver.cc``, used by ``tools/bench_sync_fanin.py``)."""
    return _build_native(_DRIVER_SRC, "tg-fanin-driver", bin_dir)


class NativeSyncService:
    """Drop-in lifecycle twin of ``SyncServiceServer``: ``.address`` and
    ``.stop()``; the server is a child process.

    ``host`` is the bind address (default loopback; ``0.0.0.0`` serves
    other hosts); ``idle_timeout`` (seconds, 0 = off) evicts silent
    connections server-side (docs/CROSSHOST.md)."""

    def __init__(
        self,
        bin_path: str,
        host: str = "127.0.0.1",
        port: int = 0,
        idle_timeout: float = 0.0,
        evict_grace: float = 2.0,
        shards: int = 0,
        max_wbuf: int = 0,
    ):
        argv = [
            bin_path,
            "--port",
            str(int(port)),
            "--host",
            host,
            "--evict-grace",
            str(float(evict_grace)),
        ]
        if idle_timeout > 0:
            argv += ["--idle-timeout", str(float(idle_timeout))]
        if shards > 0:  # 0 = server-side auto (docs/CROSSHOST.md)
            argv += ["--shards", str(int(shards))]
        if max_wbuf > 0:  # slow-reader outbound-queue bound, bytes
            argv += ["--max-wbuf", str(int(max_wbuf))]
        self._proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        line = self._proc.stdout.readline().strip()
        if not line.startswith("LISTENING "):
            self._proc.kill()
            raise RuntimeError(
                f"native sync service failed to start (got {line!r})"
            )
        self.address = (host, int(line.split()[1]))

    @property
    def pid(self) -> int:
        return self._proc.pid

    def start(self) -> "NativeSyncService":
        return self  # already serving (constructor handshake)

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
