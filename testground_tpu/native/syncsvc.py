"""Build + lifecycle wrapper for the native sync service (syncsvc.cc).

The local:exec runner's per-run sync infrastructure can be served by the
C++ event-loop server instead of the in-process Python one — the native
analog of the reference deploying its Go sync-service container
(``pkg/runner/local_common.go:77-104``). The binary is compiled once from
the packaged source with the system ``g++`` and cached by source hash in
``$TESTGROUND_HOME/work/bin``; hosts without a toolchain silently fall
back to the Python server (runner config ``sync_service = "auto"``).

Sanitizer builds (docs/CHECKING.md "Sanitizer builds"): setting
``TG_NATIVE_SANITIZE=thread`` (or ``address``, ``undefined``, or a
comma list like ``address,undefined``) compiles every native binary
with the matching ``-fsanitize=`` instrumentation at ``-O1 -g``. The
binary name embeds the sanitize mode beside the source hash, so
instrumented and production binaries never collide in the cache, and
the spawned server inherits ``TSAN_OPTIONS``/``ASAN_OPTIONS`` pointing
at the checked-in suppressions file (``native/tsan.supp``) with
``halt_on_error=1`` — a race aborts the server loudly mid-test instead
of scrolling past. CI runs the sync suites against the TSAN build
(the ``tsan-sync`` job).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import signal
import subprocess
import uuid

from testground_tpu.logging_ import S

__all__ = [
    "NativeSyncService",
    "SANITIZERS",
    "build_syncsvc",
    "build_fanin_driver",
    "native_available",
    "sanitize_mode",
]

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "syncsvc.cc")
_DRIVER_SRC = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fanin_driver.cc"
)
# Checked-in ThreadSanitizer suppressions (docs/CHECKING.md documents
# the policy: the file ships EMPTY of active entries; any suppression
# added must name the report and justify why it is benign).
_TSAN_SUPP = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tsan.supp"
)

# Supported TG_NATIVE_SANITIZE components → compile flags. "undefined"
# composes with "address" the way upstream recommends
# (-fsanitize=address,undefined); "thread" is mutually exclusive with
# "address" at the compiler level and refused readably below.
SANITIZERS = ("thread", "address", "undefined")


def sanitize_mode() -> tuple[str, ...]:
    """The parsed ``TG_NATIVE_SANITIZE`` build mode: a sorted tuple of
    sanitizer names, empty when unset. Unknown names and the
    thread+address combination (refused by g++ itself) raise a readable
    ValueError instead of a cryptic compile failure."""
    raw = os.environ.get("TG_NATIVE_SANITIZE", "").strip().lower()
    if not raw or raw in ("0", "off", "none", "false"):
        return ()
    parts = tuple(sorted({p.strip() for p in raw.split(",") if p.strip()}))
    unknown = [p for p in parts if p not in SANITIZERS]
    if unknown:
        raise ValueError(
            f"TG_NATIVE_SANITIZE={raw!r}: unknown sanitizer(s) {unknown}; "
            f"supported: {', '.join(SANITIZERS)} (comma-separated)"
        )
    if "thread" in parts and "address" in parts:
        raise ValueError(
            "TG_NATIVE_SANITIZE cannot combine 'thread' with 'address' "
            "(g++ refuses -fsanitize=thread,address); run two builds"
        )
    return parts


def sanitizer_env(base: dict | None = None) -> dict | None:
    """Child-process environment for a sanitized binary: the inherited
    env plus ``TSAN_OPTIONS``/``ASAN_OPTIONS`` wiring the checked-in
    suppressions file and ``halt_on_error=1`` (a detected race must
    abort the server — and so the test — instead of scrolling past an
    ignored stderr). Returns None (inherit untouched) when no sanitize
    mode is active. Operator-set options are preserved and win (appended
    last — later flags override earlier ones in sanitizer runtimes)."""
    mode = sanitize_mode()
    if not mode:
        return None
    env = dict(base if base is not None else os.environ)
    if "thread" in mode:
        opts = f"suppressions={_TSAN_SUPP} halt_on_error=1"
        prior = env.get("TSAN_OPTIONS", "")
        env["TSAN_OPTIONS"] = f"{opts} {prior}".strip()
    if "address" in mode:
        prior = env.get("ASAN_OPTIONS", "")
        env["ASAN_OPTIONS"] = f"halt_on_error=1 {prior}".strip()
    if "undefined" in mode:
        prior = env.get("UBSAN_OPTIONS", "")
        env["UBSAN_OPTIONS"] = (
            f"halt_on_error=1 print_stacktrace=1 {prior}".strip()
        )
    return env


def native_available() -> bool:
    return shutil.which("g++") is not None and os.path.isfile(_SRC)


def _build_native(src: str, name: str, bin_dir: str) -> str:
    """Compile (or reuse) a native binary; returns its path. The binary
    name embeds the source hash — and the active sanitize mode — so
    edits rebuild, stale caches never serve, and an instrumented build
    never shadows the production one (or vice versa)."""
    mode = sanitize_mode()
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    tag = f"-{'-'.join(mode)}" if mode else ""
    os.makedirs(bin_dir, exist_ok=True)
    out = os.path.join(bin_dir, f"{name}-{digest}{tag}")
    if os.path.isfile(out):
        return out
    if mode:
        # -O1 -g with frame pointers: the sanitizer runtimes want
        # debuggable frames, and -O2 can optimize away the exact
        # interleavings TSAN exists to catch
        flags = ["-O1", "-g", "-fno-omit-frame-pointer"] + [
            f"-fsanitize={s}" for s in mode
        ]
    else:
        flags = ["-O2"]
    # unique per builder — including threads within one engine process
    # (DEFAULT_WORKERS=2 can race here on a cold cache)
    tmp = f"{out}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    subprocess.run(
        ["g++", *flags, "-std=c++17", "-pthread", "-o", tmp, src],
        check=True,
        capture_output=True,
        text=True,
    )
    os.replace(tmp, out)  # atomic install; last writer wins with same bits
    S().debug("built native binary: %s%s", out, f" [{','.join(mode)}]" if mode else "")
    return out


def build_syncsvc(bin_dir: str) -> str:
    """Compile (or reuse) the sync-server binary; returns its path."""
    return _build_native(_SRC, "tg-syncsvc", bin_dir)


def build_fanin_driver(bin_dir: str) -> str:
    """Compile (or reuse) the fan-in bench's mini-client fleet driver
    (``fanin_driver.cc``, used by ``tools/bench_sync_fanin.py``)."""
    return _build_native(_DRIVER_SRC, "tg-fanin-driver", bin_dir)


class NativeSyncService:
    """Drop-in lifecycle twin of ``SyncServiceServer``: ``.address`` and
    ``.stop()``; the server is a child process.

    ``host`` is the bind address (default loopback; ``0.0.0.0`` serves
    other hosts); ``idle_timeout`` (seconds, 0 = off) evicts silent
    connections server-side (docs/CROSSHOST.md)."""

    def __init__(
        self,
        bin_path: str,
        host: str = "127.0.0.1",
        port: int = 0,
        idle_timeout: float = 0.0,
        evict_grace: float = 2.0,
        shards: int = 0,
        max_wbuf: int = 0,
    ):
        argv = [
            bin_path,
            "--port",
            str(int(port)),
            "--host",
            host,
            "--evict-grace",
            str(float(evict_grace)),
        ]
        if idle_timeout > 0:
            argv += ["--idle-timeout", str(float(idle_timeout))]
        if shards > 0:  # 0 = server-side auto (docs/CROSSHOST.md)
            argv += ["--shards", str(int(shards))]
        if max_wbuf > 0:  # slow-reader outbound-queue bound, bytes
            argv += ["--max-wbuf", str(int(max_wbuf))]
        # sanitized builds: wire the suppressions/halt-on-error options
        # and INHERIT stderr — a TSAN/ASAN report must land in the test
        # log, not a devnull (production builds keep the quiet stderr)
        san_env = sanitizer_env()
        self._proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=None if san_env is not None else subprocess.DEVNULL,
            env=san_env,
            text=True,
        )
        line = self._proc.stdout.readline().strip()
        if not line.startswith("LISTENING "):
            self._proc.kill()
            raise RuntimeError(
                f"native sync service failed to start (got {line!r})"
            )
        self.address = (host, int(line.split()[1]))

    @property
    def pid(self) -> int:
        return self._proc.pid

    def start(self) -> "NativeSyncService":
        return self  # already serving (constructor handshake)

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
