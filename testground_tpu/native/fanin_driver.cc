// tg-fanin-driver — native mini-client fleet for tools/bench_sync_fanin.py.
//
// One driver process owns one worker-share of the bench's concurrent
// clients and runs them through the fan-in phases (connect storm →
// signal flood → barrier storm → pubsub fanout) in a single epoll loop,
// one outstanding request per client, latency stamped send→reply — the
// native twin of the bench's selector-multiplexed Python workers.
//
// Why it exists: BENCH_SYNC_r01 measured the PYTHON workers as the
// pipeline ceiling on a small box (one worker alone tops out near 50k
// round-trips/s, so at 10k clients the harness — not the server — sets
// flood p50). A server rewrite cannot be judged through a harness that
// saturates first; this driver costs ~1-2 µs/op and hands the bottleneck
// back to the server under test. The Python workers remain the fallback
// when no C++ toolchain exists (bench --driver python).
//
// Protocol with the parent (tools/bench_sync_fanin.py):
//   stdin:  one "go\n" line per phase (connect, flood, storm, pubsub)
//   stdout: one JSON result line per phase:
//     {"phase": "connect", "connected": N, "wall": S, "errors": [...]}
//     {"phase": "flood",   "wall": S, "lats_ms": [...], "errors": [...]}
//     {"phase": "storm",   "wall": S, "lats_ms": [...], "errors": [...]}
//     {"phase": "pubsub",  "wall": S, "delivered": N, "errors": [...]}
//       (pubsub runs only under --pub-subs > 0; otherwise it reports
//        {"phase": "pubsub", "skipped": true})
// A phase that blows its --timeout records the failure in "errors" and
// still answers — a dead rung is a result, not a crash.
//
// Build: g++ -O2 -std=c++17 -o tg-fanin-driver fanin_driver.cc
// (built+cached by testground_tpu/native/syncsvc.py build_fanin_driver).

#include <arpa/inet.h>
#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

double now_secs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

struct Cl {
  int fd = -1;
  int sent = 0;        // requests sent this phase
  double t_sent = 0;   // stamp of the in-flight request
  std::string rbuf;
  bool active = false;
};

int g_ep = -1;
std::vector<Cl> g_cl;

void ep_mod(int fd, uint32_t events, int idx) {
  struct epoll_event ev{};
  ev.events = events;
  ev.data.u32 = (uint32_t)idx;
  epoll_ctl(g_ep, EPOLL_CTL_MOD, fd, &ev);
}

void ep_add(int fd, uint32_t events, int idx) {
  struct epoll_event ev{};
  ev.events = events;
  ev.data.u32 = (uint32_t)idx;
  epoll_ctl(g_ep, EPOLL_CTL_ADD, fd, &ev);
}

bool send_all(int fd, const std::string& data, double deadline,
              std::vector<std::string>& errors) {
  // requests are <200B: a transient full buffer drains with a bounded
  // blocking retry, mirroring the Python workers' _send_line fallback
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = send(fd, data.data() + off, data.size() - off, MSG_NOSIGNAL);
    if (n > 0) {
      off += (size_t)n;
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (now_secs() > deadline) {
        errors.push_back("send stalled past deadline");
        return false;
      }
      struct timespec ts {0, 2000000};  // 2ms
      nanosleep(&ts, nullptr);
      continue;
    }
    errors.push_back(std::string("send: ") + strerror(errno));
    return false;
  }
  return true;
}

void json_errors(std::string& out, const std::vector<std::string>& errors) {
  out += "\"errors\": [";
  size_t cap = errors.size() < 20 ? errors.size() : 20;
  for (size_t i = 0; i < cap; i++) {
    if (i) out += ", ";
    out += '"';
    for (char c : errors[i]) {
      if (c == '"' || c == '\\') out += '\\';
      if ((unsigned char)c >= 0x20) out += c;
    }
    out += '"';
  }
  out += "]";
}

void emit(const std::string& body) {
  printf("{%s}\n", body.c_str());
  fflush(stdout);
}

void emit_lats(std::string& out, const std::vector<double>& lats) {
  out += "\"lats_ms\": [";
  char buf[32];
  for (size_t i = 0; i < lats.size(); i++) {
    snprintf(buf, sizeof buf, i ? ", %.3f" : "%.3f", lats[i]);
    out += buf;
  }
  out += "]";
}

// ------------------------------------------------------------------ phases

void phase_connect(const char* host, int port, int n, int batch,
                   double timeout) {
  std::vector<std::string> errors;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host, &addr.sin_addr);
  double t0 = now_secs(), deadline = t0 + timeout;
  int started = 0, connected = 0, inflight = 0, one = 1;
  struct epoll_event evs[512];
  while (connected + (int)errors.size() < n) {
    if (now_secs() > deadline) {
      char b[96];
      snprintf(b, sizeof b, "connect deadline with %d/%d up", connected, n);
      errors.push_back(b);
      break;
    }
    while (started < n && inflight < batch) {
      int idx = started++;
      int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
      if (fd < 0) {
        errors.push_back(std::string("socket: ") + strerror(errno));
        continue;
      }
      int rc = connect(fd, (sockaddr*)&addr, sizeof addr);
      if (rc != 0 && errno != EINPROGRESS) {
        errors.push_back(std::string("connect: ") + strerror(errno));
        close(fd);
        continue;
      }
      g_cl[idx].fd = fd;
      ep_add(fd, EPOLLOUT, idx);
      inflight++;
    }
    if (inflight == 0 && started >= n) break;
    int rc = epoll_wait(g_ep, evs, 512, 1000);
    for (int i = 0; i < rc; i++) {
      int idx = (int)evs[i].data.u32;
      Cl& c = g_cl[idx];
      int err = 0;
      socklen_t len = sizeof err;
      getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &len);
      inflight--;
      if (err) {
        char b[64];
        snprintf(b, sizeof b, "connect SO_ERROR %d", err);
        errors.push_back(b);
        epoll_ctl(g_ep, EPOLL_CTL_DEL, c.fd, nullptr);
        close(c.fd);
        c.fd = -1;
        continue;
      }
      setsockopt(c.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      ep_mod(c.fd, EPOLLIN, idx);
      c.active = true;
      connected++;
    }
  }
  char head[96];
  snprintf(head, sizeof head, "\"phase\": \"connect\", \"connected\": %d, "
           "\"wall\": %.3f, ", connected, now_secs() - t0);
  std::string body(head);
  json_errors(body, errors);
  emit(body);
}

// Serial request/response per client, all multiplexed on the epoll set;
// reqs[i % reqs.size()] is client i's (constant) request line.
void phase_rr(const char* name, const std::vector<std::string>& reqs,
              bool per_client_req, int ops_per_client, double timeout) {
  std::vector<std::string> errors;
  std::vector<double> lats;
  lats.reserve((size_t)ops_per_client * g_cl.size());
  double t0 = now_secs(), deadline = t0 + timeout;
  int active = 0;
  for (size_t i = 0; i < g_cl.size(); i++) {
    Cl& c = g_cl[i];
    c.sent = 0;
    if (!c.active || ops_per_client <= 0) continue;
    const std::string& req =
        per_client_req ? reqs[i % reqs.size()] : reqs[0];
    c.t_sent = now_secs();
    if (!send_all(c.fd, req, deadline, errors)) {
      c.active = false;
      continue;
    }
    c.sent = 1;
    active++;
  }
  struct epoll_event evs[512];
  char rb[65536];
  while (active > 0) {
    if (now_secs() > deadline) {
      char b[96];
      snprintf(b, sizeof b, "phase deadline with %d clients pending", active);
      errors.push_back(b);
      break;
    }
    int rc = epoll_wait(g_ep, evs, 512, 1000);
    for (int i = 0; i < rc; i++) {
      int idx = (int)evs[i].data.u32;
      Cl& c = g_cl[idx];
      if (!c.active || c.sent == 0) continue;
      ssize_t n = recv(c.fd, rb, sizeof rb, 0);
      if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
        errors.push_back(n == 0 ? "server closed connection"
                                : std::string("recv: ") + strerror(errno));
        c.active = false;
        active--;
        continue;
      }
      if (n < 0) continue;
      c.rbuf.append(rb, (size_t)n);
      size_t start = 0, nl;
      while ((nl = c.rbuf.find('\n', start)) != std::string::npos) {
        double now = now_secs();
        if (c.rbuf.find("\"error\"", start) < nl)
          errors.push_back(c.rbuf.substr(start, std::min(nl - start,
                                                         (size_t)200)));
        else
          lats.push_back((now - c.t_sent) * 1e3);
        start = nl + 1;
        if (c.sent < ops_per_client) {
          const std::string& req =
              per_client_req ? reqs[idx % reqs.size()] : reqs[0];
          c.t_sent = now_secs();
          if (!send_all(c.fd, req, deadline, errors)) {
            c.active = false;
            active--;
            break;
          }
          c.sent++;
        } else {
          active--;
          break;
        }
      }
      c.rbuf.erase(0, start);
    }
  }
  char head[64];
  snprintf(head, sizeof head, "\"phase\": \"%s\", \"wall\": %.3f, ", name,
           now_secs() - t0);
  std::string body(head);
  emit_lats(body, lats);
  body += ", ";
  json_errors(body, errors);
  emit(body);
}

void phase_pubsub(int n_subs, int n_entries, double timeout) {
  std::vector<std::string> errors;
  int usable = 0;
  for (Cl& c : g_cl)
    if (c.active) usable++;
  if (usable < n_subs + 1) n_subs = usable > 1 ? usable - 1 : 0;
  if (n_subs <= 0) {
    emit("\"phase\": \"pubsub\", \"skipped\": true, "
         "\"errors\": [\"no clients left for pubsub\"]");
    return;
  }
  double deadline = now_secs() + timeout;
  // the first n_subs active clients subscribe; the next one publishes
  int pub_idx = -1, marked = 0;
  for (size_t i = 0; i < g_cl.size(); i++) {
    if (!g_cl[i].active) continue;
    if (marked < n_subs) {
      g_cl[i].sent = 1;  // reused as "is subscriber" this phase
      send_all(g_cl[i].fd,
               "{\"id\": 1, \"op\": \"subscribe\", \"topic\": \"fanout\"}\n",
               deadline, errors);
      marked++;
    } else {
      g_cl[i].sent = 0;
      if (pub_idx < 0) pub_idx = (int)i;
    }
  }
  Cl& pub = g_cl[pub_idx];
  double t0 = now_secs();
  long delivered = 0, want = (long)n_subs * n_entries;
  int published = 0, pub_inflight = 0;
  struct epoll_event evs[512];
  char rb[262144];
  char preq[128];
  while (delivered < want || published < n_entries || pub_inflight) {
    if (now_secs() > deadline) {
      char b[96];
      snprintf(b, sizeof b, "pubsub deadline: %ld/%ld frames", delivered,
               want);
      errors.push_back(b);
      break;
    }
    if (pub_inflight == 0 && published < n_entries) {
      snprintf(preq, sizeof preq,
               "{\"id\": 2, \"op\": \"publish\", \"topic\": \"fanout\", "
               "\"payload\": {\"m\": %d}}\n", published);
      if (!send_all(pub.fd, preq, deadline, errors)) break;
      published++;
      pub_inflight = 1;
    }
    int rc = epoll_wait(g_ep, evs, 512, 200);
    for (int i = 0; i < rc; i++) {
      int idx = (int)evs[i].data.u32;
      Cl& c = g_cl[idx];
      if (!c.active) continue;
      ssize_t n = recv(c.fd, rb, sizeof rb, 0);
      if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
        errors.push_back(idx == pub_idx ? "publisher connection closed"
                                        : "sub closed");
        c.active = false;
        if (idx == pub_idx) pub_inflight = 0;
        continue;
      }
      if (n < 0) continue;
      c.rbuf.append(rb, (size_t)n);
      size_t start = 0, nl;
      while ((nl = c.rbuf.find('\n', start)) != std::string::npos) {
        if (idx == pub_idx) {
          pub_inflight = 0;
        } else if (c.rbuf.find("\"entry\"", start) < nl) {
          delivered++;
        }
        start = nl + 1;
      }
      c.rbuf.erase(0, start);
    }
  }
  char head[96];
  snprintf(head, sizeof head,
           "\"phase\": \"pubsub\", \"wall\": %.3f, \"delivered\": %ld, ",
           now_secs() - t0, delivered);
  std::string body(head);
  json_errors(body, errors);
  emit(body);
}

bool await_go() {
  char line[64];
  return fgets(line, sizeof line, stdin) != nullptr;
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 0, wid = 0, clients = 0, total = 0, signal_ops = 20;
  int pub_subs = 0, pub_entries = 50, batch = 200;
  double timeout = 180.0;
  for (int i = 1; i + 1 < argc; i += 2) {
    if (!strcmp(argv[i], "--host")) host = argv[i + 1];
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--wid")) wid = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--clients")) clients = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--total")) total = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--signal-ops")) signal_ops = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--pub-subs")) pub_subs = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--pub-entries")) pub_entries = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--connect-batch")) batch = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--timeout")) timeout = atof(argv[i + 1]);
  }
  if (clients <= 0 || port == 0) {
    fprintf(stderr, "tg-fanin-driver: need --clients and --port\n");
    return 2;
  }
  if (!strcmp(host, "localhost")) host = "127.0.0.1";
  signal(SIGPIPE, SIG_IGN);
  g_ep = epoll_create1(0);
  g_cl.resize(clients);

  if (!await_go()) return 0;
  phase_connect(host, port, clients, batch, timeout);

  if (!await_go()) return 0;
  // constant per-client flood request: state flood-<wid>-<i%16>
  std::vector<std::string> reqs;
  for (int s = 0; s < 16; s++) {
    char b[128];
    snprintf(b, sizeof b,
             "{\"id\": 1, \"op\": \"signal_entry\", \"state\": "
             "\"flood-%d-%d\"}\n", wid, s);
    reqs.push_back(b);
  }
  phase_rr("flood", reqs, true, signal_ops, timeout);

  if (!await_go()) return 0;
  char storm[160];
  snprintf(storm, sizeof storm,
           "{\"id\": 1, \"op\": \"signal_and_wait\", \"state\": \"storm\", "
           "\"target\": %d, \"timeout\": %.1f}\n", total, timeout);
  phase_rr("storm", {std::string(storm)}, false, 1, timeout);

  if (!await_go()) return 0;
  if (pub_subs > 0)
    phase_pubsub(pub_subs, pub_entries, timeout);
  else
    emit("\"phase\": \"pubsub\", \"skipped\": true, \"errors\": []");

  for (Cl& c : g_cl)
    if (c.fd >= 0) close(c.fd);
  return 0;
}
