"""Optional InfluxDB push for run metric time series.

The reference SDK batches runtime metrics into InfluxDB 1.x
(``INFLUXDB_URL`` env, ``pkg/runner/local_docker.go:353``) and the
daemon's dashboard queries it (``pkg/metrics/viewer.go:35-80``). Here the
canonical store is the per-run ``timeseries.jsonl`` (see
``metrics/viewer.py``); when ``[daemon] influxdb_endpoint`` is configured
in ``.env.toml`` the same rows are ALSO pushed to InfluxDB's
``POST /write?db=<db>`` line-protocol endpoint so existing Grafana/Influx
setups keep working. Push is best-effort: failures are logged and
journaled, never fatal to the run.
"""

from __future__ import annotations

import math
import random
import time
import urllib.error
import urllib.parse
import urllib.request

from testground_tpu.logging_ import S

__all__ = ["rows_to_lines", "push_rows", "escape_tag", "escape_measurement"]

DEFAULT_DB = "testground"

# Bounded retry policy for the write POST: transient failures (connection
# refused mid-restart, a 5xx from an overloaded server) get a few
# exponentially backed-off attempts with jitter (so a fleet of runs
# finishing together doesn't re-stampede the endpoint in lockstep);
# permanent rejections (4xx — malformed lines won't improve by waiting)
# fail immediately. Module constants so tests can shrink the waits.
_RETRY_ATTEMPTS = 3
_RETRY_BASE_SECS = 0.25
_RETRY_JITTER_SECS = 0.1


def escape_measurement(s: str) -> str:
    """Line-protocol measurement escaping (commas and spaces)."""
    return s.replace(",", r"\,").replace(" ", r"\ ")


def escape_tag(s: str) -> str:
    """Line-protocol tag key/value escaping (commas, equals, spaces)."""
    return (
        s.replace(",", r"\,").replace("=", r"\=").replace(" ", r"\ ")
    )


def _field_value(v) -> str | None:
    if isinstance(v, bool):  # bool is an int subclass — check first
        return "true" if v else "false"
    if isinstance(v, int):
        return f"{v}i"
    if isinstance(v, float):
        # inf/nan are invalid line protocol; one bad field would make
        # InfluxDB 400 the whole single-POST batch
        return repr(float(v)) if math.isfinite(v) else None
    return None


def rows_to_lines(
    rows, base_ns: int = 0, dropped: list[str] | None = None
) -> list[str]:
    """Serialize timeseries rows (the ``timeseries.jsonl`` dict shape:
    plan/case/run/group_id/name/tick + numeric fields) into InfluxDB line
    protocol. The measurement name keeps the reference's
    ``results.<plan>-<case>.<metric>`` shape (``dashboard.go:112-118``).

    Non-finite floats (NaN/Inf) are invalid line protocol — one such
    field would make InfluxDB 400 the whole single-POST batch — so they
    are dropped from the line; pass ``dropped`` to collect their
    ``<measurement>.<field>`` names (push_rows journals and warns about
    them instead of losing metrics silently).

    Timestamps are ``base_ns + tick`` nanoseconds: push_rows passes the
    wall-clock push time as ``base_ns`` so points land inside Grafana's
    default ``now-6h`` window (simulated ticks alone would put everything
    at ~1970), while the +tick offset keeps per-tick points distinct and
    ordered within a series. The simulated tick itself is preserved as an
    integer field so panels can plot against it."""
    from testground_tpu.metrics.viewer import measurement_name

    lines: list[str] = []
    for row in rows:
        name = row.get("name")
        if not name:
            continue
        measurement = escape_measurement(
            measurement_name(
                str(row.get("plan", "")), str(row.get("case", "")), str(name)
            )
        )
        tags = ""
        for key in ("run", "group_id"):
            val = str(row.get(key, ""))
            if val:
                tags += f",{escape_tag(key)}={escape_tag(val)}"
        fields = []
        for k, v in row.items():
            if k in ("plan", "case", "run", "group_id", "name", "tick"):
                continue
            fv = _field_value(v)
            if fv is not None:
                fields.append(f"{escape_tag(k)}={fv}")
            elif (
                dropped is not None
                and isinstance(v, float)
                and not math.isfinite(v)
            ):
                # non-float non-values (strings, nested dicts) are simply
                # not fields; only NaN/Inf is a LOST metric worth flagging
                dropped.append(f"{measurement}.{k}")
        if not fields:
            continue
        tick = int(row.get("tick", 0))
        fields.append(f"tick={tick}i")
        lines.append(f"{measurement}{tags} {','.join(fields)} {base_ns + tick}")
    return lines


def push_rows(
    endpoint: str,
    rows,
    db: str = DEFAULT_DB,
    timeout: float = 5.0,
    base_ns: int | None = None,
) -> dict:
    """POST rows to ``<endpoint>/write?db=<db>``, with bounded retries
    (exponential backoff + jitter — see the module constants). Returns a
    journal dict ``{pushed, ok, attempts, error?}`` — callers record it
    and move on; a final failure is journaled and logged, never raised.

    ``base_ns`` must be stable per run (the executor passes the run's
    start wall-clock): a per-push ``time.time_ns()`` would interleave
    periodic flushes by push time instead of tick, write duplicate points
    on retry, and let base1+tick_a collide with base2+tick_b across
    batches, silently overwriting a point with an identical tagset. The
    per-call fallback exists only for standalone one-shot callers."""
    import time

    dropped: list[str] = []
    lines = rows_to_lines(
        rows,
        base_ns=time.time_ns() if base_ns is None else base_ns,
        dropped=dropped,
    )
    journal: dict = {"pushed": len(lines), "ok": False}
    if dropped:
        # journal the lost fields (deduped, bounded) AND warn — a NaN/Inf
        # metric must be visible somewhere, since the line protocol
        # cannot carry it
        uniq = sorted(set(dropped))
        journal["dropped_fields"] = uniq[:32]
        journal["dropped_field_count"] = len(dropped)
        S().warning(
            "influx push: dropped %d non-finite field value(s) (%s%s) — "
            "NaN/Inf is invalid line protocol",
            len(dropped),
            ", ".join(uniq[:5]),
            ", ..." if len(uniq) > 5 else "",
        )
    if not lines:
        journal["ok"] = True
        return journal
    url = endpoint.rstrip("/") + "/write?" + urllib.parse.urlencode({"db": db})
    body = ("\n".join(lines) + "\n").encode("utf-8")

    # bounded retries with exponential backoff + jitter: idempotent by
    # construction (stable base_ns means a re-push writes the same
    # points), so retrying a request whose response was lost is safe
    last_err = ""
    for attempt in range(1, _RETRY_ATTEMPTS + 1):
        journal["attempts"] = attempt
        req = urllib.request.Request(
            url,
            data=body,
            method="POST",
            headers={"Content-Type": "text/plain; charset=utf-8"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                if 200 <= resp.status < 300:
                    journal["ok"] = True
                    journal.pop("error", None)
                    return journal
                last_err = f"http {resp.status}"
                if 400 <= resp.status < 500:
                    break  # permanent: bad request won't improve
        except urllib.error.HTTPError as e:
            last_err = f"http {e.code}"
            if 400 <= e.code < 500:
                break
        except (urllib.error.URLError, OSError, ValueError) as e:
            last_err = str(e)
        journal["error"] = last_err
        if attempt < _RETRY_ATTEMPTS:
            delay = _RETRY_BASE_SECS * (2 ** (attempt - 1)) + random.uniform(
                0.0, _RETRY_JITTER_SECS
            )
            S().warning(
                "influx push to %s failed (attempt %d/%d: %s) — retrying "
                "in %.2fs",
                endpoint,
                attempt,
                _RETRY_ATTEMPTS,
                last_err,
                delay,
            )
            time.sleep(delay)
    # the FINAL failure is journaled (attempts + error) and logged — the
    # run record shows exactly how hard the mirror was tried
    journal["error"] = last_err
    S().warning(
        "influx push to %s failed after %d attempt(s): %s — %d line(s) "
        "not mirrored",
        endpoint,
        journal["attempts"],
        last_err,
        len(lines),
    )
    return journal
