"""Prometheus text-exposition rendering for the daemon's ``GET /metrics``.

Format 0.0.4 (https://prometheus.io/docs/instrumenting/exposition_formats/):
``metric_name{label="value"} number`` lines with one ``# HELP`` /
``# TYPE`` header per family. Stdlib-only and dependency-free on purpose
— the daemon is a long-lived process any standard scraper should be able
to watch without this repo growing a client library.

Three tiers of gauges/counters, all derived from the engine's task store
(no live engine internals — a scrape never blocks a running task):

- **task gauges** — tasks by lifecycle state and type, plus per-task
  queue/exec timings from the supervisor's ledger (``result["perf"]``).
- **cumulative flow counters** — a finished sim run's message-flow
  totals (``journal["sim"]``), labeled by flow leg so conservation is
  checkable in PromQL.
- **perf gauges** — the run performance ledger
  (``journal["sim"]["perf"]``): throughput, compile split, HBM
  high-water mark.
- **SLO gauges** — the run health plane (``journal["slo"]``,
  docs/OBSERVABILITY.md "Run health plane"): per-rule breach counts,
  thresholds and last-observed values, plus a per-task failed flag.

Per-task label cardinality is bounded by ``per_task_limit`` (the daemon
exports series for its most recent tasks only — configurable via
``[daemon] metrics_task_limit``); the aggregate ``tg_tasks`` counts
always cover the full task store, and truncation is never silent:
``tg_scrape_tasks_total`` / ``tg_scrape_tasks_elided`` report how much
of the store this scrape's per-task series covered.
"""

from __future__ import annotations

# the shared finite-number coercion every ledger consumer uses —
# NaN/Inf and non-numerics never reach the exposition (a scraper would
# reject the whole scrape)
from testground_tpu.sim.perf import num as _num

__all__ = ["CONTENT_TYPE", "render_prometheus", "render_sync_prometheus"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# flow legs of the conservation identity (docs/OBSERVABILITY.md):
# sent = delivered + in_flight + dropped + rejected + fault_dropped
_FLOWS = (
    ("sent", "msgs_sent"),
    ("delivered", "msgs_delivered"),
    ("enqueued", "msgs_enqueued"),
    ("dropped", "msgs_dropped"),
    ("rejected", "msgs_rejected"),
    ("in_flight", "msgs_in_flight"),
    ("fault_dropped", "msgs_fault_dropped"),
)


def _escape(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _Exposition:
    def __init__(self):
        self._families: dict[str, tuple[str, str, list[str]]] = {}

    def add(self, name: str, mtype: str, help_: str, labels: dict, value):
        v = _num(value)
        if v is None:
            return
        if name not in self._families:
            self._families[name] = (mtype, help_, [])
        lbl = ",".join(
            f'{k}="{_escape(val)}"' for k, val in labels.items()
        )
        self._families[name][2].append(
            f"{name}{{{lbl}}} {v}" if lbl else f"{name} {v}"
        )

    def render(self) -> str:
        out = []
        for name, (mtype, help_, lines) in self._families.items():
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            out.extend(lines)
        return "\n".join(out) + "\n" if out else "\n"


def render_sync_prometheus(stats: dict) -> str:
    """Render a ``sync_stats`` snapshot (v1 or v2) as the ``tg_sync_*``
    family — the ``tg sync-service --metrics-port`` exposition
    (docs/OBSERVABILITY.md "Sync plane").

    Label space is bounded by construction: ``op`` ranges over the fixed
    protocol op set, barrier ``target`` over pow2 buckets (capped at
    2^20 by the recorder), and the per-op duration histograms over the
    recorder's fixed log2 bin count — a scrape's cardinality cannot grow
    with traffic. A v1 snapshot (old server) renders just the occupancy
    gauges; reconciliation with ``tg sync-stats`` is pinned by
    ``tools/sync_fanin_smoke.py``."""
    exp = _Exposition()
    for name, key, help_ in (
        ("tg_sync_conns", "conns", "Open connections to the sync service."),
        ("tg_sync_waiters", "waiters", "Parked barrier waiters."),
        ("tg_sync_subs", "subs", "Open topic subscriptions."),
        (
            "tg_sync_uptime_seconds",
            "uptime_secs",
            "Seconds since the sync service started its stats plane.",
        ),
    ):
        exp.add(name, "gauge", help_, {}, stats.get(key))
    for op, count in sorted((stats.get("ops") or {}).items()):
        exp.add(
            "tg_sync_ops_total",
            "counter",
            "Requests dispatched, by protocol op.",
            {"op": op},
            count,
        )
    conn = stats.get("conn") if isinstance(stats.get("conn"), dict) else {}
    for name, key, help_ in (
        ("tg_sync_conn_accepts_total", "accepts", "Connections accepted."),
        ("tg_sync_conn_closes_total", "closes", "Connections closed."),
        (
            "tg_sync_conn_evictions_total",
            "evictions",
            "Connections evicted by the idle sweep (half-open peers).",
        ),
    ):
        exp.add(name, "counter", help_, {}, conn.get(key))
    exp.add(
        "tg_sync_conns_hwm",
        "gauge",
        "Concurrent-connection high-water mark.",
        {},
        conn.get("hwm"),
    )
    bar = stats.get("barriers") if isinstance(stats.get("barriers"), dict) else {}
    for name, key, help_ in (
        ("tg_sync_barrier_parked_total", "parked", "Barrier waiters parked."),
        (
            "tg_sync_barrier_released_total",
            "released",
            "Barrier waiters released (fan-in reached).",
        ),
        (
            "tg_sync_barrier_timed_out_total",
            "timed_out",
            "Barrier waiters that timed out.",
        ),
        (
            "tg_sync_barrier_canceled_total",
            "canceled",
            "Barrier waiters canceled (connection lost mid-wait).",
        ),
    ):
        exp.add(name, "counter", help_, {}, bar.get(key))
    episodes = (
        bar.get("episodes") if isinstance(bar.get("episodes"), dict) else {}
    )
    for bucket, rec in sorted(
        (episodes.get("by_target") or {}).items(),
        key=lambda kv: int(kv[0]),
    ):
        if not isinstance(rec, dict):
            continue
        lbl = {"target": str(bucket)}
        exp.add(
            "tg_sync_barrier_episodes_total",
            "counter",
            "Released barrier episodes, by pow2-bucketed fan-in target.",
            lbl,
            rec.get("count"),
        )
        exp.add(
            "tg_sync_barrier_release_ms_total",
            "counter",
            "Summed armed-to-release wall ms of barrier episodes, by "
            "pow2-bucketed fan-in target.",
            lbl,
            rec.get("total_ms"),
        )
        exp.add(
            "tg_sync_barrier_release_ms_max",
            "gauge",
            "Slowest armed-to-release wall ms observed, by "
            "pow2-bucketed fan-in target.",
            lbl,
            rec.get("max_ms"),
        )
    ps = stats.get("pubsub") if isinstance(stats.get("pubsub"), dict) else {}
    exp.add(
        "tg_sync_pubsub_published_total",
        "counter",
        "Entries appended to topics (dedup replays excluded).",
        {},
        ps.get("published"),
    )
    for name, key, help_ in (
        ("tg_sync_pubsub_topics", "topics", "Topics holding entries."),
        ("tg_sync_pubsub_entries", "entries", "Entries across all topics."),
        (
            "tg_sync_pubsub_depth_hwm",
            "depth_hwm",
            "Deepest single topic observed (queue-depth high-water).",
        ),
        (
            "tg_sync_pubsub_subs_hwm",
            "subs_hwm",
            "Concurrent-subscription high-water mark.",
        ),
    ):
        exp.add(name, "gauge", help_, {}, ps.get(key))
    dd = stats.get("dedup") if isinstance(stats.get("dedup"), dict) else {}
    for kind, key in (("signal", "signal_hits"), ("publish", "publish_hits")):
        exp.add(
            "tg_sync_dedup_hits_total",
            "counter",
            "Idempotency-token replays answered from the dedup map "
            "(reconnect at-least-once wire, exactly-once effect).",
            {"op": kind},
            dd.get(key),
        )
    out = exp.render()
    # per-op service-time histograms (python server only): proper
    # Prometheus histogram series, hand-assembled because the le-bucket
    # lines share one TYPE header with their _sum/_count — cumulative
    # buckets over the recorder's log2 µs bins, le in seconds
    op_time = (
        stats.get("op_time_us")
        if isinstance(stats.get("op_time_us"), dict)
        else {}
    )
    hist_lines = []
    for op in sorted(op_time):
        rec = op_time[op]
        bins = rec.get("bins") if isinstance(rec, dict) else None
        if not bins:
            continue
        cum = 0
        for i, c in enumerate(bins):
            cum += int(_num(c) or 0)
            le = (
                "+Inf"
                if i == len(bins) - 1
                else repr((1 << (i + 1)) / 1e6)
            )
            hist_lines.append(
                f'tg_sync_op_duration_seconds_bucket{{op="{_escape(op)}"'
                f',le="{le}"}} {cum}'
            )
        total_us = _num(rec.get("total_us")) or 0
        hist_lines.append(
            f'tg_sync_op_duration_seconds_sum{{op="{_escape(op)}"}} '
            f"{total_us / 1e6}"
        )
        hist_lines.append(
            f'tg_sync_op_duration_seconds_count{{op="{_escape(op)}"}} {cum}'
        )
    if hist_lines:
        out = out.rstrip("\n") + "\n" + "\n".join(
            [
                "# HELP tg_sync_op_duration_seconds Service time per op "
                "(barrier/signal_and_wait record the full fan-in wait).",
                "# TYPE tg_sync_op_duration_seconds histogram",
            ]
            + hist_lines
        ) + "\n"
    return out


def render_prometheus(
    tasks, per_task_limit: int | None = None, fleet: dict | None = None
) -> str:
    """Render the daemon's metric surface from a task list (most recent
    first). The fixed-cardinality ``tg_tasks`` aggregate counts EVERY
    task given; ``per_task_limit`` bounds only the task-labeled series
    (label cardinality), so counts stay honest on daemons whose history
    outgrows the per-task window. ``fleet`` is the engine's counter
    snapshot (``Engine.fleet_info()``): worker occupancy, queue-wait /
    claim-latency histogram bins, pack admission counters — rendered as
    the ``tg_fleet_*`` family alongside the fleet gauges this function
    computes over the FULL task list (never the truncated slice; the
    conservation contract Σ tg_fleet_tasks == tg_scrape_tasks_total is
    pinned by test)."""
    exp = _Exposition()

    by_state: dict[tuple[str, str], int] = {}
    for t in tasks:
        key = (t.state().state.value, t.type.value)
        by_state[key] = by_state.get(key, 0) + 1
    for (state, ttype), count in sorted(by_state.items()):
        exp.add(
            "tg_tasks",
            "gauge",
            "Tasks known to this daemon, by lifecycle state and type.",
            {"state": state, "type": ttype},
            count,
        )

    # ---------------------------------------------------------- fleet
    # Control-plane gauges over the FULL task store, computed BEFORE
    # the per-task truncation below (the fleet-total-blindness fix):
    # per-state depth (conservation: sums to the store count), queue
    # depth by priority, and compile-cache totals.
    fleet_states: dict[str, int] = {}
    fleet_prio: dict[int, int] = {}
    cache_totals = {"hit": 0, "miss": 0}
    for t in tasks:
        st = t.state().state.value
        fleet_states[st] = fleet_states.get(st, 0) + 1
        if st == "scheduled":
            fleet_prio[t.priority] = fleet_prio.get(t.priority, 0) + 1
        result = t.result if isinstance(t.result, dict) else {}
        journal = (
            result.get("journal")
            if isinstance(result.get("journal"), dict)
            else {}
        )
        sim = journal.get("sim") if isinstance(journal.get("sim"), dict) else {}
        bk = sim.get("bucket") if isinstance(sim.get("bucket"), dict) else {}
        verdict = bk.get("compile_cache")
        if verdict in cache_totals:
            cache_totals[verdict] += 1
    for state in sorted(fleet_states):
        exp.add(
            "tg_fleet_tasks",
            "gauge",
            "Tasks in the daemon's store by lifecycle state, over the "
            "FULL store (sums to tg_scrape_tasks_total).",
            {"state": state},
            fleet_states[state],
        )
    for prio in sorted(fleet_prio):
        exp.add(
            "tg_fleet_queue_depth",
            "gauge",
            "Queued (scheduled) tasks by priority, over the full store.",
            {"priority": str(prio)},
            fleet_prio[prio],
        )
    # an empty store renders only the scrape-coverage gauges (the
    # test_empty_task_list pin) — the zero-valued cache counters would
    # be noise on a daemon that has never run anything
    if tasks:
        for verdict in ("hit", "miss"):
            exp.add(
                "tg_fleet_compile_cache_total",
                "counter",
                "Bucketed runs served warm (hit) or paying a cold XLA "
                "compile (miss), totalled over the full task store.",
                {"verdict": verdict},
                cache_totals[verdict],
            )
    if fleet:
        workers = (
            fleet.get("workers") if isinstance(fleet.get("workers"), dict) else {}
        )
        busy = int(_num(workers.get("busy")) or 0)
        total_workers = int(_num(workers.get("total")) or 0)
        for state, value in (
            ("busy", busy),
            ("idle", max(0, total_workers - busy)),
        ):
            exp.add(
                "tg_fleet_workers",
                "gauge",
                "Supervisor worker slots by occupancy.",
                {"state": state},
                value,
            )
        pk = fleet.get("pack") if isinstance(fleet.get("pack"), dict) else {}
        exp.add(
            "tg_fleet_pack_admissions_total",
            "counter",
            "Pack claims that admitted >= 2 runs onto one device "
            "program since daemon start.",
            {},
            pk.get("packed", 0),
        )
        exp.add(
            "tg_fleet_pack_runs_total",
            "counter",
            "Member runs admitted via pack claims since daemon start.",
            {},
            pk.get("packed_runs", 0),
        )
        solo = pk.get("solo") if isinstance(pk.get("solo"), dict) else {}
        for reason in sorted(solo):
            exp.add(
                "tg_fleet_pack_solo_total",
                "counter",
                "Pack-requesting runs that executed solo, by cause.",
                {"reason": str(reason)[:120]},
                solo[reason],
            )
        # fleet controller counters (docs/FLEET.md): preempt/evict/refuse
        # decisions since daemon start
        exp.add(
            "tg_fleet_preemptions_total",
            "counter",
            "Running tasks checkpointed and requeued by the fleet "
            "controller (operator preempt, eviction, or drain) since "
            "daemon start.",
            {},
            fleet.get("preemptions", 0),
        )
        exp.add(
            "tg_fleet_evictions_total",
            "counter",
            "Running tasks preempted to admit a higher-priority arrival "
            "since daemon start.",
            {},
            fleet.get("evictions", 0),
        )
        exp.add(
            "tg_fleet_refused_total",
            "counter",
            "Compositions refused at submit by the admission rules "
            "engine (tg check server-side) since daemon start.",
            {},
            fleet.get("refused", 0),
        )

    # truncation is NEVER silent (the render_prometheus contract): a
    # scraper can alert on elided > 0 instead of trusting an invisibly
    # windowed task list
    total = len(tasks)
    if per_task_limit is not None:
        tasks = tasks[:per_task_limit]
    exp.add(
        "tg_scrape_tasks_total",
        "gauge",
        "Tasks in the daemon's store at scrape time.",
        {},
        total,
    )
    exp.add(
        "tg_scrape_tasks_elided",
        "gauge",
        "Tasks whose per-task series were elided from this scrape by the "
        "per-task cardinality bound ([daemon] metrics_task_limit).",
        {},
        total - len(tasks),
    )
    for t in tasks:
        ident = {"task": t.id, "plan": t.plan, "case": t.case}
        result = t.result if isinstance(t.result, dict) else {}
        # supervisor ledger: queue wait + per-run runner wall
        tperf = result.get("perf") if isinstance(result.get("perf"), dict) else {}
        exp.add(
            "tg_task_queued_seconds",
            "gauge",
            "Seconds a task waited in the queue before processing.",
            ident,
            tperf.get("queued_secs"),
        )
        for rid, wall in sorted(
            (tperf.get("runner_wall_secs") or {}).items()
        ):
            exp.add(
                "tg_task_runner_wall_seconds",
                "gauge",
                "Wall seconds the runner spent executing one run of a task.",
                {**ident, "run": rid},
                wall,
            )
        journal = (
            result.get("journal") if isinstance(result.get("journal"), dict)
            else {}
        )
        # run health plane (journal["slo"]): per-rule verdicts — checked
        # BEFORE the sim-block gate because a fail-fast SLO run archives
        # its journal through the typed-error path too
        slo = journal.get("slo") if isinstance(journal.get("slo"), dict) else {}
        rules = slo.get("rules") if isinstance(slo.get("rules"), list) else []
        if rules:
            exp.add(
                "tg_slo_rules",
                "gauge",
                "SLO rules the run declared (run health plane).",
                ident,
                len(rules),
            )
            exp.add(
                "tg_slo_failed",
                "gauge",
                "1 when a severity=fail SLO breached and canceled the run.",
                ident,
                1 if slo.get("error") else 0,
            )
            for r in rules:
                if not isinstance(r, dict):
                    continue
                rident = {
                    **ident,
                    "rule": r.get("name", "?"),
                    "metric": r.get("metric", "?"),
                    "severity": r.get("severity", "warn"),
                }
                exp.add(
                    "tg_slo_breaches_total",
                    "counter",
                    "Breaching evaluations of one SLO rule across the run.",
                    rident,
                    r.get("breaches"),
                )
                exp.add(
                    "tg_slo_threshold",
                    "gauge",
                    "Declared threshold of one SLO rule.",
                    rident,
                    r.get("threshold"),
                )
                exp.add(
                    "tg_slo_observed",
                    "gauge",
                    "Last observed value of one SLO rule's metric (the "
                    "final evaluation before the run ended).",
                    rident,
                    r.get("last_observed"),
                )
        sim = journal.get("sim") if isinstance(journal.get("sim"), dict) else {}
        if not sim:
            continue
        for flow, key in _FLOWS:
            exp.add(
                "tg_run_msgs_total",
                "counter",
                "Cumulative message-flow totals of a finished sim run, "
                "by conservation leg.",
                {**ident, "flow": flow},
                sim.get(key),
            )
        for name, key, help_ in (
            ("tg_run_ticks", "ticks", "Simulated ticks the run executed."),
            (
                "tg_run_wall_seconds",
                "wall_secs",
                "Wall seconds of the run's execute phase.",
            ),
            (
                "tg_run_compile_seconds",
                "compile_secs",
                "Init + first-dispatch seconds (trace/lower + XLA compile "
                "or persistent-cache read).",
            ),
            ("tg_run_devices", "devices", "Devices the run's mesh spanned."),
            (
                "tg_run_carry_bytes",
                "carry_bytes",
                "Device-resident carry footprint in bytes (eval_shape-exact).",
            ),
        ):
            exp.add(name, "gauge", help_, ident, sim.get(key))
        perf = sim.get("perf") if isinstance(sim.get("perf"), dict) else {}
        ex = perf.get("execute") if isinstance(perf.get("execute"), dict) else {}
        co = perf.get("compile") if isinstance(perf.get("compile"), dict) else {}
        hbm = perf.get("hbm") if isinstance(perf.get("hbm"), dict) else {}
        exp.add(
            "tg_run_peer_ticks_per_second",
            "gauge",
            "Steady-state instance*ticks per wall second (performance "
            "ledger; first dispatch excluded when more than one ran).",
            ident,
            ex.get("steady_peer_ticks_per_sec", ex.get("peer_ticks_per_sec")),
        )
        exp.add(
            "tg_run_lower_seconds",
            "gauge",
            "Trace+lower seconds of the chunk program (AOT accounting pass).",
            ident,
            co.get("lower_secs"),
        )
        exp.add(
            "tg_run_xla_compile_seconds",
            "gauge",
            "XLA compile (or persistent-cache read) seconds of the chunk "
            "program (AOT accounting pass).",
            ident,
            co.get("compile_secs"),
        )
        exp.add(
            "tg_run_est_flops_per_chunk",
            "gauge",
            "XLA cost-analysis FLOP estimate for one tick-chunk program.",
            ident,
            co.get("flops"),
        )
        exp.add(
            "tg_run_est_bytes_accessed_per_chunk",
            "gauge",
            "XLA cost-analysis bytes-accessed estimate for one tick-chunk "
            "program.",
            ident,
            co.get("bytes_accessed"),
        )
        exp.add(
            "tg_run_hbm_peak_bytes",
            "gauge",
            "Device memory high-water mark sampled across the run "
            "(absent when the backend exposes no memory stats).",
            ident,
            hbm.get("peak_bytes"),
        )
        # network topology plane (journal["sim"]["net_matrix"],
        # docs/OBSERVABILITY.md "Traffic matrix"): BOUNDED cardinality
        # by construction — only the journal's top-K pairs export as
        # tg_net_pair_* series (≤ K pairs × flow legs) plus one elision
        # gauge saying how many nonzero pairs did NOT make the page;
        # the raw G² matrix never reaches the scrape page (read it via
        # `tg netmap` or the sim_netmatrix.jsonl stream).
        nm = (
            sim.get("net_matrix")
            if isinstance(sim.get("net_matrix"), dict)
            else {}
        )
        if nm:
            from testground_tpu.sim.netmatrix import NM_MSG_BYTES

            nm_labels = nm.get("labels") or []

            def _nm_group(i) -> str:
                try:
                    return str(nm_labels[int(i)])
                except (TypeError, ValueError, IndexError):
                    return str(i)

            for pr in nm.get("top_pairs") or []:
                if not isinstance(pr, dict):
                    continue
                pident = {
                    **ident,
                    "src": _nm_group(pr.get("src")),
                    "dst": _nm_group(pr.get("dst")),
                }
                for flow in (
                    "sent",
                    "delivered",
                    "dropped",
                    "rejected",
                    "fault_dropped",
                ):
                    exp.add(
                        "tg_net_pair_msgs_total",
                        "counter",
                        "Per-(src,dst) group-pair message counts of a "
                        "finished run's traffic matrix — top-K pairs by "
                        "sent volume only (bounded cardinality; see "
                        "tg_net_pairs_elided).",
                        {**pident, "flow": flow},
                        pr.get(flow),
                    )
                enq = _num(pr.get("enqueued"))
                exp.add(
                    "tg_net_pair_bytes_total",
                    "counter",
                    "Per-(src,dst) group-pair wire bytes (enqueued "
                    "messages x fixed message size) — top-K pairs only.",
                    pident,
                    None if enq is None else enq * NM_MSG_BYTES,
                )
            exp.add(
                "tg_net_pairs_elided",
                "gauge",
                "Nonzero traffic-matrix pairs NOT exported as "
                "tg_net_pair_* series (the bounded-cardinality "
                "remainder; full matrix via tg netmap).",
                ident,
                nm.get("elided_pairs", 0),
            )
            exp.add(
                "tg_net_conservation_mismatches",
                "gauge",
                "Traffic-matrix channels whose cell sum failed to "
                "reconcile with the run's flow totals (0 = exact; "
                "nonzero is an engine bug).",
                ident,
                len(nm.get("mismatches"))
                if isinstance(nm.get("mismatches"), list)
                else None,
            )
        # checkpoint/resume plane (journal["sim"]["checkpoint"],
        # docs/CHECKPOINT.md): snapshot progress gauges so a scraper can
        # alert on a soak whose last checkpoint is falling behind
        ck = (
            sim.get("checkpoint")
            if isinstance(sim.get("checkpoint"), dict)
            else {}
        )
        if ck:
            exp.add(
                "tg_checkpoint_count",
                "gauge",
                "Snapshots the run wrote (checkpoint plane).",
                ident,
                ck.get("count"),
            )
            exp.add(
                "tg_checkpoint_last_tick",
                "gauge",
                "Sim tick of the run's newest snapshot.",
                ident,
                ck.get("last_tick"),
            )
            exp.add(
                "tg_checkpoint_bytes",
                "gauge",
                "Size in bytes of the run's newest snapshot.",
                ident,
                ck.get("bytes"),
            )
            exp.add(
                "tg_checkpoint_write_ms",
                "gauge",
                "Wall milliseconds the newest snapshot took to write "
                "(fetch + serialize + fsync + rename).",
                ident,
                ck.get("write_ms"),
            )
        # shape bucketing (journal["sim"]["bucket"], PERF.md "Serving:
        # buckets + packing"): the hit/miss counter pair makes a cold
        # compile in production observable, not silent — alert when
        # misses move after a `tg build --buckets` warmup
        bk = (
            sim.get("bucket") if isinstance(sim.get("bucket"), dict) else {}
        )
        if bk:
            verdict = bk.get("compile_cache")
            exp.add(
                "tg_compile_bucket_hit",
                "counter",
                "Bucketed runs whose program was served by the warm "
                "persistent compile cache (1 per run; sum across tasks).",
                ident,
                1 if verdict == "hit" else 0,
            )
            exp.add(
                "tg_compile_bucket_miss",
                "counter",
                "Bucketed runs that paid a cold XLA compile — the "
                "bucket ladder was not warmed for this program "
                "(tg build --buckets).",
                ident,
                1 if verdict == "miss" else 0,
            )
            exp.add(
                "tg_bucket_padded_instances",
                "gauge",
                "Canonical padded instance count of the run's bucket "
                "(live exact count rides tg_task_info/sim totals).",
                ident,
                bk.get("padded_instances"),
            )
        # run packing (journal["sim"]["pack"]): pack width + member
        # index so a scraper can see batched tenancy per task
        pk = sim.get("pack") if isinstance(sim.get("pack"), dict) else {}
        if pk:
            exp.add(
                "tg_pack_width",
                "gauge",
                "Vmapped run-axis width of the pack this run executed "
                "in (dummy padding lanes included).",
                ident,
                pk.get("width"),
            )
            exp.add(
                "tg_pack_members",
                "gauge",
                "Live member runs batched into this run's pack.",
                ident,
                pk.get("members"),
            )
        # transport resolution (journal["sim"]["transport"]): an info
        # gauge — constant 1, the record rides the labels. Cardinality
        # is bounded: requested/resolved come from the 3-value knob and
        # source from the model's fixed evidence kinds
        tr = (
            sim.get("transport")
            if isinstance(sim.get("transport"), dict)
            else {}
        )
        # the mesh plane (journal["sim"]["mesh"], docs/OBSERVABILITY.md
        # "Mesh plane"): layout labels are bounded by real hardware
        # topologies ("1", "4", "2x4", ...), never free-form
        mh = sim.get("mesh") if isinstance(sim.get("mesh"), dict) else {}
        if tr.get("resolved"):
            exp.add(
                "tg_transport_resolved",
                "gauge",
                "Transport gate resolution for this run (info gauge, "
                "value always 1): requested knob, resolved backend, the "
                "cost model's evidence source under transport=auto, and "
                "the mesh layout the decision was scored against.",
                {
                    **ident,
                    "requested": str(tr.get("requested", "?")),
                    "resolved": str(tr.get("resolved", "?")),
                    "source": str(
                        (tr.get("scores") or {}).get("source", "explicit")
                    ),
                    "mesh": str(mh.get("axes") or "1"),
                },
                1,
            )
        if mh:
            exp.add(
                "tg_mesh_shards",
                "gauge",
                "Peer shards the run's carry planes partitioned across "
                "(the mesh's instance axis; absent on a single device).",
                {**ident, "mesh": str(mh.get("axes") or "?")},
                mh.get("shards"),
            )
            exp.add(
                "tg_mesh_cross_shard_bytes_est",
                "gauge",
                "Modeled per-commit ICI exchange bytes of the sharded "
                "transport (the sorted stream's cross-shard fraction).",
                {**ident, "mesh": str(mh.get("axes") or "?")},
                mh.get("cross_shard_bytes_est"),
            )
        # phase attribution plane (journal["sim"]["phases"],
        # docs/OBSERVABILITY.md "Phase attribution"): per-phase cost
        # gauges plus the synthesized residual/total rows — the phase
        # label space is the fixed TICK_PHASES set + {residual, total},
        # so cardinality stays bounded
        phases = (
            sim.get("phases") if isinstance(sim.get("phases"), dict) else {}
        )
        if phases:
            from testground_tpu.sim.phases import phase_rows

            for row in phase_rows(phases):
                pident = {
                    **ident,
                    "phase": row.get("phase", "?"),
                    "transport": row.get("transport", "xla"),
                }
                exp.add(
                    "tg_phase_flops",
                    "gauge",
                    "XLA cost-analysis FLOP estimate for one tick of one "
                    "phase (phase=residual/total are the coverage rows).",
                    pident,
                    row.get("flops"),
                )
                exp.add(
                    "tg_phase_bytes_accessed",
                    "gauge",
                    "XLA cost-analysis bytes-accessed estimate for one "
                    "tick of one phase.",
                    pident,
                    row.get("bytes_accessed"),
                )
                exp.add(
                    "tg_phase_measured_ms",
                    "gauge",
                    "Measured wall ms per call of one phase jitted in "
                    "isolation (phases_measure calibration).",
                    pident,
                    row.get("measured_ms"),
                )
    out = exp.render()
    # fleet latency histograms (engine claim bookkeeping): proper
    # Prometheus histogram series over the engine's log2 µs bins,
    # hand-assembled like tg_sync_op_duration_seconds above
    if fleet:
        hist_lines: list[str] = []
        for name, bins_key, sum_key, help_ in (
            (
                "tg_fleet_queue_wait_seconds",
                "queue_wait_bins",
                "queue_wait_total_us",
                "Time claimed tasks spent queued (scheduled -> "
                "processing), log2 buckets.",
            ),
            (
                "tg_fleet_claim_latency_seconds",
                "claim_latency_bins",
                "claim_latency_total_us",
                "Claim overhead (processing stamp -> worker dispatch, "
                "pack admission included), log2 buckets.",
            ),
        ):
            bins = fleet.get(bins_key)
            if not bins:
                continue
            cum = 0
            lines = []
            for i, c in enumerate(bins):
                cum += int(_num(c) or 0)
                le = (
                    "+Inf"
                    if i == len(bins) - 1
                    else repr((1 << (i + 1)) / 1e6)
                )
                lines.append(f'{name}_bucket{{le="{le}"}} {cum}')
            total_us = _num(fleet.get(sum_key)) or 0
            lines.append(f"{name}_sum {total_us / 1e6}")
            lines.append(f"{name}_count {cum}")
            hist_lines.extend(
                [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
                + lines
            )
        if hist_lines:
            out = out.rstrip("\n") + "\n" + "\n".join(hist_lines) + "\n"
    return out
