"""Metrics query layer — the twin of the reference's ``pkg/metrics``
(InfluxDB viewer for the daemon dashboard) over the per-run
``timeseries.jsonl`` files the ``sim:jax`` runner writes."""

from .prometheus import render_prometheus
from .viewer import Row, Viewer, clean, measurement_name

__all__ = ["Row", "Viewer", "clean", "measurement_name", "render_prometheus"]
