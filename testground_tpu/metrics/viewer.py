"""Measurement viewer backing the daemon dashboard.

Query-surface twin of the reference's ``pkg/metrics/viewer.go:35-80``
(``GetMeasurements`` / ``GetTags`` / ``GetData`` against InfluxDB's
``results.<plan>-<case>.*`` measurements). The storage is different by
design: instead of an external InfluxDB the ``sim:jax`` runner reduces
metrics per group on a tick cadence and appends rows to
``<outputs>/<plan>/<run-id>/timeseries.jsonl``; this viewer scans those
files. Measurement names keep the reference's ``results.<plan>-<case>.
<metric>`` shape so dashboard URLs and labels look the same.

A second measurement family comes from the sim telemetry plane
(``sim_timeseries.jsonl``, docs/OBSERVABILITY.md): per-tick engine
counters — message flow, calendar depth, sync occupancy, live instances.
Each counter surfaces as measurement ``sim.<counter>`` (group_id
``_run``, since the counters are run-global), and the per-group live
counts as ``sim.live`` dimensioned by group_id. Counter rows carry the
raw per-tick value in every field slot (count/mean/min/max) so existing
dashboard tables and the Influx mirror render them unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os

from testground_tpu.config import EnvConfig

__all__ = [
    "Row",
    "Viewer",
    "clean",
    "expand_perf_row",
    "expand_sim_row",
    "measurement_name",
]

# Tag keys that identify rather than dimension a series — excluded from the
# dashboard's tag pickers like the reference's tagsIgnoreList
# (``viewer.go:13-22``).
TAGS_IGNORE = {"plan", "case", "group_id", "run"}

# The sim telemetry plane's per-run series file names — the writer owns
# the constants (sim/telemetry.py has no jax dependency). LATENCY_FILE
# rows are already viewer-shaped (group_id/name/count/mean/min/max):
# the ``sim.latency.p50/p95/p99`` measurement family, per group.
from testground_tpu.sim.telemetry import (  # noqa: E402
    LATENCY_FILE,
    PERF_FILE,
    SIM_SERIES_FILE,
)

# Keys of a sim telemetry row that identify rather than measure.
_SIM_IDENTITY = {"run", "plan", "case", "tick"}
# Perf rows additionally carry the chunk index as identity (the tick
# already orders the series; a sim.perf.chunk measurement would be noise).
_PERF_IDENTITY = _SIM_IDENTITY | {"chunk"}


def expand_sim_row(row: dict, prefix: str = "sim", identity=None):
    """One open-format jsonl counter row → viewer-shaped rows, one per
    counter: measurement ``<prefix>.<counter>`` with the per-tick value
    in every field slot, and ``<prefix>.live`` per group from a nested
    live map. Non-numeric values are skipped (the jsonl is an open
    format)."""
    if identity is None:
        identity = _SIM_IDENTITY
    base = {k: row.get(k, "") for k in ("run", "plan", "case")}
    tick = row.get("tick", 0)
    for key, val in row.items():
        if key in identity:
            continue
        if key == "live" and isinstance(val, dict):
            for gid, v in val.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    yield {
                        **base,
                        "tick": tick,
                        "group_id": str(gid),
                        "name": f"{prefix}.live",
                        "count": v,
                        "mean": v,
                        "min": v,
                        "max": v,
                    }
            continue
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        yield {
            **base,
            "tick": tick,
            "group_id": "_run",
            "name": f"{prefix}.{key}",
            "count": val,
            "mean": val,
            "min": val,
            "max": val,
        }


def expand_perf_row(row: dict):
    """One sim_perf.jsonl row (performance ledger, sim/perf.py) → the
    ``sim.perf.<gauge>`` measurement family (group_id ``_run``, like the
    counter family)."""
    yield from expand_sim_row(row, prefix="sim.perf", identity=_PERF_IDENTITY)


def clean(name: str) -> str:
    """Measurement-name sanitizer (``dashboard.go:112-118``)."""
    return name.replace("/", "-")


def measurement_name(plan: str, case: str, metric: str) -> str:
    return f"results.{clean(plan)}-{case}.{metric}"


@dataclasses.dataclass
class Row:
    """One sampled reduction (the viewer.go ``Row`` analog: Run + Timestamp
    + Fields, with simulated ticks standing in for wall timestamps)."""

    run: str
    tick: int
    group_id: str
    fields: dict  # count/mean/min/max

    def to_dict(self) -> dict:
        return {
            "run": self.run,
            "tick": self.tick,
            "group_id": self.group_id,
            **self.fields,
        }


class Viewer:
    def __init__(self, env: EnvConfig | None = None):
        self.env = env or EnvConfig.load()

    # ------------------------------------------------------------- scanning

    def _run_dirs(self, plan: str):
        """Yield (run_id, plan-metric series path | None, sim telemetry
        series path | None, latency summary path | None, perf ledger
        path | None) for every run dir carrying any of the four
        families."""
        root = os.path.join(self.env.dirs.outputs(), plan)
        if not os.path.isdir(root):
            return
        for run_id in sorted(os.listdir(root)):
            paths = [
                os.path.join(root, run_id, name)
                for name in (
                    "timeseries.jsonl",
                    SIM_SERIES_FILE,
                    LATENCY_FILE,
                    PERF_FILE,
                )
            ]
            present = [p if os.path.isfile(p) else None for p in paths]
            if any(present):
                yield (run_id, *present)

    @staticmethod
    def _read_jsonl(path: str):
        # the shared tolerant reader (sim/telemetry.py) — one
        # implementation across every observability consumer
        from testground_tpu.sim.telemetry import iter_jsonl

        yield from iter_jsonl(path)

    def _iter_rows(self, plan: str, case: str | None, run_id: str | None):
        for rid, ts_path, sim_path, lat_path, perf_path in self._run_dirs(
            plan
        ):
            # a task's runs are <task-id> (single run) or <task-id>-<run-id>
            # (multi-run [[runs]] compositions — supervisor run_id scheme),
            # so a task-scoped query matches both
            if (
                run_id is not None
                and rid != run_id
                and not rid.startswith(run_id + "-")
            ):
                continue
            if ts_path is not None:
                for row in self._read_jsonl(ts_path):
                    if case is not None and row.get("case") != case:
                        continue
                    yield row
            if sim_path is not None:
                for row in self._read_jsonl(sim_path):
                    if case is not None and row.get("case") != case:
                        continue
                    yield from expand_sim_row(row)
            if lat_path is not None:
                # latency rows are written viewer-shaped — no expansion
                for row in self._read_jsonl(lat_path):
                    if case is not None and row.get("case") != case:
                        continue
                    yield row
            if perf_path is not None:
                for row in self._read_jsonl(perf_path):
                    if case is not None and row.get("case") != case:
                        continue
                    yield from expand_perf_row(row)

    # ---------------------------------------------------------------- query

    def get_measurements(
        self, plan: str, case: str, run_id: str | None = None, limit: int = 20
    ) -> list[str]:
        """Distinct measurement names for a plan:case — ``SHOW MEASUREMENTS
        … =~ /results.<name>.*/ LIMIT 20`` (``viewer.go:45-55``)."""
        names: list[str] = []
        for row in self._iter_rows(plan, case, run_id):
            name = row.get("name")
            if name and name not in names:
                names.append(name)
                if len(names) >= limit:
                    break
        return [measurement_name(plan, case, n) for n in sorted(names)]

    def get_tags(self, measurement: str) -> list[str]:
        """Extra tag keys for a measurement (``viewer.go:78-107``): the
        identity tags are filtered like the reference's ignore list, and the
        sim pipeline produces no custom tags, so this is empty today — kept
        for surface parity with dashboards that render tag pickers."""
        return []

    def get_data(
        self,
        plan: str,
        case: str,
        metric: str,
        run_id: str | None = None,
    ) -> list[Row]:
        """All sampled rows of one metric, tick-ordered per run."""
        return self.get_all_data(plan, case, run_id).get(metric, [])

    def get_all_data(
        self, plan: str, case: str, run_id: str | None = None
    ) -> dict[str, list[Row]]:
        """One pass over the run's series files: every metric's rows,
        tick-ordered per run — what the dashboard renders tables from."""
        out: dict[str, list[Row]] = {}
        for row in self._iter_rows(plan, case, run_id):
            name = row.get("name")
            if not name:
                continue
            # coerce field types: the jsonl is an open format (documented
            # for external writers), so rows must not smuggle arbitrary
            # values into consumers like the HTML dashboard
            try:
                fields = {}
                if "count" in row:
                    fields["count"] = int(row["count"])
                for k in ("mean", "min", "max"):
                    if k in row:
                        fields[k] = float(row[k])
            except (TypeError, ValueError):
                continue
            out.setdefault(name, []).append(
                Row(
                    run=row.get("run", ""),
                    tick=int(row.get("tick", 0)),
                    group_id=row.get("group_id", ""),
                    fields=fields,
                )
            )
        for rows in out.values():
            rows.sort(key=lambda r: (r.run, r.group_id, r.tick))
        return out
