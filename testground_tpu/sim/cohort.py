"""Killable cohort-leader child: multi-host runs survive member death.

The reference's cluster runner watches pod phases and fails a run cleanly
when an instance pod dies (``pkg/runner/cluster_k8s.go:696``
``watchRunPods``). A jax.distributed cohort cannot offer that in-process:
when a member is SIGKILLed mid-run, the leader's blocked collective
aborts with a catchable error within ~1 s (gloo notices the closed TCP
pair), but the distributed runtime's error-poll thread then
``LOG(FATAL)``-terminates the whole process once the coordination
service declares the member dead — by design, and without a Python hook.
An engine daemon that joined the cohort in-process would die with it.

So the engine never joins the cohort. The leader half (process 0) runs in
a CHILD process this module spawns and supervises:

- parent → child (stdin, one JSON per line): ``{"job": {run_input, cfg,
  home}}``, ``{"cancel": true}``, ``{"shutdown": true}``;
- child → parent (stdout): the run's OutputWriter progress chunks
  verbatim, then one terminal line —
  ``{"t": "cohort_result", "result": ...}`` (run finished; cohort
  healthy, child keeps serving jobs),
  ``{"t": "cohort_error", "error": ...}`` (run failed before any program
  collective — e.g. the lockstep readiness-vote skip; cohort healthy), or
  ``{"t": "cohort_fatal", "error": ...}`` (a member died / the
  distributed runtime is poisoned; the child exits immediately WITHOUT
  the shutdown barrier, sidestepping its own pending LOG(FATAL)).

On a fatal the parent fails the task with a readable error within
seconds of the death, marks the cohort generation broken, and stays
alive — the daemon keeps serving single-host runs, and a later
multi-host run spawns a fresh child (every worker must be restarted
too: member death poisons each surviving process's distributed runtime,
exactly as a lost pod fails the reference's whole run).

The child runs the UNCHANGED ``execute_sim_run`` multi-host path
(``cfg.isolate_cohort`` is stripped for the hop), so program shapes,
outputs layout, and journal are bit-identical to the pre-isolation
design — ``tests/test_multihost.py`` bit-equality gates run through this
boundary.
"""

from __future__ import annotations

import atexit
import dataclasses
import json
import os
import queue
import subprocess
import sys
import threading
import time

__all__ = ["CohortLeader", "run_in_cohort_child", "shutdown_leader_child"]

# grace between asking the child to stop (cancel/shutdown) and killing it
_GRACE_SECS = 60.0


class CohortBrokenError(RuntimeError):
    """A cohort member died; the generation is unusable."""


class CohortLeader:
    """Parent-side handle on the long-lived leader child (one cohort
    generation). The child joins jax.distributed once and serves every
    subsequent multi-host job, like the in-process leader used to."""

    def __init__(self):
        self._proc: subprocess.Popen | None = None
        self._key: tuple | None = None
        self._lock = threading.Lock()
        # lines arrive via a reader thread: a select()+readline() loop
        # would strand lines that coalesced into one pipe read inside the
        # TextIOWrapper buffer (select polls the then-empty fd forever)
        self._lines: queue.Queue | None = None

    # ------------------------------------------------------------ lifecycle

    def _ensure(self, cfg) -> subprocess.Popen:
        key = (cfg.coordinator_address, int(cfg.num_processes))
        if self._proc is not None and self._proc.poll() is None:
            if self._key != key:
                raise RuntimeError(
                    f"cohort leader already running for {self._key}; "
                    f"cannot also join {key} — one cohort per engine"
                )
            return self._proc
        self._proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "testground_tpu.sim.cohort"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,  # gloo/coordination chatter → log
            text=True,
        )
        self._key = key
        self._lines = queue.Queue()

        def pump(proc, lines):
            for line in proc.stdout:
                lines.put(line)

        threading.Thread(
            target=pump,
            args=(self._proc, self._lines),
            daemon=True,
            name="cohort-stdout",
        ).start()
        return self._proc

    def _send(self, proc, obj) -> None:
        proc.stdin.write(json.dumps(obj) + "\n")
        proc.stdin.flush()

    # ------------------------------------------------------------------ run

    def run(self, job, cfg, ow, cancel):
        from testground_tpu.api import RunOutput
        from testground_tpu.runners.result import Result

        with self._lock:
            proc = self._ensure(cfg)
            cfg_d = dataclasses.asdict(cfg)
            cfg_d["isolate_cohort"] = False  # the hop happens once
            self._send(
                proc,
                {
                    "job": {
                        "run_input": job.to_dict(),
                        "cfg": cfg_d,
                        "home": job.env.dirs.home if job.env else "",
                    }
                },
            )
            lines = self._lines
            cancel_sent_at = None
            while True:
                if cancel.is_set() and cancel_sent_at is None:
                    try:
                        self._send(proc, {"cancel": True})
                    except OSError:
                        pass  # child gone — poll below reports it
                    cancel_sent_at = time.monotonic()
                if (
                    cancel_sent_at is not None
                    and time.monotonic() - cancel_sent_at > _GRACE_SECS
                ):
                    proc.kill()
                    raise CohortBrokenError(
                        "cohort did not stop within "
                        f"{_GRACE_SECS:.0f}s of cancellation — leader "
                        "child killed; restart the sim-workers to form a "
                        "new cohort"
                    )
                try:
                    line = lines.get(timeout=0.2)
                except queue.Empty:
                    # drain any already-queued lines before concluding
                    # the child is gone
                    if proc.poll() is not None and lines.empty():
                        raise CohortBrokenError(
                            "cohort leader child exited unexpectedly "
                            f"(code {proc.returncode}) — a cohort member "
                            "likely died mid-run and the distributed "
                            "runtime terminated the leader; restart "
                            "every `tg sim-worker` to form a new cohort "
                            "(see docs/MIGRATING.md)"
                        )
                    continue
                msg = _parse(line)
                if msg is None:  # raw runtime chatter (gloo, absl logs)
                    ow.write_progress(line)
                    continue
                t = msg.get("t")
                if t == "p":
                    ow.write_progress(msg.get("p", ""))
                elif t == "cohort_result":
                    return RunOutput(
                        run_id=job.run_id,
                        result=Result.from_dict(msg["result"]),
                    )
                elif t == "cohort_error":
                    raise RuntimeError(msg.get("error", "cohort run failed"))
                elif t == "cohort_fatal":
                    try:
                        proc.kill()
                    except OSError:
                        pass
                    raise CohortBrokenError(
                        "cohort member failure: "
                        + msg.get("error", "unknown")
                        + " — the run is aborted and this cohort "
                        "generation is unusable; restart every "
                        "`tg sim-worker` to form a new one"
                    )
                else:
                    ow.write_progress(line)

    # ------------------------------------------------------------- shutdown

    def shutdown(self) -> None:
        """Drain the cohort: the child broadcasts the shutdown sentinel to
        the workers, completes the distributed shutdown barrier with
        them, and exits."""
        with self._lock:
            proc = self._proc
            self._proc = None
            if proc is None or proc.poll() is not None:
                return
            try:
                self._send(proc, {"shutdown": True})
                proc.wait(timeout=_GRACE_SECS)
            except (OSError, subprocess.TimeoutExpired):
                proc.kill()


def _parse(line: str):
    line = line.strip()
    if not line.startswith("{"):
        return None
    try:
        return json.loads(line)
    except ValueError:
        return None


_leader = CohortLeader()


def run_in_cohort_child(job, cfg, ow, cancel):
    """Module-level entry the executor delegates multi-host runs to."""
    return _leader.run(job, cfg, ow, cancel)


def shutdown_leader_child() -> None:
    _leader.shutdown()


atexit.register(shutdown_leader_child)


# --------------------------------------------------------------------------
# child half (python -m testground_tpu.sim.cohort)
# --------------------------------------------------------------------------

# error-text markers of a poisoned distributed runtime: a member died and
# collectives/coordination can never succeed again in this generation
_FATAL_MARKERS = (
    "gloo",
    "connection closed",
    "connection reset",
    "heartbeat",
    "coordination",
    "barrier",
    "preempt",
    "distributed service",
    "unavailable",
)

# exception type names of the jax/XLA runtime layer — the only layer
# whose failures can poison the distributed runtime (VERDICT r5 weak #3)
_RUNTIME_TYPE_NAMES = (
    "XlaRuntimeError",
    "JaxRuntimeError",
    "DistributedRuntimeError",
)


def _is_runtime_error(exc: BaseException) -> bool:
    """True when ``exc`` (or a base class) was raised by the jax/XLA
    runtime layer — jaxlib bindings, the distributed-runtime client, or
    its grpc substrate — rather than by plan or framework Python code."""
    for klass in type(exc).__mro__:
        mod = (getattr(klass, "__module__", "") or "").split(".")[0]
        if mod in ("jaxlib", "grpc"):
            return True
        if klass.__name__ in _RUNTIME_TYPE_NAMES:
            return True
    return False


def _is_cohort_fatal(exc: BaseException) -> bool:
    """Typed-first classification: only a runtime-layer exception whose
    text carries a poisoned-runtime marker is fatal. A plan-authored
    ``ValueError`` that happens to mention "barrier" (plans use
    barriers!) is an ordinary run failure — killing the cohort
    generation for it would force a needless fleet-wide sim-worker
    restart.

    A :class:`~testground_tpu.sync.errors.SyncLostError` IS fatal: the
    host-side coordination plane is gone past its reconnect budget, so
    barriers/pubsub can never complete for this generation — the sync
    analog of a dead ``jax.distributed`` member (docs/CROSSHOST.md)."""
    from testground_tpu.sync.errors import SyncLostError

    if isinstance(exc, SyncLostError):
        return True
    if not _is_runtime_error(exc):
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _FATAL_MARKERS)


def _child_main() -> int:
    from testground_tpu.api import RunGroup, RunInput
    from testground_tpu.config import EnvConfig
    from testground_tpu.rpc import OutputWriter

    out = sys.stdout
    ow = OutputWriter(sink=out)
    # terminal lines share the writer's sink lock so they can never
    # interleave with a progress chunk mid-line
    emit = ow._emit

    msgs: list[dict] = []
    msgs_ready = threading.Condition()
    cancel = threading.Event()

    def reader():
        for line in sys.stdin:
            msg = _parse(line)
            if msg is None:
                continue
            if msg.get("cancel"):
                cancel.set()
                continue
            with msgs_ready:
                msgs.append(msg)
                msgs_ready.notify()
        # parent died: there is nobody to report to — leave, completing
        # no further collectives (workers will fatal out on heartbeats)
        os._exit(2)

    threading.Thread(target=reader, daemon=True, name="cohort-stdin").start()

    while True:
        with msgs_ready:
            while not msgs:
                msgs_ready.wait()
            msg = msgs.pop(0)
        if msg.get("shutdown"):
            _child_shutdown()
            return 0
        job_d = msg.get("job")
        if not job_d:
            continue
        cancel.clear()
        ri = job_d["run_input"]
        from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

        cfg = SimJaxConfig(**job_d["cfg"])
        job = RunInput(
            run_id=ri["run_id"],
            test_plan=ri["test_plan"],
            test_case=ri["test_case"],
            total_instances=ri["total_instances"],
            groups=[RunGroup.from_dict(g) for g in ri["groups"]],
            runner_config=cfg,
            disable_metrics=ri.get("disable_metrics", False),
            # run-global fault schedule and flight-recorder table
            # survive the child hop (the per-group declarations ride in
            # groups[].faults / groups[].trace via from_dict) — tracing
            # is then re-gated off by the cohort rule in the executor
            faults=[dict(f) for f in ri.get("faults", [])],
            trace=dict(ri.get("trace", {})),
            env=EnvConfig.load(job_d.get("home") or None),
        )
        try:
            result = execute_sim_run(job, ow, cancel)
        except BaseException as e:  # noqa: BLE001 — classified below
            if _is_cohort_fatal(e):
                emit(
                    {
                        "t": "cohort_fatal",
                        "error": f"{type(e).__name__}: {e}",
                    }
                )
                # skip the shutdown barrier AND interpreter atexit: both
                # would block on the dead member until the coordination
                # service LOG(FATAL)s this process anyway
                out.flush()
                os._exit(3)
            emit({"t": "cohort_error", "error": f"{type(e).__name__}: {e}"})
            continue
        emit({"t": "cohort_result", "result": result.result.to_dict()})


def _child_shutdown() -> None:
    """Broadcast the shutdown sentinel so looping workers exit, then
    complete the distributed shutdown barrier with them."""
    from testground_tpu.sim.distributed import broadcast_shutdown_if_leader

    try:
        broadcast_shutdown_if_leader()
    except Exception:  # noqa: BLE001 — shutdown is best-effort
        pass
    try:
        import jax

        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001
        pass


if __name__ == "__main__":
    sys.exit(_child_main())
