"""Host-side driver for a ``sim:jax`` run.

The sim analog of ``LocalDockerRunner.Run`` (``pkg/runner/local_docker.go:
280-683``): where the reference creates a data network, boots one container
per instance, tails logs and collects sync events, this driver loads the
plan's sim module, compiles a :class:`~testground_tpu.sim.engine.SimProgram`
for the composition's groups, steps it to completion on the device mesh,
then writes the same outputs layout and Result the control plane expects.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
import sys
import threading
import time
import uuid

import numpy as np

from testground_tpu.api import RunInput, RunOutput
from testground_tpu.engine.task import Outcome
from testground_tpu.rpc import OutputWriter

from testground_tpu.runners.outputs import instance_output_dir
from testground_tpu.runners.result import Result

__all__ = [
    "SimJaxConfig",
    "execute_packed_sim_runs",
    "execute_sim_run",
    "load_sim_testcases",
    "run_sim_worker",
]

# Map sim status codes → lifecycle event names (pretty.go:163-175).
_STATUS_NAME = {0: "incomplete", 1: "success", 2: "failure", 3: "crash"}


@dataclasses.dataclass
class SimJaxConfig:
    """Runner config for ``sim:jax`` (coalesced like LocalDockerConfig)."""

    tick_ms: float = 1.0  # simulated ms per tick
    max_ticks: int = 100_000  # sim-time budget (the 10-min task timeout analog)
    chunk: int = 128  # ticks per device dispatch
    seed: int = 0
    shard: bool = True  # shard instance axis over available devices
    # explicit mesh layout (sim/meshplan.py): "" = the shard default
    # (all visible devices on a 1-D peers mesh), "4" = 4 peer shards,
    # "2x4" = 2 run shards × 4 peer shards (the run axis feeds packs).
    # The layout keys the transport decision cache, the precompile
    # BuildKey, and bench bank rows. CLI: --run-cfg mesh=4
    mesh: str = ""
    write_outputs_max: int = 2048  # cap on per-instance output dirs
    keep_outputs: bool = True
    # metric time-series sampling cadence in ticks (0 disables) — the analog
    # of the reference SDK's periodic InfluxDB metric batches; each sample is
    # a device→host state read, so the cadence bounds the overhead
    timeseries_every: int = 1024
    # debug: direct-slot-mode collision detection — reads back occupancy
    # each tick and FAILS the run naming the colliding (receiver, slot)
    # instead of silently corrupting inbox slots (costs a per-tick sort +
    # gather, so off by default)
    validate: bool = False
    # wall-clock watchdog: fail a run whose chunk dispatch (device poll
    # included) exceeds this many seconds — the only bound besides
    # sim-time max_ticks, so a wedged device or deadlocked collective
    # journals a stall diagnostic instead of hanging the worker thread
    # forever. Size it for STEADY-STATE chunks: the first two dispatches
    # (trace + XLA compile, and the mesh sharding fixed-point recompile)
    # are exempt. 0 disables (default: dispatch latency is workload- and
    # backend-dependent, so no universal default is safe)
    chunk_timeout_secs: float = 0.0
    # debug: scan the carry for NaN/Inf after every chunk and fail fast
    # naming the offending leaf and tick range (each scan is a full
    # device→host carry read, so strictly a debug flag)
    nan_guard: bool = False
    # debug: host-side sleep per chunk dispatch, in milliseconds — a
    # deterministic synthetic slowdown for exercising the comparison
    # plane (`tg diff` / the bench sentinel must flag a slowed run;
    # tools/diff_smoke.py). Inflates the per-chunk dispatch wall the
    # perf ledger records; shapes NO part of the program and never
    # belongs in a real run
    debug_chunk_sleep_ms: float = 0.0
    # telemetry plane (docs/OBSERVABILITY.md): compile a per-tick counter
    # block into the jitted tick and flush it once per chunk dispatch
    # into the run's sim_timeseries.jsonl — message flow, calendar depth,
    # sync occupancy, live instances per group. Piggybacks on the done-
    # flag poll (zero extra host syncs); off by default because a
    # 100k-tick run writes 100k jsonl rows
    telemetry: bool = False
    # network topology plane (docs/OBSERVABILITY.md "Traffic matrix",
    # sim/netmatrix.py): compile the src-group × dst-group traffic
    # matrix into the jitted tick's carry and flush it once per chunk
    # beside the telemetry block (zero extra host syncs) into
    # sim_netmatrix.jsonl + journal sim.net_matrix — who talks to whom,
    # per channel (sent/enqueued/delivered/dropped/rejected/
    # fault_dropped), reconciling EXACTLY against the flow totals.
    # Requires telemetry=true (refused loudly otherwise, same contract
    # as the SLO plane); cohorts run matrix-free like every telemetry
    # surface. The `tg netmap` backend. CLI: --run-cfg netmatrix=true
    netmatrix: bool = False
    # performance ledger (docs/OBSERVABILITY.md "Performance ledger"):
    # per-chunk dispatch wall / ticks/s / peer·ticks/s rows into
    # sim_perf.jsonl, the AOT lower-vs-compile split, XLA cost/memory
    # analysis of the chunk program, and the device HBM high-water mark
    # — all host-side bookkeeping on state the loop already has (zero
    # extra device syncs, program untouched). On by default; follows
    # the telemetry plane's gating (disable_metrics wins, cohorts run
    # ledger-free)
    perf: bool = True
    # opt-in jax.profiler trace for the whole run — the global switch
    # beside the per-group composition flag (Group.profiles); writes the
    # XLA op + host timeline under <run outputs>/profiles
    profile: bool = False
    # bounded profiler capture: > 0 captures only this many chunks,
    # starting after the warmup dispatch (chunk 0 carries trace + XLA
    # compile — profiling it buries the steady-state ops), instead of
    # wrapping the entire run (a million-tick soak under profile=true
    # would write a multi-GB trace). 0 = whole run, as before. The
    # capture window is journaled (journal["profile"]).
    profile_chunks: int = 0
    # phase attribution plane (docs/OBSERVABILITY.md "Phase
    # attribution"): lower each tick phase standalone at the run's real
    # shapes after the run completes and journal the per-phase XLA cost
    # ledger (sim.phases + sim_phases.jsonl — the `tg perf --phases`
    # backend). Off the hot path (runs at collect time) but opt-in: each
    # phase pays one small out-of-line compile. Follows the telemetry
    # plane's gating (disable_metrics wins, cohorts run phase-free).
    phases: bool = False
    # measured calibration for the phase plane: > 0 jits each phase in
    # isolation and times this many repetitions (concrete inputs at the
    # run's shapes), emitting measured ms/tick per phase beside the
    # static cost rows. Requires phases=true; costs one extra carry
    # init plus K dispatches per phase, all post-run.
    phases_measure: int = 0
    # transport backend for the calendar hot path (PERF.md "Pallas
    # transport kernels"): "xla" (default — the scatter path, program
    # unchanged), "pallas" (segmented VMEM-streaming commit + delivery
    # kernels, sim/pallas_transport.py; interpret mode off-TPU), or
    # "auto" — the measured cost model (sim/transport_model.py) scores
    # the two per workload shape (banked chip verdicts > opt-in
    # measured probe > static phase-ledger bytes) and journals the
    # decision under sim.transport. Mesh-aware: on a mesh whose peer
    # shards divide the lane count, "pallas" shard_maps the segmented
    # kernels over per-chip plane shards (cross-shard messages routed
    # via an exchange stage before commit) and "auto" scores the mesh
    # arms from the same cost model — per-shard bytes plus modeled ICI
    # exchange traffic (sim/meshplan.py); an indivisible layout
    # resolves to xla with a loud, rule-cataloged warning. The
    # RESOLVED value is a program-shaping option like telemetry:
    # broadcast to cohort followers and keyed into the precompile
    # BuildKey. CLI: --run-cfg transport=auto
    transport: str = "xla"
    # opt-in measured calibration for transport=auto: > 0 times both
    # candidate backends' transport phases (deliver + net_commit)
    # jitted in isolation for this many reps at the run's real shapes
    # and picks the faster — two standalone compiles + 2K dispatches,
    # all before the run's own trace, so strictly opt-in (meant for
    # real-chip sessions; on CPU the pallas arm times the interpreter)
    transport_probe: int = 0
    # shape bucketing (PERF.md "Serving: buckets + packing",
    # sim/buckets.py): "off" (default — exact shapes, the pre-bucket
    # program unchanged), "auto" (pad every group's instance count up to
    # the canonical ladder, dead lanes masked out, exact counts as
    # runtime data), or an explicit "<n>" (pad every group to exactly
    # n). Any composition in the same bucket then compiles — and the
    # persistent cache serves — ONE program, so `tg build --buckets`
    # makes the cache warm-for-anyone. Results/telemetry stay exact-N,
    # pinned bit-equal to an unpadded run. Mesh-compatible when every
    # rung's padded count divides the peer shard count (the gate
    # refuses indivisible layouts loudly); trace-free, cohort-free.
    # CLI: --run-cfg bucket=auto
    bucket: str = "off"
    # the canonical instance-count ladder, comma-separated (default
    # sim/buckets.DEFAULT_LADDER: 4096,32768,131072,1048576); a group
    # above the top rung runs unbucketed with a warning
    bucket_ladder: str = ""
    # run packing (PERF.md "Serving: buckets + packing", sim/pack.py):
    # opt this run into the engine's pack admission — queued compatible
    # small runs (same plan/case/bucket/program gates, seeds free) batch
    # into ONE vmapped device program with a leading run axis and one
    # dispatch per chunk, instead of serializing through the queue.
    # Per-run results/telemetry/SLO demux host-side, bit-equal per run
    # to an isolated run; a run finishing early no-ops its lanes rather
    # than blocking the pack. CLI: --run-cfg pack=true
    pack: bool = False
    # most runs one pack may absorb (the vmapped run-axis width is
    # padded up to a power of two ≤ this, dead dummy runs masked out)
    pack_max: int = 8
    # `tg build --buckets` / `bench.py --build --buckets`: the sim:plan
    # precompile additionally warms the WHOLE canonical bucket ladder
    # (per-bucket compile_secs in the build markers) so a daemon serves
    # any instance count warm. A build-time flag — runs ignore it.
    build_buckets: bool = False
    # checkpoint/resume plane (docs/CHECKPOINT.md): > 0 snapshots the
    # full run state (device carry + RNG + telemetry/latency/SLO
    # accumulators + manifest) every K chunks into the run's
    # checkpoints/ dir with atomic write-then-rename, so a preempted
    # soak resumes from the last boundary instead of losing every tick.
    # NOT program-shaping: the jitted program is untouched and the
    # default (0) adds zero host syncs — the only cost when on is a
    # device→host carry read at each K-th chunk boundary. Cohorts run
    # checkpoint-free (a leader-local read of the cross-process carry
    # is not symmetric).
    checkpoint_chunks: int = 0
    # bounded retention: keep only the newest N snapshots (each is
    # roughly the carry footprint on disk)
    checkpoint_keep: int = 3
    # resume this run from another run's newest snapshot (a task/run id
    # under the same outputs tree — `tg run resume <task>` sets it).
    # The snapshot manifest is validated against THIS run's rebuilt
    # program (composition hash + plan-source build key + transport);
    # any mismatch refuses with a typed CheckpointError. With
    # checkpoint_chunks > 0 and no resume_from, a run whose own dir
    # already holds snapshots auto-resumes in place — the engine-side
    # recovery path for interrupted tasks rehydrated after a daemon
    # restart.
    resume_from: str = ""
    # whitelisted control-route service hosts (echo lanes past the instance
    # axis) — the ADDITIONAL_HOSTS analog (``local_docker.go:78``); plans
    # address them via ``env.host_index(name)``
    additional_hosts: list = dataclasses.field(default_factory=list)
    # per-run device-memory precheck (the cluster capacity precheck
    # analog, ``cluster_k8s.go:958-1012``): 0 = auto-detect the device's
    # bytes_limit (skipped when the backend exposes no memory stats),
    # -1 = disabled, >0 = explicit per-device budget in bytes
    memory_limit_bytes: int = 0
    # multi-host SPMD (SURVEY §2.6/§7-M5): when coordinator_address is set
    # the run joins a jax.distributed cohort — this engine is the leader
    # (process 0); every other host runs `tg sim-worker` against the same
    # coordinator and executes the same program over the global mesh
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    # run the cohort-leader half in a killable child process so member
    # death fails the TASK, not the daemon (sim/cohort.py); stripped on
    # the child hop. False = join jax.distributed in this process (the
    # sim-worker loop, and the child itself)
    isolate_cohort: bool = True


def load_sim_testcases(artifact_path: str) -> dict:
    """Import the plan's sim module and return its ``sim_testcases`` map."""
    entry = None
    for name in ("sim.py", "main.py"):
        cand = os.path.join(artifact_path, name)
        if os.path.isfile(cand):
            entry = cand
            break
    if entry is None:
        raise FileNotFoundError(
            f"no sim.py/main.py entry point in {artifact_path}"
        )
    modname = f"tg_sim_plan_{uuid.uuid4().hex[:8]}"
    spec = importlib.util.spec_from_file_location(modname, entry)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(modname, None)
    cases = getattr(mod, "sim_testcases", None)
    if not isinstance(cases, dict) or not cases:
        raise ValueError(
            f"plan module {entry} does not export a non-empty "
            "`sim_testcases` dict"
        )
    return cases


def instantiate_testcase(factory, groups, tick_ms: float):
    """Specialize-then-instantiate a testcase factory. The SINGLE code
    path for the run leader, the sim-worker followers, and bench — a
    cohort must trace identical shapes, so any drift here desyncs
    multi-host runs."""
    if isinstance(factory, type):
        return factory.specialize(groups, tick_ms=tick_ms)()
    return factory


def load_and_specialize(artifact_path, test_case, run_groups, tick_ms):
    """Plan sources → specialized testcase + group layout. Shared by the
    run leader, the sim-worker followers, and the sim:plan precompile —
    one path, so cohorts trace identical shapes and the precompile's
    cache entries are the ones the run reads."""
    from .engine import build_groups

    cases = load_sim_testcases(artifact_path)
    factory = cases.get(test_case)
    if factory is None:
        raise ValueError(
            f"unknown sim test case {test_case!r}; plan exposes "
            f"{sorted(cases)}"
        )
    groups = build_groups(run_groups)
    return instantiate_testcase(factory, groups, tick_ms), groups


def make_sim_program(
    testcase,
    groups,
    *,
    test_plan,
    test_case,
    test_run,
    tick_ms,
    mesh,
    chunk,
    hosts,
    validate,
    telemetry,
    faults,
    trace,
    transport,
    live_counts,
    netmatrix,
):
    """The ONE construction site for a run's SimProgram. Every
    program-shaping option is a REQUIRED keyword: adding one here forces
    the leader, the followers, and the precompile to thread it through,
    instead of silently compiling different programs."""
    from .engine import SimProgram

    return SimProgram(
        testcase,
        groups,
        test_plan=test_plan,
        test_case=test_case,
        test_run=test_run,
        tick_ms=tick_ms,
        mesh=mesh,
        chunk=chunk,
        hosts=hosts,
        validate=validate,
        telemetry=telemetry,
        faults=faults,
        trace=trace,
        transport=transport,
        live_counts=live_counts,
        netmatrix=netmatrix,
    )


def resolve_transport(cfg, mesh, warn=None, context=None) -> str:
    """The ONE transport-gate: validate the runner-config knob, apply
    the mesh divisibility bound, and resolve ``transport=auto`` through the
    measured cost model (``sim/transport_model.py``). Shared by the
    executor, the sim-worker followers, the pack path, and the
    sim:plan precompile so all four resolve the same program variant
    (the telemetry-gate discipline). ``warn`` is a ``(fmt, *args)``
    callable for the loud fallback; ``context`` (a
    ``transport_model.TransportContext``) carries the workload shapes
    ``auto`` scores against — callers that can resolve ``auto`` build
    one after specialization. Returns the resolved backend string;
    callers that journal the full decision call ``decide_transport``
    directly."""
    from .transport_model import decide_transport

    return decide_transport(cfg, mesh, context=context, warn=warn).resolved


def _decide_transport_for(
    job, cfg, mesh, testcase, groups, hosts, telemetry_on, ow
):
    """Executor-side transport resolution with the full workload
    context, returning the journaled ``TransportDecision`` (the
    ``resolve_transport`` gate with the scoring inputs this call site
    already has in hand)."""
    from .transport_model import TransportContext, decide_transport

    return decide_transport(
        cfg,
        mesh,
        context=TransportContext(
            testcase=testcase,
            groups=tuple(groups),
            test_plan=job.test_plan,
            test_case=job.test_case,
            tick_ms=cfg.tick_ms,
            chunk=cfg.chunk,
            telemetry=bool(telemetry_on),
            validate=bool(getattr(cfg, "validate", False)),
            hosts=tuple(hosts),
            probe_reps=int(getattr(cfg, "transport_probe", 0) or 0),
        ),
        warn=ow.warn,
    )


def resolve_buckets(cfg, counts, mesh=None, warn=None):
    """The ONE shape-bucketing gate (the ``resolve_transport``
    discipline): validate the ``bucket``/``bucket_ladder`` knobs and
    apply the structural bounds. Returns a
    :class:`~testground_tpu.sim.buckets.BucketPlan` or None (exact
    shapes). Shared by the executor, the sim:plan precompile, and the
    engine-side pack admission so all three resolve the same padded
    layout. ``warn`` is a ``(fmt, *args)`` callable for loud fallbacks."""
    from .buckets import parse_bucket_mode, parse_ladder, plan_buckets

    mode = parse_bucket_mode(getattr(cfg, "bucket", "off"))
    if mode == "off":
        return None
    if getattr(cfg, "coordinator_address", ""):
        if warn is not None:
            warn(
                "shape bucketing disabled for the cohort config (the "
                "runtime-N carry input is leader-local state a follower "
                "cannot reproduce symmetrically)"
            )
        return None
    ladder = parse_ladder(getattr(cfg, "bucket_ladder", "") or None)
    plan = plan_buckets(counts, mode, ladder)
    if plan is not None and mesh is not None:
        # mesh gate (sim/meshplan.py): a bucketed run shards the PADDED
        # instance axis, so every rung's padded count must divide across
        # the peer shards — equal contiguous per-chip blocks, no
        # resharding between rungs. Indivisible → exact shapes, loudly
        # (`tg check` catalogs this as buckets.mesh-indivisible).
        from .meshplan import indivisible_counts, peer_shards

        shards = peer_shards(mesh)
        bad = indivisible_counts(plan.padded_counts, shards)
        if bad:
            if warn is not None:
                warn(
                    "shape bucketing skipped on this mesh: padded "
                    "count(s) %s do not divide across %d peer shard(s) "
                    "— running exact shapes; pick a bucket ladder whose "
                    "rungs are multiples of the shard count",
                    ",".join(str(c) for c in bad),
                    shards,
                )
            return None
    if plan is None:
        if warn is not None:
            warn(
                "shape bucketing skipped: a group's %s instances exceed "
                "the bucket coverage (ladder %s) — running exact shapes; "
                "raise bucket_ladder to bucket runs this large",
                max(counts),
                ",".join(str(r) for r in ladder)
                if mode == "auto"
                else mode,
            )
        return None
    return plan


def fault_specs_of(run_groups, global_faults=None) -> dict:
    """Collect the declared fault tables for schedule lowering:
    {group_id: [raw fault dicts]}, with run-global declarations
    (``[[global.run.faults]]``) under the ``""`` key so their default
    target is the whole run rather than one group. Plain
    JSON-serializable data — the same dict is broadcast verbatim to
    cohort followers and hashed into the precompile BuildKey."""
    specs = {
        g.id: [dict(f) for f in (getattr(g, "faults", None) or [])]
        for g in run_groups
    }
    specs[""] = [dict(f) for f in (global_faults or [])]
    return {k: v for k, v in specs.items() if v}


def trace_specs_of(run_groups, global_trace=None) -> dict:
    """Collect the declared flight-recorder tables for plan lowering:
    {group_id: raw trace table}, with the run-global declaration
    (``[global.run.trace]``) under the ``""`` key so its default target
    is the whole run — the exact shape of :func:`fault_specs_of`. Plain
    JSON-serializable data: broadcast to cohort followers and hashed
    into the precompile BuildKey."""
    specs = {
        g.id: dict(getattr(g, "trace", None) or {}) for g in run_groups
    }
    specs[""] = dict(global_trace or {})
    return {k: v for k, v in specs.items() if v}


def slo_specs_of(run_groups, global_slo=None) -> dict:
    """Collect the declared SLO tables for plan lowering:
    {group_id: [raw slo dicts]}, with run-global declarations
    (``[[global.run.slo]]``) under the ``""`` key — the exact shape of
    :func:`fault_specs_of`. Plain JSON-serializable data: hashed into
    the precompile BuildKey (the SLO plane never shapes the program, but
    the build marker records the full run declaration) and kept out of
    the cohort broadcast (cohorts run SLO-free — see the executor
    gate)."""
    specs = {
        g.id: [dict(s) for s in (getattr(g, "slo", None) or [])]
        for g in run_groups
    }
    specs[""] = [dict(s) for s in (global_slo or [])]
    return {k: v for k, v in specs.items() if v}


class _SloRunCancel:
    """OR-composition of the task cancel event with a run-local signal —
    the run health plane's fail path. The chunk loop stops when either
    is set. ``set()`` keeps TASK-level semantics: the stall watchdog
    (and anything else holding the loop's cancel object) calls it, and
    declaring an SLO must not weaken a stall from a task cancel to a
    run-local one. The SLO evaluator cancels through ``run_local``
    instead — an SLO breach fails ONE run, the task was not canceled by
    the operator, and a multi-``[[runs]]`` composition keeps executing
    its later runs."""

    def __init__(self, task_cancel: threading.Event):
        self._task = task_cancel
        self.run_local = threading.Event()

    def set(self) -> None:
        self._task.set()

    def is_set(self) -> bool:
        return self.run_local.is_set() or self._task.is_set()


class _PreemptRunCancel:
    """OR-composition of the fleet controller's preemption signal with
    the run's existing cancel object (task cancel, or the SLO wrapper)
    — live migration's stop path (engine/controller.py, docs/FLEET.md).
    The chunk loop stops at the next boundary when either fires; the
    preempt observer has already forced a snapshot at that same
    boundary, so the requeued task resumes exactly where it stopped.
    ``set()`` keeps task-level semantics (stall watchdog et al.), same
    as :class:`_SloRunCancel`."""

    def __init__(self, inner, preempt):
        self._inner = inner
        self._preempt = preempt

    def set(self) -> None:
        self._inner.set()

    def is_set(self) -> bool:
        return self._preempt.is_set() or self._inner.is_set()


def _parse_hosts(raw) -> tuple[str, ...]:
    """Normalize the additional_hosts config: a TOML list, or a
    comma-separated string like the reference's ADDITIONAL_HOSTS env var
    (``local_docker.go:141``) — never char-split a bare string."""
    if not raw:
        return ()
    if isinstance(raw, str):
        raw = raw.split(",")
    return tuple(s for s in (str(h).strip() for h in raw) if s)


def _make_mesh(shard: bool, shape: str = ""):
    """The executor's mesh gate (sim/meshplan.py): an explicit
    ``mesh="4"``/``"2x4"`` layout wins over the boolean ``shard``
    default (all visible devices, 1-D). Either way a single-device
    world returns None — the flat-layout fast path."""
    from .meshplan import make_mesh

    if shape:
        return make_mesh(shape)
    if not shard:
        return None
    return make_mesh(None)


def _mesh_journal_block(mesh, testcase, groups, hosts):
    """The ``sim.mesh`` journal block (sim/meshplan.py,
    docs/OBSERVABILITY.md "Mesh plane"): the layout string, the shard
    extents, the rule table that placed every carry plane, and the
    modeled per-commit ICI exchange bytes (what the sharded pallas
    commit's stream all-gather moves). None on a single device. The
    `tg stats` mesh line and the tg_mesh_shards gauge read this."""
    if mesh is None:
        return None
    import types as _types

    from .meshplan import cross_shard_bytes_est, layout_str, plan_for
    from .transport_model import _stream_bytes_per_tick

    plan = plan_for(mesh)
    return {
        "axes": layout_str(mesh),
        "shards": plan.shards,
        "runs": plan.runs,
        "layout_table": plan.layout_table(),
        "cross_shard_bytes_est": int(
            cross_shard_bytes_est(
                stream_bytes=_stream_bytes_per_tick(
                    _types.SimpleNamespace(
                        testcase=testcase,
                        groups=tuple(groups),
                        hosts=tuple(hosts),
                    )
                ),
                shards=plan.shards,
            )
        ),
    }


# headroom multiplier over the exact carry footprint: donation double-
# buffers the carry between chunks and the tick body materializes
# transient planes (inbox window, outbox concat, scatter operands) of
# the calendar's order of magnitude
_MEM_HEADROOM = 2.5


def _precheck_device_memory(prog, cfg, mesh, ow) -> None:
    """Refuse an oversized composition BEFORE tracing — the per-run
    analog of the reference's cluster capacity precheck
    (``cluster_k8s.go:958-1012``: composition resources vs cluster
    capacity at schedule time, not an OOM mid-run). The estimate is the
    eval_shape-exact carry footprint × a documented headroom factor,
    divided across mesh devices (the big planes shard by instance; the
    replicated sync state is negligible beside them)."""
    limit = int(getattr(cfg, "memory_limit_bytes", 0) or 0)
    if limit < 0:
        return
    if limit == 0:
        from .perf import device_memory_stats

        limit = device_memory_stats().get("bytes_limit") or 0
        if not limit:
            return  # backend exposes no memory stats — nothing to check
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    carry = prog.estimate_carry_bytes()
    need = int(carry * _MEM_HEADROOM / n_dev)
    if need > limit:
        raise RuntimeError(
            f"composition needs ~{need / 2**30:.2f} GiB per device "
            f"(carry {carry / 2**30:.2f} GiB × {_MEM_HEADROOM} headroom "
            f"/ {n_dev} device(s)) but the device budget is "
            f"{limit / 2**30:.2f} GiB — shrink the composition "
            "(instances, IN_MSGS/MSG_WIDTH, MAX_LINK_TICKS, TOPIC_CAP) "
            "or run on more devices; set runner config "
            "memory_limit_bytes = -1 to override this precheck"
        )
    ow.infof(
        "memory precheck: ~%.2f GiB/device of %.2f GiB budget (carry "
        "%.2f GiB on %d device(s))",
        need / 2**30,
        limit / 2**30,
        carry / 2**30,
        n_dev,
    )


def _cohort_job_spec(
    job: RunInput, cfg, *, hosts, telemetry, transport, faults
) -> dict:
    """The cohort job spec — the ONE dict shape both the leader's
    ``broadcast_json`` and the pre-spawn size check build. Every
    program-shaping option must reach the followers (a mismatch would
    trace different programs and desync the cohort inside a collective),
    so gated values (telemetry/transport post their cohort gates) are
    passed in by the caller. Cohorts run trace-free, so ``trace`` is
    always the post-gate empty dict — kept explicit so a future
    symmetric-trace design cannot silently desync the followers."""
    return {
        "plan": job.test_plan,
        "case": job.test_case,
        "run_id": job.run_id,
        "groups": [
            {
                "id": g.id,
                "instances": g.instances,
                "parameters": dict(g.parameters),
            }
            for g in job.groups
        ],
        "tick_ms": cfg.tick_ms,
        "chunk": cfg.chunk,
        "seed": cfg.seed,
        "max_ticks": cfg.max_ticks,
        "hosts": list(hosts),
        "validate": bool(getattr(cfg, "validate", False)),
        "telemetry": bool(telemetry),
        "transport": str(transport),
        "faults": faults,
        "trace": {},
        # cohorts run SLO-free (the telemetry plane the rules evaluate
        # is off under a cohort) — kept explicit, like trace, so a
        # future symmetric design cannot silently desync the followers
        "slo": [],
    }


def _precheck_cohort_spec_size(job: RunInput, cfg) -> None:
    """Refuse an over-the-wire-bound cohort job spec BEFORE any process
    is spawned or collective entered (VERDICT r5 weak #5 — the
    MAX_FILTER_CELLS precheck philosophy). The broadcast buffer is a
    fixed ``distributed.SPEC_BYTES``; without this check an oversized
    composition (many groups / large parameter blobs) dies as a
    ValueError inside the cohort child, after the leader child and every
    worker have already joined collectives."""
    import json as _json

    from .distributed import SPEC_BYTES

    # same builder as the leader's broadcast, with the POST-gate scalar
    # values a cohort always broadcasts (telemetry off, transport xla —
    # the cohort gates), so the estimate is byte-exact, never under
    spec = _cohort_job_spec(
        job,
        cfg,
        hosts=_parse_hosts(getattr(cfg, "additional_hosts", None)),
        telemetry=False,
        transport="xla",
        faults=fault_specs_of(job.groups, getattr(job, "faults", None)),
    )
    raw = len(_json.dumps(spec).encode()) + 8  # the length prefix
    if raw > SPEC_BYTES:
        biggest = max(
            job.groups,
            key=lambda g: len(_json.dumps(dict(g.parameters))),
            default=None,
        )
        hint = (
            f" (largest parameter blob: group {biggest.id!r}, "
            f"{len(_json.dumps(dict(biggest.parameters)))} bytes)"
            if biggest is not None
            else ""
        )
        raise ValueError(
            f"cohort job spec is {raw:,} bytes, over the {SPEC_BYTES:,}-"
            "byte broadcast bound — shrink the composition's group "
            f"parameters or fault tables{hint}; refused before spawning "
            "the cohort (the broadcast inside the collective would fail "
            "anyway, stranding every joined worker)"
        )


def execute_sim_run(
    job: RunInput, ow: OutputWriter, cancel: threading.Event
) -> RunOutput:
    cfg = job.runner_config or SimJaxConfig()
    # oversized cohort specs are refused HERE — before the leader child
    # is spawned and before jax.distributed is initialized anywhere
    if getattr(cfg, "coordinator_address", ""):
        _precheck_cohort_spec_size(job, cfg)
    # Multi-host: the engine NEVER joins the cohort in-process — a member
    # death LOG(FATAL)s every joined process once the coordination
    # service notices (no Python hook exists), which would kill the
    # daemon. The leader half runs in a killable child instead; this
    # process supervises it and fails the task cleanly on member death
    # (the watchRunPods analog, ``cluster_k8s.go:696``). The child runs
    # THIS function again with isolate_cohort stripped.
    if getattr(cfg, "coordinator_address", "") and getattr(
        cfg, "isolate_cohort", True
    ):
        from .cohort import run_in_cohort_child

        return run_in_cohort_child(job, cfg, ow, cancel)

    outputs_root = job.env.dirs.outputs() if job.env is not None else None
    run_dir = None
    if outputs_root is not None:
        run_dir = os.path.join(outputs_root, job.test_plan, job.run_id)
        os.makedirs(run_dir, exist_ok=True)
    # run-span tracing: structured host-side phase events (run → build →
    # compile → chunk[i] → collect) as sdk/events.py-style JSON lines in
    # the run's outputs dir — see docs/OBSERVABILITY.md
    from .telemetry import SPAN_FILE, SpanTracer

    spans = SpanTracer(
        os.path.join(run_dir, SPAN_FILE)
        if run_dir is not None and not job.disable_metrics
        else None,
        ctx=getattr(job, "trace_ctx", None),
    )
    spans.start(
        "run", run_id=job.run_id, plan=job.test_plan, case=job.test_case
    )
    try:
        return _execute_sim_run(
            job, cfg, ow, cancel, outputs_root, run_dir, spans
        )
    except BaseException as e:
        # failed runs keep their span record — those are exactly the
        # ones an operator wants to inspect. A preemption is not a
        # failure: the span says so, and the requeued attempt's spans
        # join the same lifecycle tree (engine/tracetree.py).
        from testground_tpu.engine.controller import TaskPreemptedError

        outcome = (
            "preempted" if isinstance(e, TaskPreemptedError) else "error"
        )
        spans.end("run", outcome=outcome, error=str(e)[:200])
        raise
    finally:
        spans.close()


def _execute_sim_run(
    job: RunInput,
    cfg,
    ow: OutputWriter,
    cancel: threading.Event,
    outputs_root,
    run_dir,
    spans,
) -> RunOutput:
    from testground_tpu.utils.compile_cache import enable_compile_cache

    # the compiled XLA program is this framework's build artifact: route
    # compilation through the persistent cache so a precompiled build
    # (sim:plan) or any prior run of the same program skips XLA compile.
    # The perf ledger's AOT accounting pass needs to know whether the
    # cache is live: without it, lowering+compiling out-of-line would
    # force a full second XLA compile instead of a cache read.
    compile_cache_on = (
        enable_compile_cache(
            job.env.dirs.home if job.env is not None else None
        )
        is not None
    )

    # multi-host cohort join MUST precede any jax call that initializes
    # the backend (jax.distributed.initialize's contract)
    multi = False
    if getattr(cfg, "coordinator_address", ""):
        from .distributed import init_distributed, is_multiprocess

        init_distributed(
            cfg.coordinator_address, cfg.num_processes, cfg.process_id
        )
        multi = is_multiprocess()
        if int(getattr(cfg, "num_processes", 1)) > 1 and not multi:
            # a backend that "initialized" without actually joining (e.g.
            # a plugin that ignores the distributed runtime) must not
            # silently run the job on the wrong topology — the workers
            # would strand, and results would claim a cohort that never
            # existed
            raise RuntimeError(
                f"runner config requested a {cfg.num_processes}-process "
                "cohort but the distributed runtime reports a single "
                "process — the jax backend did not join (environment "
                "mismatch between cohort members?); refusing to run on "
                "the wrong topology"
            )

    from .telemetry import NETMATRIX_FILE, SIM_SERIES_FILE

    artifact = job.groups[0].artifact_path
    spans.start("build")
    # shape bucketing (PERF.md "Serving: buckets + packing"): resolve
    # the bucket/ladder knobs BEFORE specialization — the padded layout
    # is what the testcase specializes against (canonical static bounds
    # per bucket), while every lowering that addresses instances (fault
    # selectors, SLO scoping, reporting) works in the EXACT virtual
    # layout and is remapped or demuxed at the edges.
    bucket_plan = resolve_buckets(
        cfg,
        [g.instances for g in job.groups],
        mesh=(
            None
            if getattr(cfg, "coordinator_address", "")
            else _make_mesh(cfg.shard, getattr(cfg, "mesh", ""))
        ),
        warn=ow.warn,
    )
    if bucket_plan is not None:
        padded_in = [
            dataclasses.replace(g, instances=p)
            for g, p in zip(job.groups, bucket_plan.padded_counts)
        ]
    else:
        padded_in = job.groups
    # per-run static narrowing from resolved params (SimTestcase.specialize)
    testcase, groups = load_and_specialize(
        artifact, job.test_case, padded_in, cfg.tick_ms
    )
    if (
        bucket_plan is not None
        and "filter_rules" in type(testcase).SHAPING
        and len(groups) > 1
    ):
        ow.warn(
            "sim:jax %s: shape bucketing disabled — 'filter_rules' "
            "shaping with multiple groups addresses the exact layout "
            "(rule ranges cannot survive per-group padding); running "
            "exact shapes",
            job.run_id,
        )
        bucket_plan = None
        testcase, groups = load_and_specialize(
            artifact, job.test_case, job.groups, cfg.tick_ms
        )
    from .engine import build_groups as _build_groups

    # the EXACT layout every host-side surface reports in; identical to
    # ``groups`` when unbucketed
    vgroups = (
        _build_groups(job.groups) if bucket_plan is not None else groups
    )
    n = sum(g.count for g in vgroups)
    if bucket_plan is not None:
        ow.infof(
            "sim:jax %s: shape bucket — %s",
            job.run_id,
            bucket_plan.summary(),
        )
    hosts = _parse_hosts(getattr(cfg, "additional_hosts", None))

    # fault-injection plane (docs/FAULTS.md): lower the composition's
    # declared chaos schedule into static event tensors — a
    # program-shaping input like telemetry/validate, so it must be
    # resolved before construction, broadcast to cohort followers, and
    # keyed into the precompile cache. No declarations → None → the
    # engine compiles the identical pre-fault program.
    from .faults import build_fault_schedule

    fault_specs = fault_specs_of(
        job.groups, getattr(job, "faults", None)
    )
    # selectors resolve against the EXACT layout the operator declared;
    # under bucketing the lowered masks then scatter onto the padded
    # physical axis (dead pad lanes are never selected)
    fault_schedule = build_fault_schedule(vgroups, fault_specs, cfg.tick_ms)
    # network-topology plane: the static which-pairs-does-chaos-degrade
    # view (journal sim.net_matrix.faulted_pairs) reads the schedule in
    # the EXACT layout, so it is captured before any bucket remap
    # scatters the masks onto the padded physical axis
    nm_faulted = None
    if fault_schedule is not None and bool(getattr(cfg, "netmatrix", False)):
        from .netmatrix import faulted_pairs

        nm_faulted = faulted_pairs(fault_schedule, vgroups)
    if fault_schedule is not None and bucket_plan is not None:
        from .faults import remap_schedule

        fault_schedule = remap_schedule(
            fault_schedule, bucket_plan.index_map(), bucket_plan.padded_n
        )
    if fault_schedule is not None:
        ow.infof(
            "sim:jax %s: fault schedule armed — %s",
            job.run_id,
            fault_schedule.summary(),
        )

    # flight recorder (docs/OBSERVABILITY.md): lower the composition's
    # [run.trace] sampling tables into a static TracePlan — a
    # program-shaping input exactly like faults (the traced lanes bake
    # into the tick), so it resolves before construction, joins the
    # precompile BuildKey, and follows the telemetry plane's gating:
    # disable_metrics wins, and cohorts run trace-free (the per-chunk
    # leader-local block read is not symmetric across processes).
    from .trace import build_trace_plan

    trace_specs = trace_specs_of(job.groups, getattr(job, "trace", None))
    trace_plan = build_trace_plan(vgroups, trace_specs)
    if trace_plan is not None and job.disable_metrics:
        trace_plan = None
    if trace_plan is not None and bucket_plan is not None:
        ow.warn(
            "sim:jax %s: flight recorder disabled under shape bucketing "
            "(trace lanes are exact-layout selectors baked into the "
            "program; run with bucket=off to trace)",
            job.run_id,
        )
        trace_plan = None
    if trace_plan is not None and getattr(cfg, "coordinator_address", ""):
        ow.warn(
            "sim:jax %s: flight recorder disabled for the cohort config "
            "(per-chunk leader-local device reads are not symmetric "
            "across processes)",
            job.run_id,
        )
        trace_plan = None
    if trace_plan is not None:
        ow.infof(
            "sim:jax %s: flight recorder armed — %s",
            job.run_id,
            trace_plan.summary(),
        )

    # telemetry plane: the per-tick counter block is a PROGRAM-shaping
    # option (it changes the traced chunk), so it must be decided before
    # construction and broadcast to cohort followers. The composition's
    # disable_metrics opt-out (the TEST_DISABLE_METRICS analog) wins over
    # the runner config — same rule as spans and timeseries sampling.
    # Disabled for ANY cohort config (coordinator_address set, even
    # degenerate single-process ones): a leader-local per-chunk read of
    # the block is not symmetric across processes, and the gate must be
    # decidable STATICALLY so the sim:plan precompile warms the same
    # program variant the run traces (sim_plan.py mirrors this rule).
    telemetry_on = (
        bool(getattr(cfg, "telemetry", False)) and not job.disable_metrics
    )
    if telemetry_on and getattr(cfg, "coordinator_address", ""):
        ow.warn(
            "sim:jax %s: telemetry disabled for the cohort config "
            "(per-chunk leader-local device reads are not symmetric "
            "across processes)",
            job.run_id,
        )
        telemetry_on = False
    # network topology plane: same gating discipline as telemetry (it
    # IS a telemetry surface — the matrix rides the telemetry chunk
    # flush). Cohorts silently shed it with the rest of the telemetry
    # plane; a netmatrix request WITHOUT telemetry is refused loudly
    # (shared message with the static checker, rule
    # netmatrix.needs-telemetry) rather than silently unhonored.
    netmatrix_on = bool(getattr(cfg, "netmatrix", False))
    if netmatrix_on and getattr(cfg, "coordinator_address", ""):
        ow.warn(
            "sim:jax %s: traffic matrix disabled for the cohort config "
            "(it rides the telemetry plane, which cohorts run without)",
            job.run_id,
        )
        netmatrix_on = False
    if netmatrix_on and not telemetry_on:
        from .check import netmatrix_requires_telemetry_message

        raise ValueError(
            netmatrix_requires_telemetry_message(job.disable_metrics)
        )
    # run health plane (docs/OBSERVABILITY.md "Run health plane"): lower
    # the composition's [[run.slo]] tables into a static SloPlan. NOT a
    # program-shaping option — evaluation is host-side over the chunk
    # blocks the loop already flushes (jaxpr-identical with and without
    # SLOs, pinned by tests) — but every metric derives from the
    # telemetry plane, so rules without telemetry are refused loudly
    # rather than silently unenforced.
    from .slo import build_slo_plan

    slo_specs = slo_specs_of(job.groups, getattr(job, "slo", None))
    slo_plan = build_slo_plan(vgroups, slo_specs)
    if slo_plan is not None and getattr(cfg, "coordinator_address", ""):
        ow.warn(
            "sim:jax %s: SLO assertions disabled for the cohort config "
            "(the telemetry plane they evaluate is leader-local and runs "
            "off under a cohort)",
            job.run_id,
        )
        slo_plan = None
    if slo_plan is not None and not telemetry_on:
        # shared with the static checker (sim/check.py rule
        # slo.needs-telemetry) so `tg check` reports the byte-identical
        # refusal before anything queues
        from .check import slo_requires_telemetry_message

        raise ValueError(
            slo_requires_telemetry_message(
                slo_plan.count, job.disable_metrics
            )
        )
    if slo_plan is not None:
        ow.infof(
            "sim:jax %s: run health plane armed — %s",
            job.run_id,
            slo_plan.summary(),
        )

    if bool(getattr(cfg, "nan_guard", False)) and getattr(
        cfg, "coordinator_address", ""
    ):
        ow.warn(
            "sim:jax %s: nan_guard disabled for the cohort config "
            "(a leader-local read of the cross-process-sharded carry "
            "is not symmetric, and raises on non-addressable shards)",
            job.run_id,
        )

    # ------------------------------------------------- multi-host cohort
    if multi:
        from .distributed import (
            broadcast_json,
            cohort_agree,
            global_mesh,
        )

        import jax

        mesh = global_mesh()  # cfg.shard has no meaning across a cohort
        ow.infof(
            "multi-host: %d processes, %d global devices, leader=%d",
            jax.process_count(),
            mesh.devices.size,
            jax.process_index(),
        )
        # transport gate precedes the broadcast: followers must compile
        # the POST-gate variant (a cohort mesh always forces xla, so
        # auto resolves before it ever reaches a follower)
        transport_decision = _decide_transport_for(
            job, cfg, mesh, testcase, groups, hosts, telemetry_on, ow
        )
        transport = transport_decision.resolved
        # followers compile the identical program from this spec
        broadcast_json(
            _cohort_job_spec(
                job,
                cfg,
                hosts=hosts,
                telemetry=telemetry_on,
                transport=transport,
                faults=fault_specs,
            )
        )
        # readiness vote: a worker whose plans dir cannot satisfy the job
        # votes False and everyone skips in lockstep (a worker dying
        # mid-program would strand the cohort inside a collective)
        if not cohort_agree(True):
            raise RuntimeError(
                "a cohort member cannot satisfy this job (missing or "
                "stale plan sources on a worker host) — run aborted "
                "before any program collective"
            )
    else:
        mesh = _make_mesh(cfg.shard, getattr(cfg, "mesh", ""))
        transport_decision = _decide_transport_for(
            job, cfg, mesh, testcase, groups, hosts, telemetry_on, ow
        )
        transport = transport_decision.resolved
    if transport != "xla" or transport_decision.requested != "xla":
        ow.infof(
            "sim:jax %s: transport %s -> %s (%s)",
            job.run_id,
            transport_decision.requested,
            transport,
            transport_decision.reason,
        )
    ow.infof(
        "sim:jax run %s: plan=%s case=%s instances=%d groups=%d "
        "tick=%.3fms devices=%s",
        job.run_id,
        job.test_plan,
        job.test_case,
        n,
        len(groups),
        cfg.tick_ms,
        mesh.devices.size if mesh is not None else 1,
    )
    if hosts:
        ow.infof("additional hosts: %s", ",".join(hosts))

    prog = make_sim_program(
        testcase,
        groups,
        test_plan=job.test_plan,
        test_case=job.test_case,
        test_run=job.run_id,
        tick_ms=cfg.tick_ms,
        mesh=mesh,
        chunk=cfg.chunk,
        hosts=hosts,
        validate=bool(getattr(cfg, "validate", False)),
        telemetry=telemetry_on,
        faults=fault_schedule,
        trace=trace_plan,
        transport=transport,
        live_counts=(
            bucket_plan.live_counts if bucket_plan is not None else None
        ),
        netmatrix=netmatrix_on,
    )
    _precheck_device_memory(prog, cfg, mesh, ow)
    # the device-resident carry footprint is ALWAYS part of the run
    # record (log + journal + results), not just of the capacity check
    carry_bytes = prog.estimate_carry_bytes()
    ow.infof(
        "sim:jax %s: device carry footprint %.2f MiB (%d bytes, "
        "eval_shape-exact)",
        job.run_id,
        carry_bytes / 2**20,
        carry_bytes,
    )
    spans.end("build", carry_bytes=carry_bytes, instances=n)

    # ------------------------------------------- checkpoint/resume plane
    # (docs/CHECKPOINT.md) NOT program-shaping: the prog above is
    # already final, and checkpoint_chunks=0 leaves this whole block
    # inert (zero-overhead pin in tests/test_sim_checkpoint.py). The
    # identity dict is what a snapshot manifest validates against on
    # resume — everything that shapes the compiled program or the
    # deterministic tick stream, plus the plan-source digests.
    ckpt_every = int(getattr(cfg, "checkpoint_chunks", 0) or 0)
    resume_from = str(getattr(cfg, "resume_from", "") or "")
    if resume_from and getattr(cfg, "coordinator_address", ""):
        # shared with the static checker (sim/check.py rule
        # checkpoint.resume-cohort)
        from .check import resume_cohort_message

        raise ValueError(resume_cohort_message())
    if ckpt_every > 0 and getattr(cfg, "coordinator_address", ""):
        ow.warn(
            "sim:jax %s: checkpointing disabled for the cohort config "
            "(a leader-local read of the cross-process-sharded carry "
            "is not symmetric)",
            job.run_id,
        )
        ckpt_every = 0
    if resume_from and run_dir is None:
        raise ValueError(
            "resume_from requires a run outputs dir (no env attached "
            "to this run input)"
        )
    if ckpt_every > 0 and run_dir is None:
        ow.warn(
            "sim:jax %s: checkpointing disabled — no run outputs dir "
            "to hold snapshots",
            job.run_id,
        )
        ckpt_every = 0
    resume_state = None
    resume_info = None
    identity = None
    if ckpt_every > 0 or resume_from:
        from .checkpoint import (
            list_snapshots,
            prepare_resume,
            run_identity,
        )

        identity = run_identity(
            job,
            cfg,
            telemetry=telemetry_on,
            transport=transport,
            fault_specs=fault_specs,
            # post-gate: a trace plan nulled by disable_metrics/cohort
            # shapes nothing, so it must not key the identity either
            trace_specs=trace_specs if trace_plan is not None else {},
            hosts=hosts,
            # the padded layout shapes every carry leaf — a snapshot
            # from one bucket must refuse to seed another (keyed only
            # when bucketed, so pre-bucket snapshots keep resuming)
            bucket=(
                bucket_plan.padded_counts
                if bucket_plan is not None
                else None
            ),
            netmatrix=netmatrix_on,
        )
        source_run = None
        own_snaps = list_snapshots(run_dir) if run_dir is not None else []
        if resume_from:
            src_dir = os.path.join(outputs_root, job.test_plan, resume_from)
            src_snaps = (
                list_snapshots(src_dir) if os.path.isdir(src_dir) else []
            )
            # A restarted resume run prefers its OWN newest progress: a
            # daemon restart mid-resume rehydrates this task with
            # resume_from still set, and rolling back to the (older)
            # source snapshot would discard every tick this run already
            # re-earned — and the cross-run stream copy would overwrite
            # its own stream files with the source's shorter prefix.
            if own_snaps and (
                not src_snaps or own_snaps[-1][0] >= src_snaps[-1][0]
            ):
                resume_state = prepare_resume(run_dir, run_dir, identity)
                source_run = job.run_id
            else:
                if not src_snaps:
                    from .checkpoint import CheckpointError

                    raise CheckpointError(
                        f"no snapshots for {resume_from!r} under "
                        f"{os.path.join(outputs_root, job.test_plan)} — "
                        "nothing to resume from"
                    )
                resume_state = prepare_resume(src_dir, run_dir, identity)
                source_run = resume_from
        elif ckpt_every > 0 and own_snaps:
            # engine-side auto-resume: an interrupted task rehydrated
            # from the queue after a daemon restart re-runs under the
            # SAME id, so its run dir already holds its own snapshots —
            # continue instead of replaying from tick 0
            resume_state = prepare_resume(run_dir, run_dir, identity)
            source_run = job.run_id
        if resume_state is not None:
            resume_info = {
                "from_tick": resume_state.tick,
                "from_run": source_run,
                "snapshot": os.path.basename(resume_state.path),
            }
            fb = resume_state.manifest.get("_fallback")
            if fb:
                # loud fallback (sim/checkpoint.py load_latest): newer
                # retained snapshot(s) were unloadable — the resume
                # continues from an older tick, and says so everywhere
                resume_info["fallback"] = dict(fb)
                ow.warn(
                    "sim:jax %s: newest snapshot(s) unloadable (%s) — "
                    "falling back to %s: %s",
                    job.run_id,
                    ", ".join(fb.get("skipped", [])),
                    resume_info["snapshot"],
                    fb.get("error", ""),
                )
            ow.infof(
                "sim:jax %s: resuming from snapshot %s (tick %d, run %s)",
                job.run_id,
                resume_info["snapshot"],
                resume_state.tick,
                resume_info["from_run"],
            )
            spans.point(
                "resume",
                **{k: v for k, v in resume_info.items() if k != "fallback"},
                fallback_skipped=len((fb or {}).get("skipped", [])),
            )

    # duration math runs on the monotonic clock (a wall-clock step —
    # NTP slew, operator date change — must not produce negative chunk
    # timings or a wrong run wall); the wall-clock anchor survives only
    # where a real timestamp is needed (the Influx base_ns)
    t0_wall = time.time()
    t0 = time.monotonic()
    last_report = [t0]

    # bounded SLO warn lines: the first breach of each rule (and every
    # fail) reaches the task log; the full record stream is the jsonl
    slo_warned: set[str] = set()

    def on_chunk(ticks: int) -> None:
        spans.point(
            "chunk", ticks=ticks, wall_secs=round(time.monotonic() - t0, 6)
        )
        if chunk_profiler is not None:
            # bounded profiler capture: start after the warmup dispatch,
            # stop once the configured chunk window is in the trace
            chunk_profiler.on_chunk(ticks)
        if slo_eval is not None:
            # evaluate AFTER the loop delivered this chunk's telemetry
            # rows and latency delta (telemetry_cb/lat_hist_cb run
            # before on_chunk in SimProgram.run)
            for breach in slo_eval.evaluate():
                first = breach["rule"] not in slo_warned
                slo_warned.add(breach["rule"])
                if first or breach["severity"] == "fail":
                    spans.point("slo_breach", **breach)
                    ow.warn(
                        "sim:jax %s: SLO breach (%s): %s — %s = %g "
                        "violates %s %g at tick %d%s",
                        job.run_id,
                        breach["severity"],
                        breach["rule"],
                        breach["metric"],
                        breach["observed"],
                        breach["op"],
                        breach["threshold"],
                        breach["tick"],
                        " — canceling the run"
                        if breach["severity"] == "fail"
                        else "",
                    )
        now = time.monotonic()
        if now - last_report[0] >= 5.0:
            last_report[0] = now
            ow.infof(
                "sim:jax %s: %d ticks (%.1f sim-s) in %.1fs wall",
                job.run_id,
                ticks,
                ticks * cfg.tick_ms / 1000.0,
                now - t0,
            )
    # no outputs dir → nowhere to persist samples; disable_metrics is the
    # composition's opt-out (the TEST_DISABLE_METRICS analog) — either way
    # the hot loop must not pay the per-sample device→host sync. Multi-host
    # runs also disable sampling: a leader-local mid-run device read of a
    # cross-host-sharded carry is not symmetric across the cohort.
    ts_enabled = (
        outputs_root is not None and not job.disable_metrics and not multi
    )
    recorder = _TimeSeriesRecorder(
        testcase,
        vgroups,
        getattr(cfg, "timeseries_every", 0) if ts_enabled else 0,
        ow,
        # bucketed carries are padded: mid-run samples slice each
        # group's live span out of the physical layout first
        phys_groups=groups if bucket_plan is not None else None,
    )
    # Per-tick telemetry sink: blocks arrive once per chunk from the
    # jitted program (engine telemetry_cb) and stream straight to the
    # run's series file — memory stays bounded by one chunk and a
    # crashed run keeps everything flushed so far.
    row_ident = {
        "run": job.run_id,
        "plan": job.test_plan,
        "case": job.test_case,
    }
    resume_aux = resume_state.aux if resume_state is not None else {}
    tele_writer = (
        _SimTelemetryWriter(
            tuple(g.id for g in groups),
            row_ident,
            os.path.join(run_dir, SIM_SERIES_FILE)
            if run_dir is not None
            else None,
            # resumed runs APPEND past the snapshot's truncated prefix
            # (prepare_resume aligned the file to the snapshot tick) so
            # the series stays contiguous from tick 0
            append=resume_state is not None,
            rows_offset=int(resume_aux.get("telemetry_rows", 0) or 0),
        )
        if telemetry_on
        else None
    )
    # Traffic-matrix sink (network topology plane): per-chunk sparse
    # cell deltas stream to sim_netmatrix.jsonl as they arrive — the
    # delta arrays are the ones the run loop already read for its own
    # accumulator, so the writer adds no device traffic.
    netmatrix_writer = (
        _SimNetMatrixWriter(
            prog,
            row_ident,
            os.path.join(run_dir, NETMATRIX_FILE)
            if run_dir is not None
            else None,
            append=resume_state is not None,
            chunks_offset=int(resume_aux.get("netmatrix_chunks", 0) or 0),
        )
        if netmatrix_on
        else None
    )
    # Flight-recorder sink: per-chunk [chunk, R, 5] event blocks stream
    # to sim_trace.jsonl as they arrive; a bounded buffer (the plan's
    # ``events`` cap) feeds the Chrome trace export written at close.
    trace_writer = (
        _SimTraceWriter(
            groups,
            row_ident,
            run_dir,
            cfg.tick_ms,
            trace_plan,
            # resumed runs re-read the truncated jsonl prefix into the
            # Chrome-export buffer and append new events after it
            resume=resume_aux.get("trace") if resume_state else None,
        )
        if trace_plan is not None
        else None
    )
    # Run health plane evaluator: fed per chunk from the decoded
    # telemetry rows and the latency-histogram deltas the loop already
    # reads; breach records stream to sim_slo.jsonl as they fire. A
    # fail-severity breach sets the run-LOCAL cancel (the task event
    # stays untouched — see _SloRunCancel).
    slo_eval = None
    slo_cancel = None
    if slo_plan is not None:
        from .slo import SLO_FILE, SloEvaluator

        slo_cancel = _SloRunCancel(cancel)
        slo_eval = SloEvaluator(
            slo_plan,
            vgroups,
            cfg.tick_ms,
            cfg.chunk,
            ident=row_ident,
            path=(
                os.path.join(run_dir, SLO_FILE)
                if run_dir is not None
                else None
            ),
            cancel=slo_cancel.run_local,
            append=resume_state is not None,
        )
        if resume_state is not None and resume_aux.get("slo"):
            # windowed rules continue from the snapshot's ring/cums —
            # a resumed evaluation must judge the same history an
            # uninterrupted run would
            slo_eval.load_state(resume_aux["slo"])
    # Performance ledger (docs/OBSERVABILITY.md "Performance ledger"):
    # host-side only — the program is untouched — so the gate is NOT
    # program-shaping; it still follows the telemetry plane's rules
    # (disable_metrics wins, cohorts run ledger-free: the per-chunk
    # walls and AOT pass are leader-local and would skew under a
    # cohort's lockstep dispatches).
    perf_on = (
        bool(getattr(cfg, "perf", True))
        and not job.disable_metrics
        and not getattr(cfg, "coordinator_address", "")
    )
    perf_ledger = None
    if perf_on:
        from .perf import PERF_FILE, PerfLedger

        perf_ledger = PerfLedger(
            n,
            cfg.chunk,
            ident=row_ident,
            path=(
                os.path.join(run_dir, PERF_FILE)
                if run_dir is not None
                else None
            ),
            # without the persistent cache the AOT pass would pay a full
            # second XLA compile — skip it and keep only the gauges
            aot=compile_cache_on,
            # on a mesh the second dispatch retraces at the GSPMD
            # sharding fixed point (engine.run) — keep it out of the
            # steady_* throughput window
            warmup=(
                2
                if mesh is not None and int(mesh.devices.size) > 1
                else 1
            ),
            # per-backend ledger tag: every sim_perf.jsonl row and the
            # journal sim.perf block name the transport, so A/B runs
            # (`tg perf --compare`, bench) are never cross-attributed
            transport=transport,
            # padded-bucket annotation — peer·ticks/s above divide by
            # the exact live N, never the bucket size
            bucket=(
                bucket_plan.padded_n if bucket_plan is not None else None
            ),
        )
    # Profile capture — the pprof analog (``pkg/api/composition.go:153-162``
    # → TestCaptureProfiles): any group requesting profiles — or the
    # runner-config ``profile`` flag — makes the run record a
    # jax.profiler trace (XLA ops + host timeline, viewable in
    # TensorBoard/Perfetto) into the run's outputs dir.
    profile_dir = None
    chunk_profiler = None
    if run_dir is not None and (
        any(g.profiles for g in job.groups)
        or bool(getattr(cfg, "profile", False))
    ):
        profile_dir = os.path.join(run_dir, "profiles")
        os.makedirs(profile_dir, exist_ok=True)
        # bounded capture (profile_chunks=N): only the first N chunks
        # after the warmup dispatch are traced — a million-tick soak
        # under whole-run capture writes a multi-GB trace, and the
        # steady-state chunks N captures are the ones the phase table
        # points at (chunk 0 is compile + trace, not steady state)
        n_prof_chunks = int(getattr(cfg, "profile_chunks", 0) or 0)
        if n_prof_chunks > 0:
            chunk_profiler = _ChunkedProfiler(profile_dir, n_prof_chunks)
            ow.infof(
                "capturing jax.profiler trace to %s (%d chunk(s) after "
                "warmup)",
                profile_dir,
                n_prof_chunks,
            )
        else:
            ow.infof("capturing jax.profiler trace to %s", profile_dir)

    if multi:
        # cancellation must be a cohort decision: the leader's local event
        # state is broadcast once per chunk so every process stops (or
        # continues) in lockstep — see distributed.CohortCancel
        from .distributed import CohortCancel

        run_cancel = CohortCancel(cancel)
    elif slo_cancel is not None:
        # the SLO fail path cancels the RUN (chunk loop) without setting
        # the task-level event — see _SloRunCancel
        run_cancel = slo_cancel
    else:
        run_cancel = cancel

    # Fleet-controller preemption (docs/FLEET.md): the supervisor arms
    # job.preempt for solo single-[[runs]] RUN tasks; when it fires the
    # loop stops at the next chunk boundary and the tail raises
    # TaskPreemptedError so the task requeues instead of archiving.
    # Not armed under a cohort — checkpointing is disabled there, and
    # cancellation must stay a lockstep cohort decision (CohortCancel).
    preempt_ev = getattr(job, "preempt", None)
    if multi:
        preempt_ev = None
    if preempt_ev is not None:
        run_cancel = _PreemptRunCancel(run_cancel, preempt_ev)

    def on_stall(last_tick: int, chunk_index: int) -> None:
        # the stall diagnostic must outlive the failing run: a span
        # point in run_spans.jsonl plus a task-log line, both carrying
        # the last completed tick and the chunk that wedged
        spans.point(
            "stall",
            last_tick=last_tick,
            chunk_index=chunk_index,
            timeout_secs=float(getattr(cfg, "chunk_timeout_secs", 0.0)),
        )
        ow.warn(
            "sim:jax %s: chunk %d stalled past the %.1fs wall-clock "
            "watchdog (last completed tick %d) — canceling the run",
            job.run_id,
            chunk_index,
            float(getattr(cfg, "chunk_timeout_secs", 0.0)),
            last_tick,
        )

    # the telemetry writer decodes each chunk's rows anyway; when the
    # run health plane is armed the same decoded rows feed the evaluator
    # (one decode, two consumers — no second pass over the block)
    if slo_eval is not None:

        def _tele_cb(block):
            slo_eval.on_rows(tele_writer.on_block(block))

    else:
        _tele_cb = tele_writer.on_block if tele_writer else None

    # ---------------------------------------------- checkpoint write side
    # (docs/CHECKPOINT.md) rides the chunk loop's observer hook (fires
    # AFTER the chunk's telemetry/trace/SLO callbacks, so the stream
    # offsets it records are flush-exact); inert at checkpoint_chunks=0.
    checkpointer = None
    if ckpt_every > 0:
        from .checkpoint import RunCheckpointer
        from .slo import SLO_FILE as _SLO_FILE
        from .trace import TRACE_FILE as _TRACE_FILE

        def _ckpt_aux() -> dict:
            """Host-side continuation state beside the carry: stream-
            file byte offsets (for truncate/copy on resume), writer
            counters, the SLO evaluator's windows, and the metric
            recorder's sampled rows — everything a resumed run needs to
            be leaf-for-leaf an uninterrupted one."""
            aux: dict = {}
            streams: dict = {}
            if tele_writer is not None:
                aux["telemetry_rows"] = tele_writer.rows_written
                if tele_writer.path is not None:
                    try:
                        streams[SIM_SERIES_FILE] = os.path.getsize(
                            tele_writer.path
                        )
                    except OSError:
                        pass
            if slo_eval is not None:
                aux["slo"] = slo_eval.state_dict()
                if slo_eval.path is not None:
                    try:
                        streams[_SLO_FILE] = os.path.getsize(slo_eval.path)
                    except OSError:
                        pass
            if trace_writer is not None:
                aux["trace"] = {
                    "events": trace_writer.events_written,
                    "truncated": trace_writer.truncated,
                }
                if trace_writer.path is not None:
                    try:
                        streams[_TRACE_FILE] = os.path.getsize(
                            trace_writer.path
                        )
                    except OSError:
                        pass
            if netmatrix_writer is not None:
                aux["netmatrix_chunks"] = netmatrix_writer.chunks_written
                if netmatrix_writer.path is not None:
                    try:
                        streams[NETMATRIX_FILE] = os.path.getsize(
                            netmatrix_writer.path
                        )
                    except OSError:
                        pass
            if recorder.enabled:
                aux["recorder"] = recorder.state_dict()
            aux["streams"] = streams
            return aux

        checkpointer = RunCheckpointer(
            run_dir,
            every_chunks=ckpt_every,
            keep=int(getattr(cfg, "checkpoint_keep", 3) or 3),
            chunk=cfg.chunk,
            identity=identity,
            ident=row_ident,
            aux_cb=_ckpt_aux,
            spans=spans,
            warn=ow.warn,
            telemetry=telemetry_on,
            resumed_from=resume_info,
        )
        ow.infof(
            "sim:jax %s: checkpointing every %d chunk(s) (%d ticks), "
            "keeping newest %d",
            job.run_id,
            ckpt_every,
            ckpt_every * cfg.chunk,
            checkpointer.keep,
        )

    # restore the host-side continuation state captured in the snapshot
    resume_carry = None
    if resume_state is not None:
        from .checkpoint import restore_carry

        if recorder.enabled and resume_aux.get("recorder"):
            recorder.load_state(resume_aux["recorder"])
        if checkpointer is not None and resume_state.lat_hist is not None:
            checkpointer.seed_lat_hist(resume_state.lat_hist)
        if checkpointer is not None and resume_state.net_matrix is not None:
            checkpointer.seed_net_matrix(resume_state.net_matrix)
        resume_carry = restore_carry(
            prog, cfg.seed, resume_state.manifest, resume_state.leaves
        )

    # compose the per-chunk observer / latency-delta consumers: the
    # checkpoint plane shares both hooks without disturbing the
    # recorder or the run health plane
    _observers = [
        o
        for o in (
            recorder.observe if recorder.enabled else None,
            checkpointer.observe if checkpointer is not None else None,
        )
        if o is not None
    ]
    if preempt_ev is not None and checkpointer is not None:
        # live migration's snapshot-at-the-stopping-boundary: the
        # observer fires BEFORE the loop's cancel check (sim/engine.py
        # chunk loop), so when preemption lands the forced snapshot and
        # the stop happen at the SAME boundary — the resumed run
        # replays nothing. Runs after the periodic checkpointer.observe
        # above, whose write (if this boundary was a K-th one) makes
        # last_tick == ticks and skips the duplicate.

        def _preempt_observe(ticks, carry):
            if (
                preempt_ev.is_set()
                and checkpointer.last_tick != int(ticks)
            ):
                checkpointer.snapshot(int(ticks), carry)

        _observers.append(_preempt_observe)
    if not _observers:
        _observer = None
    elif len(_observers) == 1:
        _observer = _observers[0]
    else:

        def _observer(ticks, carry):
            for o in _observers:
                o(ticks, carry)

    _lat_cbs = [
        cb
        for cb in (
            slo_eval.on_lat_delta if slo_eval else None,
            checkpointer.on_lat_delta if checkpointer is not None else None,
        )
        if cb is not None
    ]
    if not _lat_cbs:
        _lat_cb = None
    elif len(_lat_cbs) == 1:
        _lat_cb = _lat_cbs[0]
    else:

        def _lat_cb(delta):
            for cb in _lat_cbs:
                cb(delta)

    _nm_cbs = [
        cb
        for cb in (
            netmatrix_writer.on_delta if netmatrix_writer else None,
            (
                checkpointer.on_net_matrix_delta
                if checkpointer is not None and netmatrix_on
                else None
            ),
        )
        if cb is not None
    ]
    if not _nm_cbs:
        _nm_cb = None
    elif len(_nm_cbs) == 1:
        _nm_cb = _nm_cbs[0]
    else:

        def _nm_cb(delta):
            for cb in _nm_cbs:
                cb(delta)

    def _run():
        return prog.run(
            seed=cfg.seed,
            max_ticks=cfg.max_ticks,
            cancel=run_cancel,
            on_chunk=on_chunk,
            observer=_observer,
            telemetry_cb=_tele_cb,
            lat_hist_cb=_lat_cb,
            trace_cb=trace_writer.on_block if trace_writer else None,
            netmatrix_cb=_nm_cb,
            chunk_timeout=float(getattr(cfg, "chunk_timeout_secs", 0.0)),
            chunk_sleep_ms=float(getattr(cfg, "debug_chunk_sleep_ms", 0.0)),
            on_stall=on_stall,
            # same rule as telemetry: a leader-local full-carry read is
            # not symmetric across a cohort (and np.asarray on a
            # cross-process-sharded leaf raises outright), so the guard
            # is single-process only
            nan_guard=bool(getattr(cfg, "nan_guard", False)) and not multi,
            perf=perf_ledger,
            resume_carry=resume_carry,
            resume_ticks=resume_state.tick if resume_state else 0,
            lat_hist_init=(
                resume_state.lat_hist if resume_state is not None else None
            ),
            net_mat_init=(
                resume_state.net_matrix
                if resume_state is not None
                else None
            ),
        )

    spans.start("execute")
    # persistent-cache traffic around the run classifies whether the
    # (bucketed) program was served warm — the bucket hit/miss signal
    from testground_tpu.utils.compile_cache import cache_event_counts

    cache_before = cache_event_counts()
    if profile_dir is not None and chunk_profiler is None:
        import jax

        with jax.profiler.trace(profile_dir):
            res = _run()
    else:
        try:
            res = _run()
        finally:
            # a run finishing (or failing) inside the capture window
            # must still close the trace — an unterminated profiler
            # session would poison the next run in this process
            if chunk_profiler is not None:
                chunk_profiler.close()
    wall = time.monotonic() - t0
    spans.point("compile", wall_secs=round(res.get("compile_secs", 0.0), 6))
    spans.end("execute", ticks=res["ticks"])
    status = res["status"]
    # ------------------------------------------------- bucket journal
    # bucketed results are already demuxed to the EXACT layout
    # (SimProgram.results) — every reporting surface below works in it
    bucket_block = None
    if bucket_plan is not None:
        groups = res["groups"]
        hits_delta = (
            cache_event_counts()["hits"] - cache_before["hits"]
        )
        if not compile_cache_on:
            cache_verdict = "off"
        elif resume_state is not None:
            # a resumed run skips the init compile — the delta is not a
            # clean signal for the chunk program alone
            cache_verdict = "unknown"
        else:
            cache_verdict = "hit" if hits_delta > 0 else "miss"
        bucket_block = {
            "instances": bucket_plan.live_n,
            "padded_instances": bucket_plan.padded_n,
            "dead_lanes": bucket_plan.padded_n - bucket_plan.live_n,
            "per_group": {
                g.id: {"live": lv, "padded": pv}
                for g, lv, pv in zip(
                    vgroups,
                    bucket_plan.live_counts,
                    bucket_plan.padded_counts,
                )
            },
            # "hit" = the persistent cache served this bucket's program
            # (zero cold compiles — what `tg build --buckets` warms);
            # "miss" = a cold compile paid in production, observable
            # here and via tg_compile_bucket_miss_total instead of
            # silent
            "compile_cache": cache_verdict,
        }
        ow.infof(
            "sim:jax %s: bucket %d (live %d) — compile cache %s",
            job.run_id,
            bucket_plan.padded_n,
            bucket_plan.live_n,
            cache_verdict,
        )
    ow.infof(
        "sim:jax %s: done — %d ticks in %.2fs wall (%.0f instance·ticks/s)",
        job.run_id,
        res["ticks"],
        wall,
        n * res["ticks"] / max(wall, 1e-9),
    )
    if fault_schedule is not None:
        ow.infof(
            "sim:jax %s: fault plane — crashed=%d restarted=%d "
            "fault_dropped=%d message(s)",
            job.run_id,
            res.get("faults_crashed", 0),
            res.get("faults_restarted", 0),
            res.get("fault_dropped", 0),
        )
    if res.get("collisions", 0) > 0:
        # a direct-mode contract violation under validate: fail the run
        # naming the collision (the data is corrupt — do not report
        # plan-level outcomes computed from it)
        c_dst, c_slot = res.get("collision_where", [0, 0])
        raise RuntimeError(
            f"direct slot-mode collision: {res['collisions']} conflicting "
            f"writes detected (first at receiver {c_dst}, inbox slot "
            f"{c_slot}) — the plan violates the ≤1 sender per (receiver, "
            "slot, tick) contract; use SLOT_MODE='sorted' or fix the "
            "traffic pattern"
        )
    if res.get("bw_rate_change_backlogged", 0) > 0:
        # informational, not fatal: the HTB queue-occupancy BOUND (tail-
        # drop point) is approximate across these events — pacing and
        # FIFO order remain exact (see net.py bandwidth_queue notes and
        # tests/test_transport_fuzz.py rate-change cases)
        ow.warn(
            "sim:jax %s: bandwidth changed under a standing egress "
            "backlog %d time(s) — the bandwidth_queue occupancy bound "
            "values standing busy time at the current rate, so tail-drop "
            "thresholds around those ticks are approximate (pacing and "
            "FIFO order are unaffected)",
            job.run_id,
            res["bw_rate_change_backlogged"],
        )
    if res.get("latency_clamped", 0) > 0:
        # netem never silently shortens a configured delay — surface the
        # clamp in the task log AND the journal (link.go:169-179 parity)
        ow.warn(
            "sim:jax %s: %d deliveries exceeded the calendar horizon and "
            "were clamped to MAX_LINK_TICKS-1 — a shaped latency/jitter/"
            "backlog does not fit the calendar; raise MAX_LINK_TICKS "
            "(results arrive EARLIER than configured)",
            job.run_id,
            res["latency_clamped"],
        )

    # ------------------------------------------------ outcomes + outputs
    spans.start("collect")
    result = Result.for_input(job)
    result.journal["events"] = {}
    write_outputs = (
        outputs_root is not None and n <= cfg.write_outputs_max
    )
    if outputs_root is not None and not write_outputs:
        # loud, in both the task log and the journal — per-instance dirs
        # are skipped above the cap, but the per-group aggregates below
        # still capture every metric
        ow.warn(
            "sim:jax %s: %d instances > write_outputs_max=%d — skipping "
            "per-instance output dirs (group metric aggregates are in the "
            "journal)",
            job.run_id,
            n,
            cfg.write_outputs_max,
        )
        result.journal["outputs_skipped"] = {
            "instances": n,
            "write_outputs_max": cfg.write_outputs_max,
        }

    metrics = {}
    collect = getattr(testcase, "collect_metrics", None)
    if callable(collect):
        for gi, g in enumerate(groups):
            try:
                metrics[g.id] = collect(
                    g,
                    _tree_slice(res["states"][gi]),
                    status[g.offset : g.offset + g.count],
                )
            except Exception as e:  # noqa: BLE001 — metrics are best-effort
                ow.warn("collect_metrics failed for group %s: %s", g.id, e)
    if metrics:
        result.journal["metrics"] = {
            gid: _aggregate_metrics(m) for gid, m in metrics.items()
        }

    # ------------------------------------------- sim telemetry time series
    # per-tick counter rows were streamed chunk-wise into
    # sim_timeseries.jsonl during the run; totals in the journal must
    # equal the rows' sums (conservation — asserted by tests/the smoke
    # target)
    if tele_writer is not None:
        tele_writer.close()
        result.journal["telemetry"] = {
            "rows": tele_writer.rows_written,
            # only claim the series file when one was actually written
            # (no outputs dir → rows were only counted)
            **(
                {"file": SIM_SERIES_FILE}
                if tele_writer.path is not None
                else {}
            ),
            "totals": {
                "delivered": res["msgs_delivered"],
                "sent": res["msgs_sent"],
                "enqueued": res["msgs_enqueued"],
                "dropped": res["msgs_dropped"],
                "rejected": res["msgs_rejected"],
                "in_flight": res["cal_depth"],
                "fault_dropped": res.get("fault_dropped", 0),
            },
        }

    # ------------------------------------------- network topology plane
    # journaled under sim.net_matrix (sim/netmatrix.py): the [G(+hosts)]²
    # traffic matrix accumulated on device, its EXACT conservation
    # verdict against the flow totals, the bounded top-K pair view (the
    # same contract the tg_net_pair_* gauges export — never raw G²),
    # link-shaping observables, and the static faulted-window pair
    # counts. A non-empty ``mismatches`` list is an engine bug: recorded
    # loudly in the journal and the task log, never papered over.
    net_matrix_block = None
    if netmatrix_writer is not None:
        netmatrix_writer.close()
    if netmatrix_on and res.get("net_matrix") is not None:
        from . import netmatrix as _netmatrix

        nm_mat = np.asarray(res["net_matrix"], np.int64)
        nm_labels = [g.id for g in groups]
        if nm_mat.shape[1] > len(nm_labels):
            nm_labels.append("hosts")
        nm_pairs, nm_elided = _netmatrix.top_pairs(nm_mat, 16)
        nm_mismatches = _netmatrix.reconcile(nm_mat, res)
        if nm_mismatches:
            ow.warn(
                "sim:jax %s: traffic matrix failed conservation — %s",
                job.run_id,
                "; ".join(nm_mismatches),
            )
        net_matrix_block = {
            "labels": nm_labels,
            "matrix": nm_mat.tolist(),
            "totals": _netmatrix.matrix_totals(nm_mat),
            "bytes_total": int(_netmatrix.matrix_bytes(nm_mat).sum()),
            "top_pairs": nm_pairs,
            "elided_pairs": nm_elided,
            "mismatches": nm_mismatches,
            # per-src-group bandwidth-queue depth high-water (messages)
            # — present only when the plan shapes with bandwidth_queue
            **(
                {"bw_queue_hiwater": res["net_bw_hiwater"]}
                if res.get("net_bw_hiwater") is not None
                else {}
            ),
            # which group pairs the declared chaos schedule degrades
            # (drop/loss windows covering the pair) — static view,
            # computed from the lowered schedule in the exact layout
            **(
                {"faulted_pairs": nm_faulted.tolist()}
                if nm_faulted is not None
                else {}
            ),
            **(
                {
                    "file": NETMATRIX_FILE,
                    "chunks": netmatrix_writer.chunks_written,
                }
                if netmatrix_writer is not None
                and netmatrix_writer.path is not None
                else {}
            ),
        }

    # ------------------------------------------- delivery-latency summary
    # per-receiver-group p50/p95/p99 estimated from the device-side log2
    # histograms (telemetry plane) — journaled under sim.latency, written
    # as viewer-shaped sim_latency.jsonl rows for the dashboard, and
    # mirrored to Influx as the ``sim.latency.*`` measurement family
    lat_rows: list[dict] = []
    latency: dict = {}
    if res.get("lat_hist") is not None:
        from .telemetry import LATENCY_FILE, latency_percentiles

        latency = {
            g.id: latency_percentiles(res["lat_hist"][gi], cfg.tick_ms)
            for gi, g in enumerate(groups)
        }
        for gid, pct in latency.items():
            for q in ("p50", "p95", "p99"):
                if f"{q}_ms" not in pct:
                    continue
                v = pct[f"{q}_ms"]
                lat_rows.append(
                    {
                        **row_ident,
                        "tick": res["ticks"],
                        "group_id": gid,
                        "name": f"sim.latency.{q}",
                        "count": pct["count"],
                        "mean": v,
                        "min": v,
                        "max": v,
                    }
                )
        if run_dir is not None and lat_rows:
            try:
                with open(os.path.join(run_dir, LATENCY_FILE), "w") as f:
                    for row in lat_rows:
                        f.write(json.dumps(row) + "\n")
            except OSError:  # observability never fails the run
                pass

    # --------------------------------------------- flight-recorder close
    if trace_writer is not None:
        trace_writer.close()
        result.journal["trace"] = trace_writer.journal()

    # ----------------------------------------------- run health plane
    # journaled under slo (rule verdicts + bounded breach records; the
    # full record stream is sim_slo.jsonl) — present whenever rules were
    # armed, breaches or not, so "no breaches" is a recorded verdict
    if slo_eval is not None:
        slo_eval.close()
        result.journal["slo"] = slo_eval.journal()

    # ---------------------------------------------- performance ledger
    # journaled under sim.perf (below) — the block every perf PR and the
    # bench trajectory report against; one task-log line so the
    # throughput is visible without digging into the journal
    perf_summary = None
    if perf_ledger is not None:
        perf_ledger.close()
        perf_summary = perf_ledger.summary()
        ex = perf_summary.get("execute", {})
        co = perf_summary.get("compile", {})
        if ex:
            ow.infof(
                "sim:jax %s: perf — %.0f peer·ticks/s over %d chunk(s)"
                "%s%s",
                job.run_id,
                ex.get(
                    "steady_peer_ticks_per_sec",
                    ex.get("peer_ticks_per_sec", 0.0),
                ),
                ex.get("chunks", 0),
                (
                    " (lower %.2fs + xla %.2fs)"
                    % (co["lower_secs"], co["compile_secs"])
                    if co
                    else ""
                ),
                (
                    ", hbm peak %.2f MiB"
                    % (perf_summary["hbm"]["peak_bytes"] / 2**20)
                    if perf_summary.get("hbm")
                    else ""
                ),
            )

    # ------------------------------------------------ profiler capture
    # the capture window is part of the run record: a remote `tg`
    # session reading the phase table must be able to find (and fetch,
    # via GET /artifact) the trace the table points at
    if profile_dir is not None:
        result.journal["profile"] = (
            chunk_profiler.journal()
            if chunk_profiler is not None
            else {"dir": "profiles", "mode": "full"}
        )

    # -------------------------------------------- phase attribution plane
    # per-phase device cost ledger (docs/OBSERVABILITY.md "Phase
    # attribution"): each tick phase lowered standalone at the run's
    # real shapes, its cost_analysis harvested beside the whole-program
    # chunk cost with an explicit residual row. Runs AFTER the run (off
    # the hot path), gated like telemetry (disable_metrics wins, cohorts
    # run phase-free — the out-of-line lowers are leader-local), and
    # best-effort: attribution must never fail the run it measures.
    phases_block = None
    phases_on = (
        bool(getattr(cfg, "phases", False))
        and not job.disable_metrics
        and not getattr(cfg, "coordinator_address", "")
    )
    if phases_on:
        from .phases import PHASES_FILE, build_phase_ledger, write_phase_rows

        spans.start("phases")
        try:
            phases_block = build_phase_ledger(
                prog,
                # the perf ledger's AOT pass already harvested the
                # whole-program chunk cost — reuse it instead of a
                # second out-of-line lower/compile
                whole=(perf_summary or {}).get("compile"),
                measure=int(getattr(cfg, "phases_measure", 0) or 0),
                seed=cfg.seed,
            )
        except Exception as e:  # noqa: BLE001 — attribution is best-effort
            ow.warn(
                "sim:jax %s: phase attribution failed: %s", job.run_id, e
            )
            phases_block = None
        if phases_block is not None:
            rows_written = (
                write_phase_rows(
                    os.path.join(run_dir, PHASES_FILE),
                    row_ident,
                    phases_block,
                )
                if run_dir is not None
                else 0
            )
            if rows_written:
                phases_block["series"] = {
                    "rows": rows_written,
                    "file": PHASES_FILE,
                }
            cov = (phases_block.get("coverage") or {}).get("bytes_frac")
            ow.infof(
                "sim:jax %s: phase attribution — %d phase(s), transport=%s"
                "%s",
                job.run_id,
                len(phases_block.get("phases") or []),
                phases_block.get("transport"),
                (
                    ", bytes coverage x%.2f of whole-program" % cov
                    if cov
                    else ""
                ),
            )
        spans.end("phases")

    # ------------------------------------------------ metric time series
    # final sample at the last tick, then persist the run's series — written
    # even above write_outputs_max (per-group reductions stay small)
    if recorder.enabled:
        recorder.sample(res["ticks"], res["states"], status)
    full_rows: list[dict] = []
    if run_dir is not None and recorder.rows:
        ts_path = os.path.join(run_dir, "timeseries.jsonl")
        full_rows = [{**row_ident, **row} for row in recorder.rows]
        with open(ts_path, "w") as f:
            for row in full_rows:
                f.write(json.dumps(row) + "\n")
        result.journal["timeseries"] = {
            "samples": len(recorder.rows),
            "every_ticks": recorder.every,
        }
    # optional InfluxDB mirror (the reference batches SDK metrics into
    # InfluxDB, ``local_docker.go:353``); best-effort. Both families go:
    # the plan-metric rows verbatim, and the sim telemetry rows expanded
    # to the same viewer shape (measurement sim.<counter> — exactly what
    # the dashboard renders, so Grafana sees the same series)
    influx_endpoint = (
        job.env.daemon.influxdb_endpoint if job.env is not None else ""
    )
    # base_ns = run start, NOT push time: stable per run, so re-pushes
    # are idempotent and batches never collide
    base_ns = int(t0_wall * 1e9)
    if influx_endpoint and full_rows:
        from testground_tpu.metrics.influx import push_rows

        result.journal["influx"] = push_rows(
            influx_endpoint, full_rows, base_ns=base_ns
        )
    has_tele_series = (
        tele_writer is not None
        and tele_writer.path is not None
        and tele_writer.rows_written > 0
    )
    if influx_endpoint and has_tele_series:
        # the sim.* family goes in its OWN bounded batches: a long run's
        # per-tick series can exceed InfluxDB's request-size limit, and
        # one oversized POST must not also lose the small plan-metric
        # batch above
        result.journal["influx_telemetry"] = _push_sim_series(
            influx_endpoint, tele_writer.iter_rows(), base_ns
        )
    if influx_endpoint and lat_rows:
        # per-group latency percentiles (sim.latency.* family) — already
        # viewer-shaped, a handful of rows, one small batch
        from testground_tpu.metrics.influx import push_rows

        result.journal["influx_latency"] = push_rows(
            influx_endpoint, lat_rows, base_ns=base_ns
        )
    if (
        influx_endpoint
        and perf_ledger is not None
        and perf_ledger.path is not None
        and perf_ledger.rows_written > 0
    ):
        # performance-ledger rows (sim.perf.* family) — one row per
        # chunk dispatch, so one small batch like the latency family
        from testground_tpu.metrics.influx import push_rows
        from testground_tpu.metrics.viewer import expand_perf_row

        from .telemetry import iter_jsonl

        result.journal["influx_perf"] = push_rows(
            influx_endpoint,
            [
                r
                for row in iter_jsonl(perf_ledger.path)
                for r in expand_perf_row(row)
            ],
            base_ns=base_ns,
        )

    for gi, g in enumerate(groups):
        st = status[g.offset : g.offset + g.count]
        ok = int(np.sum(st == 1))
        result.outcomes[g.id].ok = ok
        counts = {
            name: int(np.sum(st == code)) for code, name in _STATUS_NAME.items()
        }
        result.journal["events"][g.id] = counts
        ow.infof(
            "group %s: %d/%d ok (%s)",
            g.id,
            ok,
            g.count,
            ", ".join(f"{k}={v}" for k, v in counts.items() if v),
        )
        if write_outputs:
            _write_instance_outputs(
                outputs_root, job, g, st, res, metrics.get(g.id)
            )

    # ------------------------------------------- checkpoint/resume plane
    # journaled under sim.checkpoint whenever snapshots were armed OR
    # the run was resumed — "resumed from tick T" is part of the run
    # record (tg stats / Prometheus tg_checkpoint_* read this block)
    checkpoint_block = None
    if checkpointer is not None:
        checkpoint_block = checkpointer.journal()
        if checkpointer.count:
            ow.infof(
                "sim:jax %s: checkpoint plane — %d snapshot(s), last at "
                "tick %d (%.2f MiB, %.1f ms write)",
                job.run_id,
                checkpointer.count,
                checkpointer.last_tick,
                checkpointer.last_bytes / 2**20,
                checkpointer.last_write_ms,
            )
    elif resume_info is not None:
        checkpoint_block = {
            "every_chunks": 0,
            "count": 0,
            "resumed": resume_info,
        }

    import jax as _jax

    mesh_block = _mesh_journal_block(mesh, testcase, groups, hosts)

    result.journal["sim"] = {
        "ticks": res["ticks"],
        "tick_ms": cfg.tick_ms,
        "wall_secs": wall,
        "processes": int(_jax.process_count()),
        # init + first chunk (trace/lower + XLA compile or persistent-cache
        # read + one chunk's execution) — drops to a small fraction when a
        # build precompiled this program (see builders/sim_plan.py)
        "compile_secs": round(res.get("compile_secs", 0.0), 3),
        "devices": int(mesh.devices.size) if mesh is not None else 1,
        # transport resolution record (sim/transport_model.py): what the
        # runner config asked, what the gate resolved, and why — the
        # `tg stats` pretty line and the tg_transport_resolved gauge
        # read this block. Host-side bookkeeping only: the default
        # transport=xla program stays jaxpr-pinned unchanged.
        "transport": transport_decision.block(),
        "pub_dropped": res["pub_dropped"].tolist(),
        "latency_clamped": res.get("latency_clamped", 0),
        "bw_queue_dropped": res.get("bw_queue_dropped", 0),
        "bw_rate_change_backlogged": res.get("bw_rate_change_backlogged", 0),
        # always-on observability floor (telemetry plane totals + memory
        # footprint): every run reports these whether or not the per-tick
        # block was compiled in — the contract perf PRs report against
        "msgs_delivered": res.get("msgs_delivered", 0),
        "msgs_sent": res.get("msgs_sent", 0),
        "msgs_enqueued": res.get("msgs_enqueued", 0),
        "msgs_dropped": res.get("msgs_dropped", 0),
        "msgs_rejected": res.get("msgs_rejected", 0),
        "msgs_in_flight": res.get("cal_depth", 0),
        # fault-injection plane (docs/FAULTS.md) — zeros when no schedule
        # was declared; msgs_fault_dropped is the chaos term of the flow
        # conservation identity (sent = delivered + in-flight + dropped
        # + rejected + fault_dropped)
        "faults_crashed": res.get("faults_crashed", 0),
        "faults_restarted": res.get("faults_restarted", 0),
        "msgs_fault_dropped": res.get("fault_dropped", 0),
        "carry_bytes": res.get("carry_bytes", carry_bytes),
        # per-receiver-group delivery-latency percentiles (telemetry
        # plane; docs/OBSERVABILITY.md) — absent when telemetry was off
        **({"latency": latency} if latency else {}),
        # performance ledger (compile split + cost/memory analysis +
        # throughput gauges; docs/OBSERVABILITY.md) — absent only under
        # disable_metrics, cohorts, or an explicit perf=false
        **({"perf": perf_summary} if perf_summary else {}),
        # phase attribution plane (per-phase cost ledger + residual;
        # docs/OBSERVABILITY.md "Phase attribution") — opt-in, phases=true
        **({"phases": phases_block} if phases_block else {}),
        # network topology plane (docs/OBSERVABILITY.md "Traffic
        # matrix") — present when netmatrix=true resolved on; the block
        # `tg netmap` and the tg_net_pair_* gauges read
        **({"net_matrix": net_matrix_block} if net_matrix_block else {}),
        # checkpoint/resume plane (docs/CHECKPOINT.md) — present when
        # snapshots were armed or the run resumed from one
        **({"checkpoint": checkpoint_block} if checkpoint_block else {}),
        # shape bucketing (PERF.md "Serving: buckets + packing") —
        # present when the run was padded to a canonical bucket; all
        # totals above remain exact-N (dead lanes contribute nothing)
        **({"bucket": bucket_block} if bucket_block else {}),
        # mesh placement plane (sim/meshplan.py, docs/OBSERVABILITY.md
        # "Mesh plane") — present when the run was sharded
        **({"mesh": mesh_block} if mesh_block else {}),
    }
    result.update_outcome()
    if cancel.is_set():
        result.outcome = Outcome.CANCELED
    spans.end("collect")
    # fail-severity SLO breach: the chunk loop was canceled (run-local,
    # the task event untouched); the fully-assembled result — journal
    # included — rides the typed error so the supervisor archives the
    # failed soak's complete telemetry record (docs/OBSERVABILITY.md
    # "Run health plane"). An operator kill wins: a task-canceled run
    # stays CANCELED, not an SLO failure.
    if (
        slo_eval is not None
        and slo_eval.fatal is not None
        and not cancel.is_set()
    ):
        from .slo import SloBreachError

        result.outcome = Outcome.FAILURE
        err = SloBreachError(slo_eval.fatal)
        result.journal["slo"]["error"] = str(err)
        err.run_output = RunOutput(run_id=job.run_id, result=result)
        raise err
    # fleet-controller preemption (docs/FLEET.md): the loop stopped at a
    # chunk boundary because the preempt signal fired. Raise the typed
    # error so the supervisor requeues the task to resume — AFTER the
    # SLO block (a condemned run must not launder its failure into a
    # migration) and only when the operator did not cancel (a kill
    # stays CANCELED).
    if (
        preempt_ev is not None
        and preempt_ev.is_set()
        and not cancel.is_set()
    ):
        from testground_tpu.engine.controller import TaskPreemptedError

        resumable = checkpointer is not None and checkpointer.count > 0
        spans.point(
            "preempt",
            tick=int(res["ticks"]),
            snapshot_tick=(
                int(checkpointer.last_tick) if resumable else 0
            ),
            resumable=resumable,
        )
        # the run span is closed by execute_sim_run's except hook,
        # which labels a preemption outcome="preempted", not "error"
        raise TaskPreemptedError(
            job.run_id,
            tick=int(res["ticks"]),
            snapshot_tick=(
                int(checkpointer.last_tick) if resumable else 0
            ),
            snapshots=(
                int(checkpointer.count) if checkpointer is not None else 0
            ),
            resumable=resumable,
        )
    spans.end("run", outcome=result.outcome.value, ticks=res["ticks"])
    return RunOutput(run_id=job.run_id, result=result)


def execute_packed_sim_runs(
    jobs: list[RunInput], ows: list[OutputWriter], cancels: list
) -> list:
    """Execute N compatible sim runs as ONE vmapped device program (run
    packing — PERF.md "Serving: buckets + packing"; the device half is
    ``sim/pack.py``). Every job keeps its own task identity: outputs
    dir, telemetry/SLO/perf streams, journal, Result — demuxed from the
    pack's ``[R, ...]`` blocks each chunk.

    The engine's pack admission (``engine/pack.py``) guarantees the
    jobs share a program (same plan/case/params/bucket layout/gates, no
    faults/trace/hosts/cohort/checkpoint); this function asserts the
    essentials and returns one ``RunOutput`` OR ``Exception`` per job
    (a member's failure is its own task's failure, never the pack's).

    Supported planes per member: telemetry, latency histograms, SLO
    assertions (a fail cancels only that member — its lanes freeze via
    snapshot while the pack continues), performance ledger, metrics,
    instance outputs. Out of scope in packs (the admission key refuses
    them): faults, flight recorder, checkpoints, profiles, phases,
    cohorts, additional hosts.
    """
    from testground_tpu.utils.compile_cache import (
        cache_event_counts,
        enable_compile_cache,
    )

    from .engine import build_groups as _build_groups
    from .pack import PackMember, PackRunner, pack_width
    from .telemetry import SIM_SERIES_FILE, SpanTracer, SPAN_FILE

    assert len(jobs) == len(ows) == len(cancels) and len(jobs) >= 2
    job0, cfg = jobs[0], jobs[0].runner_config or SimJaxConfig()
    compile_cache_on = (
        enable_compile_cache(
            job0.env.dirs.home if job0.env is not None else None
        )
        is not None
    )
    outputs_root = (
        job0.env.dirs.outputs() if job0.env is not None else None
    )

    # ---------------------------------------------------- shared program
    # The run axis takes the vmap, but the INSTANCE axis may still
    # shard: the inner program is built unmeshed (make_sim_program
    # below gets mesh=None — a sharding constraint under the vmap
    # would pin per-member layouts) and PackRunner places the stacked
    # carry through the same rule table OUTSIDE the vmap
    # (sim/meshplan.py). The bucket gate sees the pack's real mesh so
    # padded counts divide the peer shards; when they do not, the pack
    # falls back to the unmeshed single-device world rather than
    # breaking the admission signature's bucketed promise.
    pack_mesh = (
        None
        if getattr(cfg, "coordinator_address", "")
        else _make_mesh(cfg.shard, getattr(cfg, "mesh", ""))
    )
    bucket_plan = resolve_buckets(
        cfg,
        [g.instances for g in job0.groups],
        mesh=pack_mesh,
        warn=ows[0].warn,
    )
    if bucket_plan is None and pack_mesh is not None:
        unmeshed_plan = resolve_buckets(
            cfg, [g.instances for g in job0.groups], mesh=None
        )
        if unmeshed_plan is not None:
            ows[0].warn(
                "pack runs on a single device: the bucket ladder does "
                "not divide across the mesh peer shards"
            )
            pack_mesh = None
            bucket_plan = unmeshed_plan
    if bucket_plan is None:
        for j in jobs[1:]:
            if [g.instances for g in j.groups] != [
                g.instances for g in job0.groups
            ]:
                raise ValueError(
                    "pack admission bug: unbucketed members with "
                    "different instance counts share a pack"
                )
    if bucket_plan is not None:
        padded_in = [
            dataclasses.replace(g, instances=p)
            for g, p in zip(job0.groups, bucket_plan.padded_counts)
        ]
    else:
        padded_in = job0.groups
    testcase, groups = load_and_specialize(
        job0.groups[0].artifact_path,
        job0.test_case,
        padded_in,
        cfg.tick_ms,
    )
    telemetry_on = bool(getattr(cfg, "telemetry", False)) and not any(
        j.disable_metrics for j in jobs
    )
    # auto resolves ONCE for the whole pack against the pack's real
    # mesh (admission already grouped members by the same
    # plan/case/shape signature, so the decision is shared by
    # construction). A meshed pack cannot run the pallas kernels — the
    # vmapped single-device calls do not partition over the mesh, and
    # the shard_map variant is the solo path — so pallas resolves to
    # xla here, loudly, with the override journaled.
    transport_decision = _decide_transport_for(
        job0, cfg, pack_mesh, testcase, groups, (), telemetry_on, ows[0]
    )
    transport = transport_decision.resolved
    if transport == "pallas" and pack_mesh is not None:
        ows[0].warn(
            "transport=pallas on a packed mesh resolves to xla (the "
            "vmapped kernels cannot shard over the run axis and the "
            "mesh at once)"
        )
        transport_decision = dataclasses.replace(
            transport_decision,
            resolved="xla",
            reason=transport_decision.reason
            + " — overridden: a packed mesh run uses the XLA transport",
        )
        transport = "xla"
    prog = make_sim_program(
        testcase,
        groups,
        test_plan=job0.test_plan,
        test_case=job0.test_case,
        test_run=job0.run_id,
        tick_ms=cfg.tick_ms,
        mesh=None,
        chunk=cfg.chunk,
        hosts=(),
        validate=bool(getattr(cfg, "validate", False)),
        telemetry=telemetry_on,
        faults=None,
        trace=None,
        transport=transport,
        live_counts=(
            bucket_plan.live_counts if bucket_plan is not None else None
        ),
        # the matrix plane is a pack exclusion (engine/pack.py): a
        # member asking for netmatrix runs solo, so the shared pack
        # program is always matrix-free
        netmatrix=False,
    )
    width = pack_width(len(jobs), int(getattr(cfg, "pack_max", 8) or 8))
    runner = PackRunner(prog, width, mesh=pack_mesh)

    # ------------------------------------------------ per-member plumbing
    members: list[PackMember] = []
    contexts: list[dict] = []
    cache_before = cache_event_counts()
    for idx, (job, ow, cancel) in enumerate(zip(jobs, ows, cancels)):
        jcfg = job.runner_config or cfg
        run_dir = None
        if outputs_root is not None:
            run_dir = os.path.join(
                outputs_root, job.test_plan, job.run_id
            )
            os.makedirs(run_dir, exist_ok=True)
        spans = SpanTracer(
            os.path.join(run_dir, SPAN_FILE)
            if run_dir is not None and not job.disable_metrics
            else None,
            ctx=getattr(job, "trace_ctx", None),
        )
        spans.start(
            "run",
            run_id=job.run_id,
            plan=job.test_plan,
            case=job.test_case,
            pack_index=idx,
        )
        vgroups = _build_groups(job.groups)
        member_bucket = (
            resolve_buckets(jcfg, [g.instances for g in job.groups])
            if bucket_plan is not None
            else None
        )
        n_live = sum(g.count for g in vgroups)
        row_ident = {
            "run": job.run_id,
            "plan": job.test_plan,
            "case": job.test_case,
        }
        tele_writer = (
            _SimTelemetryWriter(
                tuple(g.id for g in vgroups),
                row_ident,
                os.path.join(run_dir, SIM_SERIES_FILE)
                if run_dir is not None
                else None,
            )
            if telemetry_on
            else None
        )
        slo_eval = None
        slo_cancel = None
        slo_specs = slo_specs_of(job.groups, getattr(job, "slo", None))
        from .slo import build_slo_plan

        slo_plan = build_slo_plan(vgroups, slo_specs)
        if slo_plan is not None and not telemetry_on:
            raise ValueError(
                f"pack member {job.run_id} declares SLO rules but the "
                "pack's telemetry plane is off"
            )
        if slo_plan is not None:
            from .slo import SLO_FILE, SloEvaluator

            slo_cancel = _SloRunCancel(cancel)
            slo_eval = SloEvaluator(
                slo_plan,
                vgroups,
                cfg.tick_ms,
                cfg.chunk,
                ident=row_ident,
                path=(
                    os.path.join(run_dir, SLO_FILE)
                    if run_dir is not None
                    else None
                ),
                cancel=slo_cancel.run_local,
            )
        perf_ledger = None
        if bool(getattr(jcfg, "perf", True)) and not job.disable_metrics:
            from .perf import PERF_FILE, PerfLedger

            perf_ledger = PerfLedger(
                n_live,
                cfg.chunk,
                ident=row_ident,
                path=(
                    os.path.join(run_dir, PERF_FILE)
                    if run_dir is not None
                    else None
                ),
                aot=False,  # one AOT pass per pack member would
                # serialize compiles the pack exists to amortize
                bucket=(
                    bucket_plan.padded_n
                    if bucket_plan is not None
                    else None
                ),
                transport=transport,
            )

        def _tele_cb(block, _w=tele_writer, _s=slo_eval):
            rows = _w.on_block(block) if _w is not None else []
            if _s is not None:
                _s.on_rows(rows)

        def _on_chunk(ticks, _s=slo_eval, _ow=ow, _r=job.run_id):
            # the run health plane judges AFTER this chunk's rows and
            # latency delta landed (telemetry_cb/lat_hist_cb run first
            # in PackRunner) — the solo executor's on_chunk contract
            if _s is None:
                return
            for breach in _s.evaluate():
                _ow.warn(
                    "sim:jax %s: SLO breach (%s): %s — %s = %g "
                    "violates %s %g at tick %d%s",
                    _r,
                    breach["severity"],
                    breach["rule"],
                    breach["metric"],
                    breach["observed"],
                    breach["op"],
                    breach["threshold"],
                    breach["tick"],
                    " — stopping this pack member"
                    if breach["severity"] == "fail"
                    else "",
                )

        # eviction (engine/controller.py) rides the same in-program
        # lane-freeze path as cancellation: the member stops at the
        # next chunk boundary, collect raises TaskPreemptedError
        preempt_ev = getattr(job, "preempt", None)

        def _cancel_check(_c=cancel, _sc=slo_cancel, _p=preempt_ev):
            return (
                _c.is_set()
                or (_sc is not None and _sc.run_local.is_set())
                or (_p is not None and _p.is_set())
            )

        ow.infof(
            "sim:jax %s: packed run %d/%d (width %d) — plan=%s case=%s "
            "instances=%d%s",
            job.run_id,
            idx + 1,
            len(jobs),
            width,
            job.test_plan,
            job.test_case,
            n_live,
            (
                f", bucket {bucket_plan.padded_n}"
                if bucket_plan is not None
                else ""
            ),
        )
        members.append(
            PackMember(
                seed=int(getattr(jcfg, "seed", 0) or 0),
                live_counts=(
                    member_bucket.live_counts
                    if member_bucket is not None
                    else None
                ),
                max_ticks=int(getattr(jcfg, "max_ticks", 10_000)),
                telemetry_cb=_tele_cb if telemetry_on else None,
                lat_hist_cb=(
                    slo_eval.on_lat_delta if slo_eval is not None else None
                ),
                on_chunk=_on_chunk if slo_eval is not None else None,
                cancel_check=_cancel_check,
                perf=perf_ledger,
            )
        )
        contexts.append(
            {
                "job": job,
                "ow": ow,
                "cancel": cancel,
                "spans": spans,
                "vgroups": vgroups,
                "run_dir": run_dir,
                "tele_writer": tele_writer,
                "slo_eval": slo_eval,
                "perf": perf_ledger,
                "row_ident": row_ident,
                "bucket": member_bucket,
                "n": n_live,
                "testcase": testcase,
                "leader_run": job0.run_id,
            }
        )

    # ------------------------------------------------------- one dispatch
    t0 = time.monotonic()
    for ctx in contexts:
        ctx["spans"].start("execute")
    try:
        pack_results = runner.run(members)
    except BaseException as e:  # noqa: BLE001 — whole-pack failure
        for ctx in contexts:
            ctx["spans"].end("execute", outcome="error")
            ctx["spans"].end("run", outcome="error", error=str(e)[:200])
            ctx["spans"].close()
        raise
    wall = time.monotonic() - t0
    hits_delta = cache_event_counts()["hits"] - cache_before["hits"]
    mesh_block = _mesh_journal_block(pack_mesh, testcase, groups, ())

    # ------------------------------------------------- per-member collect
    outs: list = []
    for idx, (ctx, m, res) in enumerate(
        zip(contexts, members, pack_results)
    ):
        job, ow, spans = ctx["job"], ctx["ow"], ctx["spans"]
        try:
            outs.append(
                _collect_pack_member(
                    idx,
                    ctx,
                    m,
                    res,
                    width,
                    len(jobs),
                    wall,
                    telemetry_on,
                    transport_decision,
                    bucket_plan,
                    compile_cache_on,
                    hits_delta,
                    outputs_root,
                    mesh_block=mesh_block,
                )
            )
        except Exception as e:  # noqa: BLE001 — member-local failure
            from testground_tpu.engine.controller import (
                TaskPreemptedError,
            )

            outcome = (
                "preempted"
                if isinstance(e, TaskPreemptedError)
                else "error"
            )
            spans.end("run", outcome=outcome, error=str(e)[:200])
            outs.append(e)
        finally:
            spans.close()
    return outs


def _collect_pack_member(
    idx,
    ctx,
    member,
    res,
    width,
    n_members,
    wall,
    telemetry_on,
    transport_decision,
    bucket_plan,
    compile_cache_on,
    hits_delta,
    outputs_root,
    mesh_block=None,
):
    """Assemble one pack member's RunOutput: outcomes, metrics, journal
    (sim block + pack/bucket/mesh annotations), instance outputs — the
    reduced-plane analog of ``_execute_sim_run``'s collect phase."""
    job, ow, spans = ctx["job"], ctx["ow"], ctx["spans"]
    cancel = ctx["cancel"]
    groups = res["groups"]
    status = res["status"]
    n = ctx["n"]
    spans.end("execute", ticks=res["ticks"])
    spans.start("collect")
    result = Result.for_input(job)
    result.journal["events"] = {}

    if member.canceled and cancel.is_set():
        ow.warn("sim:jax %s: pack member canceled", job.run_id)

    metrics: dict = {}
    collect = getattr(ctx["testcase"], "collect_metrics", None)
    if callable(collect):
        for gi, g in enumerate(groups):
            try:
                metrics[g.id] = collect(
                    g,
                    res["states"][gi],
                    status[g.offset : g.offset + g.count],
                )
            except Exception as e:  # noqa: BLE001 — best-effort
                ow.warn(
                    "collect_metrics failed for group %s: %s", g.id, e
                )
    if metrics:
        result.journal["metrics"] = {
            gid: _aggregate_metrics(m) for gid, m in metrics.items()
        }

    if ctx["tele_writer"] is not None:
        ctx["tele_writer"].close()
        result.journal["telemetry"] = {
            "rows": ctx["tele_writer"].rows_written,
            **(
                {"file": "sim_timeseries.jsonl"}
                if ctx["tele_writer"].path is not None
                else {}
            ),
            "totals": {
                "delivered": res["msgs_delivered"],
                "sent": res["msgs_sent"],
                "enqueued": res["msgs_enqueued"],
                "dropped": res["msgs_dropped"],
                "rejected": res["msgs_rejected"],
                "in_flight": res["cal_depth"],
                "fault_dropped": res.get("fault_dropped", 0),
            },
        }
    latency = {}
    if res.get("lat_hist") is not None:
        from .telemetry import latency_percentiles

        latency = {
            g.id: latency_percentiles(
                res["lat_hist"][gi], res["tick_ms"]
            )
            for gi, g in enumerate(groups)
        }
    if ctx["slo_eval"] is not None:
        ctx["slo_eval"].close()
        result.journal["slo"] = ctx["slo_eval"].journal()
    perf_summary = None
    if ctx["perf"] is not None:
        ctx["perf"].close()
        perf_summary = ctx["perf"].summary()

    write_outputs = (
        outputs_root is not None
        and n <= int(getattr(job.runner_config, "write_outputs_max", 2048)
                     if job.runner_config is not None else 2048)
    )
    for gi, g in enumerate(groups):
        st = status[g.offset : g.offset + g.count]
        result.outcomes[g.id].ok = int(np.sum(st == 1))
        result.journal["events"][g.id] = {
            name: int(np.sum(st == code))
            for code, name in _STATUS_NAME.items()
        }
        if write_outputs:
            _write_instance_outputs(
                outputs_root, job, g, st, res, metrics.get(g.id)
            )

    bucket_block = None
    if bucket_plan is not None and ctx["bucket"] is not None:
        mb = ctx["bucket"]
        bucket_block = {
            "instances": mb.live_n,
            "padded_instances": mb.padded_n,
            "dead_lanes": mb.padded_n - mb.live_n,
            "per_group": {
                g.id: {"live": lv, "padded": pv}
                for g, lv, pv in zip(
                    ctx["vgroups"], mb.live_counts, mb.padded_counts
                )
            },
            "compile_cache": (
                "off"
                if not compile_cache_on
                else ("hit" if hits_delta > 0 else "miss")
            ),
        }
    result.journal["sim"] = {
        "ticks": res["ticks"],
        "tick_ms": res["tick_ms"],
        "wall_secs": wall,
        "processes": 1,
        "compile_secs": round(res.get("compile_secs", 0.0), 3),
        "devices": (
            int(mesh_block["shards"]) * int(mesh_block["runs"])
            if mesh_block
            else 1
        ),
        "pub_dropped": res["pub_dropped"].tolist(),
        "latency_clamped": res.get("latency_clamped", 0),
        "bw_queue_dropped": res.get("bw_queue_dropped", 0),
        "bw_rate_change_backlogged": res.get(
            "bw_rate_change_backlogged", 0
        ),
        "msgs_delivered": res.get("msgs_delivered", 0),
        "msgs_sent": res.get("msgs_sent", 0),
        "msgs_enqueued": res.get("msgs_enqueued", 0),
        "msgs_dropped": res.get("msgs_dropped", 0),
        "msgs_rejected": res.get("msgs_rejected", 0),
        "msgs_in_flight": res.get("cal_depth", 0),
        "faults_crashed": res.get("faults_crashed", 0),
        "faults_restarted": res.get("faults_restarted", 0),
        "msgs_fault_dropped": res.get("fault_dropped", 0),
        "carry_bytes": res.get("carry_bytes", 0),
        # the pack-shared transport resolution (one decision per pack)
        "transport": transport_decision.block(),
        # run packing: this member's slot in the shared device program
        "pack": {
            "width": width,
            "members": n_members,
            "index": idx,
            "leader_run": ctx["leader_run"],
        },
        **({"latency": latency} if latency else {}),
        **({"perf": perf_summary} if perf_summary else {}),
        **({"bucket": bucket_block} if bucket_block else {}),
        # mesh placement plane (sim/meshplan.py) — the pack-shared
        # layout, present when the stacked carry sharded over a mesh
        **({"mesh": mesh_block} if mesh_block else {}),
    }
    result.update_outcome()
    if member.canceled and cancel.is_set():
        result.outcome = Outcome.CANCELED
    if (
        ctx["slo_eval"] is not None
        and ctx["slo_eval"].fatal is not None
        and not cancel.is_set()
    ):
        from .slo import SloBreachError

        result.outcome = Outcome.FAILURE
        err = SloBreachError(ctx["slo_eval"].fatal)
        result.journal["slo"]["error"] = str(err)
        err.run_output = RunOutput(run_id=job.run_id, result=result)
        spans.end("collect")
        spans.end("run", outcome=result.outcome.value, ticks=res["ticks"])
        raise err
    preempt_ev = getattr(job, "preempt", None)
    if (
        member.canceled
        and preempt_ev is not None
        and preempt_ev.is_set()
        and not cancel.is_set()
    ):
        from testground_tpu.engine.controller import TaskPreemptedError

        # evicted member: lanes froze at the chunk boundary, but a pack
        # member never writes disk snapshots (engine/pack.py exclusion)
        # — the supervisor requeues it to rerun from scratch. Ordered
        # after the SLO raise: a fatal breach wins over eviction.
        spans.point(
            "preempt",
            tick=int(res["ticks"]),
            snapshot_tick=0,
            resumable=False,
        )
        spans.end("collect")
        raise TaskPreemptedError(
            job.run_id, tick=int(res["ticks"]), resumable=False
        )
    ow.infof(
        "sim:jax %s: packed run done — %d ticks, %s",
        job.run_id,
        res["ticks"],
        result.outcome.value,
    )
    spans.end("collect")
    spans.end("run", outcome=result.outcome.value, ticks=res["ticks"])
    return RunOutput(run_id=job.run_id, result=result)


def sim_worker_loop(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    plans_dir: str,
    once: bool = False,
    log=print,
    connect_attempts: int = 3,
    connect_timeout_secs: float = 60.0,
) -> None:
    """Follower half of a multi-host cohort (the ``tg sim-worker`` verb).

    Joins the jax.distributed job, then for each job spec the leader
    broadcasts: load the same plan from this host's plans dir, compile the
    identical program over the global mesh, and run it to completion —
    the multi-controller contract. Results live in the global arrays; the
    leader owns reporting. ``once`` serves at most one job, then keeps
    participating in the spec broadcast until the leader's shutdown
    sentinel arrives — leaving the collective early would desync the
    cohort (tests use this; a second job spec in once mode is skipped
    via the readiness vote)."""
    from .distributed import broadcast_json, global_mesh, init_distributed
    from testground_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    # a worker routinely starts before the leader across hosts: join
    # with the bounded-retry budget (readable failure naming the
    # coordinator, not a 5-minute silent hang)
    init_distributed(
        coordinator_address,
        num_processes,
        process_id,
        connect_attempts=connect_attempts,
        connect_timeout_seconds=connect_timeout_secs,
    )
    import jax

    log(
        f"sim-worker: process {jax.process_index()}/{jax.process_count()} "
        f"joined, {len(jax.devices())} global devices"
    )
    from testground_tpu.api import RunGroup

    from .distributed import CohortCancel, cohort_agree

    served = False
    while True:
        spec = broadcast_json(None)
        if spec.get("shutdown"):
            log("sim-worker: shutdown")
            return
        # readiness vote BEFORE any program collective: if this (or any)
        # host cannot build the job, the whole cohort skips it
        try:
            if once and served:
                raise RuntimeError("once-mode worker already served a job")
            # same load + specialization as the leader — the cohort must
            # trace identical shapes (shared helper, not a copy)
            testcase, groups = load_and_specialize(
                os.path.join(plans_dir, spec["plan"]),
                spec["case"],
                [
                    RunGroup(
                        id=d["id"],
                        instances=d["instances"],
                        parameters=d["parameters"],
                    )
                    for d in spec["groups"]
                ],
                spec["tick_ms"],
            )
            ok = True
        except Exception as e:  # noqa: BLE001 — voted, not raised
            log(f"sim-worker: cannot satisfy {spec['plan']}:{spec['case']}: {e}")
            ok = False
        if not cohort_agree(ok):
            log(f"sim-worker: cohort skipped run {spec['run_id']}")
            continue
        from .faults import build_fault_schedule as _build_faults
        from .trace import build_trace_plan as _build_trace

        prog = make_sim_program(
            testcase,
            groups,
            test_plan=spec["plan"],
            test_case=spec["case"],
            test_run=spec["run_id"],
            tick_ms=spec["tick_ms"],
            mesh=global_mesh(),
            chunk=spec["chunk"],
            hosts=tuple(spec.get("hosts", ())),
            validate=bool(spec.get("validate", False)),
            telemetry=bool(spec.get("telemetry", False)),
            # post-gate value from the leader (cohort meshes always
            # resolve to xla today; threaded so a future single-device
            # symmetric design cannot silently desync the followers)
            transport=spec.get("transport", "xla"),
            # deterministic lowering: the same spec dict produces the
            # same event tensors on every process, so the cohort traces
            # one program
            faults=_build_faults(
                groups, spec.get("faults") or {}, spec["tick_ms"]
            ),
            trace=_build_trace(groups, spec.get("trace") or {}),
            # cohorts run bucket-free (the resolve_buckets gate): the
            # runtime-N carry input is leader-local and a padded layout
            # would have to ride the broadcast symmetrically
            live_counts=None,
            # cohorts run matrix-free (the leader sheds netmatrix with a
            # warning — per-chunk leader-local delta reads are not
            # symmetric across processes), so the spec never carries it
            netmatrix=False,
        )
        res = prog.run(
            seed=spec["seed"],
            max_ticks=spec["max_ticks"],
            cancel=CohortCancel(None),
        )
        log(
            f"sim-worker: run {spec['run_id']} done — {res['ticks']} ticks"
        )
        served = True


def run_sim_worker(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    plans_dir: str,
    once: bool = False,
    log=print,
    _exit=os._exit,
    connect_attempts: int = 3,
    connect_timeout_secs: float = 60.0,
) -> int:
    """The ``tg sim-worker`` entry: :func:`sim_worker_loop` wrapped so a
    DEAD LEADER ends the worker with one readable line instead of a
    distributed-runtime ``LOG(FATAL)`` stack (VERDICT r5 weak #4).

    When the leader (or any member) dies, this worker's blocked
    collective aborts with a catchable runtime error within ~1 s — but
    the distributed runtime's error-poll thread will ``LOG(FATAL)`` the
    whole process shortly after, without a Python hook. So: classify the
    exception with the cohort child's typed-first classifier, print the
    one-line diagnosis, and ``os._exit`` IMMEDIATELY — same sidestep the
    leader child uses (``sim/cohort.py`` ``cohort_fatal``) — beating the
    fatal poll to the exit. Non-cohort exceptions re-raise unchanged;
    ``_exit`` is injectable for tests."""
    try:
        sim_worker_loop(
            coordinator_address,
            num_processes,
            process_id,
            plans_dir,
            once=once,
            log=log,
            connect_attempts=connect_attempts,
            connect_timeout_secs=connect_timeout_secs,
        )
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — classified below
        from .cohort import _is_cohort_fatal

        if _is_cohort_fatal(e):
            log(
                "sim-worker: cohort lost (leader or member died: "
                f"{type(e).__name__}) — exiting cleanly; restart every "
                "sim-worker to form a new cohort"
            )
            sys.stdout.flush()
            _exit(1)
            return 1  # only reached when _exit is a test stub
        raise
    return 0


def _tree_slice(state_group):
    """Per-group states are already host numpy pytrees; identity hook kept
    for future lazy device slicing."""
    return state_group


# Influx lines per POST for the sim telemetry family — far under
# InfluxDB's default 25 MB body cap (a line is ~100 bytes) while still
# amortizing the HTTP round trip.
_INFLUX_BATCH_LINES = 5000


def _push_sim_series(endpoint: str, rows_iter, base_ns: int) -> dict:
    """Expand streamed sim telemetry rows to viewer shape and push them
    to Influx in bounded batches. Returns one merged journal dict
    ({pushed, ok, batches, error?, aborted?}). A failed batch marks
    ok=False and ABORTS the mirror: push_rows already retried it with
    backoff, so the endpoint is known dead/rejecting, and burning the
    full retry budget again on each of a long run's dozens of batches
    would stall teardown for minutes on an endpoint that isn't coming
    back (best-effort means the run never pays more than one batch's
    worth of failure)."""
    from testground_tpu.metrics.influx import push_rows
    from testground_tpu.metrics.viewer import expand_sim_row

    journal: dict = {"pushed": 0, "ok": True, "batches": 0}

    def push(batch: list) -> bool:
        j = push_rows(endpoint, batch, base_ns=base_ns)
        journal["pushed"] += j.get("pushed", 0)
        journal["batches"] += 1
        if not j.get("ok"):
            journal["ok"] = False
            journal.setdefault("error", j.get("error", "push failed"))
            journal["aborted"] = True  # remaining batches not attempted
            return False
        return True

    batch: list = []
    for row in rows_iter:
        batch.extend(expand_sim_row(row))
        if len(batch) >= _INFLUX_BATCH_LINES:
            if not push(batch):
                return journal
            batch = []
    if batch:
        push(batch)
    return journal


class _ChunkedProfiler:
    """Bounded ``jax.profiler`` capture: trace only the first N chunk
    dispatches after warmup (``profile_chunks=N``), instead of wrapping
    the whole run. ``on_chunk(ticks)`` fires at every chunk boundary:
    the first call (the warmup dispatch — compile + trace — just
    completed) starts the trace, and once N further chunks have
    completed it stops. Best-effort like every observability hook: a
    profiler failure disables the capture, never the run."""

    def __init__(self, profile_dir: str, chunks: int):
        self.dir = profile_dir
        self.chunks = max(1, int(chunks))
        self.started = False
        self.done = False
        self.from_tick: int | None = None
        self.to_tick: int | None = None
        self.captured = 0

    def on_chunk(self, ticks: int) -> None:
        if self.done:
            return
        if not self.started:
            try:
                import jax

                jax.profiler.start_trace(self.dir)
            except Exception:  # noqa: BLE001 — capture is best-effort
                self.done = True
                return
            self.started = True
            self.from_tick = int(ticks)
            return
        self.captured += 1
        self.to_tick = int(ticks)
        if self.captured >= self.chunks:
            self._stop()

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass
        self.done = True

    def close(self) -> None:
        if self.started and not self.done:
            self._stop()

    def journal(self) -> dict:
        out: dict = {
            "dir": "profiles",
            "mode": "chunks",
            "chunks": self.captured,
        }
        if self.from_tick is not None:
            out["from_tick"] = self.from_tick
        if self.to_tick is not None:
            out["to_tick"] = self.to_tick
        if self.started and not self.captured:
            # a run whose ticks fit the warmup dispatch ends before any
            # post-warmup chunk: the trace exists but holds no
            # steady-state ops — say so instead of reporting an empty
            # capture as a window
            out["note"] = (
                "run ended before any post-warmup chunk completed — the "
                "capture is empty; use profile_chunks=0 (whole-run) for "
                "runs this short"
            )
        return out


class _SimTelemetryWriter:
    """Streams the chunk-flushed ``[chunk, K]`` telemetry blocks to the
    run's series file as they arrive: each block decodes to at most
    ``chunk`` jsonl rows and is written immediately, so host memory
    stays bounded by one chunk regardless of run length and a crashed
    run keeps every row flushed so far. The per-chunk cost is a few
    hundred dict builds + a buffered write — microseconds against a
    multi-ms device dispatch. With no outputs dir (``path=None``) the
    writer only counts rows (and nothing downstream needs them: the
    Influx mirror requires an env, which also provides the dir)."""

    def __init__(
        self,
        group_ids: tuple,
        ident: dict,
        path: str | None,
        append: bool = False,
        rows_offset: int = 0,
    ):
        self.group_ids = group_ids
        self.ident = ident
        self.path = path
        # resumed runs (sim/checkpoint.py) continue the series: the
        # file was truncated to the snapshot's byte offset, the row
        # counter continues from the snapshot's count
        self.rows_written = int(rows_offset)
        self._f = None
        if path is not None:
            try:
                self._f = open(path, "a" if append else "w")
            except OSError:
                self.path = None  # observe best-effort, never fail the run

    def on_block(self, block) -> list:
        """Decode + stream one chunk's block; returns the decoded rows
        so the run health plane can evaluate them without a second
        decode."""
        from .telemetry import rows_from_blocks

        rows = rows_from_blocks([block], self.group_ids)
        self.rows_written += len(rows)
        if self._f is not None:
            # observability must never fail the run it observes (the
            # SpanTracer rule): on ENOSPC etc., drop the file and keep
            # counting — the journal then reports rows without a file
            try:
                for row in rows:
                    self._f.write(json.dumps({**self.ident, **row}) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                self.path = None
        return rows

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                self.path = None
            finally:
                self._f = None

    def iter_rows(self):
        """Re-read the written series (for the Influx mirror) — the
        rows were streamed out, not retained. Unparseable lines are
        skipped (best-effort, like the push itself)."""
        from .telemetry import iter_jsonl

        if self.path is None:
            return
        yield from iter_jsonl(self.path)


class _SimNetMatrixWriter:
    """Streams the chunk-flushed traffic-matrix deltas (network topology
    plane, ``sim/netmatrix.py``) to the run's ``sim_netmatrix.jsonl``:
    one row per chunk, sparse nonzero cells only, so a quiet topology
    costs bytes-per-chunk and a hot one is bounded by the pairs that
    actually talked. EXACTLY one row per chunk dispatch — deterministic
    row count, which is what lets the checkpoint plane align the stream
    byte-exactly on resume. Same best-effort discipline as the
    telemetry writer: an unwritable file drops to counting, never fails
    the run."""

    def __init__(
        self,
        prog,
        ident: dict,
        path: str | None,
        append: bool = False,
        chunks_offset: int = 0,
    ):
        self.chunk = int(prog.chunk)
        self.ident = ident
        self.path = path
        self.chunks_written = int(chunks_offset)
        self._f = None
        if path is not None:
            try:
                self._f = open(path, "a" if append else "w")
            except OSError:
                self.path = None

    def on_delta(self, delta) -> None:
        from .netmatrix import delta_row

        idx = self.chunks_written
        self.chunks_written += 1
        if self._f is None:
            return
        row = delta_row(
            delta,
            tick=(idx + 1) * self.chunk,
            chunk=idx,
            ident=self.ident,
        )
        try:
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            self.path = None

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                self.path = None
            finally:
                self._f = None


class _SimTraceWriter:
    """Streams the chunk-flushed ``[chunk, R, 5]`` flight-recorder
    blocks (``sim/trace.py``) into the run's ``sim_trace.jsonl`` as they
    arrive — host memory stays bounded by one chunk for the jsonl path,
    and a crashed run keeps everything flushed so far. Decoded events are
    additionally buffered (bounded by the plan's ``events`` cap) for the
    Chrome trace export written at :meth:`close`; past the cap the jsonl
    keeps streaming and ``truncated`` counts what the export lost. With
    no outputs dir the writer only counts events (same rule as the
    telemetry writer)."""

    def __init__(
        self,
        groups,
        ident: dict,
        run_dir,
        tick_ms: float,
        plan,
        resume: dict | None = None,
    ):
        from .trace import TRACE_EVENTS_FILE, TRACE_FILE

        self.plan = plan
        self.ident = ident
        self.tick_ms = float(tick_ms)
        # resumed runs (sim/checkpoint.py) continue the stream where
        # the snapshot left it: counters come from the snapshot aux,
        # the Chrome-export buffer is re-seeded from the truncated
        # jsonl prefix below
        self.events_written = int((resume or {}).get("events", 0) or 0)
        self.truncated = int((resume or {}).get("truncated", 0) or 0)
        self._buffer: list[dict] = []
        self._groups = groups
        # lane → (group id, group-relative seq), for the TRACED lanes
        # only (≤ MAX_TRACE_LANES): a fleet-wide map would cost O(N)
        # memory for lookups that only ever hit the sample; the Chrome
        # export's track names derive from the same resolution
        self._lane_group = {}
        for lane in plan.lanes:
            lane = int(lane)
            g = next(
                (
                    g
                    for g in groups
                    if g.offset <= lane < g.offset + g.count
                ),
                None,
            )
            self._lane_group[lane] = (
                (g.id, lane - g.offset) if g is not None else ("", -1)
            )
        self._gid_of = {
            lane: gid for lane, (gid, _) in self._lane_group.items()
        }
        self.path = (
            os.path.join(run_dir, TRACE_FILE)
            if run_dir is not None
            else None
        )
        self.events_path = (
            os.path.join(run_dir, TRACE_EVENTS_FILE)
            if run_dir is not None
            else None
        )
        self._f = None
        if self.path is not None:
            if resume is not None:
                self._seed_buffer_from_file()
            try:
                self._f = open(self.path, "a" if resume is not None else "w")
            except OSError:  # observe best-effort, never fail the run
                self.path = None

    def _seed_buffer_from_file(self) -> None:
        """Re-read the (truncated-to-snapshot) jsonl prefix into the
        Chrome-export buffer so a resumed run's ``trace_events.json``
        still covers the whole run. Bounded by the plan's ``events``
        cap, exactly like the live path; best-effort."""
        from testground_tpu.sim.telemetry import iter_jsonl

        drop = set(self.ident)
        try:
            for row in iter_jsonl(self.path):
                if len(self._buffer) >= self.plan.events_cap:
                    break
                self._buffer.append(
                    {k: v for k, v in row.items() if k not in drop}
                )
        except OSError:
            pass

    def on_block(self, block) -> None:
        from .trace import events_from_blocks

        events = events_from_blocks(
            [block], lambda i: self._gid_of.get(i, "")
        )
        self.events_written += len(events)
        room = self.plan.events_cap - len(self._buffer)
        if room > 0:
            self._buffer.extend(events[:room])
        self.truncated += max(0, len(events) - max(room, 0))
        if self._f is not None:
            try:
                for ev in events:
                    self._f.write(json.dumps({**self.ident, **ev}) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                self.path = None

    def close(self) -> None:
        from .trace import chrome_trace

        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                self.path = None
            finally:
                self._f = None
        if self.events_path is None:
            return
        lane_names = {
            lane: f"{gid}[{seq}] i{lane}"
            for lane, (gid, seq) in self._lane_group.items()
        }
        try:
            with open(self.events_path, "w") as f:
                json.dump(
                    chrome_trace(
                        self._buffer,
                        self.plan.lanes,
                        lane_names,
                        self.tick_ms,
                    ),
                    f,
                )
        except (OSError, ValueError):
            self.events_path = None

    def journal(self) -> dict:
        from .trace import TRACE_EVENTS_FILE, TRACE_FILE

        out: dict = {
            "events": self.events_written,
            "instances": self.plan.count,
        }
        if self.path is not None:
            out["file"] = TRACE_FILE
        if self.events_path is not None:
            out["events_file"] = TRACE_EVENTS_FILE
        if self.truncated:
            out["truncated"] = self.truncated
        return out


class _TimeSeriesRecorder:
    """Periodic per-group metric reductions over the live sim carry — the
    pipeline the reference implements as SDK metric batches flushed to
    InfluxDB (``plans/example/metrics.go:15-19`` → viewer tables,
    ``pkg/metrics/viewer.go:45-80``). Each sample re-runs the plan's
    ``collect_metrics`` on the in-flight state and reduces it per group;
    rows land in ``timeseries.jsonl`` under the run's outputs dir."""

    def __init__(
        self,
        testcase,
        groups,
        every: int,
        ow: OutputWriter,
        phys_groups=None,
    ):
        self._collect = getattr(testcase, "collect_metrics", None)
        # ``groups`` is always the EXACT (virtual) layout samples report
        # in; ``phys_groups`` is the padded physical layout of a
        # bucketed carry (sim/buckets.py) — live-run samples then slice
        # each group's live span before reducing, so dead pad lanes
        # never enter a metric
        self.groups = groups
        self._phys = phys_groups
        self.every = int(every or 0)
        self._next_at = self.every
        self._last_tick = -1
        self.rows: list[dict] = []
        self.ow = ow
        self._warned: set[str] = set()

    @property
    def enabled(self) -> bool:
        return callable(self._collect) and self.every > 0

    # the recorder's sampled rows ride run checkpoints (sim/checkpoint.py)
    # so a resumed run's timeseries.jsonl still covers the whole run
    def state_dict(self) -> dict:
        return {
            "rows": list(self.rows),
            "next_at": self._next_at,
            "last_tick": self._last_tick,
        }

    def load_state(self, state: dict) -> None:
        self.rows = [dict(r) for r in state.get("rows", [])]
        self._next_at = int(state.get("next_at", self.every))
        self._last_tick = int(state.get("last_tick", -1))

    def observe(self, ticks: int, carry) -> None:
        if ticks < self._next_at:
            return
        self._next_at = ticks + self.every
        states, status = carry.states, np.asarray(carry.status)
        if self._phys is not None:
            import jax

            states = tuple(
                jax.tree.map(
                    lambda leaf, _lv=g.count: np.asarray(leaf)[:_lv],
                    states[gi],
                )
                for gi, g in enumerate(self.groups)
            )
            status = np.concatenate(
                [
                    status[pg.offset : pg.offset + g.count]
                    for pg, g in zip(self._phys, self.groups)
                ]
            )
        self.sample(ticks, states, status)

    def sample(self, tick: int, states, status) -> None:
        import jax

        if tick == self._last_tick:  # final sample on a cadence boundary
            return
        self._last_tick = tick
        for gi, g in enumerate(self.groups):
            try:
                m = self._collect(
                    g,
                    jax.tree.map(np.asarray, states[gi]),
                    status[g.offset : g.offset + g.count],
                )
            except Exception as e:  # noqa: BLE001 — sampling is best-effort
                if g.id not in self._warned:
                    self._warned.add(g.id)
                    self.ow.warn(
                        "timeseries sample failed for group %s: %s", g.id, e
                    )
                continue
            for name, agg in _aggregate_metrics(m).items():
                self.rows.append(
                    {"tick": int(tick), "group_id": g.id, "name": name, **agg}
                )


def _aggregate_metrics(group_metrics: dict) -> dict:
    """Per-group reductions of the per-instance metric arrays — the journal
    analog of the InfluxDB measurement tables the reference dashboard
    queries (``pkg/metrics/viewer.go:45-80``). NaN entries (instances for
    which a metric does not apply, e.g. the subtree publisher's receive
    timers) are excluded."""
    agg = {}
    for name, arr in group_metrics.items():
        a = np.asarray(arr, np.float64).reshape(-1)
        a = a[~np.isnan(a)]
        if a.size == 0:
            agg[name] = {"count": 0}
            continue
        agg[name] = {
            "count": int(a.size),
            "mean": float(a.mean()),
            "min": float(a.min()),
            "max": float(a.max()),
        }
    return agg


def _write_instance_outputs(
    outputs_root, job, g, st, res, group_metrics
) -> None:
    """Write the reference's outputs layout (``local_docker.go:258-267``):
    one dir per instance with run.out / metrics.out."""
    for i in range(g.count):
        d = instance_output_dir(
            outputs_root, job.test_plan, job.run_id, g.id, i
        )
        os.makedirs(d, exist_ok=True)
        name = _STATUS_NAME.get(int(st[i]), "incomplete")
        fin = int(res["finished_at"][g.offset + i])
        with open(os.path.join(d, "run.out"), "w") as f:
            f.write(
                json.dumps(
                    {
                        "ts": time.time_ns(),
                        "event": {
                            "type": name if name != "incomplete" else "message",
                            **(
                                {"message": "incomplete (max_ticks reached)"}
                                if name == "incomplete"
                                else {}
                            ),
                        },
                        "group_id": g.id,
                        "finished_at_tick": fin,
                    }
                )
                + "\n"
            )
        if group_metrics:
            with open(os.path.join(d, "metrics.out"), "w") as f:
                for mname, arr in group_metrics.items():
                    f.write(
                        json.dumps(
                            {
                                "ts": time.time_ns(),
                                "name": mname,
                                "value": float(np.asarray(arr)[i]),
                                "type": "point",
                            }
                        )
                        + "\n"
                    )
