"""Host-side driver for a sim run (placeholder; filled in with the sim
kernel milestone)."""

from __future__ import annotations

import threading

from testground_tpu.api import RunInput, RunOutput
from testground_tpu.rpc import OutputWriter


def execute_sim_run(
    job: RunInput, ow: OutputWriter, cancel: threading.Event
) -> RunOutput:
    raise NotImplementedError("sim:jax executor lands with the sim kernel")
