"""``sim:jax`` runner: executes a composition as a TPU simulation.

The north-star replacement for the reference's ``local:docker``/
``cluster:k8s`` runners: instead of one container per instance, one jitted
program hosts every instance (BASELINE.md targets 100k instances on a v4-8).
"""

from __future__ import annotations

import threading

from testground_tpu.api import RunInput, RunOutput
from testground_tpu.rpc import OutputWriter

from testground_tpu.runners.base import HealthcheckedRunner, Runner

__all__ = ["SimJaxRunner"]


class SimJaxRunner(Runner, HealthcheckedRunner):
    def id(self) -> str:
        return "sim:jax"

    def compatible_builders(self) -> list[str]:
        return ["sim:plan"]

    def config_type(self) -> type | None:
        from .executor import SimJaxConfig

        return SimJaxConfig

    def healthcheck(self, fix: bool, ow: OutputWriter):
        from testground_tpu.healthcheck.report import Report

        try:
            import jax  # noqa: F401
        except ImportError:
            from testground_tpu.healthcheck.report import CheckResult, FAILED

            return Report(checks=[CheckResult("jax-importable", FAILED)])
        return Report.all_ok(["jax-importable"])

    def run(
        self, job: RunInput, ow: OutputWriter, cancel: threading.Event
    ) -> RunOutput:
        from .executor import execute_sim_run

        return execute_sim_run(job, ow, cancel)
