"""``sim:jax`` runner: executes a composition as a TPU simulation.

The north-star replacement for the reference's ``local:docker``/
``cluster:k8s`` runners: instead of one container per instance, one jitted
program hosts every instance (BASELINE.md targets 100k instances on a v4-8).
"""

from __future__ import annotations

import threading

from testground_tpu.api import RunInput, RunOutput
from testground_tpu.rpc import OutputWriter

from testground_tpu.runners.base import (
    HealthcheckedRunner,
    Runner,
    Terminatable,
)

__all__ = ["SimJaxRunner"]


_mesh_check_ok: dict[tuple, str] = {}


def _mesh_check(devs_key: tuple) -> tuple[bool, str]:
    """Compile + execute a tiny sharded program over every device. Only
    SUCCESS is cached per device set (the supervisor healthchecks every
    run, but a transient failure must not poison the process)."""
    if devs_key in _mesh_check_ok:
        return True, _mesh_check_ok[devs_key]
    import jax
    import numpy as np

    devs = jax.devices()
    mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("i")
    )
    x = jax.device_put(np.arange(8 * len(devs), dtype=np.int32), sharding)
    y = np.asarray(jax.jit(lambda a: a + 1)(x))
    if int(y.sum()) != int(np.arange(8 * len(devs)).sum()) + y.size:
        return False, "mesh program computed a wrong result"
    msg = f"{len(devs)}-device mesh compiled and executed"
    _mesh_check_ok[devs_key] = msg
    return True, msg


class SimJaxRunner(Runner, HealthcheckedRunner, Terminatable):
    def id(self) -> str:
        return "sim:jax"

    def compatible_builders(self) -> list[str]:
        return ["sim:plan"]

    def config_type(self) -> type | None:
        from .executor import SimJaxConfig

        return SimJaxConfig

    def terminate_all(self, ow: OutputWriter) -> None:
        """In-flight device dispatches stop at the next chunk boundary via
        the task's cancel event; no containers/services persist a run."""
        ow.infof("sim:jax: no persistent resources to terminate")

    def healthcheck(self, fix: bool, ow: OutputWriter, env=None):
        """Real device checks: jax imports, at least one device answers, a
        mesh over every device compiles and executes a program, and device
        memory is not exhausted (the sim:jax analog of the reference's
        infra healthcheck booting redis/sidecar containers,
        ``local_common.go:18-122``) — plus the outputs dir with a mkdir
        fixer."""
        from testground_tpu.config import EnvConfig
        from testground_tpu.healthcheck import Helper, checkers, fixers

        def jax_importable():
            import jax  # noqa: F401

            return True, f"jax {jax.__version__}"

        def device_available():
            import jax

            devs = jax.devices()
            if not devs:
                return False, "no devices"
            return True, f"{len(devs)} device(s): {devs[0].platform}"

        def mesh_buildable():
            import jax

            devs = jax.devices()
            if not devs:
                return False, "no devices to build a mesh from"
            # cached per device set: the supervisor healthchecks before
            # every run and must not re-trace/compile each time
            return _mesh_check(tuple(str(d) for d in devs))

        def device_memory():
            import jax

            from .perf import device_memory_stats

            devs = jax.devices()
            if not devs:
                return False, "no devices"
            # the shared never-raising probe (sim/perf.py) — one place
            # normalizes backend-dependent memory_stats key presence
            stats = device_memory_stats(devs[0])
            limit = stats.get("bytes_limit")
            in_use = stats.get("bytes_in_use")
            if not limit or in_use is None:
                return True, "memory stats unavailable on this backend"
            frac = in_use / limit
            if frac > 0.95:
                return False, (
                    f"device memory nearly exhausted: "
                    f"{in_use}/{limit} bytes in use"
                )
            return True, f"{in_use}/{limit} bytes in use ({frac:.0%})"

        if env is None:  # observe the environment, don't repair it
            env = EnvConfig.load(ensure_dirs=False)
        h = Helper()
        h.enlist(
            "jax-importable",
            jax_importable,
            fixers.requires_manual_fixing("install jax"),
        )
        h.enlist(
            "device-available",
            device_available,
            fixers.requires_manual_fixing(
                "check JAX_PLATFORMS / device tunnel"
            ),
        )
        h.enlist("mesh-buildable", mesh_buildable)
        h.enlist("device-memory", device_memory)
        h.enlist(
            "outputs-dir-writable",
            checkers.check_dir_writable(env.dirs.outputs()),
            fixers.create_directory(env.dirs.outputs()),
        )
        return h.run_checks(fix, ow)

    def run(
        self, job: RunInput, ow: OutputWriter, cancel: threading.Event
    ) -> RunOutput:
        from .executor import execute_sim_run

        return execute_sim_run(job, ow, cancel)
