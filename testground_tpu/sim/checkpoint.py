"""Checkpoint/resume plane: durable live-sim snapshots with bit-identical
continuation (docs/CHECKPOINT.md).

Every prior observability plane autopsies or watches a run; none can
*revive* one. A preempted million-tick soak loses every tick even though
the carry is already a closed pytree the chunk loop syncs on once per
dispatch. This module closes that gap: snapshot the full run state every
K chunks (``--run-cfg checkpoint_chunks=K``) into the run's artifact
dir, and seed a later run from the newest snapshot so the resumed run is
**leaf-for-leaf identical** to an uninterrupted one — the checkpointing
trait preemptible-TPU economics (and run migration between chips)
actually needs.

What a snapshot holds — one atomic ``checkpoints/ckpt-<tick>.npz``:

- the **device carry** pytree, leaf for leaf, host-fetched at the chunk
  boundary the loop already syncs on (PRNG key leaves round-trip through
  ``jax.random.key_data`` / ``wrap_key_data`` with the impl recorded);
- the host-side **latency-histogram accumulator** (telemetry runs);
- a JSON **manifest** embedded in the archive: tick, chunk index, the
  composition identity + its hash, the plan-source ``build_key`` (the
  sim:plan precompile's BuildKey analog — an edited plan refuses to
  resume), transport backend, and the host-side **aux state** needed
  for exact continuation (SLO evaluator state, stream-file byte
  offsets, metric-recorder rows, writer counters).

Contract (the discipline every plane in this repo carries):

- **Zero overhead when off.** ``checkpoint_chunks`` shapes NOTHING: the
  program is jaxpr-identical and the host-sync count unchanged with the
  knob at 0 (pinned by ``tests/test_sim_checkpoint.py``). When on, the
  only cost is a device→host carry read every K-th chunk boundary.
- **Atomic, bounded, honest.** Snapshots write to a temp file and
  ``os.replace`` into place (a crash mid-write can never leave a
  half-snapshot under the final name); retention keeps the newest
  ``checkpoint_keep``; every write is journaled (``sim.checkpoint``),
  span-pointed, and exported (``tg_checkpoint_*``).
- **Refuse loudly, never resume garbage.** A corrupt/truncated archive,
  a manifest that fails validation, or a snapshot from a different
  composition/plan-source/transport raises :class:`CheckpointError`
  naming exactly what mismatched. Resume falls back LOUDLY from an
  unloadable newest snapshot to the next retained one (warned +
  journaled, see :func:`load_latest`); only when every retained
  snapshot is unloadable does the resume refuse.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import time
import zipfile

import numpy as np

__all__ = [
    "CHECKPOINT_DIR",
    "CheckpointError",
    "ResumeState",
    "RunCheckpointer",
    "identity_hash",
    "list_snapshots",
    "load_latest",
    "load_snapshot",
    "prepare_resume",
    "restore_carry",
    "run_identity",
    "save_snapshot",
    "snapshot_carry",
]

# Snapshots live under <run outputs dir>/checkpoints/ckpt-<tick>.npz —
# inside the run's artifact dir so `tg collect` tars them and the
# daemon's GET /artifact whitelist can serve them for run migration.
CHECKPOINT_DIR = "checkpoints"
_PREFIX = "ckpt-"
_SUFFIX = ".npz"
_TICK_WIDTH = 12  # zero-padded so lexical order == tick order

# Resume-load retry budget (the influx exporter's idiom, metrics/
# influx.py): a snapshot being fetched or copied for a migration can
# hit transient I/O that is indistinguishable from corruption on the
# first read — retry with bounded exponential backoff + jitter before
# declaring the candidate unloadable. Module-level so tests can shrink
# the waits.
_RETRY_ATTEMPTS = 3
_RETRY_BASE_SECS = 0.25
_RETRY_JITTER_SECS = 0.1

# Bumped when the archive layout changes; a mismatch refuses to resume
# (an old snapshot must never be silently reinterpreted).
FORMAT_VERSION = 1

_MANIFEST_KEY = "__manifest__"
_LEAF_FMT = "leaf_{:05d}"
_AUX_LAT_KEY = "aux_lat_hist"
_AUX_NM_KEY = "aux_net_matrix"


class CheckpointError(RuntimeError):
    """A snapshot could not be written, read, validated, or restored.

    The typed refusal of the checkpoint plane: resuming from a corrupt,
    truncated, or mismatched snapshot must fail HERE with a readable
    reason — never seed a run with garbage state."""


# --------------------------------------------------------------- identity


def run_identity(
    job,
    cfg,
    *,
    telemetry: bool,
    transport: str,
    fault_specs: dict,
    trace_specs: dict,
    hosts,
    bucket=None,
    netmatrix: bool = False,
) -> dict:
    """The resume-compatibility identity of a run: everything that shapes
    the compiled program or the deterministic tick stream. A snapshot
    taken under one identity refuses to seed a run built under another
    (``validate_manifest``). ``max_ticks`` is deliberately ABSENT — it
    is a stop budget, not a program shape, so a run interrupted by a
    short budget can be resumed with a longer one.

    ``sources`` digests each group's plan-source artifact (the sim:plan
    precompile's ``_source_digest``) — the BuildKey ingredient that makes
    an edited plan refuse to resume instead of silently diverging."""
    from testground_tpu.builders.sim_plan import _source_digest

    sources = {}
    for g in job.groups:
        try:
            sources[g.id] = _source_digest(g.artifact_path)
        except OSError:
            sources[g.id] = ""
    return {
        "plan": job.test_plan,
        "case": job.test_case,
        "groups": [
            {
                "id": g.id,
                "instances": g.instances,
                "parameters": dict(g.parameters),
            }
            for g in job.groups
        ],
        "sources": sources,
        "tick_ms": cfg.tick_ms,
        "chunk": cfg.chunk,
        "seed": cfg.seed,
        "validate": bool(getattr(cfg, "validate", False)),
        "telemetry": bool(telemetry),
        "transport": str(transport),
        "faults": fault_specs,
        "trace": trace_specs,
        "hosts": list(hosts),
        # shape bucketing (sim/buckets.py): the padded per-group layout
        # shapes every carry leaf, so a snapshot taken under one bucket
        # refuses to seed a program built under another. Keyed only when
        # bucketed, so pre-bucket snapshots keep resuming unchanged.
        **({"bucket": list(bucket)} if bucket else {}),
        # traffic-matrix plane (sim/netmatrix.py): program-shaping (the
        # matrix rides the carry) AND aux-shaping (the host accumulator
        # + sim_netmatrix.jsonl alignment). Keyed only when on, so
        # pre-matrix snapshots keep resuming unchanged.
        **({"netmatrix": True} if netmatrix else {}),
    }


def identity_hash(identity: dict, drop: tuple = ()) -> str:
    """Stable hash of an identity dict (the sim:plan BuildKey style:
    sha256 of the sorted-key JSON, truncated)."""
    d = {k: v for k, v in identity.items() if k not in drop}
    return hashlib.sha256(
        json.dumps(d, sort_keys=True).encode()
    ).hexdigest()[:32]


# ------------------------------------------------------------ carry <-> np


def _is_prng_leaf(leaf) -> bool:
    import jax

    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return False
    try:
        return jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def snapshot_carry(carry) -> tuple[list, list]:
    """Flatten a live carry to host arrays: ``(leaves, metas)``.

    Typed PRNG-key leaves (extended dtype — ``np.asarray`` would raise)
    are exported via ``jax.random.key_data`` with the impl name recorded
    so restore can refuse a cross-impl resume instead of producing a
    silently different random stream. The device→host reads here are the
    checkpoint plane's only cost, paid at K-chunk boundaries only."""
    import jax

    flat = jax.tree_util.tree_leaves(carry)
    leaves: list = []
    metas: list = []
    for leaf in flat:
        if _is_prng_leaf(leaf):
            impl = str(jax.random.key_impl(leaf))
            data = np.asarray(jax.random.key_data(leaf))
            leaves.append(data)
            metas.append(
                {
                    "kind": "prng",
                    "impl": impl,
                    "shape": list(data.shape),
                    "dtype": str(data.dtype),
                }
            )
        else:
            data = np.asarray(leaf)
            leaves.append(data)
            metas.append(
                {
                    "kind": "array",
                    "shape": list(data.shape),
                    "dtype": str(data.dtype),
                }
            )
    return leaves, metas


def restore_carry(prog, seed: int, manifest: dict, leaves: list):
    """Rebuild the device carry from snapshot leaves against ``prog``'s
    OWN carry structure: ``eval_shape`` over ``init_carry`` supplies the
    reference treedef and avals (no allocation, no compile), every leaf
    is validated shape-and-dtype against it, PRNG leaves re-wrap through
    ``wrap_key_data``, and the assembled pytree lands on device through
    the same ``_constrain`` jit ``init_carry`` uses — so a mesh run
    reshards the restored carry exactly as it would a fresh one."""
    import jax

    if getattr(prog, "live_counts", None) is not None:
        # bucketed programs init against runtime live counts (shapes
        # depend only on the padded layout the identity validated)
        shapes = jax.eval_shape(
            lambda: prog.init_carry(
                seed, np.asarray(prog.live_counts, np.int32)
            )
        )
    else:
        shapes = jax.eval_shape(lambda: prog.init_carry(seed))
    ref_leaves, treedef = jax.tree_util.tree_flatten(shapes)
    metas = manifest.get("leaves") or []
    if len(leaves) != len(ref_leaves) or len(metas) != len(ref_leaves):
        raise CheckpointError(
            f"snapshot holds {len(leaves)} carry leaves but this program's "
            f"carry has {len(ref_leaves)} — the snapshot was taken under a "
            "different program shape (plan edit? different telemetry/"
            "transport gates?); refusing to resume"
        )
    out = []
    for i, (data, meta, ref) in enumerate(zip(leaves, metas, ref_leaves)):
        kind = meta.get("kind", "array")
        if kind == "prng":
            if not _is_prng_leaf(ref):
                raise CheckpointError(
                    f"snapshot leaf {i} is a PRNG key but the program "
                    "expects a plain array there — program shape drift; "
                    "refusing to resume"
                )
            try:
                restored = jax.random.wrap_key_data(np.asarray(data))
            except (TypeError, ValueError) as e:
                raise CheckpointError(
                    f"snapshot PRNG leaf {i} does not re-wrap as key "
                    f"data ({e}); refusing to resume"
                ) from e
            impl = str(jax.random.key_impl(restored))
            if meta.get("impl") and meta["impl"] != impl:
                raise CheckpointError(
                    f"snapshot PRNG leaf {i} was saved under key impl "
                    f"{meta.get('impl')!r} but this jax resolves "
                    f"{impl!r} — resuming would change the random "
                    "stream; refusing"
                )
            if restored.shape != ref.shape or str(restored.dtype) != str(
                ref.dtype
            ):
                raise CheckpointError(
                    f"snapshot PRNG leaf {i} restores as "
                    f"{restored.dtype}{list(restored.shape)} but the "
                    f"program expects {ref.dtype}{list(ref.shape)}; "
                    "refusing to resume"
                )
            out.append(restored)
            continue
        if _is_prng_leaf(ref):
            raise CheckpointError(
                f"snapshot leaf {i} is a plain array but the program "
                "expects a PRNG key there — program shape drift; "
                "refusing to resume"
            )
        if tuple(data.shape) != tuple(ref.shape) or str(
            data.dtype
        ) != str(ref.dtype):
            raise CheckpointError(
                f"snapshot leaf {i} is {data.dtype}{list(data.shape)} but "
                f"the program expects {ref.dtype}{list(ref.shape)} — the "
                "snapshot was taken under a different composition; "
                "refusing to resume"
            )
        out.append(data)
    host_carry = jax.tree_util.tree_unflatten(treedef, out)
    # same device/sharding treatment as init_carry: the identity-or-
    # constrain jit materializes every leaf on device (and reshards
    # under a mesh at the exact constraints a fresh carry gets)
    return jax.jit(prog._constrain)(host_carry)


# ------------------------------------------------------------ file format


def _snapshot_name(tick: int) -> str:
    return f"{_PREFIX}{int(tick):0{_TICK_WIDTH}d}{_SUFFIX}"


def _tick_of(name: str) -> int | None:
    if not (name.startswith(_PREFIX) and name.endswith(_SUFFIX)):
        return None
    digits = name[len(_PREFIX) : -len(_SUFFIX)]
    return int(digits) if digits.isdigit() else None


def list_snapshots(run_dir: str) -> list[tuple[int, str]]:
    """``[(tick, path)]`` ascending by tick; unparseable names and
    in-flight temp files are ignored."""
    d = os.path.join(run_dir, CHECKPOINT_DIR)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return []
    for name in names:
        tick = _tick_of(name)
        if tick is not None:
            out.append((tick, os.path.join(d, name)))
    out.sort()
    return out


def save_snapshot(
    run_dir: str, manifest: dict, leaves: list, lat_hist=None, net_matrix=None
) -> tuple[str, int, float]:
    """Write one snapshot atomically; returns ``(path, bytes, write_ms)``.

    The archive is a plain (uncompressed) npz: carry leaves under
    ``leaf_NNNNN``, the optional latency and traffic-matrix accumulators
    under ``aux_lat_hist`` / ``aux_net_matrix``, and the manifest JSON
    as a uint8 array under ``__manifest__`` — ONE file, so
    ``os.replace`` makes the commit atomic and a crash mid-write can
    never leave a half-snapshot under a final name."""
    t0 = time.perf_counter()
    d = os.path.join(run_dir, CHECKPOINT_DIR)
    try:
        os.makedirs(d, exist_ok=True)
        arrays = {
            _LEAF_FMT.format(i): leaf for i, leaf in enumerate(leaves)
        }
        if lat_hist is not None:
            arrays[_AUX_LAT_KEY] = np.asarray(lat_hist)
        if net_matrix is not None:
            arrays[_AUX_NM_KEY] = np.asarray(net_matrix)
        arrays[_MANIFEST_KEY] = np.frombuffer(
            json.dumps(manifest).encode(), dtype=np.uint8
        )
        final = os.path.join(d, _snapshot_name(manifest["tick"]))
        tmp = final + f".tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        size = os.path.getsize(final)
    except OSError as e:
        raise CheckpointError(f"snapshot write failed: {e}") from e
    return final, size, (time.perf_counter() - t0) * 1000.0


def prune_snapshots(run_dir: str, keep: int) -> int:
    """Bounded retention: delete all but the newest ``keep`` snapshots.
    Returns how many were removed. Best-effort (an undeletable old
    snapshot must not fail the run that just wrote a new one)."""
    if keep <= 0:
        return 0
    snaps = list_snapshots(run_dir)
    removed = 0
    for _, path in snaps[:-keep]:
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


def load_snapshot(path: str) -> tuple[dict, list]:
    """Read one snapshot → ``(manifest, carry leaves)``.

    Every failure mode — unreadable file, truncated zip, missing
    manifest, malformed JSON, missing/extra leaf entries, version drift
    — raises :class:`CheckpointError` naming the file and the defect:
    a damaged snapshot must refuse loudly, never resume garbage."""
    try:
        # np.load streams members out of the zip on access — the
        # archive is never materialized whole beside its leaves (a
        # million-instance carry snapshot is GBs; doubling it on the
        # resume path would OOM exactly the runs checkpointing is for)
        with np.load(path, allow_pickle=False) as z:
            names = set(z.files)
            if _MANIFEST_KEY not in names:
                raise CheckpointError(
                    f"snapshot {path} has no embedded manifest — not a "
                    "checkpoint archive (or one written by an "
                    "incompatible version); refusing to resume"
                )
            try:
                manifest = json.loads(bytes(z[_MANIFEST_KEY]).decode())
            except (ValueError, UnicodeDecodeError) as e:
                raise CheckpointError(
                    f"snapshot {path} manifest is not valid JSON ({e}) — "
                    "corrupt archive; refusing to resume"
                ) from e
            if manifest.get("version") != FORMAT_VERSION:
                raise CheckpointError(
                    f"snapshot {path} is format version "
                    f"{manifest.get('version')!r}, this build reads "
                    f"{FORMAT_VERSION} — refusing to reinterpret"
                )
            n = len(manifest.get("leaves") or [])
            leaves = []
            for i in range(n):
                key = _LEAF_FMT.format(i)
                if key not in names:
                    raise CheckpointError(
                        f"snapshot {path} is missing carry leaf {i} of "
                        f"{n} — truncated or corrupt archive; refusing "
                        "to resume"
                    )
                leaves.append(z[key])
            if manifest.get("aux", {}).get("lat_hist"):
                if _AUX_LAT_KEY not in names:
                    raise CheckpointError(
                        f"snapshot {path} manifest promises a latency "
                        "accumulator but the archive has none — corrupt; "
                        "refusing to resume"
                    )
                manifest["_lat_hist"] = z[_AUX_LAT_KEY]
            if manifest.get("aux", {}).get("net_matrix"):
                if _AUX_NM_KEY not in names:
                    raise CheckpointError(
                        f"snapshot {path} manifest promises a traffic-"
                        "matrix accumulator but the archive has none — "
                        "corrupt; refusing to resume"
                    )
                manifest["_net_matrix"] = z[_AUX_NM_KEY]
    except CheckpointError:
        raise
    except (zipfile.BadZipFile, ValueError, KeyError, OSError, EOFError) as e:
        raise CheckpointError(
            f"snapshot {path} is corrupt or truncated ({type(e).__name__}: "
            f"{e}); refusing to resume"
        ) from e
    return manifest, leaves


def _load_snapshot_retrying(path: str) -> tuple[dict, list]:
    """:func:`load_snapshot` under the bounded retry budget above."""
    last: CheckpointError | None = None
    for attempt in range(1, _RETRY_ATTEMPTS + 1):
        try:
            return load_snapshot(path)
        except CheckpointError as e:
            last = e
            if attempt < _RETRY_ATTEMPTS:
                time.sleep(
                    _RETRY_BASE_SECS * 2 ** (attempt - 1)
                    + random.uniform(0, _RETRY_JITTER_SECS)
                )
    raise last  # type: ignore[misc]  # loop always sets it


def load_latest(run_dir: str) -> tuple[dict, list, str]:
    """Load the newest LOADABLE snapshot of a run dir → ``(manifest,
    leaves, path)``. No snapshots → :class:`CheckpointError`.

    Each candidate load gets the bounded retry budget above, so
    transient I/O during a migration fetch does not read as corruption.
    A newest snapshot that still fails falls back LOUDLY to the next
    retained one: the fallback rides the returned manifest
    (``_fallback``: skipped files + the first error) so the resume
    warns and journals it — resuming from an older tick *silently*
    would be its own kind of garbage, but refusing a run that holds a
    perfectly good previous snapshot strands exactly the preempted runs
    checkpointing exists for. Only when EVERY retained snapshot is
    unloadable does the resume refuse."""
    snaps = list_snapshots(run_dir)
    if not snaps:
        raise CheckpointError(
            f"no snapshots under {os.path.join(run_dir, CHECKPOINT_DIR)} — "
            "was the run checkpointed (--run-cfg checkpoint_chunks=K)?"
        )
    skipped: list[str] = []
    first_error = ""
    for _, path in reversed(snaps):
        try:
            manifest, leaves = _load_snapshot_retrying(path)
        except CheckpointError as e:
            if not skipped:
                first_error = str(e)
            skipped.append(os.path.basename(path))
            continue
        if skipped:
            manifest["_fallback"] = {
                "skipped": list(skipped),
                "error": first_error[:300],
            }
        return manifest, leaves, path
    raise CheckpointError(
        "every retained snapshot under "
        f"{os.path.join(run_dir, CHECKPOINT_DIR)} is corrupt or "
        f"unreadable ({', '.join(skipped)}) — refusing to resume; "
        f"newest failed with: {first_error}"
    )


def validate_manifest(manifest: dict, identity: dict) -> None:
    """Refuse a snapshot whose identity does not match the run being
    resumed — naming WHAT differs, because "hash mismatch" is not an
    actionable error."""
    want = identity_hash(identity)
    got = manifest.get("build_key")
    if got == want:
        return
    theirs = manifest.get("identity") or {}
    diffs = [
        k
        for k in sorted(set(identity) | set(theirs))
        if identity.get(k) != theirs.get(k)
    ]
    raise CheckpointError(
        "snapshot was taken under a different run identity — "
        f"mismatched field(s): {diffs or ['<unrecorded identity>']} "
        f"(snapshot build_key {got!r}, this run {want!r}); a resumed run "
        "must rebuild the exact program that wrote the snapshot"
    )


# ---------------------------------------------------------------- resume


@dataclasses.dataclass
class ResumeState:
    """Everything the executor needs to continue a run from a snapshot."""

    manifest: dict
    leaves: list
    path: str  # snapshot file the state came from
    source_run_dir: str

    @property
    def tick(self) -> int:
        return int(self.manifest.get("tick", 0))

    @property
    def lat_hist(self):
        h = self.manifest.get("_lat_hist")
        return None if h is None else np.asarray(h, dtype=np.int64)

    @property
    def net_matrix(self):
        m = self.manifest.get("_net_matrix")
        return None if m is None else np.asarray(m, dtype=np.int64)

    @property
    def aux(self) -> dict:
        return self.manifest.get("aux") or {}


def _sync_stream_files(
    source_run_dir: str, dest_run_dir: str, offsets: dict
) -> None:
    """Make the destination run dir's stream files hold EXACTLY the
    rows written up to the snapshot tick, so appended post-resume rows
    continue the stream where the snapshot left it:

    - in-place resume (same dir): truncate each file to its recorded
      byte offset (rows the interrupted run wrote PAST the snapshot
      would otherwise duplicate when the resumed run re-executes those
      ticks);
    - cross-run resume (new dir): copy each file's prefix bytes over.

    Offsets were taken after the writers' per-chunk flush, so they land
    exactly on row boundaries."""
    for name, offset in (offsets or {}).items():
        # stream names come from the snapshot manifest: constrain to
        # plain basenames so a doctored manifest cannot path-traverse
        if name != os.path.basename(name) or not isinstance(offset, int):
            raise CheckpointError(
                f"snapshot stream-offset entry {name!r} is not a plain "
                "file name — refusing to resume from a doctored manifest"
            )
        src = os.path.join(source_run_dir, name)
        dst = os.path.join(dest_run_dir, name)
        try:
            if os.path.abspath(src) == os.path.abspath(dst):
                if os.path.exists(src):
                    with open(src, "r+b") as f:
                        f.truncate(offset)
                continue
            if not os.path.exists(src):
                continue
            with open(src, "rb") as fin, open(dst, "wb") as fout:
                remaining = int(offset)
                while remaining > 0:
                    buf = fin.read(min(remaining, 4 << 20))
                    if not buf:
                        break
                    fout.write(buf)
                    remaining -= len(buf)
        except OSError as e:
            raise CheckpointError(
                f"could not prepare stream file {name} for resume: {e}"
            ) from e


def prepare_resume(
    source_run_dir: str, dest_run_dir: str | None, identity: dict
) -> ResumeState:
    """Load + validate the newest snapshot of ``source_run_dir`` and
    align the destination run dir's stream files to the snapshot tick
    (see :func:`_sync_stream_files`). The carry itself is restored later
    by :func:`restore_carry`, against the rebuilt program."""
    manifest, leaves, path = load_latest(source_run_dir)
    validate_manifest(manifest, identity)
    tick = int(manifest.get("tick", -1))
    chunk = int(identity.get("chunk") or 0)
    if tick < 0 or (chunk > 0 and tick % chunk != 0):
        raise CheckpointError(
            f"snapshot {path} records tick {tick}, which is not a "
            f"{chunk}-tick chunk boundary — corrupt manifest; refusing "
            "to resume"
        )
    if dest_run_dir is not None:
        _sync_stream_files(
            source_run_dir,
            dest_run_dir,
            (manifest.get("aux") or {}).get("streams") or {},
        )
    return ResumeState(
        manifest=manifest,
        leaves=leaves,
        path=path,
        source_run_dir=source_run_dir,
    )


# ------------------------------------------------------------ write side


class RunCheckpointer:
    """Per-run snapshot writer, driven from the chunk loop's observer
    hook (``SimProgram.run(observer=...)`` — called after the chunk's
    telemetry/trace/SLO callbacks, so the aux offsets it records are
    flush-exact). Every K-th chunk boundary: fetch the carry, assemble
    the manifest (identity + aux state from ``aux_cb``), write
    atomically, prune retention, journal + span the write. Failures
    raise nothing past the first warn — a run must never die because
    its snapshot could not be written — but are recorded in the journal
    (``errors``)."""

    def __init__(
        self,
        run_dir: str,
        *,
        every_chunks: int,
        keep: int,
        chunk: int,
        identity: dict,
        ident: dict,
        aux_cb=None,
        spans=None,
        warn=None,
        telemetry: bool = False,
        resumed_from: dict | None = None,
    ):
        self.run_dir = run_dir
        self.every = max(1, int(every_chunks))
        self.keep = max(1, int(keep))
        self.chunk = max(1, int(chunk))
        self.identity = identity
        self.ident = dict(ident or {})
        self.aux_cb = aux_cb
        self.spans = spans
        self.warn = warn
        self.telemetry = bool(telemetry)
        self.resumed_from = resumed_from
        self.count = 0
        self.last_tick: int | None = None
        self.last_bytes = 0
        self.last_write_ms = 0.0
        self.total_write_ms = 0.0
        self.errors = 0
        self._lat_hist = None  # [G, LATENCY_BINS] int64 mirror
        self._net_mat = None  # [NM_CHANNELS, GH, GH] int64 mirror
        self._warned = False

    # fed from the run loop's lat_hist_cb (telemetry programs only):
    # mirrors the engine's own accumulator so a snapshot can restore it
    def on_lat_delta(self, delta) -> None:
        d = np.asarray(delta, dtype=np.int64)
        self._lat_hist = d if self._lat_hist is None else self._lat_hist + d

    def seed_lat_hist(self, acc) -> None:
        if acc is not None:
            self._lat_hist = np.asarray(acc, dtype=np.int64).copy()

    # same mirror discipline for the traffic-matrix plane's accumulator
    # (netmatrix programs only; fed from the loop's netmatrix_cb)
    def on_net_matrix_delta(self, delta) -> None:
        d = np.asarray(delta, dtype=np.int64)
        self._net_mat = d if self._net_mat is None else self._net_mat + d

    def seed_net_matrix(self, acc) -> None:
        if acc is not None:
            self._net_mat = np.asarray(acc, dtype=np.int64).copy()

    def observe(self, ticks: int, carry) -> None:
        chunk_index = int(ticks) // self.chunk
        if chunk_index % self.every != 0:
            return
        self.snapshot(int(ticks), carry)

    def snapshot(self, ticks: int, carry) -> None:
        import jax

        try:
            leaves, metas = snapshot_carry(carry)
            aux = dict(self.aux_cb() if self.aux_cb is not None else {})
            aux["lat_hist"] = self._lat_hist is not None
            aux["net_matrix"] = self._net_mat is not None
            manifest = {
                "version": FORMAT_VERSION,
                "tick": int(ticks),
                "chunk_index": int(ticks) // self.chunk,
                "chunk": self.chunk,
                "transport": self.identity.get("transport", "xla"),
                "telemetry": self.telemetry,
                "composition_hash": identity_hash(
                    self.identity, drop=("sources",)
                ),
                "build_key": identity_hash(self.identity),
                "identity": self.identity,
                "leaves": metas,
                "aux": aux,
                "jax": jax.__version__,
                **self.ident,
            }
            path, size, write_ms = save_snapshot(
                self.run_dir,
                manifest,
                leaves,
                lat_hist=self._lat_hist,
                net_matrix=self._net_mat,
            )
            prune_snapshots(self.run_dir, self.keep)
        except Exception as e:  # noqa: BLE001
            # snapshotting is best-effort observability-style: the run
            # it protects must never die because a write failed
            self.errors += 1
            if self.warn is not None and not self._warned:
                self._warned = True
                self.warn(
                    "checkpoint at tick %d failed (further failures "
                    "counted silently): %s",
                    int(ticks),
                    e,
                )
            return
        self.count += 1
        self.last_tick = int(ticks)
        self.last_bytes = int(size)
        self.last_write_ms = round(write_ms, 3)
        self.total_write_ms += write_ms
        if self.spans is not None:
            self.spans.point(
                "checkpoint",
                tick=int(ticks),
                bytes=int(size),
                write_ms=round(write_ms, 3),
                file=os.path.basename(path),
            )

    def journal(self) -> dict:
        out: dict = {
            "every_chunks": self.every,
            "keep": self.keep,
            "count": self.count,
            "dir": CHECKPOINT_DIR,
        }
        if self.last_tick is not None:
            out["last_tick"] = self.last_tick
            out["bytes"] = self.last_bytes
            out["write_ms"] = self.last_write_ms
            out["total_write_ms"] = round(self.total_write_ms, 3)
        if self.errors:
            out["errors"] = self.errors
        if self.resumed_from:
            out["resumed"] = dict(self.resumed_from)
        return out
