"""Sampled flight recorder: per-instance message-lifecycle event traces.

The telemetry plane (docs/OBSERVABILITY.md) answers *how much* — counter
rows and latency histograms. This module answers *what happened to
instance i*: a composition samples instances via ``[global.run.trace]``
/ ``[groups.run.trace]`` (range / seeded-fraction selectors, the same
machinery as the fault plane's target selectors), and for those lanes
the jitted tick appends fixed-shape event rows — status transitions,
sync signals (barrier entry), per-message sends with their transport
fate, deliveries with provenance — to the chunk scan's stacked outputs.
The rows ride the same dispatch result as the ``done`` flag and the
counter block, so tracing adds **zero extra host syncs**; with no
``[run.trace]`` declared the plan lowers to ``None`` and the engine
compiles the identical no-trace program (the zero-overhead contract,
pinned by jaxpr equality exactly like the fault plane).

Host-side, the flushed blocks decode into ``sim_trace.jsonl`` (one JSON
event per line) and export as Chrome trace-event JSON
(``trace_events.json``, one Perfetto/chrome://tracing track per traced
instance) — the per-instance timeline view the reference scatters
across container logs, made structured and loadable in a profiler UI.

Event rows are ``[R, 5]`` int32 per tick with columns
``(tick, lane, kind, a, b)``; ``kind == -1`` marks an unused slot (the
decoder drops them). R is static: one status slot + one slot per sync
state + one per outbox slot + one per inbox slot, per traced lane — a
bounded ring per tick, so a fully quiet traced instance costs R rows of
-1 and nothing else.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# The trace plane reuses the fault plane's validated target selectors
# (group / "lo:hi" range / seeded fraction) — one selector grammar for
# "which instances does this declaration touch", whether it kills them
# or records them.
from .faults import _Selector, _resolve_mask

__all__ = [
    "EVENT_KINDS",
    "FATE_NAMES",
    "MAX_TRACE_LANES",
    "TRACE_EVENTS_FILE",
    "TRACE_FILE",
    "TracePlan",
    "build_trace_plan",
    "chrome_trace",
    "events_from_blocks",
    "parse_trace",
    "read_trace_events",
]

# Per-run output file names (under <outputs>/<plan>/<run_id>/).
TRACE_FILE = "sim_trace.jsonl"
TRACE_EVENTS_FILE = "trace_events.json"

# Event kind codes (column 2 of a device row; -1 = unused slot).
EV_STATUS, EV_SIGNAL, EV_SEND, EV_DELIVER = range(4)
EVENT_KINDS = ("status", "signal", "send", "deliver")

# Transport fate codes for a traced send (column ``b`` of an EV_SEND
# row) — where the message landed in the flow-conservation identity.
FATE_NAMES = ("enqueued", "rejected", "fault_dropped", "dropped")

# Status code names (sim/api.py RUNNING/SUCCESS/FAILURE/CRASH).
_STATUS_NAMES = ("running", "success", "failure", "crash")

# Refuse schedules that trace an unbounded slice of a big run: every
# traced lane emits (1 + S + O + IN) rows per tick through the scan
# output, so tracing is a SAMPLING tool — a full-fleet trace of a 100k
# run would dwarf the calendar itself. Loud static refusal, same policy
# as MAX_FILTER_CELLS.
MAX_TRACE_LANES = 4096

# Keys a [run.trace] table may carry — an unknown key is a typo'd
# selector, and a silently-ignored selector records the wrong instances.
_KNOWN_KEYS = {"group", "instances", "fraction", "seed", "events"}

# Default host-side cap on decoded events kept for the Chrome export
# (sim_trace.jsonl streams unbounded; the in-memory export buffer must
# not). Overridable per composition via the ``events`` key.
DEFAULT_EVENTS_CAP = 200_000


@dataclasses.dataclass(frozen=True)
class TracePlan:
    """The lowered trace declaration: which lanes to record, statically.

    ``mask`` is [N] bool over the plan instance axis; ``lanes`` its
    sorted nonzero indices (the static gather index the engine bakes
    into the traced tick). ``events_cap`` bounds the host-side Chrome
    export buffer."""

    n: int
    mask: np.ndarray  # [N] bool
    lanes: np.ndarray  # [L] int32, sorted
    events_cap: int = DEFAULT_EVENTS_CAP

    @property
    def count(self) -> int:
        return int(self.lanes.size)

    def summary(self) -> str:
        shown = ", ".join(str(i) for i in self.lanes[:8])
        if self.count > 8:
            shown += ", …"
        return f"{self.count} traced instance(s) [{shown}]"


def parse_trace(d: dict, default_group: str = "") -> tuple[_Selector, int]:
    """Validate one raw ``[run.trace]`` table → (selector, events cap).

    ``default_group`` scopes a group-level declaration to its own group
    when no explicit ``group`` key is given (run-global tables pass
    ``""``) — the same scoping rule as ``faults.parse_fault``."""
    if not isinstance(d, dict):
        raise ValueError(
            f"trace entry must be a table, got {type(d).__name__}"
        )
    unknown = set(d) - _KNOWN_KEYS
    if unknown:
        raise ValueError(
            f"trace entry has unknown key(s) {sorted(unknown)}; known "
            f"keys: {sorted(_KNOWN_KEYS)}"
        )
    fraction = float(d.get("fraction", 0.0))
    if fraction and not (0.0 < fraction <= 1.0):
        raise ValueError(f"trace: fraction {fraction} not in (0, 1]")
    events = int(d.get("events", 0))
    if events < 0:
        raise ValueError(f"trace: events cap {events} must be >= 0")
    sel = _Selector(
        group=str(d.get("group", "") or default_group),
        instances=str(d.get("instances", "")),
        fraction=fraction,
        seed=int(d.get("seed", 0)),
    )
    return sel, events


def build_trace_plan(groups, trace_by_group: dict) -> TracePlan | None:
    """Validate + lower every declared trace table into one static plan.

    ``groups`` is the resolved ``GroupSpec`` layout; ``trace_by_group``
    maps group id → raw ``[groups.run.trace]`` table (key ``""`` holds
    the run-global ``[global.run.trace]``). Returns ``None`` when
    nothing is declared — the engine then compiles the identical
    no-trace program (the zero-overhead contract)."""
    n = sum(g.count for g in groups)
    mask = np.zeros((n,), bool)
    cap = 0
    declared = False
    for gid, table in sorted((trace_by_group or {}).items()):
        if not table:
            continue
        declared = True
        sel, events = parse_trace(table, default_group=gid)
        mask |= _resolve_mask(sel, groups, n, "trace")
        cap = max(cap, events)
    if not declared:
        return None
    lanes = np.flatnonzero(mask).astype(np.int32)
    if lanes.size > MAX_TRACE_LANES:
        raise ValueError(
            f"trace selects {lanes.size} instances, over the "
            f"MAX_TRACE_LANES budget of {MAX_TRACE_LANES} — the flight "
            "recorder is a sampling tool (every traced lane emits event "
            "rows each tick); narrow the range or use a fraction"
        )
    return TracePlan(
        n=n, mask=mask, lanes=lanes, events_cap=cap or DEFAULT_EVENTS_CAP
    )


def read_trace_events(
    outputs_root: str, plan: str, task_id: str, limit: int = 0
) -> list[dict]:
    """Read a task's recorded ``sim_trace.jsonl`` events back from the
    outputs tree — the ONE resolver behind ``tg trace`` (in-process) and
    the daemon's ``GET /trace`` route, so the two surfaces cannot drift.
    A task's runs live under ``<outputs>/<plan>/<task_id>`` (single run)
    or ``<task_id>-<run_id>`` (multi-``[[runs]]`` compositions); events
    from every matching run dir are returned in file order, each tagged
    with its ``run``. ``limit`` > 0 truncates."""
    import os

    from .telemetry import iter_jsonl

    root = os.path.join(outputs_root, plan)
    if not os.path.isdir(root):
        return []
    events: list[dict] = []
    for run_id in sorted(os.listdir(root)):
        if run_id != task_id and not run_id.startswith(task_id + "-"):
            continue
        path = os.path.join(root, run_id, TRACE_FILE)
        if not os.path.isfile(path):
            continue
        for ev in iter_jsonl(path):
            events.append(ev)
            if limit and len(events) >= limit:
                return events
    return events


# --------------------------------------------------------------- decoding


def events_from_blocks(blocks, group_of_instance) -> list[dict]:
    """Decode flushed ``[chunk, R, 5]`` trace blocks into jsonl-ready
    event dicts, dropping unused (kind < 0) and post-completion padding
    rows. ``group_of_instance(i)`` resolves an instance index to its
    group id for the ``group`` field."""
    out: list[dict] = []
    for block in blocks:
        arr = np.asarray(block).reshape(-1, 5)
        # vectorized prefilter: a quiet traced lane still emits its full
        # static row budget as kind = -1 padding, so the Python loop
        # must only ever see actual events, not the (much larger) blank
        # slot space
        arr = arr[(arr[:, 2] >= 0) & (arr[:, 0] >= 0)]
        for tick, lane, kind, a, b in arr:
            kind = int(kind)
            ev: dict = {
                "tick": int(tick),
                "instance": int(lane),
                "group": group_of_instance(int(lane)),
                "event": EVENT_KINDS[kind],
            }
            if kind == EV_STATUS:
                ev["status"] = _STATUS_NAMES[int(a) % 4]
                ev["prev"] = _STATUS_NAMES[int(b) % 4]
            elif kind == EV_SIGNAL:
                ev["state"] = int(a)
            elif kind == EV_SEND:
                ev["dst"] = int(a)
                ev["fate"] = FATE_NAMES[int(b) % 4]
            elif kind == EV_DELIVER:
                ev["src"] = int(a)
            out.append(ev)
    return out


def chrome_trace(events, lanes, lane_names: dict, tick_ms: float) -> dict:
    """Events → Chrome trace-event JSON (the ``trace_events.json``
    payload): one metadata-named track (tid) per traced instance, one
    instant event per recorded row, timestamps in microseconds of
    simulated time. Loads in Perfetto / chrome://tracing unchanged."""
    te: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "args": {"name": "tpu-testground sim"},
        }
    ]
    for lane in lanes:
        lane = int(lane)
        te.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": lane,
                "args": {"name": lane_names.get(lane, f"instance {lane}")},
            }
        )
    us_per_tick = tick_ms * 1000.0
    for ev in events:
        kind = ev["event"]
        if kind == "status":
            name = f"status→{ev['status']}"
        elif kind == "signal":
            name = f"signal s{ev['state']}"
        elif kind == "send":
            name = f"send→{ev['dst']} ({ev['fate']})"
        else:
            name = f"deliver←{ev.get('src', '?')}"
        args = {k: v for k, v in ev.items() if k not in ("tick", "instance")}
        te.append(
            {
                "name": name,
                "cat": kind,
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "pid": 0,
                "tid": ev["instance"],
                "ts": ev["tick"] * us_per_tick,
                "args": args,
            }
        )
    return {"traceEvents": te, "displayTimeUnit": "ms"}
