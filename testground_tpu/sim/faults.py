"""Deterministic fault-injection plane: declared chaos schedules lowered
to static per-tick event tensors.

Testground's reason to exist is testing distributed systems under
adversity — the reference sidecar shapes and *breaks* links, and
``plans/splitbrain`` ships a partition scenario. This module is the sim
analog of a Jepsen/netem **nemesis schedule**: a composition declares a
list of fault events (``[[groups.run.faults]]`` per group, or
``[[global.run.faults]]`` for everyone), each with a kind, a target
selector, and a start/duration in simulated milliseconds, and the
schedule is *lowered at program-build time* into small static numpy
tensors the jitted tick consumes:

- ``crash`` / ``restart``  → (tick, [N] mask) point events applied at
  tick start (``sim/engine.py``): crash forces status CRASH, purges the
  instance's in-flight calendar rows, and kills its future traffic;
  restart re-runs ``testcase.init`` for the slot and revives it.
- ``partition`` / ``link_flap`` / ``latency_spike`` / ``loss_burst``
  → piecewise-constant windows layered over the link model at send time
  (``sim/net.py``): message kills between partition sides, periodic
  up/down flapping, additive egress latency, and extra Bernoulli loss.

Everything is static shape and statically scheduled, so two runs with
the same seed and fault schedule are bit-identical — the property that
makes a chaos failure replayable (SURVEY.md §5). A plan with **no**
faults declared lowers to ``None`` and the engine compiles the exact
same program as before this plane existed (zero overhead off-path).

Event counts are tiny (a handful per run), so the [E, N] masks cost
nothing beside the calendar planes; the per-tick work is an [E]
compare + mask broadcast.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "FAULT_KINDS",
    "FaultSchedule",
    "build_fault_schedule",
    "parse_fault",
    "remap_schedule",
]

# Every supported nemesis kind. Point events (crash/restart) fire once at
# start_ms; window events hold for [start_ms, start_ms + duration_ms).
FAULT_KINDS = (
    "crash",
    "restart",
    "partition",
    "link_flap",
    "latency_spike",
    "loss_burst",
)
_WINDOW_KINDS = ("partition", "link_flap", "latency_spike", "loss_burst")

# Keys a fault table may carry — anything else is a typo'd schedule, and
# a silently-ignored key is a nemesis that never fires, so refuse loudly.
_KNOWN_KEYS = {
    "kind",
    "group",
    "instances",
    "fraction",
    "seed",
    "start_ms",
    "duration_ms",
    "latency_ms",
    "loss",
    "period_ms",
    "duty",
    "to_group",
    "to_instances",
    "bidirectional",
}


@dataclasses.dataclass(frozen=True)
class _Selector:
    """A resolved target selector: which instances a fault applies to."""

    group: str = ""  # group id; "" = whole run
    instances: str = ""  # half-open "lo:hi" range, group-relative
    fraction: float = 0.0  # seeded fraction of the candidate set
    seed: int = 0


def _parse_range(spec: str, what: str) -> tuple[int, int]:
    try:
        lo_s, hi_s = spec.split(":")
        lo, hi = int(lo_s), int(hi_s)
    except ValueError:
        raise ValueError(
            f"{what} range {spec!r} is not 'lo:hi' (half-open ints)"
        ) from None
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"{what} range {spec!r} is empty or negative"
        )
    return lo, hi


def _resolve_mask(sel: _Selector, groups, n: int, what: str) -> np.ndarray:
    """Selector → [N] bool mask over the global instance axis.

    Candidates = the named group's slots (or all N); an ``instances``
    range narrows them (group-relative when a group is named, global
    otherwise); a ``fraction`` then keeps a seeded, deterministic subset
    (round half-up, so 30% of 10 is 3 — the Jepsen "kill 30% of A"
    idiom). Selection must be non-empty: a fault that targets nobody is
    a schedule typo, not a no-op."""
    mask = np.zeros((n,), bool)
    if sel.group:
        g = next((g for g in groups if g.id == sel.group), None)
        if g is None:
            raise ValueError(
                f"{what} targets unknown group {sel.group!r}; run "
                f"groups are {[g.id for g in groups]}"
            )
        lo, hi = g.offset, g.offset + g.count
    else:
        lo, hi = 0, n
    if sel.instances:
        rlo, rhi = _parse_range(sel.instances, what)
        if rhi > hi - lo:
            raise ValueError(
                f"{what} range {sel.instances!r} exceeds the "
                f"{hi - lo} instance(s) of its target"
            )
        lo, hi = lo + rlo, lo + rhi
    mask[lo:hi] = True
    if sel.fraction:
        idx = np.flatnonzero(mask)
        k = int(np.floor(sel.fraction * idx.size + 0.5))
        if k <= 0:
            raise ValueError(
                f"{what}: fraction {sel.fraction} of {idx.size} "
                "instance(s) selects nobody — raise the fraction or "
                "widen the target"
            )
        rng = np.random.default_rng(sel.seed)
        keep = rng.choice(idx, size=min(k, idx.size), replace=False)
        mask = np.zeros((n,), bool)
        mask[keep] = True
    if not mask.any():
        raise ValueError(f"{what} selects no instances")
    return mask


@dataclasses.dataclass(frozen=True)
class _Fault:
    """One validated fault event, still in milliseconds (pre-lowering)."""

    kind: str
    sel: _Selector
    start_ms: float
    duration_ms: float
    latency_ms: float = 0.0
    loss: float = 0.0
    period_ms: float = 0.0
    duty: float = 0.0
    to_sel: _Selector | None = None
    bidirectional: bool = True


def parse_fault(d: dict, default_group: str = "") -> _Fault:
    """Validate one raw ``[[...faults]]`` table → :class:`_Fault`.

    ``default_group`` scopes group-level declarations to their own group
    when no explicit ``group`` key is given; global declarations pass
    ``""`` (whole run)."""
    if not isinstance(d, dict):
        raise ValueError(f"fault entry must be a table, got {type(d).__name__}")
    unknown = set(d) - _KNOWN_KEYS
    if unknown:
        raise ValueError(
            f"fault entry has unknown key(s) {sorted(unknown)}; known "
            f"keys: {sorted(_KNOWN_KEYS)}"
        )
    kind = d.get("kind", "")
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r}; kinds: {list(FAULT_KINDS)}"
        )
    start_ms = float(d.get("start_ms", -1.0))
    if start_ms < 0:
        raise ValueError(f"fault {kind}: start_ms is required and must be >= 0")
    duration_ms = float(d.get("duration_ms", 0.0))
    if kind in _WINDOW_KINDS and duration_ms <= 0:
        raise ValueError(
            f"fault {kind}: duration_ms > 0 is required (window fault)"
        )
    if kind not in _WINDOW_KINDS and duration_ms:
        raise ValueError(
            f"fault {kind}: duration_ms does not apply (point event — "
            "declare a matching restart/second event instead)"
        )
    fraction = float(d.get("fraction", 0.0))
    if fraction and not (0.0 < fraction <= 1.0):
        raise ValueError(f"fault {kind}: fraction {fraction} not in (0, 1]")
    sel = _Selector(
        group=str(d.get("group", "") or default_group),
        instances=str(d.get("instances", "")),
        fraction=fraction,
        seed=int(d.get("seed", 0)),
    )
    latency_ms = float(d.get("latency_ms", 0.0))
    loss = float(d.get("loss", 0.0))
    period_ms = float(d.get("period_ms", 0.0))
    duty = float(d.get("duty", 0.0))
    to_sel = None
    if kind == "latency_spike" and latency_ms <= 0:
        raise ValueError("fault latency_spike: latency_ms > 0 is required")
    if kind == "loss_burst" and not (0.0 < loss <= 100.0):
        raise ValueError("fault loss_burst: loss must be in (0, 100] percent")
    if kind == "link_flap":
        if period_ms < 0 or (period_ms > 0 and not (0.0 <= duty < 1.0)):
            raise ValueError(
                "fault link_flap: period_ms >= 0 and duty (fraction of "
                "each period the link is UP) in [0, 1) — period 0 means "
                "down for the whole window"
            )
    if kind == "partition":
        if not (d.get("to_group") or d.get("to_instances")):
            raise ValueError(
                "fault partition: the other side needs to_group and/or "
                "to_instances"
            )
        to_sel = _Selector(
            group=str(d.get("to_group", "")),
            instances=str(d.get("to_instances", "")),
        )
    return _Fault(
        kind=kind,
        sel=sel,
        start_ms=start_ms,
        duration_ms=duration_ms,
        latency_ms=latency_ms,
        loss=loss,
        period_ms=period_ms,
        duty=duty,
        to_sel=to_sel,
        bidirectional=bool(d.get("bidirectional", True)),
    )


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """The lowered schedule: static numpy event tensors, one family per
    mechanism. All ticks are absolute; masks are over the plan instance
    axis [N] (host lanes never fault). ``drop_*`` unifies partition and
    link_flap: a message is killed while an entry is active and
    ``a[src] & b[dst]`` (plus the symmetric pair when ``sym``); flapping
    entries are active only during the DOWN phase of each period.
    Consumed as closed-over constants by the traced tick — event counts
    are tiny, so the embedded masks are noise beside the calendar."""

    n: int
    crash_ticks: np.ndarray  # [Ec] int32
    crash_masks: np.ndarray  # [Ec, N] bool
    restart_ticks: np.ndarray  # [Er] int32
    restart_masks: np.ndarray  # [Er, N] bool
    drop_t0: np.ndarray  # [Ed] int32 (window start, inclusive)
    drop_t1: np.ndarray  # [Ed] int32 (window end, exclusive)
    drop_a: np.ndarray  # [Ed, N] bool
    drop_b: np.ndarray  # [Ed, N] bool
    drop_sym: tuple  # [Ed] static bools
    drop_period: np.ndarray  # [Ed] int32 — 0: down all window
    drop_up: np.ndarray  # [Ed] int32 — ticks UP at each period start
    lat_t0: np.ndarray  # [El] int32
    lat_t1: np.ndarray  # [El] int32
    lat_masks: np.ndarray  # [El, N] bool (src side)
    lat_ms: np.ndarray  # [El] float32 additive egress latency
    loss_t0: np.ndarray  # [Eo] int32
    loss_t1: np.ndarray  # [Eo] int32
    loss_masks: np.ndarray  # [Eo, N] bool (src side)
    loss_pct: np.ndarray  # [Eo] float32
    last_event_tick: int  # run must not report done before this tick

    @property
    def has_crashes(self) -> bool:
        return self.crash_ticks.size > 0

    @property
    def has_restarts(self) -> bool:
        return self.restart_ticks.size > 0

    @property
    def has_drops(self) -> bool:
        return self.drop_t0.size > 0

    @property
    def has_latency(self) -> bool:
        return self.lat_t0.size > 0

    @property
    def has_loss(self) -> bool:
        return self.loss_t0.size > 0

    def summary(self) -> str:
        return (
            f"{self.crash_ticks.size} crash, {self.restart_ticks.size} "
            f"restart, {self.drop_t0.size} drop-window, "
            f"{self.lat_t0.size} latency-window, {self.loss_t0.size} "
            f"loss-window event(s), last at tick {self.last_event_tick}"
        )

    # ------------------------------------------------- per-tick resolution
    # (traced — t is a tracer; everything else is a baked-in constant)

    def crash_mask_at(self, t):
        """[N] bool — instances whose crash event fires at tick ``t``."""
        import jax.numpy as jnp

        hit = jnp.asarray(self.crash_ticks) == t  # [Ec]
        return jnp.any(jnp.asarray(self.crash_masks) & hit[:, None], axis=0)

    def restart_mask_at(self, t):
        import jax.numpy as jnp

        hit = jnp.asarray(self.restart_ticks) == t
        return jnp.any(jnp.asarray(self.restart_masks) & hit[:, None], axis=0)

    def drop_active_at(self, t):
        """[Ed] bool — which drop windows are killing traffic at tick
        ``t`` (window open, and in the DOWN phase for flapping entries)."""
        import jax.numpy as jnp

        t0 = jnp.asarray(self.drop_t0)
        act = (t >= t0) & (t < jnp.asarray(self.drop_t1))
        period = jnp.asarray(self.drop_period)
        phase = jnp.mod(t - t0, jnp.maximum(period, 1))
        down = jnp.where(period > 0, phase >= jnp.asarray(self.drop_up), True)
        return act & down

    def window_active_at(self, t, t0, t1):
        import jax.numpy as jnp

        return (t >= jnp.asarray(t0)) & (t < jnp.asarray(t1))


def remap_schedule(
    sched: FaultSchedule, index_map: np.ndarray, n_phys: int
) -> FaultSchedule:
    """Re-target a schedule lowered over the EXACT (virtual) layout onto
    a padded physical instance axis (shape bucketing, sim/buckets.py):
    every per-lane mask scatters through ``index_map`` (virtual lane →
    physical lane), so chaos selectors — declared against the
    composition the operator wrote — keep hitting the same instances,
    and dead pad lanes are never selected. Ticks and window parameters
    are layout-free and pass through unchanged."""
    from .buckets import remap_lane_masks

    index_map = np.asarray(index_map, np.int32)
    if sched.n != index_map.size:
        raise ValueError(
            f"fault schedule lowered for {sched.n} instance(s) but the "
            f"bucket plan maps {index_map.size} — remap must run on the "
            "virtual-layout schedule"
        )

    def remap(masks: np.ndarray) -> np.ndarray:
        return remap_lane_masks(masks, index_map, n_phys)

    return dataclasses.replace(
        sched,
        n=n_phys,
        crash_masks=remap(sched.crash_masks),
        restart_masks=remap(sched.restart_masks),
        drop_a=remap(sched.drop_a),
        drop_b=remap(sched.drop_b),
        lat_masks=remap(sched.lat_masks),
        loss_masks=remap(sched.loss_masks),
    )


def _ticks(ms: float, tick_ms: float) -> int:
    # half-up (like the instance-percentage resolution), not banker's:
    # a 5 ms window at 2 ms/tick is 3 ticks, not 2
    return int(np.floor(ms / tick_ms + 0.5))


def build_fault_schedule(
    groups, faults_by_group: dict, tick_ms: float
) -> FaultSchedule | None:
    """Validate + lower every declared fault into one static schedule.

    ``groups`` is the resolved :class:`~testground_tpu.sim.api.GroupSpec`
    layout; ``faults_by_group`` maps group id → list of raw fault tables
    (the key ``""`` holds run-global declarations). Returns ``None``
    when nothing is declared — the engine then compiles the identical
    pre-fault program (the zero-overhead contract)."""
    n = sum(g.count for g in groups)
    parsed: list[_Fault] = []
    for gid, entries in sorted(faults_by_group.items()):
        for d in entries or ():
            parsed.append(parse_fault(d, default_group=gid))
    if not parsed:
        return None
    if tick_ms <= 0:
        raise ValueError(f"tick_ms must be positive, got {tick_ms}")

    crash_ticks, crash_masks = [], []
    restart_ticks, restart_masks = [], []
    drop_t0, drop_t1, drop_a, drop_b, drop_sym = [], [], [], [], []
    drop_period, drop_up = [], []
    lat_t0, lat_t1, lat_masks, lat_ms = [], [], [], []
    loss_t0, loss_t1, loss_masks, loss_pct = [], [], [], []
    last = 0
    for f in parsed:
        mask = _resolve_mask(f.sel, groups, n, f"fault {f.kind}")
        t0 = _ticks(f.start_ms, tick_ms)
        t1 = t0 + max(_ticks(f.duration_ms, tick_ms), 1)
        if f.kind == "crash":
            crash_ticks.append(t0)
            crash_masks.append(mask)
            last = max(last, t0)
        elif f.kind == "restart":
            restart_ticks.append(t0)
            restart_masks.append(mask)
            last = max(last, t0)
        elif f.kind == "partition":
            other = _resolve_mask(f.to_sel, groups, n, "fault partition:to")
            if (mask & other).any():
                raise ValueError(
                    "fault partition: the two sides overlap — an instance "
                    "cannot be partitioned from itself"
                )
            drop_t0.append(t0)
            drop_t1.append(t1)
            drop_a.append(mask)
            drop_b.append(other)
            drop_sym.append(f.bidirectional)
            drop_period.append(0)
            drop_up.append(0)
            last = max(last, t1)
        elif f.kind == "link_flap":
            drop_t0.append(t0)
            drop_t1.append(t1)
            drop_a.append(mask)
            # any traffic touching a flapped instance drops while down
            drop_b.append(np.ones((n,), bool))
            drop_sym.append(True)
            period = _ticks(f.period_ms, tick_ms) if f.period_ms else 0
            drop_period.append(max(period, 0))
            drop_up.append(
                int(np.floor(f.duty * period)) if period > 0 else 0
            )
            last = max(last, t1)
        elif f.kind == "latency_spike":
            lat_t0.append(t0)
            lat_t1.append(t1)
            lat_masks.append(mask)
            lat_ms.append(f.latency_ms)
            last = max(last, t1)
        elif f.kind == "loss_burst":
            loss_t0.append(t0)
            loss_t1.append(t1)
            loss_masks.append(mask)
            loss_pct.append(f.loss)
            last = max(last, t1)

    def arr(x, dtype):
        return np.asarray(x, dtype)

    def masks(x):
        return (
            np.asarray(x, bool)
            if x
            else np.zeros((0, n), bool)
        )

    # a restart landing on the same tick as a crash of the same instance
    # would be silently lost (the engine applies restarts before crashes,
    # and the slot is still RUNNING when the restart mask is evaluated) —
    # ms-to-tick quantization can collapse distinct start_ms onto one
    # tick, so refuse loudly instead of dropping a declared revival
    for ci, ct in enumerate(crash_ticks):
        for ri, rt in enumerate(restart_ticks):
            if ct == rt and (crash_masks[ci] & restart_masks[ri]).any():
                raise ValueError(
                    f"a crash and a restart both land on tick {ct} for "
                    "overlapping instances (start_ms values quantize to "
                    f"the same tick at tick_ms={tick_ms}) — separate "
                    "them by at least one tick; the restart would "
                    "otherwise be lost (crash wins within a tick)"
                )

    return FaultSchedule(
        n=n,
        crash_ticks=arr(crash_ticks, np.int32),
        crash_masks=masks(crash_masks),
        restart_ticks=arr(restart_ticks, np.int32),
        restart_masks=masks(restart_masks),
        drop_t0=arr(drop_t0, np.int32),
        drop_t1=arr(drop_t1, np.int32),
        drop_a=masks(drop_a),
        drop_b=masks(drop_b),
        drop_sym=tuple(drop_sym),
        drop_period=arr(drop_period, np.int32),
        drop_up=arr(drop_up, np.int32),
        lat_t0=arr(lat_t0, np.int32),
        lat_t1=arr(lat_t1, np.int32),
        lat_masks=masks(lat_masks),
        lat_ms=arr(lat_ms, np.float32),
        loss_t0=arr(loss_t0, np.int32),
        loss_t1=arr(loss_t1, np.int32),
        loss_masks=masks(loss_masks),
        loss_pct=arr(loss_pct, np.float32),
        last_event_tick=last,
    )
