"""The sim-plan API: test plans as traceable, vmappable state machines.

This is the TPU-native re-expression of the reference's SDK contract
(sdk-go ``run.InvokeMap`` + ``runtime.RunEnv`` + ``sync.Client`` +
``network.Client`` — SURVEY.md §2.6, §3.3). The reference lets a plan run
arbitrary blocking Go with real sockets; one chip here hosts every instance
inside a single jitted program, so a sim plan is written as a **cooperative
state machine**: a per-instance ``init`` and a per-tick ``step``, both
lifted over the instance axis with ``jax.vmap`` and stepped by the engine
(:mod:`testground_tpu.sim.engine`) inside ``lax.scan``.

Correspondence with the reference contract:

- blocking test body                → ``step(...)`` called once per simulated
  tick; "blocking" = remaining in a waiting phase until a condition holds
- ``SignalEntry(state)``            → set ``StepOut.signals[state_id] = 1``;
  the 1-based sequence number arrives next tick in ``SyncView.last_seq``
  (``pkg/sidecar`` ↔ Redis round-trip latency becomes one tick)
- ``Barrier(state, target)``        → read ``SyncView.counts[state_id] >= target``
- ``Publish/Subscribe(topic)``      → ``StepOut.pub_valid/pub_payload`` and the
  ordered ``SyncView.sub_*`` window + ``StepOut.sub_consume`` cursor advance
- ``network.Client.ConfigureNetwork`` → ``StepOut.net_shape/net_filters`` (+
  ``*_valid``), applied to the link tensors before the next tick's delivery
- real sockets on the data network  → bounded outbox/inbox message tensors
  routed through the link model (:mod:`testground_tpu.sim.net`)
- ``RecordSuccess/Failure/Crash``   → ``StepOut.status`` ∈ {RUNNING, SUCCESS,
  FAILURE, CRASH}; first terminal status wins, later steps are masked out

A plan exposes ``sim_testcases: dict[str, type[SimTestcase]]`` from its
``sim.py`` (or ``main.py``) module; the ``sim:plan`` builder validates the
entry point and the ``sim:jax`` runner executes it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar

import jax
import jax.numpy as jnp

__all__ = [
    "RUNNING",
    "SUCCESS",
    "FAILURE",
    "CRASH",
    "GroupSpec",
    "SimEnv",
    "Inbox",
    "Outbox",
    "SyncView",
    "StepOut",
    "SimTestcase",
    "FILTER_ACCEPT",
    "FILTER_REJECT",
    "FILTER_DROP",
]

# Instance status codes (reference lifecycle events Success/Failure/Crash,
# ``pkg/runner/pretty.go:163-175``; RUNNING without a terminal event by run
# end maps to the PrettyPrinter's "Incomplete").
RUNNING = 0
SUCCESS = 1
FAILURE = 2
CRASH = 3

# Per-(src instance, dst group) routing filter actions — the tensor analog of
# the sidecar's per-subnet Accept / Reject(PROHIBIT) / Drop(BLACKHOLE) routing
# rules (``pkg/sidecar/link.go:187-217``). Both reject and drop suppress
# delivery; reject additionally surfaces in the sender's ``rejected`` count.
FILTER_ACCEPT = 0
FILTER_REJECT = 1
FILTER_DROP = 2


@dataclasses.dataclass(frozen=True)
class GroupSpec:
    """Static layout of one group on the instance axis."""

    id: str
    index: int
    offset: int  # first global instance index
    count: int
    params: dict[str, str]


@dataclasses.dataclass(frozen=True)
class SimEnv:
    """Per-instance view handed to ``init``/``step`` (under vmap).

    Static (python) fields are identical across the group; array fields are
    per-instance scalars. The twin of ``runtime.RunEnv`` / RunParams
    (``pkg/runner/local_docker.go:325-336`` env contract).
    """

    # --- static, per-run
    test_plan: str
    test_case: str
    test_run: str
    test_instance_count: int
    tick_ms: float  # simulated milliseconds per tick
    groups: tuple[GroupSpec, ...]
    # --- static, per-group (this instance's group)
    group: GroupSpec
    # --- traced, per-instance scalars
    global_seq: jax.Array  # int32 ∈ [0, N)
    group_seq: jax.Array  # int32 ∈ [0, group.count)
    key: jax.Array  # per-instance PRNG key
    # --- static: additional service hosts (the whitelisted-control-routes
    # analog, ``pkg/sidecar/docker_reactor.go:69-103`` + ADDITIONAL_HOSTS
    # env) — echo lanes past the instance axis, reachable via
    # :meth:`host_index`, whose traffic bypasses shaping and filters
    hosts: tuple = ()

    # -- typed param accessors (RunEnv.StringParam/IntParam/... parity);
    # params are static so these resolve at trace time.
    def string_param(self, name: str) -> str:
        v = self.group.params.get(name)
        if v is None:
            raise KeyError(f"missing param: {name}")
        return v

    def int_param(self, name: str) -> int:
        return int(self.string_param(name))

    def float_param(self, name: str) -> float:
        return float(self.string_param(name))

    def bool_param(self, name: str) -> bool:
        return self.string_param(name).lower() in ("true", "1", "yes")

    def group_index_of(self, group_id: str) -> int:
        for g in self.groups:
            if g.id == group_id:
                return g.index
        raise KeyError(f"unknown group: {group_id}")

    def group_offset_of(self, group_id: str) -> int:
        return self.groups[self.group_index_of(group_id)].offset

    def ms_to_ticks(self, ms: float) -> int:
        """Convert simulated milliseconds to whole ticks (≥1)."""
        return max(1, round(ms / self.tick_ms))

    def host_index(self, name: str) -> int:
        """Data-plane address of an additional host (static). Raises if the
        runner config does not whitelist it — the analog of a DNS failure
        for a host missing from ADDITIONAL_HOSTS."""
        if name not in self.hosts:
            raise KeyError(
                f"host {name!r} not in additional_hosts {list(self.hosts)}"
            )
        return self.test_instance_count + self.hosts.index(name)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Inbox:
    """Messages arriving at this instance this tick (fixed shape).

    Per instance (inside vmap): ``payload [MSG_WIDTH, IN_MSGS] int32``
    (word-major: ``payload[w]`` is word w of every slot — the layout keeps
    the big instance axis minor on-device, see ``net.py``),
    ``src [IN_MSGS] int32``, ``valid [IN_MSGS] bool``.
    """

    payload: jax.Array
    src: jax.Array
    valid: jax.Array

    def word(self, w: int) -> jax.Array:
        """Payload word ``w`` across slots: ``[IN_MSGS] int32``."""
        return self.payload[w]

    @property
    def count(self) -> jax.Array:
        return jnp.sum(self.valid.astype(jnp.int32), axis=-1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Outbox:
    """Messages this instance emits this tick (fixed shape).

    Per instance: ``dst [OUT_MSGS] int32`` (global instance index),
    ``payload [OUT_MSGS, MSG_WIDTH] int32``, ``valid [OUT_MSGS] bool``.
    """

    dst: jax.Array
    payload: jax.Array
    valid: jax.Array

    @staticmethod
    def empty(out_msgs: int, msg_width: int) -> "Outbox":
        return Outbox(
            dst=jnp.zeros((out_msgs,), jnp.int32),
            payload=jnp.zeros((out_msgs, msg_width), jnp.int32),
            valid=jnp.zeros((out_msgs,), bool),
        )

    @staticmethod
    def single(dst, payload, valid, out_msgs: int, msg_width: int) -> "Outbox":
        """Convenience: an outbox whose slot 0 carries one message."""
        ob = Outbox.empty(out_msgs, msg_width)
        pay = jnp.asarray(payload, jnp.int32)
        pay = jnp.concatenate(
            [pay, jnp.zeros((msg_width - pay.shape[0],), jnp.int32)]
        )
        return Outbox(
            dst=ob.dst.at[0].set(jnp.asarray(dst, jnp.int32)),
            payload=ob.payload.at[0].set(pay),
            valid=ob.valid.at[0].set(jnp.asarray(valid, bool)),
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SyncView:
    """Global coordination state visible to an instance at tick start.

    Per instance (inside vmap):
    - ``counts [S] int32`` — value of each declared state counter
      (``sync.Client.Barrier`` reads these; S = len(STATES))
    - ``last_seq [S] int32`` — 1-based sequence number returned for this
      instance's most recent signal on each state, 0 if it never signalled
      (``SignalEntry`` return value, one tick delayed)
    - ``sub_payload [T, SUB_K, PUB_WIDTH] int32`` / ``sub_valid [T, SUB_K]``
      — the next SUB_K entries of each topic stream past this instance's
      read cursor, in publish order (``Subscribe`` window)
    - ``rejected int32`` — count of this instance's messages suppressed by a
      REJECT filter last tick (the PROHIBIT-route "connection refused"
      signal a reference sender observes)
    - ``dropped [T] int32`` — cumulative publishes lost to each topic's full
      TOPIC_CAP stream (global, same value for every instance). A plan that
      publishes into a possibly-full topic can observe the overflow instead
      of silently losing entries; the reference's Redis stream would grow
      unboundedly instead, so any nonzero value here flags an undersized
      TOPIC_CAP. Also surfaced per-run in the journal (``sim.pub_dropped``).
    - ``live [G] int32`` — RUNNING instances per group at tick start
      (global, same value for every instance): the sync service's live
      membership view. Barriers written against it —
      ``counts[s] >= jnp.sum(sync.live)`` — degrade gracefully when the
      fault plane crashes instances mid-barrier (docs/FAULTS.md), instead
      of deadlocking on a fixed target the dead can never reach. Order
      matches ``SimEnv.groups``.
    """

    counts: jax.Array
    last_seq: jax.Array
    sub_payload: jax.Array
    sub_valid: jax.Array
    rejected: jax.Array
    dropped: jax.Array
    live: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepOut:
    """Everything a step may do. Use :meth:`SimTestcase.out` to build one
    with defaults."""

    state: Any
    status: jax.Array  # int32 scalar ∈ {RUNNING, SUCCESS, FAILURE, CRASH}
    outbox: Outbox
    signals: jax.Array  # [S] int32 0/1 — SignalEntry per declared state
    pub_payload: jax.Array  # [T, PUB_WIDTH] int32
    pub_valid: jax.Array  # [T] bool
    sub_consume: jax.Array  # [T] int32 — advance read cursor by k ≤ SUB_K
    net_shape: jax.Array  # [7] float32 — new egress LinkShape
    net_shape_valid: jax.Array  # bool — apply net_shape this tick
    net_filters: jax.Array  # [R] int32 — per-dst-region filter actions
    net_filters_valid: jax.Array  # bool
    # [K, 3] int32 — this instance's new range-rule list (start, end,
    # action) per rule, first match wins; the "filter_rules" feature's
    # reconfiguration surface (replaces the whole list when valid)
    net_rules: jax.Array
    net_rules_valid: jax.Array  # bool
    region: jax.Array  # int32 — this instance's new region id
    region_valid: jax.Array  # bool — apply region this tick


class SimTestcase:
    """Base class for sim testcases.

    Class attributes size every tensor (all static at trace time):
    - ``STATES``: sync state names usable in signals/counts
    - ``TOPICS``: pubsub topic names
    - ``MSG_WIDTH`` / ``OUT_MSGS`` / ``IN_MSGS``: point-to-point message shape
    - ``PUB_WIDTH`` / ``SUB_K`` / ``TOPIC_CAP``: pubsub stream shape
    - ``MAX_LINK_TICKS``: calendar-queue horizon — the max deliverable
      latency+jitter in ticks (messages beyond it clamp to the horizon)
    """

    STATES: ClassVar[list[str]] = []
    TOPICS: ClassVar[list[str]] = []
    # Filter partition granularity: 0 → one region per group (the default
    # — ``net_filters[g]`` is the action toward group g). A positive value
    # declares that many regions; instances start in region = their group
    # index and may reassign themselves mid-run via ``StepOut.region``
    # (splitbrain's dynamic seq%3 partitioning). PARITY BOUND: the
    # reference allows arbitrarily many per-subnet rules
    # (``link.go:187-217``); here ``N_REGIONS = N`` with
    # ``region = global_seq`` gives full per-instance granularity, but
    # the dense [R, N] filter table is O(N²) — practical to ~8k
    # instances (a 64 MB table at 4k). Beyond that, coarsen regions or
    # switch to "filter_rules" range-rule lists (below), which keep
    # per-instance rules O(N·K) at any scale.
    # Tables over ``engine.MAX_FILTER_CELLS`` (1 GiB of int32) are
    # refused statically at program build with a readable error rather
    # than dying as an XLA allocation failure mid-trace.
    N_REGIONS: ClassVar[int] = 0
    # Max range rules per instance for the "filter_rules" SHAPING feature
    # (the scalable per-instance filter model — LinkState.rules): each
    # instance carries up to K (start, end, action) rules over dst
    # indices, first match wins, no match = Accept. Declare K here AND
    # "filter_rules" in SHAPING; mutually exclusive with "filters".
    FILTER_RULES: ClassVar[int] = 0
    MSG_WIDTH: ClassVar[int] = 4
    OUT_MSGS: ClassVar[int] = 1
    IN_MSGS: ClassVar[int] = 4
    PUB_WIDTH: ClassVar[int] = 4
    SUB_K: ClassVar[int] = 4
    TOPIC_CAP: ClassVar[int] = 256
    MAX_LINK_TICKS: ClassVar[int] = 256
    # TRACK_SRC=False drops the sender-id plane from the calendar (the
    # inbox's ``src`` reads as 0) — one less O(L·N·SLOTS) store per tick
    # for plans that never look at message provenance.
    TRACK_SRC: ClassVar[bool] = True
    # CROSS_TICK_STACKING=False declares a traffic contract: between two
    # deliveries of a calendar bucket, all messages landing in it are sent
    # on a SINGLE tick (true whenever every link uses one uniform static
    # latency — no jitter/reorder/duplicate shaping, no mid-run latency
    # reshape, and no additional_hosts, whose control lanes ride at the
    # 1-tick floor while plan traffic rides the shaped latency). The
    # transport then skips the bucket-fill derivation + per-message base
    # gather (~25% of the sorted path at 100k instances). If the contract
    # is violated, later sends overwrite earlier occupants of the same
    # bucket instead of stacking into free slots; SimProgram rejects the
    # statically-detectable violations (duplicate shaping, hosts).
    CROSS_TICK_STACKING: ClassVar[bool] = True
    # SLOT_MODE picks how same-tick messages to one receiver share inbox
    # slots:
    # - "sorted" (default, fully general): messages are sorted by
    #   (arrival, dst) and ranked, so any fan-in up to IN_MSGS works and
    #   overflow drops deterministically.
    # - "direct": slot = the sender's outbox index; skips the sort
    #   entirely (the dominant per-tick cost at 100k instances). Only
    #   valid when the traffic pattern guarantees at most ONE sender per
    #   (receiver, outbox-slot, tick) — pairwise or ring topologies —
    #   and ignores duplicate-shaping. Colliding sends are undefined;
    #   the runner's ``validate = true`` debug option detects them and
    #   fails the run naming the colliding (receiver, slot) instead of
    #   silently corrupting (see SimJaxConfig.validate).
    SLOT_MODE: ClassVar[str] = "sorted"
    # Egress-queue bound (messages) under "bandwidth_queue" shaping —
    # HTB's queue limit; only a full queue drops (tail-drop).
    BW_QUEUE_MSGS: ClassVar[int] = 128
    # Which LinkShape features this plan's network configs may exercise.
    # Features not declared are compiled out of the transport (their RNG
    # draws and gathers disappear): a latency-only plan pays nothing for
    # loss/corrupt/reorder/duplicate machinery. "filters" covers the
    # Accept/Reject/Drop table. Declaring "bandwidth_queue" (not in the
    # default set) switches the bandwidth knob from the per-tick
    # admission cap (drop) to the HTB-faithful token bucket: excess
    # messages queue per-src and arrive late, only a full queue
    # (BW_QUEUE_MSGS) tail-drops — see the semantics note in ``net.py``.
    SHAPING: ClassVar[tuple] = (
        "latency",
        "jitter",
        "bandwidth",
        "loss",
        "corrupt",
        "reorder",
        "duplicate",
        "filters",
    )
    DEFAULT_LINK: ClassVar[tuple[float, ...]] = (
        1.0,  # latency ms (a real bridge hop is ~O(0.05ms); 1 tick floor)
        0.0,  # jitter ms
        0.0,  # bandwidth, bytes/s (0 = unlimited)
        0.0,  # loss %
        0.0,  # corrupt %
        0.0,  # reorder %
        0.0,  # duplicate %
    )

    @classmethod
    def specialize(
        cls, groups: tuple[GroupSpec, ...], tick_ms: float = 1.0
    ) -> type:
        """Hook: return a (possibly narrowed) testcase class for this run.

        Called once per run with the resolved group layout and the
        runner's tick duration BEFORE the program is traced, so a plan
        can size its static tensor bounds from run parameters instead of
        compiling worst-case shapes — e.g. storm narrows ``OUT_MSGS``
        from its manifest upper bound (8) to the actual ``conn_outgoing``
        (default 5), and ping-pong sizes ``MAX_LINK_TICKS`` to the shaped
        latency instead of its 512-tick bound (the calendar is
        O(horizon · N · slots), so the bound is what limits instance
        count per chip). Return ``cls`` unchanged (the default) or a
        subclass with overridden ClassVars; never mutate ``cls`` in
        place (it is shared across runs)."""
        return cls

    def state_id(self, name: str) -> int:
        return type(self).STATES.index(name)

    def topic_id(self, name: str) -> int:
        return type(self).TOPICS.index(name)

    # ------------------------------------------------------------ plan hooks

    def init(self, env: SimEnv) -> Any:
        """Per-instance initial state pytree (vmapped)."""
        return {}

    def step(
        self,
        env: SimEnv,
        state: Any,
        inbox: Inbox,
        sync: SyncView,
        t: jax.Array,
    ) -> StepOut:
        """One simulated tick for one instance (vmapped). Must be traceable:
        no data-dependent python control flow — use jnp.where / lax.cond."""
        raise NotImplementedError

    # --------------------------------------------------------------- helpers

    def out(
        self,
        state: Any,
        status=RUNNING,
        outbox: Outbox | None = None,
        signals: jax.Array | None = None,
        pub_payload=None,
        pub_valid=None,
        sub_consume=None,
        net_shape=None,
        net_shape_valid=False,
        net_filters=None,
        net_filters_valid=False,
        net_rules=None,
        net_rules_valid=False,
        region=None,
        region_valid=False,
    ) -> StepOut:
        cls = type(self)
        s, tt = len(cls.STATES), len(cls.TOPICS)
        return StepOut(
            state=state,
            status=jnp.asarray(status, jnp.int32),
            outbox=outbox
            if outbox is not None
            else Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH),
            signals=jnp.zeros((s,), jnp.int32)
            if signals is None
            else jnp.asarray(signals, jnp.int32),
            pub_payload=jnp.zeros((tt, cls.PUB_WIDTH), jnp.int32)
            if pub_payload is None
            else jnp.asarray(pub_payload, jnp.int32),
            pub_valid=jnp.zeros((tt,), bool)
            if pub_valid is None
            else jnp.asarray(pub_valid, bool),
            sub_consume=jnp.zeros((tt,), jnp.int32)
            if sub_consume is None
            else jnp.asarray(sub_consume, jnp.int32),
            net_shape=jnp.zeros((7,), jnp.float32)
            if net_shape is None
            else jnp.asarray(net_shape, jnp.float32),
            net_shape_valid=jnp.asarray(net_shape_valid, bool),
            net_filters=jnp.zeros((0,), jnp.int32)
            if net_filters is None
            else jnp.asarray(net_filters, jnp.int32),
            net_filters_valid=jnp.asarray(net_filters_valid, bool),
            net_rules=jnp.zeros((0, 3), jnp.int32)
            if net_rules is None
            else jnp.asarray(net_rules, jnp.int32),
            net_rules_valid=jnp.asarray(net_rules_valid, bool),
            region=jnp.int32(0)
            if region is None
            else jnp.asarray(region, jnp.int32),
            region_valid=jnp.asarray(region_valid, bool),
        )

    def signal(self, *names: str) -> jax.Array:
        """One-hot(ish) signals vector for the named states."""
        sig = jnp.zeros((len(type(self).STATES),), jnp.int32)
        for n in names:
            sig = sig.at[self.state_id(n)].set(1)
        return sig

    def link_shape(
        self,
        latency_ms=0.0,
        jitter_ms=0.0,
        bandwidth=0.0,
        loss=0.0,
        corrupt=0.0,
        reorder=0.0,
        duplicate=0.0,
    ) -> jax.Array:
        """Build a LinkShape vector (``network.LinkShape`` field order,
        ``pkg/sidecar/link.go:155-183``).

        Bandwidth semantics follow the plan's SHAPING declaration:
        "bandwidth" is a per-tick admission cap (over-cap messages drop
        at send time); "bandwidth_queue" is the HTB-faithful token
        bucket (excess messages queue and arrive late; only a full
        BW_QUEUE_MSGS queue drops) — see ``sim/net.py``."""
        return jnp.stack(
            [
                jnp.asarray(x, jnp.float32)
                for x in (
                    latency_ms,
                    jitter_ms,
                    bandwidth,
                    loss,
                    corrupt,
                    reorder,
                    duplicate,
                )
            ]
        )

    def filter_rules(self, *rules) -> jax.Array:
        """Build a [FILTER_RULES, 3] rule list for ``StepOut.net_rules``.

        Each rule is ``(start, end, action)``: the action applies to
        sends whose dst index lies in ``[start, end)``, FIRST match
        wins, unmatched sends are Accepted. Unused tail slots are padded
        with the never-matching (0, 0, Accept). Entries may be traced
        arrays — ranges can depend on runtime state (the analog of the
        reference instance reconfiguring its own subnet rules mid-run).
        """
        k = type(self).FILTER_RULES
        if len(rules) > k:
            raise ValueError(
                f"{len(rules)} rules > FILTER_RULES={k}; raise the "
                "declaration"
            )
        rows = [
            jnp.stack(
                [
                    jnp.asarray(s, jnp.int32),
                    jnp.asarray(e, jnp.int32),
                    jnp.asarray(a, jnp.int32),
                ]
            )
            for (s, e, a) in rules
        ]
        rows += [jnp.zeros((3,), jnp.int32)] * (k - len(rows))
        return jnp.stack(rows)
