"""The simulated data network: link shaping + message transport as tensors.

TPU-native re-expression of the sidecar's kernel dataplane
(``pkg/sidecar/link.go`` HTB+netem tree, ``route.go`` policies — SURVEY.md
§2.4.1/§2.5): instead of shaping real packets with tc, every in-flight
message lives in a fixed-shape **calendar queue** indexed by arrival tick,
and the ``LinkShape`` knobs become arithmetic applied at send time:

- latency/jitter  → arrival bucket = (t + ticks(latency + U·jitter)) % L
- bandwidth       → per-src cap on messages admitted per tick
- loss%           → Bernoulli drop mask
- corrupt%        → XOR a random bit into payload word 0
- reorder%        → message skips the latency queue (netem's gap semantics)
- duplicate%      → second copy enqueued one tick later
- subnet filters  → per-(src, dst-group) Accept/Reject/Drop table
  (``link.go:187-217`` PROHIBIT/BLACKHOLE routes); Reject feeds back into
  the sender's ``rejected`` count next tick
- scheduled faults → piecewise-constant windows layered over the link
  state at send time (partition/flap kills, latency spikes, loss
  bursts — ``sim/faults.py``, docs/FAULTS.md), each kill counted in
  ``NetFeedback.fault_dropped``; :func:`purge_dst` implements the crash
  semantics for in-flight traffic

Everything is static-shape: delivery is one dynamic-index row gather; sends
are sort + segmented-rank + scatter over the N·OUT_MSGS(·2 for duplicates)
flattened message axis. The instance axis shards over the device mesh; XLA
turns the cross-shard scatter into collective traffic on ICI.

**Layout rule** (the perf-critical design decision): every big tensor keeps
its LARGE axis (N or N·SLOTS) minor/last, and multi-word payloads are
stored as separate 2-D planes rather than a trailing word axis. TPU tiled
layouts pad the two minor dims to (8, 128), so a [..., W=4]-shaped array is
physically ~32× its logical size and every touch of it moves gigabytes;
vmapping a scatter over a leading plane axis also inserts whole-array
layout-conversion copies. Positions on the N·SLOTS axis are encoded
``slot·N + dst`` so a bucket row reshapes to [SLOTS, N] with N still minor.
Measured effect at 100k instances: ~83 ms/tick → sub-ms with this layout.

Negative result (measured on v4, kept so nobody retries it): re-encoding
positions dst-major (``dst·SLOTS + slot``) to make the enqueue scatter's
flat indices fully ascending does NOT speed the scatter — its indices are
already bucket-ascending from the sort, and TPU scatter throughput only
collapses (~300×) for genuinely random index streams — while the
slots-minor views it forces (occupancy reduce over a size-2 minor axis,
transposed inbox unpack) cost +35% on the sustained full path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .api import FILTER_ACCEPT, FILTER_REJECT, Inbox

__all__ = [
    "LinkState",
    "Calendar",
    "NetFeedback",
    "deliver",
    "enqueue",
    "latency_histogram",
    "make_link_state",
    "purge_dst",
    "purge_dst_matrix",
]

# LinkShape plane indices (order of network.LinkShape fields,
# ``pkg/sidecar/link.go:155-183``).
LATENCY, JITTER, BANDWIDTH, LOSS, CORRUPT, REORDER, DUPLICATE = range(7)

# Assumed wire size per message for bandwidth accounting (bytes). The
# reference shapes bits/s on real frames; messages here are fixed-width
# records, so bandwidth B bytes/s admits B·tick_s/MSG_BYTES msgs per tick.
#
# Two bandwidth semantics, chosen by the plan's SHAPING declaration:
# - "bandwidth": per-tick admission cap — messages past the cap are
#   DROPPED at send time (cheapest; fine for plans asserting throughput
#   ceilings). A bandwidth below MSG_BYTES/tick_s (cap floor() → 0)
#   admits nothing at all under this mode.
# - "bandwidth_queue": HTB-faithful token bucket (``link.go:155-183``) —
#   excess messages are HELD in a per-src FIFO egress queue and released
#   as service accrues (rate = B·tick_s/MSG_BYTES msgs/tick, fractional
#   rates < 1 msg/tick trickle messages late instead of blackholing);
#   the queue is bounded (BW_QUEUE_MSGS) and only overflow drops, which
#   is HTB's actual behavior. Costs one [N] backlog state + a small
#   per-message cumsum, so it is opt-in.
MSG_BYTES = 256.0

# Every LinkShape feature (``SimTestcase.SHAPING`` defaults to all).
FULL_SHAPING = (
    "latency",
    "jitter",
    "bandwidth",
    "loss",
    "corrupt",
    "reorder",
    "duplicate",
    "filters",
)

# FULL_SHAPING minus duplicate-shaping, whose second-copy pass doubles
# the message axis — the declaration for plans that exercise every other
# knob but never duplicate (both network ping-pong workloads).
SHAPING_NO_DUPLICATE = tuple(f for f in FULL_SHAPING if f != "duplicate")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LinkState:
    """Per-instance egress shaping + per-(instance, dst-region) filters.

    egress:    [7, N] float32 — one plane per LinkShape component
    filters:   [R, N] int32 — filter action of instance n toward region r
    region_of: [N] int32 — dst instance → region index
    backlog:   [N] float32 — per-src egress-queue depth in messages (the
               HTB token-bucket state; None unless the plan declares
               "bandwidth_queue" shaping)
    rules:     [K, 3, N] int32 — per-instance RANGE-RULE lists (the
               "filter_rules" feature; None unless declared). Rule k of
               instance n is (start, end, action) over dst indices,
               matching dst ∈ [start, end) — the iptables-style rule
               list the reference sidecar applies per instance
               (``link.go:187-217``: each instance's own rules keyed by
               dst subnet, and a subnet IS a contiguous index range
               under sequential instance addressing). FIRST match wins;
               no match = Accept; start ≥ end = unset. O(N·K) state and
               O(m·K) lookups, so per-instance granularity stays usable
               at ANY instance count — the scalable alternative to the
               dense ``N_REGIONS = N`` escape hatch (O(N²), ~8k bound).

    Regions default to groups (``region_of`` starts as the group index),
    reproducing per-dst-group filtering; plans that partition *within* a
    group (splitbrain's seq%3 regions, ``plans/splitbrain/main.go:85-88``)
    declare ``N_REGIONS`` and reassign ``region_of`` dynamically via
    ``StepOut.region`` — the tensor analog of the reference's arbitrary
    per-subnet rules (``link.go:187-217``). ``N_REGIONS = N`` with
    ``region = global_seq`` gives full per-instance granularity; the
    dense [R, N] table is then O(N²), so that escape hatch is for runs
    up to ~8k instances (see the parity note in ``sim/api.py``) — past
    that, use ``rules`` above.
    """

    egress: jax.Array
    filters: jax.Array
    region_of: jax.Array
    backlog: jax.Array | None = None
    rules: jax.Array | None = None


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NetFeedback:
    """Per-tick transport feedback returned by :func:`enqueue`.

    rejected:  [N] int32 — sender's messages suppressed by a REJECT filter
               (surfaced to the sender next tick — ``link.go:196-205``)
    clamped:   int32 scalar — messages whose computed delay exceeded the
               calendar horizon and was CLAMPED to horizon-1 this tick. A
               nonzero count means the run's MAX_LINK_TICKS is undersized
               for a shaped latency/jitter/backlog — netem never silently
               shortens a configured delay (``link.go:169-179``), so the
               engine accumulates this and the runner surfaces it loudly.
    bw_dropped: int32 scalar — messages dropped by a FULL bandwidth_queue
               egress queue this tick (HTB tail-drop)
    backlog:   [N] float32 | None — next tick's egress-queue depths
               (None unless "bandwidth_queue" shaping is compiled in)
    collisions: int32 scalar — direct-slot-mode (receiver, slot, tick)
               write conflicts detected this tick (always 0 unless
               ``validate=True``)
    collision_where: [2] int32 — (dst, slot) of the first collision this
               tick (undefined when collisions == 0)
    sent:      int32 scalar — messages entering the transport this tick:
               valid outbox entries plus duplicate-shaping copies, so the
               flow conservation sent = enqueued + rejected + dropped +
               fault_dropped closes per tick (the telemetry invariant)
    enqueued:  int32 scalar — messages actually scattered into the
               calendar this tick (survivors of filters, loss, bandwidth,
               horizon/slot bounds)
    fault_dropped: int32 scalar — messages killed at send time by the
               fault-injection plane (partition/link-flap windows, fault
               loss bursts, traffic to/from crashed instances); always 0
               when no fault schedule is compiled in
    fate:      [O·N] int32 | None — per-message transport fate in the
               ORIGINAL outbox order (m = o·N + src), for the flight
               recorder's traced send events: 0 enqueued, 1 rejected,
               2 fault_dropped, 3 dropped, -1 invalid outbox slot.
               None unless ``want_fate`` was requested (trace plane
               compiled in); duplicate-shaping copies report through
               their original's fate (enqueued if either copy made it)
    flow:      [4, O·N] int32 | None — per-message flow COUNTS in the
               ORIGINAL outbox order, for the traffic-matrix plane
               (``sim/netmatrix.py``): row 0 copies entering the
               transport (1 per valid outbox entry, +1 for a duplicate-
               shaping copy), row 1 copies actually enqueued into the
               calendar, row 2 rejected (0/1), row 3 fault-dropped
               (0/1). Per message, dropped = row0 − row1 − row2 − row3,
               so the scalar conservation identity closes CELL-WISE
               after any per-(src, dst) scatter. None unless
               ``want_flow`` was requested (identical program when off)
    """

    rejected: jax.Array
    clamped: jax.Array
    bw_dropped: jax.Array
    backlog: jax.Array | None
    collisions: jax.Array
    collision_where: jax.Array
    sent: jax.Array
    enqueued: jax.Array
    fault_dropped: jax.Array
    fate: jax.Array | None = None
    flow: jax.Array | None = None


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Calendar:
    """The in-flight message store, bucketed by arrival tick mod L.

    payload: tuple of W planes, each [L, N·SLOTS] int32
    src:     [L, N·SLOTS] int32 — sender index **+1**, 0 = empty slot
             (None when the plan opted out via TRACK_SRC=False)
    valid:   [L, N·SLOTS] bool — only materialized when ``src`` is None;
             with provenance on, validity is ``src != 0``, which saves a
             whole plane scatter per tick (~18% of the sustained full
             path at 100k instances)
    etick:   [L, N·SLOTS] int32 — the tick each in-flight message was
             enqueued at (None unless the telemetry plane is compiled
             in): at delivery, ``t - etick`` is the message's end-to-end
             delivery latency in ticks, binned into the per-group
             latency histogram (:func:`latency_histogram`). Stale values
             survive ``deliver``'s row clear exactly like payload words
             (masked by the occupancy plane), so the plane costs one
             extra scatter per tick and nothing at delivery.

    Bucket fill counts (how many slots of (bucket, dst) are taken, so
    messages enqueued on LATER ticks stack into the next free slots
    instead of overwriting — a TCP accept queue keeps earlier
    connections; only overflow drops) are NOT materialized state: they
    are re-derived each tick from the occupancy plane by a slot-strided
    reduction (see ``enqueue``). A previous revision carried an
    ``occ: [L, N]`` tensor updated by a third scatter per tick; deriving
    replaces that ~1.2 ms/tick scalar-core scatter (at 100k instances)
    with ~30 µs of vector reads.

    The N·SLOTS axis is ordered slot-major (``pos = slot·N + dst``) so a
    row reshapes to [SLOTS, N]. ``slots`` is static structure, not data.

    **Plane storage layout** (``flat``, static): unsharded programs store
    each plane FLAT as [L·N·SLOTS] — the T(1024) linear layout XLA's
    scatter lowering wants — so the per-tick scatters touch the buffers
    directly. With the 2-D [L, N·SLOTS] form, XLA materializes a full
    plane layout conversion (tiled (8,128) ↔ linear) around EVERY
    scatter: invisible at an 8-tick horizon (~13 MB planes) but ~2.6 ms
    per plane per direction per tick at horizon 128 (205 MB at 100k
    instances) — most of the ping-pong correctness case's runtime.
    Mesh-sharded programs keep the 2-D form, whose N·SLOTS axis carries
    the instance-axis sharding; the sharded-vs-unsharded equality tests
    cross-validate the two layouts against each other.
    """

    payload: tuple
    src: jax.Array | None
    valid: jax.Array | None
    etick: jax.Array | None = None
    slots: int = dataclasses.field(metadata=dict(static=True), default=4)
    flat: bool = dataclasses.field(metadata=dict(static=True), default=False)
    # bucket count — static; required to address flat planes (the 2-D
    # form carries it in shape[0], kept in sync by empty())
    horizon: int = dataclasses.field(metadata=dict(static=True), default=0)

    @staticmethod
    def empty(
        horizon: int,
        n: int,
        slots: int,
        width: int,
        track_src: bool = True,
        flat: bool = False,
        track_etick: bool = False,
    ) -> "Calendar":
        ns = n * slots
        shape = (horizon * ns,) if flat else (horizon, ns)
        return Calendar(
            payload=tuple(jnp.zeros(shape, jnp.int32) for _ in range(width)),
            src=jnp.zeros(shape, jnp.int32) if track_src else None,
            valid=None if track_src else jnp.zeros(shape, bool),
            etick=jnp.zeros(shape, jnp.int32) if track_etick else None,
            slots=slots,
            flat=flat,
            horizon=horizon,
        )

    @property
    def width(self) -> int:
        return len(self.payload)

    @property
    def occupancy_plane(self) -> jax.Array:
        """The plane that marks filled slots: src (≠0) or valid (True)."""
        return self.src if self.src is not None else self.valid


def make_link_state(
    n: int,
    n_regions: int,
    default_shape,
    region_of=None,
    track_backlog: bool = False,
    n_rules: int = 0,
) -> LinkState:
    egress = jnp.tile(
        jnp.asarray(default_shape, jnp.float32)[:, None], (1, n)
    )
    filters = jnp.full((n_regions, n), FILTER_ACCEPT, jnp.int32)
    if region_of is None:
        region_of = jnp.zeros((n,), jnp.int32)
    return LinkState(
        egress=egress,
        filters=filters,
        region_of=jnp.asarray(region_of, jnp.int32),
        backlog=jnp.zeros((n,), jnp.float32) if track_backlog else None,
        # all-zero = every rule unset (start 0 ≥ end 0): accept everything
        rules=jnp.zeros((n_rules, 3, n), jnp.int32) if n_rules > 0 else None,
    )


def deliver(
    cal: Calendar, t: jax.Array, transport: str = "xla", mesh=None
) -> tuple[Calendar, Inbox]:
    """Pop the bucket arriving at tick ``t`` → inboxes in plane layout
    (payload [W, SLOTS, N], src/valid [SLOTS, N]); the bucket's occupancy
    plane row is zeroed for reuse at t+L (stale payloads stay, masked) —
    which also resets the bucket's derived fill counts. With provenance
    on, the src plane doubles as occupancy (src+1, 0 = empty); invalid
    inbox slots then read src = -1.

    ``transport="pallas"`` routes the pop through the hand-tiled
    delivery kernel (``sim/pallas_transport.py``): one grid step reads
    the arriving bucket's rows and writes the cleared occupancy row back
    in the same pass, instead of XLA's separate dynamic-slice read and
    clear-row update. Bit-identical output; requires the 2-D plane
    layout the pallas backend keeps (``Calendar.flat=False``)."""
    slots = cal.slots
    if transport == "pallas":
        from .pallas_transport import pop_bucket

        horizon, ns = cal.occupancy_plane.shape
        n = ns // slots
        cal, occ_row, pay_rows = pop_bucket(cal, t, mesh=mesh)
        if cal.src is not None:
            row_v = occ_row != 0
            row_s = occ_row - 1
        else:
            row_v = occ_row
            row_s = jnp.zeros((ns,), jnp.int32)
        inbox = Inbox(
            payload=jnp.stack([r.reshape(slots, n) for r in pay_rows]),
            src=row_s.reshape(slots, n),
            valid=row_v.reshape(slots, n),
        )
        return cal, inbox
    if cal.flat:
        horizon = cal.horizon
        ns = cal.occupancy_plane.shape[0] // horizon
    else:
        horizon, ns = cal.occupancy_plane.shape
    n = ns // slots
    b = jnp.mod(t, horizon)

    if cal.flat:
        off = (b * ns,)

        def row_of(p):
            return jax.lax.dynamic_slice(p, off, (ns,))

        def clear_row(p):
            return jax.lax.dynamic_update_slice(
                p, jnp.zeros((ns,), p.dtype), off
            )

    else:

        def row_of(p):
            return jax.lax.dynamic_index_in_dim(p, b, axis=0, keepdims=False)

        def clear_row(p):
            return jax.lax.dynamic_update_index_in_dim(
                p, jnp.zeros((ns,), p.dtype), b, axis=0
            )

    rows = [row_of(p) for p in cal.payload]
    if cal.src is not None:
        row_s1 = row_of(cal.src)
        row_v = row_s1 != 0
        row_s = row_s1 - 1
        new_src = clear_row(cal.src)
        new_valid = None
    else:
        row_v = row_of(cal.valid)
        row_s = jnp.zeros((ns,), jnp.int32)
        new_src = None
        new_valid = clear_row(cal.valid)
    inbox = Inbox(
        payload=jnp.stack([r.reshape(slots, n) for r in rows]),
        src=row_s.reshape(slots, n),
        valid=row_v.reshape(slots, n),
    )
    cal = dataclasses.replace(cal, src=new_src, valid=new_valid)
    return cal, inbox


def purge_dst(cal: Calendar, dst_mask: jax.Array) -> tuple[Calendar, jax.Array]:
    """Remove every in-flight calendar entry destined to a masked
    instance — the fault plane's crash semantics: a killed container's
    socket buffers vanish with it, so messages already on the wire toward
    it are lost, not delivered to the next occupant of the slot.

    ``dst_mask`` is [N] bool over the receiver axis. Only the occupancy
    plane is cleared (payload words stay stale, exactly like a bucket
    after ``deliver``'s row clear). Returns ``(cal', purged_count)`` so
    the engine can move the purged messages from the in-flight depth to
    the ``fault_dropped`` counter. O(L·N·SLOTS) reads — the engine gates
    the call behind ``lax.cond`` on a crash actually firing this tick."""
    slots = cal.slots
    plane = cal.occupancy_plane
    if cal.flat:
        ns = plane.shape[0] // cal.horizon
    else:
        ns = plane.shape[1]
    n = ns // slots
    # both layouts reshape to [L·SLOTS, N] with the instance axis minor
    # (positions are slot-major: pos = slot·N + dst)
    view = plane.reshape(-1, n)
    kill = (view != 0) & dst_mask[None, :]
    purged = jnp.sum(kill.astype(jnp.int32))
    new_plane = jnp.where(kill, jnp.zeros_like(view), view).reshape(
        plane.shape
    )
    if cal.src is not None:
        cal = dataclasses.replace(cal, src=new_plane)
    else:
        cal = dataclasses.replace(cal, valid=new_plane)
    return cal, purged


def purge_dst_matrix(
    cal: Calendar, dst_mask: jax.Array, group_of, gh: int
) -> tuple[Calendar, jax.Array, jax.Array]:
    """:func:`purge_dst` with per-(src group, dst group) attribution for
    the traffic-matrix plane: every purged in-flight message is charged
    to the (sender group, crashed-receiver group) cell, so crash kills
    land in the right ``fault_dropped`` cells and the matrix keeps exact
    conservation through chaos. Needs provenance (``track_src=True`` —
    the matrix plane forces it): the occupancy plane stores src+1, so
    the sender of every live slot is recoverable without extra state.

    ``group_of`` is [N] int32 lane → group (host lanes mapped to the
    extra hosts row); ``gh`` the static matrix side (groups + hosts
    row). Returns ``(cal', purged_count, mat [gh, gh] int32)``."""
    assert cal.src is not None, (
        "purge_dst_matrix needs a Calendar built with track_src=True"
    )
    slots = cal.slots
    plane = cal.src
    if cal.flat:
        ns = plane.shape[0] // cal.horizon
    else:
        ns = plane.shape[1]
    n = ns // slots
    view = plane.reshape(-1, n)
    kill = (view != 0) & dst_mask[None, :]
    purged = jnp.sum(kill.astype(jnp.int32))
    g = jnp.asarray(group_of, jnp.int32)
    srcg = g[jnp.clip(view - 1, 0, n - 1)]  # [L·SLOTS, N]
    dstg = g[None, :]  # column j IS receiver lane j
    idx = jnp.where(kill, srcg * gh + dstg, jnp.int32(gh * gh))
    mat = (
        jnp.zeros((gh * gh,), jnp.int32)
        .at[idx.reshape(-1)]
        .add(1, mode="drop")
        .reshape(gh, gh)
    )
    new_plane = jnp.where(kill, jnp.zeros_like(view), view).reshape(
        plane.shape
    )
    return dataclasses.replace(cal, src=new_plane), purged, mat


def latency_histogram(
    cal: Calendar,
    inbox: Inbox,
    t: jax.Array,
    group_of,  # [N_lanes] int32 — receiver lane → group (>=n_groups drops)
    n_groups: int,
    n_bins: int,
) -> jax.Array:
    """Per-receiver-group delivery-latency histogram of the bucket
    delivered at tick ``t`` → ``[n_groups, n_bins]`` int32.

    Latency = ``t - etick`` (the enqueue tick stored per message when the
    telemetry plane is on), binned log2: bin b counts delays in
    [2^b, 2^(b+1)) ticks, last bin open-ended (the clamp-to-last-bin
    contract). Call with the PRE-deliver calendar (or post — ``deliver``
    clears only the occupancy plane, the etick row survives) and the
    inbox it popped; invalid inbox slots and lanes whose ``group_of``
    entry is out of range (additional hosts) are dropped by the scatter,
    so ``sum(hist) == delivered plan messages`` holds exactly per tick.
    The cost is ~n_bins compares per inbox slot plus one scatter-add into
    a [G·B] vector — noise beside the delivery gather itself."""
    assert cal.etick is not None, (
        "latency_histogram needs a Calendar built with track_etick=True"
    )
    slots = cal.slots
    plane = cal.etick
    b = jnp.mod(t, cal.horizon if cal.flat else plane.shape[0])
    if cal.flat:
        ns = plane.shape[0] // cal.horizon
        row = jax.lax.dynamic_slice(plane, (b * ns,), (ns,))
    else:
        ns = plane.shape[1]
        row = jax.lax.dynamic_index_in_dim(plane, b, axis=0, keepdims=False)
    n = ns // slots
    delay = t - row.reshape(slots, n)  # [SLOTS, N]; >= 1 when valid
    # integer edge compares, not float log2 — exact at every power of two
    edges = jnp.asarray([1 << e for e in range(1, n_bins)], jnp.int32)
    binidx = jnp.sum(
        (delay[..., None] >= edges).astype(jnp.int32), axis=-1
    )
    g = jnp.asarray(group_of, jnp.int32)
    idx = g[None, :] * n_bins + binidx  # host lanes index out of range
    oob = jnp.int32(n_groups * n_bins)
    idx = jnp.where(inbox.valid, idx, oob)
    hist = (
        jnp.zeros((n_groups * n_bins,), jnp.int32)
        .at[idx.reshape(-1)]
        .add(1, mode="drop")
    )
    return hist.reshape(n_groups, n_bins)


def enqueue(
    cal: Calendar,
    link: LinkState,
    dst: jax.Array,  # [O, N] int32
    payload: jax.Array,  # [O, W, N] int32
    valid: jax.Array,  # [O, N] bool
    t: jax.Array,
    tick_ms: float,
    key: jax.Array,
    slot_mode: str = "sorted",
    features: tuple = FULL_SHAPING,
    control_start: int | None = None,
    stacking: bool = True,
    bw_queue_cap: int = 128,
    validate: bool = False,
    faults=None,
    dead: jax.Array | None = None,
    want_fate: bool = False,
    want_flow: bool = False,
    transport: str = "xla",
    dice_idx: jax.Array | None = None,
    mesh=None,
) -> tuple[Calendar, NetFeedback]:
    """Shape + schedule this tick's sends (inputs in plane layout, message
    m = o·N + src). Returns (cal', NetFeedback).

    ``NetFeedback.rejected[i]`` counts instance i's messages suppressed by
    a REJECT filter (surfaced to the sender next tick, mirroring a
    PROHIBIT route's immediate "connection refused" — ``link.go:196-205``);
    see :class:`NetFeedback` for the clamp/queue/collision counters.

    ``slot_mode`` — see ``SimTestcase.SLOT_MODE``: "sorted" (general,
    sort-based slot ranking) or "direct" (slot = outbox index; no sort, no
    duplicate-shaping; only for fan-in-free traffic patterns).

    ``features`` — static set of LinkShape features compiled in
    (``SimTestcase.SHAPING``); undeclared features cost nothing.

    ``control_start`` — lanes at indices ≥ this are control-route
    endpoints (additional hosts): traffic to or from them bypasses
    filters and every shaping feature and travels at the 1-tick floor,
    the tensor analog of the sidecar's whitelisted control routes
    (``docker_reactor.go:69-103`` — control traffic is never shaped).

    ``stacking`` — ``SimTestcase.CROSS_TICK_STACKING``: when False the
    bucket-fill derivation and base gather are compiled out (ranks start
    at 0 every tick; see the contract note in ``api.py``).

    ``bw_queue_cap`` — ``SimTestcase.BW_QUEUE_MSGS``: bound (in messages)
    of the per-src egress queue under "bandwidth_queue" shaping.

    ``validate`` — direct-slot-mode debug check: read back occupancy and
    detect same-tick duplicate (receiver, slot) writes, reporting them in
    ``NetFeedback.collisions`` instead of silently corrupting slots.

    ``faults`` — a lowered :class:`~testground_tpu.sim.faults.FaultSchedule`
    (or None): its piecewise-constant windows layer over the link model at
    send time — partition/link-flap kills, additive latency spikes, and
    extra Bernoulli loss bursts — all resolved against ``t`` with static
    event tensors, so a schedule-free program compiles identically.

    ``dead`` — [N] bool (or None): instances currently crashed by the
    fault plane. Traffic to or from a dead lane is killed and counted in
    ``NetFeedback.fault_dropped`` (its in-flight backlog was purged at
    crash time by :func:`purge_dst`). Control-route traffic is exempt
    from every fault, like it is from shaping.

    ``want_fate`` — flight-recorder support (``sim/trace.py``): also
    return ``NetFeedback.fate``, the per-message transport fate in
    original outbox order. Compiled out (fate = None, identical program)
    when False.

    ``want_flow`` — traffic-matrix support (``sim/netmatrix.py``): also
    return ``NetFeedback.flow``, the per-message flow counts in
    original outbox order (see :class:`NetFeedback`). Compiled out
    (flow = None, identical program) when False.

    A calendar built with ``track_etick=True`` additionally records each
    enqueued message's send tick, the latency plane's ground truth
    (:func:`latency_histogram`).

    ``transport`` — "xla" (default: the scatter path below, program
    unchanged) or "pallas": commit the sorted message stream through the
    hand-tiled calendar-commit kernel (``sim/pallas_transport.py``),
    which fuses the payload + occupancy (+ etick) plane writes into one
    bucket-partitioned traversal and computes slot ranks and cross-tick
    stacking bases in-kernel from the in-VMEM occupancy row — replacing
    the two plane scatters, the derived fill table, and its per-message
    base gather (the three ops PERF.md measures at 84% of the sustained
    tick). Sorted slot mode only; direct mode keeps its XLA scatter
    (one index per message, no sort — no bucket ordering to exploit).
    Bit-identical results either way, pinned by the equality suites.
    """
    slots = cal.slots
    width = cal.width
    if cal.flat:
        horizon = cal.horizon
        ns = cal.occupancy_plane.shape[0] // horizon
    else:
        horizon, ns = cal.occupancy_plane.shape
    n = ns // slots
    o, n_src = valid.shape
    assert n_src == n

    def scat(plane, b_idx, p_idx, vals):
        """Scatter (bucket, pos) → plane in its storage layout. Dropped
        entries carry b_idx == horizon, which lands out of range in both
        forms (flat: ≥ horizon·ns with a unique p_idx riding along)."""
        if cal.flat:
            return plane.at[b_idx * ns + p_idx].set(
                vals, mode="drop", unique_indices=True
            )
        return plane.at[b_idx, p_idx].set(
            vals, mode="drop", unique_indices=True
        )

    midx = jnp.arange(o * n, dtype=jnp.int32)
    src_f = midx if o == 1 else jnp.mod(midx, n)
    slot_in_src = midx // n  # o index: which of the src's O slots
    dst_f = dst.reshape(-1)
    pay_w = [payload[:, w, :].reshape(-1) for w in range(width)]  # W× [M]
    val_f = valid.reshape(-1)
    m = val_f.shape[0]
    # flight-recorder fate tracking (want_fate): the original validity
    # plus the per-stage kill masks, all in ORIGINAL message order —
    # assembled into a per-message fate code at the end
    val0 = val_f
    rej_m = None
    fault_m = None
    # telemetry: messages entering the transport (before any shaping or
    # bounds masking — out-of-range dsts count as sent-then-dropped);
    # duplicate-shaping copies are added below so conservation closes
    sent = jnp.sum(val_f.astype(jnp.int32))
    # traffic matrix (want_flow): the same quantity per ORIGINAL message
    sent_m = val0.astype(jnp.int32) if want_flow else None

    def eg(plane):
        # per-message egress attribute: src_f == midx % n, so the gather
        # is exactly an o-fold tile of the per-instance plane — a
        # broadcast, never a random-access gather
        if o == 1:
            return link.egress[plane]
        return jnp.tile(link.egress[plane], o)

    # Per-feature uniforms come from a murmur3-finalizer hash of
    # (message index, per-tick key salt, feature id) rather than threefry
    # (~3× cheaper on the VPU at these sizes; full-avalanche mixing is
    # plenty for shaping decisions — this is a simulator's netem dice,
    # not cryptography).
    # int32-native (wrapping multiplies are two's-complement, logical
    # shifts via lax) — no dtype conversions to break XLA fusion
    shr = jax.lax.shift_right_logical
    kd = jax.random.key_data(key).astype(jnp.int32).reshape(-1)
    salt = kd[0] ^ (kd[-1] * np.int32(-1640531527))  # 0x9E3779B9
    # ``dice_idx`` (shape bucketing, sim/buckets.py): the caller may
    # substitute VIRTUAL message indices for the hash inputs so a padded
    # run's shaping draws bit-match the unpadded run's — the flat index
    # over a padded plane would re-deal every die. Default: the flat
    # index, the pre-bucket program unchanged.
    iota_m = (
        jnp.arange(m, dtype=jnp.int32)
        if dice_idx is None
        else dice_idx.reshape(-1).astype(jnp.int32)
    )

    def uhash_id(fid: int):
        # fid·0x9E3779B9 folded on the host (int32 wraparound). Feature
        # ids 1..len(FULL_SHAPING) are the shaping knobs; the fault plane
        # draws its loss-burst dice from ids past that range so its
        # stream is independent of every shaping draw.
        fid_mix = jnp.int32(
            np.multiply(
                np.int32(fid),
                np.int32(-1640531527),
                dtype=np.int32,
                casting="unsafe",
            )
        )
        x = iota_m * np.int32(-1640531535) + salt + fid_mix
        x = x ^ shr(x, 16)
        x = x * np.int32(-2048144789)  # 0x85EBCA6B
        x = x ^ shr(x, 13)
        x = x * np.int32(-1028477387)  # 0xC2B2AE35
        return x ^ shr(x, 16)

    def uhash(feat):
        return uhash_id(1 + FULL_SHAPING.index(feat))

    def u(feat):
        return shr(uhash(feat), 8).astype(jnp.float32) * jnp.float32(
            2**-24
        )

    dst_safe = jnp.clip(dst_f, 0, n - 1)
    val_f = val_f & (dst_f >= 0) & (dst_f < n)

    # --- control routes: host-lane traffic is exempt from everything below
    is_ctrl = (
        (dst_safe >= control_start) | (src_f >= control_start)
        if control_start is not None
        else None
    )

    # --- filters: Accept / Reject / Drop — two granularity models
    action = None
    if "filters" in features:
        # per-(src instance, dst REGION) dense table
        n_regions = link.filters.shape[0]
        if n_regions == 1:
            # single region (one group, no N_REGIONS declaration): the
            # action depends on src only — a tile of the one filter row,
            # no gathers at all (the dominant filters cost at 100k)
            action = (
                link.filters[0] if o == 1 else jnp.tile(link.filters[0], o)
            )
        elif n_regions <= 4:
            # few regions: replace the flat [R·N] random gather with R
            # broadcast selects; only the per-dst region lookup gathers
            region = link.region_of[dst_safe]
            action = jnp.zeros((m,), jnp.int32)
            for r in range(n_regions):
                row = (
                    link.filters[r]
                    if o == 1
                    else jnp.tile(link.filters[r], o)
                )
                action = jnp.where(region == r, row, action)
        else:
            action = link.filters.reshape(-1)[
                link.region_of[dst_safe] * n + src_f
            ]
    elif "filter_rules" in features:
        # per-src RANGE-RULE lists over dst indices (see LinkState.rules):
        # K first-match passes. Rule fields are SRC-indexed, and src_f is
        # an o-fold tile of the instance axis, so — exactly like eg() —
        # every row read is a broadcast tile, never a random gather: the
        # whole lookup is 3K tiled VPU compares, O(m·K) elementwise with
        # NO scalar-core lanes (measured ~11× at 64k when written as
        # gathers; ~free as tiles), unlike the dense table's O(N²)
        # region gather at per-instance granularity
        assert link.rules is not None, (
            "filter_rules shaping needs a LinkState built with n_rules>0"
        )

        def srow(x):  # src-indexed [N] row → per-message
            return x if o == 1 else jnp.tile(x, o)

        action = jnp.full((m,), FILTER_ACCEPT, jnp.int32)
        matched = jnp.zeros((m,), bool)
        for k in range(link.rules.shape[0]):
            start = srow(link.rules[k, 0])
            end = srow(link.rules[k, 1])
            act_k = srow(link.rules[k, 2])
            hit = (
                ~matched & (dst_safe >= start) & (dst_safe < end)
            )  # unset rules (start >= end) can never hit
            action = jnp.where(hit, act_k, action)
            matched = matched | hit
    if action is not None:
        accept = action == FILTER_ACCEPT
        rejected_msg = val_f & (action == FILTER_REJECT)
        if is_ctrl is not None:
            accept = accept | is_ctrl
            rejected_msg = rejected_msg & ~is_ctrl
        val_f = val_f & accept
        rej_m = rejected_msg
        rejected = jnp.sum(
            rejected_msg.reshape(o, n).astype(jnp.int32), axis=0
        )
    else:
        rejected = jnp.zeros((n,), jnp.int32)

    # --- fault plane: deterministic scheduled kills, layered over the
    # link state AFTER filters (the reject feedback a sender observes is
    # fault-independent) and BEFORE shaping losses, so every fault kill
    # lands in fault_dropped and nowhere else. Schedule masks cover the
    # plan instance axis; host lanes past it never fault (and is_ctrl
    # exempts their traffic entirely, mirroring the shaping exemption).
    fault_dropped = jnp.int32(0)

    def src_row(row):  # [n]-indexed by src → per-message (tile)
        row = jnp.asarray(row)
        return row if o == 1 else jnp.tile(row, o)

    def padded(mask_np):  # [faults.n] schedule mask → [n] lane mask
        if faults.n < n:
            return np.pad(mask_np, (0, n - faults.n))
        return mask_np

    if faults is not None or dead is not None:
        kill = jnp.zeros((m,), bool)
        if dead is not None:
            kill = src_row(dead) | dead[dst_safe]
        if faults is not None and faults.has_drops:
            act = faults.drop_active_at(t)  # [Ed] bool
            for e in range(faults.drop_t0.size):
                a_np, b_np = padded(faults.drop_a[e]), padded(faults.drop_b[e])
                hit = src_row(a_np) & jnp.asarray(b_np)[dst_safe]
                if faults.drop_sym[e]:
                    hit = hit | (src_row(b_np) & jnp.asarray(a_np)[dst_safe])
                kill = kill | (hit & act[e])
        if faults is not None and faults.has_loss:
            act = faults.window_active_at(t, faults.loss_t0, faults.loss_t1)
            for e in range(faults.loss_t0.size):
                # independent dice per loss window (ids past the shaping
                # range); same murmur3 finalizer as the netem draws
                uf = shr(uhash_id(1 + len(FULL_SHAPING) + e), 8).astype(
                    jnp.float32
                ) * jnp.float32(2**-24)
                lossy = uf * 100.0 < jnp.float32(faults.loss_pct[e])
                kill = kill | (
                    lossy & src_row(padded(faults.loss_masks[e])) & act[e]
                )
        if is_ctrl is not None:
            kill = kill & ~is_ctrl
        killed = val_f & kill
        fault_m = killed
        fault_dropped = jnp.sum(killed.astype(jnp.int32))
        val_f = val_f & ~killed

    # --- bandwidth, admission-cap semantics: admit the first
    # floor(B·tick/MSG_BYTES) msgs per src, drop the rest (the cheap
    # mode; "bandwidth_queue" below supersedes it with HTB queueing)
    if "bandwidth" in features and "bandwidth_queue" not in features:
        bw = eg(BANDWIDTH)
        cap = jnp.where(
            bw <= 0.0,
            jnp.float32(o),
            jnp.floor(bw * (tick_ms / 1000.0) / MSG_BYTES),
        )
        admit = slot_in_src.astype(jnp.float32) < cap
        val_f = val_f & (admit | is_ctrl if is_ctrl is not None else admit)

    # --- loss
    if "loss" in features:
        keep = u("loss") * 100.0 >= eg(LOSS)
        val_f = val_f & (keep | is_ctrl if is_ctrl is not None else keep)

    # --- corrupt: flip one random bit of payload word 0 (the decision
    # uses the hash's high bits, the bit index its low byte)
    if "corrupt" in features:
        hc = uhash("corrupt")
        corrupt = shr(hc, 8).astype(jnp.float32) * jnp.float32(
            2**-24
        ) * 100.0 < eg(CORRUPT)
        if is_ctrl is not None:
            corrupt = corrupt & ~is_ctrl
        bit = jnp.mod(hc & 0xFF, 31)
        pay_w[0] = jnp.where(
            corrupt, pay_w[0] ^ (jnp.int32(1) << bit), pay_w[0]
        )

    # --- latency + jitter → delay in ticks; reorder = skip the queue
    delay_ms = eg(LATENCY)
    if "jitter" in features:
        delay_ms = delay_ms + eg(JITTER) * u("jitter")
    if faults is not None and faults.has_latency:
        # latency_spike windows: additive egress delay on the targeted
        # senders while the window is open (netem delay bumped mid-run);
        # clamping past the calendar horizon is counted like any other
        # oversized configured delay
        act = faults.window_active_at(t, faults.lat_t0, faults.lat_t1)
        extra = jnp.zeros((n,), jnp.float32)
        for e in range(faults.lat_t0.size):
            extra = extra + jnp.where(
                jnp.asarray(padded(faults.lat_masks[e])) & act[e],
                jnp.float32(faults.lat_ms[e]),
                0.0,
            )
        per_msg = src_row(extra)
        if is_ctrl is not None:
            per_msg = jnp.where(is_ctrl, 0.0, per_msg)
        delay_ms = delay_ms + per_msg
    delay = jnp.ceil(delay_ms / tick_ms).astype(jnp.int32)
    delay = jnp.maximum(delay, 1)
    if "reorder" in features:
        reorder = u("reorder") * 100.0 < eg(REORDER)
        delay = jnp.where(reorder, 1, delay)

    # --- bandwidth, HTB-queue semantics (``link.go:155-183``): excess
    # messages are deferred, not dropped — each src's egress is a FIFO
    # served at rate B·tick_s/MSG_BYTES msgs/tick (fractional rates
    # trickle messages late instead of blackholing); only a FULL queue
    # tail-drops, which is HTB's actual behavior. The queue is virtual:
    # deferring a message k service-ticks = scheduling its calendar
    # arrival k ticks later, so the only state is the per-src backlog,
    # measured in TICKS of remaining link busy time (not messages): a
    # mid-run rate INCREASE then drains the backlog at the new rate
    # without overtaking already-scheduled messages — FIFO holds, as in
    # HTB. (A rate DECREASE cannot retroactively slow messages already
    # scheduled — the calendar cannot recall them; new traffic queues at
    # the new rate behind the old busy time.)
    bw_dropped = jnp.int32(0)
    new_backlog = link.backlog
    if "bandwidth_queue" in features:
        assert link.backlog is not None, (
            "bandwidth_queue shaping needs a LinkState built with "
            "track_backlog=True"
        )
        bw = eg(BANDWIDTH)
        rate = bw * (tick_ms / 1000.0) / MSG_BYTES  # msgs/tick, per-msg
        safe_rate = jnp.maximum(rate, 1e-9)
        queued = val_f & (bw > 0.0)  # bw ≤ 0 = unshaped, bypasses queue
        if is_ctrl is not None:
            queued = queued & ~is_ctrl
        # FIFO position this tick: outbox order among the src's queued
        # messages, each occupying 1/rate ticks of link time behind the
        # standing busy-time backlog
        qmask = queued.reshape(o, n).astype(jnp.float32)
        ahead = (jnp.cumsum(qmask, axis=0) - qmask).reshape(-1)
        backlog_m = link.backlog if o == 1 else jnp.tile(link.backlog, o)
        # bound the queue in MESSAGES at the current rate (HTB's packet
        # limit): standing ticks × rate + position ahead. Across a rate
        # change this is an approximation — the standing busy time is
        # valued in CURRENT-rate message equivalents (exact counting
        # would need per-message departure state); steady-rate plans get
        # the exact HTB bound
        q_msgs = backlog_m * rate + ahead
        overflow_q = queued & (q_msgs >= jnp.float32(bw_queue_cap))
        bw_dropped = jnp.sum(overflow_q.astype(jnp.int32))
        val_f = val_f & ~overflow_q
        queued = queued & ~overflow_q
        # departure offset = whole ticks of busy time ahead; the 1e-4
        # nudge keeps exact boundaries (k·(1/rate)) from rounding to the
        # LATER tick under float32 (1.0/0.5000001 → 1.99…)
        dt = jnp.floor(
            backlog_m + ahead / safe_rate + 1e-4
        ).astype(jnp.int32)
        delay = delay + jnp.where(queued, dt, 0)
        # admitted messages extend the busy time by 1/rate each; one tick
        # of service elapses before the next enqueue
        admitted = jnp.sum(
            queued.reshape(o, n).astype(jnp.float32), axis=0
        )
        rate_src = jnp.maximum(
            link.egress[BANDWIDTH] * (tick_ms / 1000.0) / MSG_BYTES, 1e-9
        )
        new_backlog = jnp.where(
            link.egress[BANDWIDTH] <= 0.0,
            jnp.float32(0.0),
            jnp.maximum(link.backlog + admitted / rate_src - 1.0, 0.0),
        )

    if is_ctrl is not None:  # control routes ride at the 1-tick floor
        delay = jnp.where(is_ctrl, 1, delay)

    # --- calendar-horizon overflow: netem never silently shortens a
    # configured delay (``link.go:169-179``), so every clamp is COUNTED
    # and surfaced (engine accumulates → journal + runner warning)
    # rather than silently speeding the link up.
    clamped = jnp.sum((val_f & (delay > horizon - 1)).astype(jnp.int32))
    delay = jnp.clip(delay, 1, horizon - 1)

    def fate_of(survived):
        """Per-message fate in original order (see NetFeedback.fate):
        the catch-all is 'dropped' (bounds, loss, bandwidth, slot
        overflow), overridden by the specific kill masks, overridden by
        survival — the precedence matches the flow-conservation
        accounting, so a traced send's fate names the counter its
        message landed in."""
        if not want_fate:
            return None
        f = jnp.full((m,), 3, jnp.int32)  # dropped
        if fault_m is not None:
            f = jnp.where(fault_m, 2, f)  # fault_dropped
        if rej_m is not None:
            f = jnp.where(rej_m, 1, f)  # rejected
        f = jnp.where(survived, 0, f)  # enqueued
        return jnp.where(val0, f, -1)

    def flow_of(enq_m):
        """Per-message flow counts in original order (see
        NetFeedback.flow); ``enq_m`` is [M] int32 enqueued-copy counts
        (a duplicate-shaping original and its copy merge by sum)."""
        if not want_flow:
            return None
        z = jnp.zeros((m,), jnp.int32)
        return jnp.stack(
            [
                sent_m,
                enq_m,
                rej_m.astype(jnp.int32) if rej_m is not None else z,
                fault_m.astype(jnp.int32) if fault_m is not None else z,
            ]
        )

    if slot_mode == "direct":
        # slot = the sender's outbox index: one scatter index per message
        # with no sort and no duplicate pass. Unique under the mode's
        # contract (≤1 sender per (receiver, slot, tick)).
        if o > slots:
            raise ValueError(
                f"direct slot mode needs OUT_MSGS ({o}) <= IN_MSGS ({slots})"
            )
        buck_i = jnp.where(val_f, jnp.mod(t + delay, horizon), jnp.int32(horizon))
        pos_i = jnp.where(val_f, slot_in_src * n + dst_safe, midx)

        # Debug-mode collision detection: the mode's contract is ≤1
        # sender per (receiver, slot, tick) and a blind scatter silently
        # corrupts on violation — under validate, detect both same-tick
        # duplicate targets (sorted adjacent equal keys) and writes onto
        # a still-occupied slot (pre-scatter occupancy readback), and
        # report the first colliding (dst, slot).
        collisions = jnp.int32(0)
        collision_where = jnp.zeros((2,), jnp.int32)
        if validate:
            big_c = horizon * ns
            big_i = jnp.int32(big_c)
            lin = jnp.where(val_f, buck_i * ns + pos_i, big_i)
            # argsort (not sort) so sorted-adjacent duplicates map back to
            # their message index: a message that BOTH duplicates a
            # same-tick key AND lands on an occupied slot is one conflict,
            # not two — the masks OR per message before counting
            perm = jnp.argsort(lin)
            ks = lin[perm]
            dup_sorted = (ks[1:] == ks[:-1]) & (ks[1:] < big_i)
            dup = (
                jnp.zeros_like(val_f).at[perm[1:]].set(dup_sorted)
            )
            plane = cal.occupancy_plane
            flatp = plane if cal.flat else plane.reshape(-1)
            occ = (flatp[jnp.minimum(lin, big_i - 1)] != 0) & val_f
            conflict = dup | occ
            collisions = jnp.sum(conflict.astype(jnp.int32))
            first = jnp.min(
                jnp.where(conflict, lin, big_i), initial=big_c
            )
            p = jnp.mod(first, jnp.int32(ns))
            collision_where = jnp.stack([jnp.mod(p, n), p // n])

        new_payload = tuple(
            scat(p, buck_i, pos_i, pw)
            for p, pw in zip(cal.payload, pay_w)
        )
        if cal.src is not None:  # src+1 doubles as the occupancy mark
            new_src = scat(cal.src, buck_i, pos_i, src_f + 1)
            new_valid = None
        else:
            new_src = None
            new_valid = scat(cal.valid, buck_i, pos_i, True)
        new_etick = (
            scat(cal.etick, buck_i, pos_i, jnp.broadcast_to(t, pos_i.shape))
            if cal.etick is not None
            else None
        )
        return (
            dataclasses.replace(
                cal,
                payload=new_payload,
                src=new_src,
                valid=new_valid,
                etick=new_etick,
            ),
            NetFeedback(
                rejected=rejected,
                clamped=clamped,
                bw_dropped=bw_dropped,
                backlog=new_backlog,
                collisions=collisions,
                collision_where=collision_where,
                sent=sent,
                enqueued=jnp.sum(val_f.astype(jnp.int32)),
                fault_dropped=fault_dropped,
                fate=fate_of(val_f),
                flow=flow_of(val_f.astype(jnp.int32)),
            ),
        )

    # --- duplicate: second copy, one tick later
    if "duplicate" in features:
        dup = val_f & (u("duplicate") * 100.0 < eg(DUPLICATE))
        if is_ctrl is not None:
            dup = dup & ~is_ctrl
        sent = sent + jnp.sum(dup.astype(jnp.int32))
        if want_flow:
            sent_m = sent_m + dup.astype(jnp.int32)
        dst2 = jnp.concatenate([dst_safe, dst_safe])
        pay2 = [jnp.concatenate([p, p]) for p in pay_w]
        src2 = jnp.concatenate([src_f, src_f])
        val2 = jnp.concatenate([val_f, dup])
        # a copy whose +1 lands past the horizon clips back onto its
        # original's tick — that too is a shortened configured delay, so
        # it joins the clamp count (delay is already ≤ horizon-1 here)
        clamped = clamped + jnp.sum(
            (dup & (delay >= horizon - 1)).astype(jnp.int32)
        )
        delay2 = jnp.concatenate(
            [delay, jnp.clip(delay + 1, 1, horizon - 1)]
        )
        m2 = 2 * m
        # fate/flow ride the sort as the original message index; a
        # duplicate copy shares its original's index (fates merge by
        # max, flow counts by sum)
        orig2 = (
            jnp.concatenate([midx, midx])
            if want_fate or want_flow
            else None
        )
    else:
        dst2, pay2, src2, val2, delay2, m2 = (
            dst_safe,
            pay_w,
            src_f,
            val_f,
            delay,
            m,
        )
        orig2 = midx if want_fate or want_flow else None

    bucket = jnp.mod(t + delay2, horizon)

    # --- slot assignment: one stable multi-operand sort by (bucket, dst)
    # carries every message attribute in the same pass (cheaper than
    # argsort + per-attribute gathers), then rank within equal-key runs
    # via a prefix-max of run starts (one cummax — no binary-search
    # while-loop). The key encodes everything positional — bucket, dst,
    # AND validity (invalid ⇒ key = big, sorting to the end) — so only
    # src and the payload words ride along as sort values; bucket/dst/
    # valid are re-derived from the sorted key instead of sorted.
    #
    # Sharded pallas commit: the key becomes SHARD-major —
    # (dst_shard, bucket, local_dst) — so one global stable sort yields
    # every shard's segment contiguously, in exactly the order the
    # per-shard kernel walk expects after rebasing (the (bucket, dst)
    # equivalence classes are unchanged, so within-class stable order —
    # and therefore slot assignment — is bit-identical to the
    # bucket-major key). `big = horizon·n` still sorts invalids last:
    # the max valid shard-major key is shards·horizon·n_loc − 1 = big−1.
    big = jnp.int32(horizon * n)
    if transport == "pallas" and mesh is not None:
        shards = int(mesh.shape["i"])
        n_loc = n // shards
        sort_key = jnp.where(
            val2,
            (dst2 // n_loc) * jnp.int32(horizon * n_loc)
            + bucket * n_loc
            + jnp.mod(dst2, n_loc),
            big,
        )
    else:
        sort_key = jnp.where(val2, bucket * n + dst2, big)
    sort_vals = [sort_key, src2] + list(pay2)
    if orig2 is not None:
        sort_vals.append(orig2)
    sorted_ops = jax.lax.sort(sort_vals, num_keys=1, is_stable=True)
    sk, src_s = sorted_ops[:2]
    pay_s = sorted_ops[2 : 2 + width]
    orig_s = sorted_ops[-1] if orig2 is not None else None

    if transport == "pallas":
        # hand-tiled calendar commit (sim/pallas_transport.py): slot
        # ranks, stacking bases, and every plane write happen inside one
        # bucket-partitioned kernel pass over the sorted stream — the
        # fill-table derivation, base gather, rank cummax, and the
        # scatters below are all compiled out of the XLA program.
        from .pallas_transport import commit_calendar

        occ_vals = (
            src_s + 1 if cal.src is not None else jnp.ones_like(src_s)
        )
        cal, survived = commit_calendar(
            cal, sk, occ_vals, list(pay_s), t, stacking=stacking, mesh=mesh
        )
        if orig_s is not None:
            # map sorted survival back to original order (duplicate
            # copies share an index). Fate needs only "either copy made
            # it" (max); flow needs the copy COUNT (add) — the fate-only
            # program keeps its scatter-max so the trace plane's jaxpr
            # is untouched when the matrix plane is off.
            acc = jnp.zeros((m,), jnp.int32)
            surv_orig = (
                acc.at[orig_s].add(survived)
                if want_flow
                else acc.at[orig_s].max(survived)
            )
            fate = fate_of(surv_orig > 0)
            flow = flow_of(surv_orig)
        else:
            fate = None
            flow = None
        return (
            cal,
            NetFeedback(
                rejected=rejected,
                clamped=clamped,
                bw_dropped=bw_dropped,
                backlog=new_backlog,
                collisions=jnp.int32(0),
                collision_where=jnp.zeros((2,), jnp.int32),
                sent=sent,
                enqueued=jnp.sum(survived),
                fault_dropped=fault_dropped,
                fate=fate,
                flow=flow,
            ),
        )

    val_sorted = sk < big
    buck_s = jnp.where(val_sorted, sk // n, horizon)
    dst_s = jnp.mod(sk, n)
    pos = jnp.arange(m2, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    rank = pos - jax.lax.cummax(jnp.where(is_start, pos, 0))

    # --- cross-tick stacking: ranks start at the bucket's current fill
    # so messages landing in a bucket over several ticks occupy
    # successive slots instead of overwriting earlier arrivals. The fill
    # table [L, N] is DERIVED from the occupancy plane by summing marks
    # over the slot axis (slot-strided [L, n] slices — pure vector
    # reads, no retiling reshape), not carried as state: the plane
    # already records exactly which slots are taken, and deliver()'s
    # row clear resets a bucket's counts for free. This removes what was
    # a third 200k-index scalar-core scatter per tick (~20% of the
    # sustained full path at 100k instances). The plane's flat index
    # space is slot-major, so slice s covers positions [s·n, (s+1)·n);
    # the fill table's flat index IS the sort key (bucket·n + dst).
    if stacking:
        marks = cal.occupancy_plane
        if cal.flat:
            # flat plane → [L, slots, n] view; the compare+sum fuse over
            # the linear buffer (no retiling copy materializes)
            m3 = marks.reshape(horizon, slots, n)
            occ_table = (m3 != 0).sum(axis=1, dtype=jnp.int32)
        else:
            occ_table = marks[:, 0:n] != 0
            occ_table = occ_table.astype(jnp.int32)
            for s in range(1, slots):
                occ_table = occ_table + (marks[:, s * n : (s + 1) * n] != 0)
        occ_flat = occ_table.reshape(-1)
        base = occ_flat[jnp.minimum(sk, big - 1)]
        rank = rank + jnp.where(val_sorted, base, 0)
    val_s = val_sorted & (rank < slots)  # per-dst inbox overflow

    # Scatter into the [L, N·SLOTS] planes at (bucket, slot·N + dst).
    # Indices are unique by construction (rank is unique within each
    # (bucket, dst) run); dropped messages get an out-of-range bucket with
    # a unique position so the scatter keeps its no-duplicate path
    # (duplicate indices force XLA into a sort-based dedup lowering).
    buck_i = jnp.where(val_s, buck_s, jnp.int32(horizon))
    pos_i = jnp.where(val_s, rank * n + dst_s, pos)

    new_payload = tuple(
        scat(p, buck_i, pos_i, pw) for p, pw in zip(cal.payload, pay_s)
    )
    if cal.src is not None:  # src+1 doubles as the occupancy mark
        new_src = scat(cal.src, buck_i, pos_i, src_s + 1)
        new_valid = None
    else:
        new_src = None
        new_valid = scat(cal.valid, buck_i, pos_i, True)
    new_etick = (
        scat(cal.etick, buck_i, pos_i, jnp.broadcast_to(t, pos_i.shape))
        if cal.etick is not None
        else None
    )

    if orig_s is not None:
        # map slot survival back to original order (duplicate copies
        # share an index). Scatter-max for fate ("enqueued if either
        # copy was"), scatter-add when the matrix plane wants copy
        # counts — fate-only programs keep their pre-matrix jaxpr.
        acc = jnp.zeros((m,), jnp.int32)
        surv = (
            acc.at[orig_s].add(val_s.astype(jnp.int32))
            if want_flow
            else acc.at[orig_s].max(val_s.astype(jnp.int32))
        )
        fate = fate_of(surv > 0)
        flow = flow_of(surv)
    else:
        fate = None
        flow = None

    return (
        dataclasses.replace(
            cal,
            payload=new_payload,
            src=new_src,
            valid=new_valid,
            etick=new_etick,
        ),
        NetFeedback(
            rejected=rejected,
            clamped=clamped,
            bw_dropped=bw_dropped,
            backlog=new_backlog,
            collisions=jnp.int32(0),
            collision_where=jnp.zeros((2,), jnp.int32),
            sent=sent,
            enqueued=jnp.sum(val_s.astype(jnp.int32)),
            fault_dropped=fault_dropped,
            fate=fate,
            flow=flow,
        ),
    )


def apply_net_updates(
    link: LinkState,
    net_shape: jax.Array,  # [7, N] plane layout (from step out_axes=-1)
    net_shape_valid: jax.Array,  # [N]
    net_filters: jax.Array,  # [R, N]
    net_filters_valid: jax.Array,  # [N]
    net_region: jax.Array | None = None,  # [N] int32
    net_region_valid: jax.Array | None = None,  # [N]
    net_rules: jax.Array | None = None,  # [K, 3, N] int32
    net_rules_valid: jax.Array | None = None,  # [N]
) -> LinkState:
    """Apply per-instance network reconfigurations emitted by steps — the
    sidecar handler's "apply each network.Config received" loop
    (``pkg/sidecar/sidecar_handler.go:49-82``) with one-tick turnaround."""
    egress = jnp.where(net_shape_valid[None, :], net_shape, link.egress)
    if link.filters.shape[0] > 0 and net_filters.shape[0] > 0:
        filters = jnp.where(
            net_filters_valid[None, :], net_filters, link.filters
        )
    else:
        filters = link.filters
    region_of = link.region_of
    if net_region is not None and net_region_valid is not None:
        region_of = jnp.where(net_region_valid, net_region, region_of)
    rules = link.rules
    if net_rules is not None and net_rules_valid is not None:
        # shape agreement is the engine's contract — a silent skip here
        # would mask an engine-side plumbing bug as "rules never applied"
        if rules is None:
            raise ValueError(
                "net_rules update against a LinkState without rule "
                "planes (n_rules=0) — declare 'filter_rules' shaping"
            )
        if net_rules.shape[0] != rules.shape[0]:
            raise ValueError(
                f"net_rules K={net_rules.shape[0]} != LinkState "
                f"K={rules.shape[0]}"
            )
        # a valid emission replaces the instance's WHOLE rule list (the
        # reference's ConfigureNetwork replaces the rule set, it does
        # not merge)
        rules = jnp.where(net_rules_valid[None, None, :], net_rules, rules)
    # replace() preserves fields with no reconfiguration surface (the
    # HTB backlog) by construction — a field-by-field rebuild would
    # silently drop whatever LinkState grows next
    return dataclasses.replace(
        link,
        egress=egress,
        filters=filters,
        region_of=region_of,
        rules=rules,
    )
