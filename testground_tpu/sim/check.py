"""Static-analysis plane: the shared gate-rules engine behind ``tg check``
(docs/CHECKING.md).

The reference front-loads failure with ``plan``/``describe``/
``healthcheck`` verbs so a bad composition dies before a 10k-instance
run is scheduled. This module is the sim analog, grown past the point
the reference reached: every composition-level admission rule the
``sim:jax`` executor enforces — the mutually-gated transport/bucket/
pack/trace/SLO/checkpoint/fault knobs whose refusals historically lived
as scattered ``ValueError``s deep in ``sim/executor.py``, firing only
after queueing — is catalogued here as a typed :class:`Rule` and
evaluated statically against a composition + run-config + device
context, ALL findings reported in one pass instead of dying on the
first.

Drift discipline: the checker does not re-implement the gates — it
**calls the same functions** the executor calls (``resolve_buckets``,
``decide_transport``, ``build_fault_schedule``, ``build_trace_plan``,
``build_slo_plan``, ``pack`` admission, the cohort spec-size precheck),
catching their refusals and collecting their warnings, so an error
message here is byte-identical to the one the executor would raise. The
few refusals the executor states inline (SLO-without-telemetry,
resume-under-cohort) are extracted into shared message helpers the
executor now imports back. ``tests/test_check.py`` pins the no-drift
property over a matrix of bad configs: the executor cannot refuse a
config the checker passes, and vice versa.

Three layers share the engine:

1. **config rules** — pure composition + run-cfg + device-context
   evaluation (no jax import, milliseconds): knob validation, gate
   exclusions, pack-admission preview, cohort bounds.
2. **abstract plan tracing** (``trace_plans=True``) — each referenced
   plan's testcase runs under ``jax.eval_shape`` at the composition's
   real (and, when bucketed, padded-ladder) shapes — no device
   allocation — catching traced-count contract violations
   (``docs/WRITING_PLANS.md``), shape/dtype errors, and build-time
   refusals before anything compiles.
3. **jaxpr invariant lints** (with ``trace_plans``) — the lowered tick
   jaxpr is scanned for host callbacks in the hot path
   (``pure_callback``/``io_callback``/``debug_print``), unbounded
   ``while`` loops in the tick, and weak-type state leaves (recompile
   hazards).

Import-light on purpose for layer 1 (stdlib + numpy + the sibling gate
modules): without ``--trace-plans`` the only jax touches are device
detection for the mesh-bound rules (skipped by an explicit
``devices=N``) and the divisibility arithmetic ``sim/meshplan.py``
hosts, paid only when a multi-device mesh is actually in play.
"""

from __future__ import annotations

import dataclasses
import types

__all__ = [
    "CheckContext",
    "Finding",
    "Rule",
    "RULES",
    "check_composition",
    "render_findings",
    "resume_cohort_message",
    "rule_by_id",
    "slo_requires_telemetry_message",
]


# --------------------------------------------------------------- catalog


@dataclasses.dataclass(frozen=True)
class Rule:
    """One catalogued admission rule: a stable id, the severity the
    executor enforces it at (``error`` = the run is refused, ``warn`` =
    the executor falls back loudly), the knob layer it guards, and a
    one-line summary for the catalog table (docs/CHECKING.md)."""

    id: str
    severity: str  # "error" | "warn"
    layer: str
    summary: str


RULES: tuple[Rule, ...] = (
    # ---- composition structure
    Rule(
        "composition.invalid",
        "error",
        "composition",
        "composition fails structural validation / preparation",
    ),
    Rule(
        "run-cfg.unknown-key",
        "warn",
        "run-cfg",
        "runner-config key matches no SimJaxConfig field (silently ignored)",
    ),
    # ---- transport
    Rule(
        "transport.unknown",
        "error",
        "transport",
        "transport knob is not xla|pallas|auto",
    ),
    Rule(
        "transport.mesh-indivisible",
        "warn",
        "transport",
        "pallas/auto lanes do not divide across the mesh peer shards; "
        "resolves to xla",
    ),
    # ---- mesh layout
    Rule(
        "mesh.shape-invalid",
        "error",
        "mesh",
        "mesh knob is not N or AxB (e.g. '4' or '2x4')",
    ),
    # ---- shape buckets
    Rule(
        "buckets.mode-invalid",
        "error",
        "buckets",
        "bucket knob is not off|auto|<n>",
    ),
    Rule(
        "buckets.ladder-invalid",
        "error",
        "buckets",
        "bucket_ladder is not a positive instance-count list",
    ),
    Rule(
        "buckets.cohort-disabled",
        "warn",
        "buckets",
        "bucketing disabled under a cohort config",
    ),
    Rule(
        "buckets.mesh-indivisible",
        "warn",
        "buckets",
        "a padded rung does not divide across the mesh peer shards; "
        "runs exact shapes",
    ),
    Rule(
        "buckets.over-ladder",
        "warn",
        "buckets",
        "a group exceeds the ladder coverage; runs exact shapes",
    ),
    Rule(
        "buckets.filter-rules",
        "warn",
        "buckets",
        "filter_rules shaping with multiple groups disables bucketing",
    ),
    # ---- faults / flight recorder
    Rule(
        "faults.invalid",
        "error",
        "faults",
        "a [[run.faults]] table fails validation/lowering",
    ),
    Rule(
        "trace.invalid",
        "error",
        "trace",
        "a [run.trace] table fails validation/lowering",
    ),
    Rule(
        "trace.bucket-disabled",
        "warn",
        "trace",
        "flight recorder disabled under shape bucketing",
    ),
    Rule(
        "trace.cohort-disabled",
        "warn",
        "trace",
        "flight recorder disabled under a cohort config",
    ),
    # ---- telemetry / SLO
    Rule(
        "telemetry.cohort-disabled",
        "warn",
        "telemetry",
        "telemetry plane disabled under a cohort config",
    ),
    # ---- traffic matrix
    Rule(
        "netmatrix.needs-telemetry",
        "error",
        "netmatrix",
        "netmatrix = true but the telemetry plane is off",
    ),
    Rule(
        "netmatrix.cohort-disabled",
        "warn",
        "netmatrix",
        "traffic matrix disabled under a cohort config",
    ),
    Rule(
        "slo.invalid",
        "error",
        "slo",
        "a [[run.slo]] table fails validation",
    ),
    Rule(
        "slo.needs-telemetry",
        "error",
        "slo",
        "SLO rules declared but the telemetry plane is off",
    ),
    Rule(
        "slo.cohort-disabled",
        "warn",
        "slo",
        "SLO assertions disabled under a cohort config",
    ),
    # ---- checkpoint / resume
    Rule(
        "checkpoint.cohort-disabled",
        "warn",
        "checkpoint",
        "checkpointing disabled under a cohort config",
    ),
    Rule(
        "checkpoint.resume-cohort",
        "error",
        "checkpoint",
        "resume_from is not supported under a multi-host cohort",
    ),
    Rule(
        "checkpoint.resume-multi-runs",
        "error",
        "checkpoint",
        "resume_from on a multi-[[runs]] composition is ambiguous",
    ),
    # ---- debug knobs
    Rule(
        "debug.nan-guard-cohort",
        "warn",
        "debug",
        "nan_guard disabled under a cohort config",
    ),
    # ---- cohort
    Rule(
        "cohort.spec-oversize",
        "error",
        "cohort",
        "cohort job spec exceeds the broadcast byte bound",
    ),
    # ---- run packing
    Rule(
        "pack.solo",
        "warn",
        "pack",
        "pack=true but the composition must run solo",
    ),
    # ---- abstract plan tracing (--trace-plans)
    Rule(
        "plan.load-failed",
        "error",
        "plan",
        "plan sources fail to import/specialize for this composition",
    ),
    Rule(
        "plan.traced-int",
        "error",
        "plan",
        "python int()/len()/control flow on a traced count "
        "(the traced-count contract, docs/WRITING_PLANS.md)",
    ),
    Rule(
        "plan.trace-error",
        "error",
        "plan",
        "the testcase fails to trace at the composition's shapes",
    ),
    Rule(
        "plan.memory",
        "error",
        "plan",
        "estimated carry footprint exceeds the device memory budget",
    ),
    Rule(
        "plan.host-callback",
        "warn",
        "plan",
        "host callback (pure_callback/io_callback/debug_print) in the "
        "jitted tick",
    ),
    Rule(
        "plan.while-loop",
        "warn",
        "plan",
        "while loop in the jitted tick (unbounded per-tick work)",
    ),
    Rule(
        "plan.weak-type",
        "warn",
        "plan",
        "weak-typed leaf in the instance state (recompile hazard)",
    ),
)

_RULE_INDEX = {r.id: r for r in RULES}


def rule_by_id(rule_id: str) -> Rule:
    return _RULE_INDEX[rule_id]


@dataclasses.dataclass
class Finding:
    """One rule firing against one composition: the rule id, its
    severity/layer (denormalized for the JSON surface), the
    executor-identical message, and where it fired (``run`` = the
    [[runs]] entry id, when attributable; ``plan_file`` for the
    plan-tracing layer)."""

    rule: str
    severity: str
    layer: str
    message: str
    run: str = ""
    plan_file: str = ""

    def to_dict(self) -> dict:
        out = {
            "rule": self.rule,
            "severity": self.severity,
            "layer": self.layer,
            "message": self.message,
        }
        if self.run:
            out["run"] = self.run
        if self.plan_file:
            out["plan_file"] = self.plan_file
        return out


# ------------------------------------------------- shared message helpers
# The executor imports these back, so the refusal it raises and the
# finding the checker reports are the same string by construction.


def slo_requires_telemetry_message(count: int, disable_metrics: bool) -> str:
    """The SLO-without-telemetry refusal (executor + checker)."""
    return (
        f"composition declares {count} SLO rule(s) but the telemetry "
        "plane is off"
        + (
            " (disable_metrics = true wins over everything)"
            if disable_metrics
            else " — set telemetry = true in the runner config "
            "(--run-cfg telemetry=true)"
        )
        + "; refusing to run with unenforceable SLOs"
    )


def netmatrix_requires_telemetry_message(disable_metrics: bool) -> str:
    """The netmatrix-without-telemetry refusal (executor + checker)."""
    return (
        "netmatrix = true but the telemetry plane is off"
        + (
            " (disable_metrics = true wins over everything)"
            if disable_metrics
            else " — the traffic matrix rides the telemetry chunk "
            "flush; set telemetry = true in the runner config "
            "(--run-cfg telemetry=true)"
        )
        + "; refusing to run with an unobservable matrix plane"
    )


def resume_cohort_message() -> str:
    """The resume-under-cohort refusal (executor + checker)."""
    return (
        "resume_from is not supported under a multi-host cohort "
        "(checkpoints are leader-local reads of a cross-process "
        "carry); run the resumed composition single-host"
    )


# ---------------------------------------------------------------- context


@dataclasses.dataclass
class CheckContext:
    """Everything one check pass evaluates against: the prepared
    composition, the coalesced runner config, and the device context
    (``devices`` = how many devices the run would see; overridable so a
    laptop can check what an 8-chip host would refuse)."""

    comp: object  # api.Composition, post prepare_for_run
    cfg: object  # SimJaxConfig
    devices: int = 1
    trace_plans: bool = False
    plan_sources: str = ""  # plan source dir (for trace_plans)
    raw_run_config: dict = dataclasses.field(default_factory=dict)

    @property
    def cohort(self) -> bool:
        return bool(getattr(self.cfg, "coordinator_address", ""))

    @property
    def mesh_layout(self) -> str:
        """The explicit ``mesh`` knob (``mesh="2x4"``); empty when
        unset, under a cohort (the cohort builds the global mesh), or
        malformed (``mesh.shape-invalid`` reports that refusal)."""
        if self.cohort:
            return ""
        layout = str(getattr(self.cfg, "mesh", "") or "")
        return layout if _parse_layout(layout) is not None else ""

    @property
    def mesh_devices(self) -> int:
        """Devices the executor's ``_make_mesh`` would mesh over: the
        explicit layout's extent product when the ``mesh`` knob is set,
        else > 1 only when sharding is on and this is not a cohort
        config (a cohort builds the global mesh instead — which is
        always multi-device, so cohort gates subsume the mesh gates
        there)."""
        dims = _parse_layout(self.mesh_layout) if self.mesh_layout else None
        if dims is not None:
            n = 1
            for d in dims:
                n *= int(d)
            return n
        if not getattr(self.cfg, "shard", True) or self.cohort:
            return 1
        return max(int(self.devices), 1)

    @property
    def peer_shards(self) -> int:
        """Extent of the instance (``i``) axis the divisibility gates
        divide by — the LAST layout extent (a 2-D mesh spends its
        leading extent on the pack run axis), the device count for the
        implicit 1-D ``shard=true`` mesh."""
        dims = _parse_layout(self.mesh_layout) if self.mesh_layout else None
        if dims is not None:
            return int(dims[-1])
        return self.mesh_devices


def _parse_layout(text: str) -> tuple[int, ...] | None:
    """``meshplan.parse_mesh_shape``'s grammar without the jax import
    (the config layer stays import-light); returns None instead of
    raising — the ``mesh.shape-invalid`` pass reports the refusal with
    the real function's message."""
    parts = str(text).lower().replace("×", "x").split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        return None
    if not (1 <= len(dims) <= 2) or any(d < 1 for d in dims):
        return None
    return dims


class _FakeMesh:
    """Duck-typed stand-in for a ``jax.sharding.Mesh``: the gates read
    ``mesh.devices.size`` and — when an explicit layout is known — the
    ``shape`` Mapping (``meshplan.peer_shards``/``layout_str`` fall
    back duck-type safely when it is absent), letting the config layer
    evaluate mesh rules without importing jax."""

    def __init__(self, n: int, layout: str = ""):
        self.devices = types.SimpleNamespace(size=int(n))
        dims = _parse_layout(layout) if layout else None
        if dims is not None:
            names = ("i",) if len(dims) == 1 else ("runs", "i")
            self.shape = dict(zip(names, (int(d) for d in dims)))


def _mesh_of(ctx: CheckContext):
    n = ctx.mesh_devices
    return _FakeMesh(n, ctx.mesh_layout) if n > 1 else None


def _group_layout(run_groups):
    """The resolved per-run group layout the lowering gates resolve
    selectors against — the exact construction of
    ``sim/engine.build_groups`` without the jax import (the gates only
    read ``id``/``index``/``offset``/``count``/``params``)."""
    specs = []
    off = 0
    for i, rg in enumerate(run_groups):
        count = int(rg.calculated_instance_count)
        specs.append(
            types.SimpleNamespace(
                id=rg.id,
                index=i,
                offset=off,
                count=count,
                params=dict(rg.test_params),
            )
        )
        off += count
    return tuple(specs)


class _WarnCollector:
    """A ``(fmt, *args)`` warn callable (the gates' contract) that
    collects the rendered lines; also quacks like an OutputWriter
    (``warn``/``infof``) for the helpers that take one."""

    def __init__(self):
        self.lines: list[str] = []

    def __call__(self, fmt, *args):
        self.warn(fmt, *args)

    def warn(self, fmt, *args):
        try:
            self.lines.append(str(fmt) % args if args else str(fmt))
        except (TypeError, ValueError):
            self.lines.append(str(fmt))

    def infof(self, fmt, *args):  # the gates' info lines are not findings
        pass


# ------------------------------------------------------------ rule passes


def _add(findings, rule_id, message, run="", plan_file=""):
    r = rule_by_id(rule_id)
    findings.append(
        Finding(
            rule=r.id,
            severity=r.severity,
            layer=r.layer,
            message=message,
            run=run,
            plan_file=plan_file,
        )
    )


def _check_run_cfg_keys(ctx, findings) -> None:
    """Unknown runner-config keys: ``coalesce_into`` silently drops
    them, so a typo'd knob (``trasnport=pallas``) configures nothing —
    surfaced here instead of silently ignored at run time."""
    from .executor import SimJaxConfig

    known = {f.name for f in dataclasses.fields(SimJaxConfig)}
    # runner-level keys that are legitimately not SimJaxConfig fields:
    # "enabled" is the manifest's runner toggle (prepare_for_run folds
    # manifest runner defaults into run_config), the rest are consumed
    # by the engine/runner layer before the executor
    known |= {"enabled", "pack", "sync_service"}
    for key in sorted(ctx.raw_run_config or {}):
        if key not in known:
            _add(
                findings,
                "run-cfg.unknown-key",
                f"runner config key {key!r} matches no sim:jax option and "
                "is silently ignored — known options: "
                f"{', '.join(sorted(known))}",
            )


def _check_mesh(ctx, findings) -> None:
    """An explicit ``mesh`` knob that fails the layout grammar — the
    executor's ``parse_mesh_shape`` refusal, reported statically. The
    meshplan import (and its jax dependency) is paid only on the
    failing path; the happy path parses locally."""
    layout = str(getattr(ctx.cfg, "mesh", "") or "")
    if not layout or _parse_layout(layout) is not None:
        return
    from .meshplan import parse_mesh_shape

    try:
        parse_mesh_shape(layout)
    except ValueError as e:
        _add(findings, "mesh.shape-invalid", str(e))


def _check_transport(ctx, findings) -> None:
    """The transport knob's static gates. A multi-device mesh no longer
    falls anything back wholesale (ISSUE 20): only an INDIVISIBLE lane
    count does, per run — the same arithmetic ``decide_transport``
    applies, with the same message (``mesh_lanes_message``)."""
    from .transport_model import (
        TRANSPORTS,
        decide_transport,
        mesh_lanes_message,
    )

    requested = str(getattr(ctx.cfg, "transport", "xla") or "xla").lower()
    if requested not in TRANSPORTS:
        try:
            decide_transport(ctx.cfg, None)
        except ValueError as e:
            _add(findings, "transport.unknown", str(e))
        return
    shards = ctx.peer_shards
    if requested == "xla" or shards <= 1:
        return
    from .executor import _parse_hosts

    hosts = _parse_hosts(getattr(ctx.cfg, "additional_hosts", None))
    for run in ctx.comp.runs:
        n_lanes = sum(
            int(rg.calculated_instance_count) for rg in run.groups
        ) + len(hosts)
        if n_lanes % shards != 0:
            _add(
                findings,
                "transport.mesh-indivisible",
                mesh_lanes_message(requested, n_lanes, shards),
                run=run.id,
            )


def _check_buckets(ctx, run, findings):
    """Returns the resolved BucketPlan (or None) for this run — the
    trace/pack layers need it — while collecting the gate's refusals
    and warnings as findings."""
    from .executor import resolve_buckets

    counts = [rg.calculated_instance_count for rg in run.groups]
    warns = _WarnCollector()
    try:
        plan = resolve_buckets(ctx.cfg, counts, mesh=_mesh_of(ctx), warn=warns)
    except ValueError as e:
        msg = str(e)
        rule = (
            "buckets.ladder-invalid"
            if "bucket_ladder" in msg
            else "buckets.mode-invalid"
        )
        _add(findings, rule, msg, run=run.id)
        return None
    for line in warns.lines:
        if "cohort" in line:
            rule = "buckets.cohort-disabled"
        elif "divide" in line:
            rule = "buckets.mesh-indivisible"
        else:
            rule = "buckets.over-ladder"
        _add(findings, rule, line, run=run.id)
    return plan


def _run_specs(ctx, run):
    """The three spec dicts the executor collects for one run — built
    from the SAME ``*_specs_of`` helpers on the same layout."""
    from .executor import fault_specs_of, slo_specs_of, trace_specs_of

    g = ctx.comp.global_
    run_global = g.run if g.run is not None else None
    fault_specs = fault_specs_of(
        run.groups, run_global.faults if run_global else None
    )
    trace_specs = trace_specs_of(
        run.groups, run_global.trace if run_global else None
    )
    slo_specs = slo_specs_of(
        run.groups, run_global.slo if run_global else None
    )
    return fault_specs, trace_specs, slo_specs


def _check_run(ctx, run, findings) -> dict:
    """All config-layer rules for one [[runs]] entry. Returns the
    resolved pieces the plan-tracing layer reuses."""
    from .faults import build_fault_schedule
    from .slo import build_slo_plan
    from .trace import build_trace_plan

    vgroups = _group_layout(run.groups)
    fault_specs, trace_specs, slo_specs = _run_specs(ctx, run)
    bucket_plan = _check_buckets(ctx, run, findings)

    fault_schedule = None
    try:
        fault_schedule = build_fault_schedule(
            vgroups, fault_specs, ctx.cfg.tick_ms
        )
    except ValueError as e:
        _add(findings, "faults.invalid", str(e), run=run.id)

    trace_plan = None
    try:
        trace_plan = build_trace_plan(vgroups, trace_specs)
    except ValueError as e:
        _add(findings, "trace.invalid", str(e), run=run.id)
    disable_metrics = bool(ctx.comp.global_.disable_metrics)
    if trace_plan is not None and disable_metrics:
        trace_plan = None  # silent at run time too (the opt-out wins)
    if trace_plan is not None and bucket_plan is not None:
        _add(
            findings,
            "trace.bucket-disabled",
            "flight recorder disabled under shape bucketing (trace "
            "lanes are exact-layout selectors baked into the program; "
            "run with bucket=off to trace)",
            run=run.id,
        )
        trace_plan = None
    if trace_plan is not None and ctx.cohort:
        _add(
            findings,
            "trace.cohort-disabled",
            "flight recorder disabled for the cohort config (per-chunk "
            "leader-local device reads are not symmetric across "
            "processes)",
            run=run.id,
        )
        trace_plan = None

    telemetry_on = (
        bool(getattr(ctx.cfg, "telemetry", False)) and not disable_metrics
    )
    if telemetry_on and ctx.cohort:
        _add(
            findings,
            "telemetry.cohort-disabled",
            "telemetry disabled for the cohort config (per-chunk "
            "leader-local device reads are not symmetric across "
            "processes)",
            run=run.id,
        )
        telemetry_on = False

    # network-topology plane: same gate ladder as the executor —
    # cohorts shed it (per-chunk leader-local delta reads), and asking
    # for the matrix with the telemetry plane off is a hard refusal
    # (the executor raises the same message at run time)
    netmatrix_on = bool(getattr(ctx.cfg, "netmatrix", False))
    if netmatrix_on and ctx.cohort:
        _add(
            findings,
            "netmatrix.cohort-disabled",
            "traffic matrix disabled for the cohort config (per-chunk "
            "leader-local delta reads are not symmetric across "
            "processes)",
            run=run.id,
        )
        netmatrix_on = False
    if netmatrix_on and not telemetry_on:
        _add(
            findings,
            "netmatrix.needs-telemetry",
            netmatrix_requires_telemetry_message(disable_metrics),
            run=run.id,
        )
        netmatrix_on = False

    slo_plan = None
    try:
        slo_plan = build_slo_plan(vgroups, slo_specs)
    except ValueError as e:
        _add(findings, "slo.invalid", str(e), run=run.id)
    if slo_plan is not None and ctx.cohort:
        _add(
            findings,
            "slo.cohort-disabled",
            "SLO assertions disabled for the cohort config (the "
            "telemetry plane they evaluate is leader-local and runs "
            "off under a cohort)",
            run=run.id,
        )
        slo_plan = None
    if slo_plan is not None and not telemetry_on:
        _add(
            findings,
            "slo.needs-telemetry",
            slo_requires_telemetry_message(slo_plan.count, disable_metrics),
            run=run.id,
        )

    # checkpoint / resume / debug gates
    ckpt_every = int(getattr(ctx.cfg, "checkpoint_chunks", 0) or 0)
    resume_from = str(getattr(ctx.cfg, "resume_from", "") or "")
    if resume_from and ctx.cohort:
        _add(
            findings,
            "checkpoint.resume-cohort",
            resume_cohort_message(),
            run=run.id,
        )
    if ckpt_every > 0 and ctx.cohort:
        _add(
            findings,
            "checkpoint.cohort-disabled",
            "checkpointing disabled for the cohort config (a "
            "leader-local read of the cross-process-sharded carry is "
            "not symmetric)",
            run=run.id,
        )
    if bool(getattr(ctx.cfg, "nan_guard", False)) and ctx.cohort:
        _add(
            findings,
            "debug.nan-guard-cohort",
            "nan_guard disabled for the cohort config (a leader-local "
            "read of the cross-process-sharded carry is not symmetric, "
            "and raises on non-addressable shards)",
            run=run.id,
        )

    if ctx.cohort:
        _check_cohort_spec_size(ctx, run, findings)

    return {
        "vgroups": vgroups,
        "bucket_plan": bucket_plan,
        "fault_schedule": fault_schedule,
        "fault_specs": fault_specs,
        "trace_plan": trace_plan,
        "telemetry_on": telemetry_on,
        "netmatrix_on": netmatrix_on,
    }


def _check_cohort_spec_size(ctx, run, findings) -> None:
    """The broadcast-bound precheck, via the executor's OWN function on
    a job shaped like the one ``do_run`` would build — same builder,
    same bound, same message."""
    try:
        from testground_tpu.api import RunGroup

        from .executor import _precheck_cohort_spec_size

        job = types.SimpleNamespace(
            test_plan=ctx.comp.global_.plan,
            test_case=ctx.comp.global_.case,
            run_id=run.id,
            groups=[
                RunGroup(
                    id=rg.id,
                    instances=rg.calculated_instance_count,
                    parameters=dict(rg.test_params),
                    faults=[dict(f) for f in getattr(rg, "faults", [])],
                )
                for rg in run.groups
            ],
            faults=[
                dict(f)
                for f in (
                    ctx.comp.global_.run.faults
                    if ctx.comp.global_.run is not None
                    else []
                )
            ],
        )
        _precheck_cohort_spec_size(job, ctx.cfg)
    except ValueError as e:
        _add(findings, "cohort.spec-oversize", str(e), run=run.id)
    except Exception:  # noqa: BLE001 — the precheck needs jax's
        # distributed constants; a host without them skips this rule
        pass


def _check_pack(ctx, findings) -> None:
    """Pack-admission preview: when the composition opts into packing
    but would run solo, name the cause — the same classification the
    engine journals as ``sim.pack.solo_reason``."""
    from testground_tpu.engine.pack import solo_reason_for_composition

    env_layer = dict(ctx.raw_env_layer) if hasattr(ctx, "raw_env_layer") else {}
    reason = solo_reason_for_composition(ctx.comp.to_dict(), env_layer)
    if reason is not None:
        _add(
            findings,
            "pack.solo",
            f"pack=true but this composition runs solo: {reason}",
        )


def _check_resume_multi_runs(ctx, findings) -> None:
    """Composition-level checkpoint rule: ``resume_from`` with multiple
    ``[[runs]]`` entries is ambiguous (the per-run rules live in
    :func:`_check_run` beside ``checkpoint.resume-cohort``)."""
    if str(getattr(ctx.cfg, "resume_from", "") or "") and (
        len(ctx.comp.runs) > 1
    ):
        _add(
            findings,
            "checkpoint.resume-multi-runs",
            f"resume_from is set on a multi-[[runs]] composition "
            f"({len(ctx.comp.runs)} runs) — every run would resume from "
            "the same snapshot dir; resume one run at a time "
            "(--run-ids <id>)",
        )


# ------------------------------------------------- abstract plan tracing


_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}


def _iter_eqns(jaxpr):
    """Every equation in a jaxpr, recursing into call/control-flow
    sub-jaxprs (pjit, scan, while, cond, custom_* …)."""
    try:  # jax ≥ 0.4.34 exports these from jax.extend.core; the
        # jax.core aliases are deprecated and removed in newer releases
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:
        from jax.core import ClosedJaxpr, Jaxpr

    def subs(v):
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from subs(item)

    for eqn in jaxpr.eqns:
        yield eqn
        for param in eqn.params.values():
            for sub in subs(param):
                yield from _iter_eqns(sub)


def _classify_trace_error(e) -> str:
    """Map a trace-time exception to a rule id: the traced-count
    contract violations get their own id (the actionable one), the rest
    report as generic trace errors."""
    try:
        import jax

        traced_types = tuple(
            t
            for t in (
                getattr(jax.errors, "TracerIntegerConversionError", None),
                getattr(jax.errors, "TracerBoolConversionError", None),
                getattr(jax.errors, "TracerArrayConversionError", None),
                getattr(jax.errors, "ConcretizationTypeError", None),
            )
            if t is not None
        )
    except Exception:  # noqa: BLE001
        traced_types = ()
    return (
        "plan.traced-int"
        if isinstance(e, traced_types)
        else "plan.trace-error"
    )


def _trace_one_program(ctx, run, resolved, findings, *, bucketed) -> None:
    """Build one SimProgram variant for this run and lint it: eval_shape
    the carry (allocates nothing), lower the tick to a jaxpr, and scan
    for the invariant lints. ``bucketed`` traces the padded-ladder
    variant (runtime live counts — the traced-count contract's teeth)."""
    import jax

    from testground_tpu.api import RunGroup

    from .executor import (
        _parse_hosts,
        _precheck_device_memory,
        load_and_specialize,
        make_sim_program,
    )

    plan_file = ctx.plan_sources or ctx.comp.global_.plan
    label = f"{ctx.comp.global_.plan}:{ctx.comp.global_.case}"
    bucket_plan = resolved["bucket_plan"] if bucketed else None
    counts = [
        (
            p
            if bucket_plan is not None
            else rg.calculated_instance_count
        )
        for rg, p in zip(
            run.groups,
            (
                bucket_plan.padded_counts
                if bucket_plan is not None
                else [0] * len(run.groups)
            ),
        )
    ]
    run_groups_in = [
        RunGroup(
            id=rg.id,
            instances=c,
            parameters=dict(rg.test_params),
        )
        for rg, c in zip(run.groups, counts)
    ]
    shape_note = (
        f"padded shapes {tuple(counts)}" if bucketed else "exact shapes"
    )
    try:
        testcase, groups = load_and_specialize(
            ctx.plan_sources,
            ctx.comp.global_.case,
            run_groups_in,
            ctx.cfg.tick_ms,
        )
    except Exception as e:  # noqa: BLE001 — import/specialize failures
        _add(
            findings,
            "plan.load-failed",
            f"{label}: plan failed to load/specialize at {shape_note}: {e}",
            run=run.id,
            plan_file=plan_file,
        )
        return
    if (
        bucket_plan is not None
        and "filter_rules" in type(testcase).SHAPING
        and len(groups) > 1
    ):
        _add(
            findings,
            "buckets.filter-rules",
            "shape bucketing disabled — 'filter_rules' shaping with "
            "multiple groups addresses the exact layout (rule ranges "
            "cannot survive per-group padding); running exact shapes",
            run=run.id,
            plan_file=plan_file,
        )
        return

    hosts = _parse_hosts(getattr(ctx.cfg, "additional_hosts", None))
    try:
        prog = make_sim_program(
            testcase,
            groups,
            test_plan=ctx.comp.global_.plan,
            test_case=ctx.comp.global_.case,
            test_run="check",
            tick_ms=ctx.cfg.tick_ms,
            mesh=None,
            chunk=ctx.cfg.chunk,
            hosts=hosts,
            validate=bool(getattr(ctx.cfg, "validate", False)),
            telemetry=resolved["telemetry_on"],
            faults=resolved["fault_schedule"] if not bucketed else None,
            trace=resolved["trace_plan"] if not bucketed else None,
            transport=(
                str(getattr(ctx.cfg, "transport", "xla") or "xla").lower()
                if str(getattr(ctx.cfg, "transport", "xla")).lower()
                in ("xla", "pallas")
                else "xla"
            ),
            live_counts=(
                bucket_plan.live_counts if bucket_plan is not None else None
            ),
            netmatrix=resolved["netmatrix_on"],
        )
    except Exception as e:  # noqa: BLE001 — build-time refusals
        _add(
            findings,
            _classify_trace_error(e),
            f"{label}: program build failed at {shape_note}: {e}",
            run=run.id,
            plan_file=plan_file,
        )
        return

    # the executor's capacity precheck, verbatim (same function)
    try:
        _precheck_device_memory(prog, ctx.cfg, None, _WarnCollector())
    except RuntimeError as e:
        _add(
            findings,
            "plan.memory",
            f"{label}: {e}",
            run=run.id,
            plan_file=plan_file,
        )

    if bucket_plan is not None:
        import numpy as np

        lc = np.asarray(bucket_plan.live_counts, np.int32)

        def _init():
            return prog.init_carry(int(ctx.cfg.seed), lc)

    else:

        def _init():
            return prog.init_carry(int(ctx.cfg.seed))

    try:
        carry = jax.eval_shape(_init)
    except Exception as e:  # noqa: BLE001 — abstract init failures
        _add(
            findings,
            _classify_trace_error(e),
            f"{label}: init failed under eval_shape at {shape_note} "
            f"({type(e).__name__}): {e}",
            run=run.id,
            plan_file=plan_file,
        )
        return

    # weak-type lint: a weakly-typed state leaf re-promotes against the
    # first strongly-typed operand it meets — two plans differing only
    # in a python literal then trace different programs (a recompile
    # hazard the persistent cache cannot dedup)
    weak = []
    try:
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            carry.states
        )[0]:
            if getattr(leaf, "weak_type", False):
                weak.append(jax.tree_util.keystr(path))
    except Exception:  # noqa: BLE001 — lint is best-effort
        pass
    if weak:
        shown = ", ".join(weak[:4]) + ("…" if len(weak) > 4 else "")
        _add(
            findings,
            "plan.weak-type",
            f"{label}: {len(weak)} weak-typed state leaf/leaves "
            f"({shown}) — give literals an explicit dtype "
            "(jnp.float32(0.0), jnp.zeros((), jnp.int32)) so retraces "
            "and the compile cache see one stable program",
            run=run.id,
            plan_file=plan_file,
        )

    try:
        jaxpr = jax.make_jaxpr(prog._chunk_step)(carry)
    except Exception as e:  # noqa: BLE001 — tick trace failures
        _add(
            findings,
            _classify_trace_error(e),
            f"{label}: tick failed to trace at {shape_note} "
            f"({type(e).__name__}): {e}",
            run=run.id,
            plan_file=plan_file,
        )
        return

    callbacks = set()
    whiles = 0
    for eqn in _iter_eqns(jaxpr.jaxpr):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            callbacks.add(name)
        elif name == "while":
            whiles += 1
    if callbacks:
        _add(
            findings,
            "plan.host-callback",
            f"{label}: host callback(s) {sorted(callbacks)} inside the "
            "jitted tick — each dispatch synchronizes device→host; "
            "debug prints and python callbacks do not belong in the hot "
            "path (gate them out of production steps)",
            run=run.id,
            plan_file=plan_file,
        )
    if whiles:
        _add(
            findings,
            "plan.while-loop",
            f"{label}: {whiles} while loop(s) inside the jitted tick — "
            "per-tick work must be bounded (the chunk scan is the only "
            "sanctioned loop); unroll with lax.fori_loop over a static "
            "bound or restructure as multi-tick state",
            run=run.id,
            plan_file=plan_file,
        )


def _check_plans(ctx, run, resolved, findings) -> None:
    """Layer 2+3 for one run: trace the program variant the run would
    actually compile — the padded-ladder shapes with runtime live
    counts when the bucket gate resolved a plan (ONLY that variant
    gives the traced-count contract teeth: exact-shape programs see
    static python counts), the exact shapes otherwise."""
    _trace_one_program(
        ctx,
        run,
        resolved,
        findings,
        bucketed=resolved["bucket_plan"] is not None,
    )


# ------------------------------------------------------------ entry point


def check_composition(
    comp,
    manifest,
    *,
    env_layer: dict | None = None,
    devices: int = 0,
    trace_plans: bool = False,
    plan_sources: str = "",
) -> list[Finding]:
    """Evaluate every catalogued rule against one composition.

    ``comp`` is an ``api.Composition`` (pre-preparation — this function
    prepares its own clone, like ``do_run``); ``manifest`` its plan
    manifest; ``env_layer`` the daemon's ``[runners."sim:jax"]`` config
    layer (coalesced under the composition's run_config, the executor's
    precedence); ``devices`` the device-context override (0 = detect
    via jax when available, else 1); ``trace_plans`` enables the
    abstract-tracing + jaxpr-lint layers against ``plan_sources``.

    Returns ALL findings, error and warn, in evaluation order — the
    caller decides presentation and exit codes."""
    from testground_tpu.api import prepare_for_run, validate_for_run
    from testground_tpu.config import CoalescedConfig

    from .executor import SimJaxConfig

    findings: list[Finding] = []
    try:
        validate_for_run(comp)
        prepared = prepare_for_run(comp, manifest)
    except Exception as e:  # noqa: BLE001 — structural refusals
        _add(findings, "composition.invalid", str(e))
        return findings

    if (prepared.global_.runner or "") != "sim:jax":
        # the rules catalog guards the sim:jax admission surface; other
        # runners only get the structural validation above
        return findings

    raw_cfg = dict(prepared.global_.run_config or {})
    cfg = (
        CoalescedConfig()
        .append(env_layer)
        .append(raw_cfg)
        .coalesce_into(SimJaxConfig)
    )
    if devices <= 0:
        try:
            import jax

            devices = len(jax.devices())
        except Exception:  # noqa: BLE001 — jax-free hosts check at n=1
            devices = 1
    ctx = CheckContext(
        comp=prepared,
        cfg=cfg,
        devices=devices,
        trace_plans=trace_plans,
        plan_sources=plan_sources,
        raw_run_config=raw_cfg,
    )
    ctx.raw_env_layer = dict(env_layer or {})

    _check_run_cfg_keys(ctx, findings)
    _check_mesh(ctx, findings)
    _check_transport(ctx, findings)
    _check_pack(ctx, findings)
    _check_resume_multi_runs(ctx, findings)
    for run in prepared.runs:
        resolved = _check_run(ctx, run, findings)
        if trace_plans and plan_sources:
            _check_plans(ctx, run, resolved, findings)
    return findings


# ------------------------------------------------------------- rendering


def render_findings(path: str, findings: list[Finding]) -> str:
    """Human-readable report for one composition file — one line per
    finding, errors first (stable within severity)."""
    errors = [f for f in findings if f.severity == "error"]
    warns = [f for f in findings if f.severity != "error"]
    if not findings:
        return f"{path}: ok (no findings)"
    head = (
        f"{path}: {len(errors)} error(s), {len(warns)} warning(s)"
    )
    lines = [head]
    for f in errors + warns:
        where = f" (run {f.run})" if f.run else ""
        lines.append(f"  [{f.severity:5}] {f.rule}{where}: {f.message}")
    return "\n".join(lines)


def findings_payload(results: list[tuple[str, list[Finding]]]) -> dict:
    """The ``tg check --json`` document — schema pinned by
    tests/test_check.py (version bumps on shape changes)."""
    comps = [
        {
            "file": path,
            "findings": [f.to_dict() for f in fs],
            "errors": sum(1 for f in fs if f.severity == "error"),
            "warnings": sum(1 for f in fs if f.severity != "error"),
        }
        for path, fs in results
    ]
    return {
        "version": 1,
        "compositions": comps,
        "errors": sum(c["errors"] for c in comps),
        "warnings": sum(c["warnings"] for c in comps),
    }
