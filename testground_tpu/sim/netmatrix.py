"""The network-topology plane's host half: traffic-matrix schema,
conservation checks, top-K pair selection, and the ``tg netmap`` cut
advisor (docs/OBSERVABILITY.md "Traffic matrix").

The device half lives in the engine: a ``[NM_CHANNELS, GH, GH]`` int32
src-group × dst-group counter matrix rides the jitted tick's carry
(``SimCarry.net_mat``; GH = declared groups + one hosts row when
additional hosts are attached) and is flushed once per chunk beside the
telemetry block — zero extra host syncs, jaxpr pinned identical with the
plane off. This module is import-light on purpose (stdlib + numpy): the
CLI renders heatmaps and runs the cut advisor against a daemon without
touching jax.

Channel semantics mirror the flow-conservation identity the telemetry
plane already pins, now CELL-WISE: per (src group, dst group) pair,
``sent = enqueued + dropped + rejected + fault_dropped`` at send time,
and cumulatively ``sent = delivered + in-flight + dropped + rejected +
fault_dropped``. Attribution rules (each kept exact so the sums close):

- send-side channels charge the (sender group, PHYSICAL destination
  group) cell; a message to an out-of-range destination is charged to
  the clipped lane's group (the same sent-then-dropped accounting the
  scalar counters apply);
- ``delivered`` charges the (calendar provenance, receiver lane) cell —
  host echo deliveries land in the hosts row/column, so the matrix total
  equals the engine's ``msgs_delivered`` exactly;
- crash purges charge ``fault_dropped`` at the (sender, crashed
  receiver) cell (``net.purge_dst_matrix`` recovers the sender from the
  occupancy plane's src+1 encoding).
"""

from __future__ import annotations

import itertools
import json
import math

import numpy as np

__all__ = [
    "NM_CHANNELS",
    "NM_CHANNEL_NAMES",
    "NM_DELIVERED",
    "NM_DROPPED",
    "NM_ENQUEUED",
    "NM_FAULT",
    "NM_MSG_BYTES",
    "NM_REJECTED",
    "NM_SENT",
    "cut_advisor",
    "delta_cells",
    "delta_row",
    "faulted_pairs",
    "matrix_bytes",
    "matrix_from_rows",
    "matrix_totals",
    "reconcile",
    "top_pairs",
]

# Channel order of the device matrix's leading axis — fixed schema, the
# jsonl cell rows and every host surface use the same order.
NM_SENT, NM_ENQUEUED, NM_DELIVERED, NM_DROPPED, NM_REJECTED, NM_FAULT = (
    range(6)
)
NM_CHANNELS = 6
NM_CHANNEL_NAMES = (
    "sent",
    "enqueued",
    "delivered",
    "dropped",
    "rejected",
    "fault_dropped",
)

# Wire size per message for the bytes view — MUST equal net.MSG_BYTES
# (pinned by tests; duplicated here so this module stays jax-free).
NM_MSG_BYTES = 256

# matrix channel → the engine's cumulative flow-total key it must sum
# back to, exactly (journal ``sim.telemetry.totals`` / results keys)
_FLOW_KEYS = (
    ("sent", "msgs_sent"),
    ("enqueued", "msgs_enqueued"),
    ("delivered", "msgs_delivered"),
    ("dropped", "msgs_dropped"),
    ("rejected", "msgs_rejected"),
    ("fault_dropped", "fault_dropped"),
)


# --------------------------------------------------------------- rows

def delta_cells(delta) -> list[list[int]]:
    """Sparse nonzero cells of one chunk's ``[NM_CHANNELS, GH, GH]``
    delta: ``[src, dst, sent, enqueued, delivered, dropped, rejected,
    fault_dropped]`` per touched pair, row-major. The sparse form keeps
    quiet topologies' jsonl rows tiny regardless of G²."""
    d = np.asarray(delta, np.int64)
    touched = np.argwhere(d.any(axis=0))
    return [
        [int(s), int(t)] + [int(d[c, s, t]) for c in range(NM_CHANNELS)]
        for s, t in touched
    ]


def delta_row(delta, tick: int, chunk: int, ident=None) -> dict:
    """One ``sim_netmatrix.jsonl`` row for a chunk's matrix delta:
    ``tick`` is the tick count at the END of the chunk, ``cells`` the
    sparse nonzero pairs (see :func:`delta_cells`)."""
    row = dict(ident or {})
    row.update(tick=int(tick), chunk=int(chunk), cells=delta_cells(delta))
    return row


def matrix_from_rows(rows, gh: int) -> np.ndarray:
    """Sum decoded jsonl rows (dicts with ``cells``) back into the dense
    ``[NM_CHANNELS, gh, gh]`` int64 cumulative matrix."""
    mat = np.zeros((NM_CHANNELS, gh, gh), np.int64)
    for row in rows:
        for cell in row.get("cells") or ():
            s, t = int(cell[0]), int(cell[1])
            for c in range(NM_CHANNELS):
                mat[c, s, t] += int(cell[2 + c])
    return mat


def iter_rows(path: str):
    """Best-effort jsonl reader (the writer's crash-truncated final line
    is skipped, matching the telemetry decoder's contract)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue


# ------------------------------------------------------------- totals

def matrix_totals(mat) -> dict[str, int]:
    """Σ over all cells per channel — the numbers that must equal the
    engine's cumulative flow totals exactly."""
    m = np.asarray(mat, np.int64)
    return {
        name: int(m[c].sum()) for c, name in enumerate(NM_CHANNEL_NAMES)
    }


def matrix_bytes(mat) -> np.ndarray:
    """[GH, GH] int64 bytes view: enqueued messages × the fixed wire
    size (the same bytes accounting as the telemetry ``bytes`` column)."""
    m = np.asarray(mat, np.int64)
    return m[NM_ENQUEUED] * NM_MSG_BYTES


def reconcile(mat, flow_totals: dict) -> list[str]:
    """Exact conservation check: per channel, Σ matrix cells vs the
    engine's cumulative flow total. Returns human-readable mismatch
    strings — empty means the matrix reconciles exactly."""
    totals = matrix_totals(mat)
    out = []
    for channel, key in _FLOW_KEYS:
        if key not in flow_totals:
            continue
        want = int(flow_totals[key])
        got = totals[channel]
        if got != want:
            out.append(
                f"{channel}: matrix sums to {got}, flow total "
                f"{key}={want} (Δ {got - want:+d})"
            )
    return out


def top_pairs(mat, k: int) -> tuple[list[dict], int]:
    """The top-``k`` (src, dst) pairs by sent volume plus the count of
    ELIDED nonzero pairs — the bounded-cardinality contract behind the
    ``tg_net_pair_*`` Prometheus gauges (≤ k series per channel plus one
    elision gauge, never raw G²)."""
    m = np.asarray(mat, np.int64)
    gh = m.shape[1]
    sent = m[NM_SENT]
    nz = np.argwhere(m.any(axis=0))
    order = sorted(
        (tuple(p) for p in nz),
        key=lambda p: (-int(sent[p[0], p[1]]), p[0], p[1]),
    )
    pairs = [
        {
            "src": int(s),
            "dst": int(t),
            **{
                name: int(m[c, s, t])
                for c, name in enumerate(NM_CHANNEL_NAMES)
            },
        }
        for s, t in order[: max(0, int(k))]
    ]
    del gh
    return pairs, max(0, len(order) - len(pairs))


# ------------------------------------------------------- fault windows

def faulted_pairs(schedule, groups) -> np.ndarray:
    """[G, G] int64 count of declared fault WINDOWS covering each group
    pair — the static link-shaping observable (which pairs a chaos
    schedule degrades), computed host-side from the lowered schedule's
    event masks: a partition/flap drop window charges its (src-mask
    group, dst-mask group) pairs (both directions when symmetric); a
    loss-burst window charges its source groups' whole rows."""
    g_n = len(groups)
    out = np.zeros((g_n, g_n), np.int64)
    if schedule is None:
        return out

    def gmask(mask_np) -> np.ndarray:
        m = np.asarray(mask_np, bool)
        return np.array(
            [
                bool(m[g.offset : g.offset + g.count].any())
                if g.offset < m.shape[0]
                else False
                for g in groups
            ]
        )

    if getattr(schedule, "has_drops", False):
        for e in range(schedule.drop_t0.size):
            a = gmask(schedule.drop_a[e])
            b = gmask(schedule.drop_b[e])
            out += np.outer(a, b).astype(np.int64)
            if schedule.drop_sym[e]:
                out += np.outer(b, a).astype(np.int64)
    if getattr(schedule, "has_loss", False):
        ones = np.ones((g_n,), bool)
        for e in range(schedule.loss_t0.size):
            a = gmask(schedule.loss_masks[e])
            out += np.outer(a, ones).astype(np.int64)
    return out


# --------------------------------------------------------- cut advisor

def _cut_of(assign, sym) -> float:
    """Cross-cut traffic of a group→shard assignment under the
    symmetrized matrix (each unordered pair counted once)."""
    a = np.asarray(assign)
    cross = a[:, None] != a[None, :]
    return float(sym[cross].sum()) / 2.0


def _canon(assign) -> list[int]:
    """Renumber shards in first-appearance order so equivalent
    assignments print identically."""
    remap: dict[int, int] = {}
    out = []
    for s in assign:
        if s not in remap:
            remap[s] = len(remap)
        out.append(remap[s])
    return out


def cut_advisor(
    traffic,
    shards: int,
    labels=None,
    exhaustive_limit: int = 20_000,
) -> dict:
    """Score group→shard assignments by cross-cut traffic from the
    measured matrix — the partition advisor behind ``tg netmap --cut N``
    (ROADMAP item 1's instance-axis → mesh-axis mapping, measured).

    ``traffic`` is any [G, G] volume matrix (use :func:`matrix_bytes`
    for the bytes view); direction is ignored (a cut severs both). The
    search minimizes cut volume subject to balance (no shard over
    ⌈G/N⌉ groups — an unconstrained minimum is the trivial everything-
    on-one-shard answer) and uses every shard when G ≥ N. Exhaustive
    enumeration when the assignment space is ≤ ``exhaustive_limit``
    (exact optimum, small G), else greedy agglomerative merging: every
    group starts alone and the pair of clusters with the heaviest
    inter-traffic merges first — heavy talkers co-locate, which is the
    clustered-composition structure the advisor exists to recover.

    Returns ``assignment`` (canonical [G] shard ids), ``shards`` (label
    lists per shard), ``cut``, ``total`` (cross-group volume), and
    ``cut_fraction = cut / total`` (0 when there is no cross-group
    traffic at all)."""
    w = np.asarray(traffic, np.float64)
    if w.ndim != 2 or w.shape[0] != w.shape[1]:
        raise ValueError(f"traffic matrix must be square, got {w.shape}")
    g_n = w.shape[0]
    shards = int(shards)
    if shards < 1:
        raise ValueError(f"--cut needs at least 1 shard, got {shards}")
    if labels is None:
        labels = [str(i) for i in range(g_n)]
    if len(labels) != g_n:
        raise ValueError(
            f"{len(labels)} labels for a {g_n}-group matrix"
        )
    sym = w + w.T
    np.fill_diagonal(sym, 0.0)  # intra-group volume never crosses a cut
    total = float(sym.sum()) / 2.0
    shards_eff = min(shards, g_n)
    cap = math.ceil(g_n / shards_eff)

    best: list[int] | None = None
    best_cut = math.inf
    if shards_eff**g_n <= exhaustive_limit:
        method = "exhaustive"
        for assign in itertools.product(range(shards_eff), repeat=g_n):
            sizes = np.bincount(assign, minlength=shards_eff)
            if sizes.max(initial=0) > cap or (sizes == 0).any():
                continue
            cut = _cut_of(assign, sym)
            if cut < best_cut - 1e-9:
                best_cut = cut
                best = list(assign)
    else:
        method = "greedy"
        clusters: list[list[int]] = [[i] for i in range(g_n)]
        inter = sym.copy()
        while len(clusters) > shards_eff:
            # heaviest mergeable pair first; if balance blocks every
            # pair, merge the lightest-traffic smallest pair so the
            # loop always terminates (the cap is advisory there)
            pick = None
            pick_w = -1.0
            fallback = None
            fallback_key = (math.inf, math.inf)
            for i in range(len(clusters)):
                for j in range(i + 1, len(clusters)):
                    wij = float(inter[i, j])
                    size = len(clusters[i]) + len(clusters[j])
                    if size <= cap and wij > pick_w:
                        pick, pick_w = (i, j), wij
                    key = (size, wij)
                    if key < fallback_key:
                        fallback, fallback_key = (i, j), key
            i, j = pick if pick is not None else fallback
            clusters[i] = clusters[i] + clusters[j]
            del clusters[j]
            inter[i, :] += inter[j, :]
            inter[:, i] += inter[:, j]
            inter = np.delete(np.delete(inter, j, axis=0), j, axis=1)
            inter[i, i] = 0.0
        assign_arr = [0] * g_n
        for s, members in enumerate(clusters):
            for gi in members:
                assign_arr[gi] = s
        best = assign_arr
        best_cut = _cut_of(best, sym)

    assert best is not None
    assignment = _canon(best)
    n_used = max(assignment) + 1
    return {
        "assignment": assignment,
        "shards": [
            [labels[i] for i in range(g_n) if assignment[i] == s]
            for s in range(n_used)
        ],
        "cut": best_cut,
        "total": total,
        "cut_fraction": (best_cut / total) if total > 0 else 0.0,
        "method": method,
    }
