"""Shape bucketing: pad the instance axis to a canonical size ladder so
any instance count hits a precompiled program (ROADMAP item 2, PERF.md
"Serving: buckets + packing").

The compile cost of the jitted chunk program is ~45 s cold and nearly
scale-invariant (PERF.md "Compile cost"), yet the traced HLO bakes in
every array SHAPE — so a daemon serving arbitrary tenant compositions
pays the full compile again for every new ``-i``. This module makes the
persistent compile cache "warm-for-anyone":

- every group's instance count is padded UP to a small canonical ladder
  (default 4k/32k/128k/1M, configurable via ``bucket_ladder``), so the
  physical program shapes take only a handful of values;
- the *exact* live counts become RUNTIME inputs riding the carry
  (``SimCarry.live_counts``) instead of trace-time constants: the
  engine serves plans a virtualized :class:`~testground_tpu.sim.api.SimEnv`
  (traced ``test_instance_count`` / ``group.count`` / ``global_seq``),
  translates plan-emitted virtual destinations to physical lanes, and
  derives per-lane PRNG keys that bit-match an unpadded run — so two
  compositions in the same bucket compile (and cache) ONE program;
- padded lanes are dead from tick 0 — status CRASH, frozen by the
  engine's terminal-instance masking (the same live-lane machinery the
  faults plane uses, docs/FAULTS.md) — and contribute nothing to flow
  totals, telemetry, results, or sync state. Results are demuxed back
  to exact-N arrays, pinned bit-equal to an unpadded run by
  ``tests/test_sim_buckets.py``.

Import-light on purpose (numpy + stdlib): the engine-side pack
admission (``engine/pack.py``) computes bucket keys for queued tasks
without loading jax.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "DEFAULT_LADDER",
    "BucketPlan",
    "parse_bucket_mode",
    "parse_ladder",
    "resolve_rung",
    "bucketed_counts",
    "plan_buckets",
    "remap_lane_masks",
]

# The canonical instance-count ladder (per group). Small compositions
# all land on the first rung; the top rung matches the 1M envelope
# PERF.md benches. Configurable per run (``bucket_ladder = "a,b,c"``)
# so tests can use tiny rungs.
DEFAULT_LADDER = (4096, 32768, 131072, 1048576)


def parse_ladder(raw) -> tuple[int, ...]:
    """``"4096,32768"`` (or a TOML list) → ascending unique int tuple."""
    if raw is None or raw == "":
        return DEFAULT_LADDER
    if isinstance(raw, str):
        parts = [p for p in (s.strip() for s in raw.split(",")) if p]
    elif isinstance(raw, int):
        # `--run-cfg bucket_ladder=32` (a single rung) coalesces as a
        # bare int, not a "32" string
        parts = [raw]
    else:
        parts = list(raw)
    try:
        rungs = sorted({int(p) for p in parts})
    except (TypeError, ValueError):
        raise ValueError(
            f"bucket_ladder {raw!r} is not a comma-separated list of "
            "instance counts"
        ) from None
    if not rungs or rungs[0] <= 0:
        raise ValueError(
            f"bucket_ladder {raw!r} must hold positive instance counts"
        )
    return tuple(rungs)


def parse_bucket_mode(raw) -> str | int:
    """The ``bucket`` runner-config knob: ``off`` (default), ``auto``
    (pad every group to the ladder), or an explicit ``<n>`` (pad every
    group to exactly n)."""
    if raw is None or raw == "" or raw is False:
        return "off"
    s = str(raw).strip().lower()
    if s in ("off", "false", "0", "none"):
        return "off"
    if s in ("auto", "true", "on"):
        return "auto"
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"unknown bucket mode {raw!r}: expected 'auto', 'off', or an "
            "explicit instance count (--run-cfg bucket=auto)"
        ) from None
    if n <= 0:
        raise ValueError(f"bucket={n} must be a positive instance count")
    return n


def resolve_rung(n: int, ladder: tuple[int, ...]) -> int | None:
    """Smallest ladder rung ≥ n, or None when n is above the top rung
    (the caller then runs unbucketed, loudly)."""
    for rung in ladder:
        if n <= rung:
            return rung
    return None


def bucketed_counts(
    counts, mode, ladder: tuple[int, ...]
) -> tuple[int, ...] | None:
    """Per-group padded counts for a composition, or None when bucketing
    does not apply (mode off, or a group exceeds the coverage). Pure
    count math — shared by the executor gate and the engine-side pack
    admission key."""
    if mode == "off":
        return None
    padded = []
    for c in counts:
        c = int(c)
        if isinstance(mode, int):
            if c > mode:
                return None
            padded.append(mode)
            continue
        rung = resolve_rung(c, ladder)
        if rung is None:
            return None
        padded.append(rung)
    return tuple(padded)


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """A resolved padding layout: physical (padded) per-group counts
    beside the exact live counts, plus the static virtual↔physical
    index maps the lowering helpers need."""

    live_counts: tuple[int, ...]  # exact per-group counts (virtual)
    padded_counts: tuple[int, ...]  # canonical per-group counts (physical)

    @property
    def live_n(self) -> int:
        return sum(self.live_counts)

    @property
    def padded_n(self) -> int:
        return sum(self.padded_counts)

    @property
    def virt_offsets(self) -> tuple[int, ...]:
        out, off = [], 0
        for c in self.live_counts:
            out.append(off)
            off += c
        return tuple(out)

    @property
    def phys_offsets(self) -> tuple[int, ...]:
        out, off = [], 0
        for c in self.padded_counts:
            out.append(off)
            off += c
        return tuple(out)

    def index_map(self) -> np.ndarray:
        """``[live_n] int32`` — virtual lane id → physical lane id (each
        group's live lanes are the first ``live`` of its padded span)."""
        segs = [
            np.arange(live, dtype=np.int32) + poff
            for live, poff in zip(self.live_counts, self.phys_offsets)
        ]
        return (
            np.concatenate(segs)
            if segs
            else np.zeros((0,), np.int32)
        )

    def summary(self) -> str:
        return (
            f"{self.live_n} live instance(s) padded to {self.padded_n} "
            "(per-group "
            + ", ".join(
                f"{l}→{p}"
                for l, p in zip(self.live_counts, self.padded_counts)
            )
            + f"; {self.padded_n - self.live_n} dead lane(s))"
        )


def plan_buckets(counts, mode, ladder=None) -> BucketPlan | None:
    """Resolve a composition's group counts against the knob + ladder.
    Returns None when bucketing does not apply — the caller runs the
    exact-shape program, as before this plane existed."""
    ladder = parse_ladder(ladder) if not isinstance(ladder, tuple) else ladder
    padded = bucketed_counts(counts, mode, ladder)
    if padded is None:
        return None
    return BucketPlan(
        live_counts=tuple(int(c) for c in counts), padded_counts=padded
    )


def remap_lane_masks(masks: np.ndarray, index_map: np.ndarray, n_phys: int):
    """Scatter ``[E, live_n]`` virtual-lane masks onto the padded
    physical axis (pad lanes never selected) — the fault-schedule
    remap: chaos selectors are declared over the composition's EXACT
    layout and must keep targeting the same instances after padding."""
    masks = np.asarray(masks, bool)
    out = np.zeros((masks.shape[0], n_phys), bool)
    if masks.size:
        out[:, index_map] = masks
    return out
