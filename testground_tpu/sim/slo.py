"""Run health plane: in-flight SLO assertions evaluated per chunk.

The observability stack's first six tiers (docs/OBSERVABILITY.md) are
post-hoc: counters, histograms, traces and ledgers become visible when a
run *finishes* — useless for failing-fast a week-long soak whose p99
went sideways in hour one. This module is the missing tier: a
composition declares service-level objectives in ``[[global.run.slo]]``
/ ``[[groups.run.slo]]`` tables (metric + comparator + threshold +
evaluation window + severity), they lower into a static
:class:`SloPlan`, and a host-side :class:`SloEvaluator` checks every
rule once per chunk dispatch against the telemetry blocks and
latency-histogram deltas the run loop **already flushes** — the
Prometheus recording/alerting-rules idiom layered over the sim's own
metric stream.

Contract (the same one every other plane carries):

- **The jitted program is untouched.** SLOs are pure host-side
  bookkeeping over already-materialized chunk results: the compiled
  program is jaxpr-identical with and without them and the host-sync
  count is unchanged (both pinned by ``tests/test_sim_slo.py``).
- **Telemetry required, loudly.** Every metric derives from the
  per-tick counter block / latency histograms, so a composition
  declaring SLOs without ``telemetry = true`` (or under
  ``disable_metrics``) is refused at run start with a readable error —
  never silently unenforced. Cohorts run SLO-free with a warning (their
  telemetry plane is off by construction).
- **Breaches are records, not just log lines.** Every breaching
  evaluation streams to ``sim_slo.jsonl`` as it happens, aggregates
  into journal ``slo`` (→ ``results()``, ``tg stats``, Prometheus
  ``tg_slo_*``), and — at ``severity = "fail"`` — cancels the run
  through the chunk loop's cancel path with a typed
  :class:`SloBreachError` that carries the fully-assembled run result,
  so a failed-fast soak keeps its telemetry record.

Import-light on purpose (numpy + the telemetry schema only, no jax):
the daemon, supervisor and CLI import this module.
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque

import numpy as np

from .telemetry import latency_percentiles

__all__ = [
    "SLO_FILE",
    "SLO_METRICS",
    "SLO_OPS",
    "SloBreachError",
    "SloEvaluator",
    "SloPlan",
    "SloRule",
    "build_slo_plan",
    "parse_slo",
]

# Per-run breach-record stream (under <outputs>/<plan>/<run_id>/): one
# JSON line per breaching evaluation, appended the chunk it fires —
# survives a canceled/failed run, feeds GET /stream and `tg watch`.
SLO_FILE = "sim_slo.jsonl"

# Metrics a rule may assert, and where each is computed from:
#
#   latency_p50_ticks / latency_p95_ticks / latency_p99_ticks
#       delivery-latency percentile in TICKS, estimated from the
#       per-receiver-group log2 histograms (telemetry plane) summed over
#       the evaluation window; a ``group`` key scopes it to one
#       receiver group, else all groups aggregate. Skipped (no breach
#       possible) while the window holds zero deliveries.
#   delivered_per_tick
#       mean messages delivered per simulated tick over the window.
#   drop_rate
#       (dropped + fault_dropped) / sent over the window; skipped while
#       the window holds zero sends.
#   crashed_fraction
#       currently-crashed fraction of the fleet: cumulative
#       (faults_crashed - faults_restarted) / instances — a STATE
#       metric, so the window does not apply (the current value is
#       asserted each evaluation).
#
# delivered_per_tick / drop_rate / crashed_fraction are run-global (the
# counter block is run-global); only the latency metrics accept a
# ``group`` scope.
SLO_METRICS = (
    "latency_p50_ticks",
    "latency_p95_ticks",
    "latency_p99_ticks",
    "delivered_per_tick",
    "drop_rate",
    "crashed_fraction",
)
_LATENCY_METRICS = {
    "latency_p50_ticks": 0.50,
    "latency_p95_ticks": 0.95,
    "latency_p99_ticks": 0.99,
}

# Comparators state what must HOLD; a breach is the assertion failing.
SLO_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

_SEVERITIES = ("warn", "fail")

# Keys a [[run.slo]] table may carry — an unknown key is a typo'd rule,
# and a silently-ignored key is an SLO that never fires (the fault/trace
# plane's loud-refusal policy).
_KNOWN_KEYS = {
    "name",
    "metric",
    "op",
    "threshold",
    "window_ticks",
    "severity",
    "group",
}

# Bounded per-rule breach records kept in the journal (the jsonl stream
# keeps everything): a soak breaching every chunk for a week must not
# grow the task record unboundedly.
JOURNAL_RECORDS_CAP = 20


class SloBreachError(RuntimeError):
    """A ``severity = "fail"`` SLO breached: the run was canceled at the
    chunk boundary. ``breach`` is the structured record; ``run_output``
    (attached by the executor) carries the fully-assembled RunOutput —
    journal included — so the supervisor can archive the failed run's
    complete telemetry record instead of a bare error string."""

    def __init__(self, breach: dict):
        self.breach = dict(breach)
        self.run_output = None  # attached by the executor before raising
        super().__init__(
            "SLO breach ({severity}): {rule} — {metric} = {observed:g} "
            "violates {op} {threshold:g} over window ticks "
            "[{lo}, {hi}]".format(
                severity=breach.get("severity", "fail"),
                rule=breach.get("rule", "?"),
                metric=breach.get("metric", "?"),
                observed=float(breach.get("observed", float("nan"))),
                op=breach.get("op", "?"),
                threshold=float(breach.get("threshold", float("nan"))),
                lo=breach.get("window", [0, 0])[0],
                hi=breach.get("window", [0, 0])[1],
            )
        )


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One validated SLO assertion (still declaration-shaped; the
    evaluator resolves groups/windows against the run layout)."""

    name: str
    metric: str
    op: str
    threshold: float
    window_ticks: int = 0  # 0 = whole run so far
    severity: str = "warn"
    group: str = ""  # latency metrics only; "" = all receiver groups

    def describe(self) -> str:
        win = (
            f"last {self.window_ticks} tick(s)"
            if self.window_ticks
            else "whole run"
        )
        return (
            f"{self.name}: {self.metric} {self.op} {self.threshold:g} "
            f"over {win} [{self.severity}]"
        )


@dataclasses.dataclass(frozen=True)
class SloPlan:
    """The lowered SLO declaration: a static rule tuple. ``None`` (from
    :func:`build_slo_plan`) means nothing declared — the run then pays
    nothing, not even the evaluator object."""

    rules: tuple  # tuple[SloRule, ...]

    @property
    def count(self) -> int:
        return len(self.rules)

    def max_window_ticks(self) -> int:
        """Longest finite window any rule needs — bounds the evaluator's
        per-chunk ring buffer. 0 when every rule is whole-run (the
        evaluator then keeps cumulative sums only)."""
        return max((r.window_ticks for r in self.rules), default=0)

    def has_fail(self) -> bool:
        return any(r.severity == "fail" for r in self.rules)

    def summary(self) -> str:
        shown = "; ".join(r.describe() for r in self.rules[:4])
        if self.count > 4:
            shown += "; …"
        return f"{self.count} rule(s): {shown}"


def parse_slo(d: dict, default_group: str = "", index: int = 0) -> SloRule:
    """Validate one raw ``[[...run.slo]]`` table → :class:`SloRule`.

    ``default_group`` scopes a group-level declaration of a *latency*
    metric to its own receiver group when no explicit ``group`` key is
    given (run-global tables pass ``""``) — the ``faults.parse_fault``
    scoping rule. Run-global metrics (delivered_per_tick / drop_rate /
    crashed_fraction) refuse BOTH an explicit ``group`` key and a
    group-level (``[[groups.run.slo]]``) placement: the counter block
    they derive from is run-global, and a silently ignored scope —
    written or implied — would assert something other than what the
    operator declared."""
    if not isinstance(d, dict):
        raise ValueError(
            f"slo entry must be a table, got {type(d).__name__}"
        )
    unknown = set(d) - _KNOWN_KEYS
    if unknown:
        raise ValueError(
            f"slo entry has unknown key(s) {sorted(unknown)}; known "
            f"keys: {sorted(_KNOWN_KEYS)}"
        )
    metric = str(d.get("metric", ""))
    if metric not in SLO_METRICS:
        raise ValueError(
            f"unknown slo metric {metric!r}; metrics: {list(SLO_METRICS)}"
        )
    op = str(d.get("op", ""))
    if op not in SLO_OPS:
        raise ValueError(
            f"unknown slo op {op!r}; ops: {sorted(SLO_OPS)}"
        )
    if "threshold" not in d or isinstance(d["threshold"], bool):
        raise ValueError(f"slo {metric}: a numeric threshold is required")
    try:
        threshold = float(d["threshold"])
    except (TypeError, ValueError):
        raise ValueError(
            f"slo {metric}: threshold {d['threshold']!r} is not a number"
        ) from None
    if not np.isfinite(threshold):
        raise ValueError(f"slo {metric}: threshold must be finite")
    wt_raw = d.get("window_ticks", 0)
    if isinstance(wt_raw, bool) or (
        isinstance(wt_raw, float) and not wt_raw.is_integer()
    ):
        raise ValueError(
            f"slo {metric}: window_ticks {wt_raw!r} must be a whole "
            "number of ticks"
        )
    try:
        window_ticks = int(wt_raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"slo {metric}: window_ticks {wt_raw!r} is not an integer"
        ) from None
    if window_ticks < 0:
        raise ValueError(
            f"slo {metric}: window_ticks {window_ticks} must be >= 0 "
            "(0 = whole run)"
        )
    severity = str(d.get("severity", "warn"))
    if severity not in _SEVERITIES:
        raise ValueError(
            f"slo {metric}: severity {severity!r} must be one of "
            f"{list(_SEVERITIES)}"
        )
    explicit_group = str(d.get("group", ""))
    if metric in _LATENCY_METRICS:
        group = explicit_group or default_group
    else:
        if explicit_group or default_group:
            raise ValueError(
                f"slo {metric}: the metric is computed from run-global "
                "counters and cannot be scoped to group "
                f"{(explicit_group or default_group)!r} — declare it "
                "under [[global.run.slo]] (only the latency_* metrics "
                "are per receiver group)"
            )
        group = ""
    name = str(d.get("name", "")) or (
        f"{metric}{'@' + group if group else ''}#{index}"
    )
    return SloRule(
        name=name,
        metric=metric,
        op=op,
        threshold=threshold,
        window_ticks=window_ticks,
        severity=severity,
        group=group,
    )


def build_slo_plan(groups, slo_by_group: dict) -> SloPlan | None:
    """Validate + lower every declared SLO table into one static plan.

    ``groups`` is the resolved ``GroupSpec`` layout; ``slo_by_group``
    maps group id → list of raw ``[[groups.run.slo]]`` tables (key
    ``""`` holds the run-global ``[[global.run.slo]]`` list) — the exact
    shape of ``fault_specs_of``. Returns ``None`` when nothing is
    declared. Duplicate rule names are refused (a breach record must
    name its rule unambiguously)."""
    known = {g.id for g in groups}
    rules: list[SloRule] = []
    idx = 0
    for gid in sorted(slo_by_group or {}):
        for table in slo_by_group[gid] or []:
            rule = parse_slo(table, default_group=gid, index=idx)
            idx += 1
            if rule.group and rule.group not in known:
                raise ValueError(
                    f"slo {rule.name} targets unknown group "
                    f"{rule.group!r}; run groups are {sorted(known)}"
                )
            rules.append(rule)
    if not rules:
        return None
    names = [r.name for r in rules]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(
            f"duplicate slo rule name(s) {dupes}: give each rule a "
            "distinct 'name'"
        )
    return SloPlan(rules=tuple(rules))


class SloEvaluator:
    """Host-side per-chunk SLO evaluation over the run's own metric
    stream. Fed by the executor from state the run loop already holds:

    - :meth:`on_rows` — the chunk's decoded telemetry rows (the
      ``sim_timeseries.jsonl`` writer decodes them anyway);
    - :meth:`on_lat_delta` — the chunk's ``[G, LATENCY_BINS]``
      latency-histogram delta (flushed-and-zeroed each dispatch);
    - :meth:`evaluate` — once per chunk, after both: checks every rule,
      streams breach records to ``sim_slo.jsonl``, and on the first
      ``fail``-severity breach sets the run-cancel event so the chunk
      loop stops before the next dispatch.

    No device reads, no program shaping — pure python over numpy blocks
    that were already host-resident (the zero-overhead contract)."""

    def __init__(
        self,
        plan: SloPlan,
        groups,
        tick_ms: float,
        chunk: int,
        ident: dict | None = None,
        path: str | None = None,
        cancel=None,
        append: bool = False,
    ):
        self.plan = plan
        self.group_ids = tuple(g.id for g in groups)
        self.n_instances = int(sum(g.count for g in groups))
        self.tick_ms = float(tick_ms)
        self.chunk = max(int(chunk), 1)
        self.ident = dict(ident or {})
        self.path = path
        self._cancel = cancel
        self.fatal: dict | None = None
        self.records_written = 0
        # per-rule aggregation for the journal
        self._agg: dict[str, dict] = {
            r.name: {"breaches": 0, "worst": None, "last_observed": None}
            for r in plan.rules
        }
        self._records: list[dict] = []  # bounded (JOURNAL_RECORDS_CAP)
        # windowed state: ring of per-chunk summaries, sized by the
        # longest finite window (whole-run rules use cumulative sums)
        max_win = plan.max_window_ticks()
        self._ring_chunks = (
            -(-max_win // self.chunk) if max_win else 0
        )  # ceil
        self._ring: deque = deque()
        self._cum = {
            k: 0
            for k in (
                "ticks",
                "delivered",
                "sent",
                "dropped",
                "fault_dropped",
                "faults_crashed",
                "faults_restarted",
            )
        }
        self._cum_lat = None  # [G, LATENCY_BINS] int64 once fed
        self._pending_rows: list[dict] = []
        self._pending_lat = None
        self._last_tick = -1
        self._f = None
        if path is not None:
            try:
                # append mode: a resumed run (sim/checkpoint.py) continues
                # the record stream past the snapshot's truncated prefix
                self._f = open(path, "a" if append else "w")
            except OSError:  # observe best-effort, never fail the run
                self.path = None

    # ------------------------------------------------- checkpoint state
    # The evaluator's whole mutable state is JSON-able by construction
    # (python ints/floats + the int64 histogram arrays): it rides run
    # checkpoints so a resumed run judges windowed rules against the
    # same history an uninterrupted run would (docs/CHECKPOINT.md).

    def state_dict(self) -> dict:
        return {
            "agg": {k: dict(v) for k, v in self._agg.items()},
            "records": [dict(r) for r in self._records],
            "records_written": self.records_written,
            "cum": dict(self._cum),
            "cum_lat": (
                self._cum_lat.tolist() if self._cum_lat is not None else None
            ),
            "ring": [
                {
                    **{k: s[k] for k in s if k != "lat"},
                    "lat": (
                        s["lat"].tolist() if s["lat"] is not None else None
                    ),
                }
                for s in self._ring
            ],
            "last_tick": self._last_tick,
            "fatal": dict(self.fatal) if self.fatal is not None else None,
        }

    def load_state(self, state: dict) -> None:
        for name, agg in (state.get("agg") or {}).items():
            if name in self._agg:
                self._agg[name] = dict(agg)
        self._records = [dict(r) for r in state.get("records", [])]
        self.records_written = int(state.get("records_written", 0))
        for k in self._cum:
            self._cum[k] = int((state.get("cum") or {}).get(k, 0))
        cl = state.get("cum_lat")
        self._cum_lat = (
            np.asarray(cl, dtype=np.int64) if cl is not None else None
        )
        self._ring.clear()
        for s in state.get("ring") or []:
            lat = s.get("lat")
            self._ring.append(
                {
                    **{k: v for k, v in s.items() if k != "lat"},
                    "lat": (
                        np.asarray(lat, dtype=np.int64)
                        if lat is not None
                        else None
                    ),
                }
            )
        self._last_tick = int(state.get("last_tick", -1))
        fatal = state.get("fatal")
        self.fatal = dict(fatal) if fatal else None

    # ------------------------------------------------------------- feeding

    def on_rows(self, rows: list[dict]) -> None:
        """One chunk's decoded telemetry rows (padding already dropped)."""
        self._pending_rows.extend(rows)

    def on_lat_delta(self, delta) -> None:
        """One chunk's [G, LATENCY_BINS] histogram delta (host numpy)."""
        d = np.asarray(delta, dtype=np.int64)
        self._pending_lat = (
            d if self._pending_lat is None else self._pending_lat + d
        )

    # ---------------------------------------------------------- evaluation

    def _fold_chunk(self) -> dict:
        """Pending rows + lat delta → one chunk summary, folded into the
        cumulative sums and the window ring."""
        rows = self._pending_rows
        self._pending_rows = []
        lat = self._pending_lat
        self._pending_lat = None
        summ = {
            "ticks": len(rows),
            "delivered": sum(r.get("delivered", 0) for r in rows),
            "sent": sum(r.get("sent", 0) for r in rows),
            "dropped": sum(r.get("dropped", 0) for r in rows),
            "fault_dropped": sum(r.get("fault_dropped", 0) for r in rows),
            "faults_crashed": sum(r.get("faults_crashed", 0) for r in rows),
            "faults_restarted": sum(
                r.get("faults_restarted", 0) for r in rows
            ),
            "lat": lat,
        }
        if rows:
            self._last_tick = max(self._last_tick, rows[-1].get("tick", -1))
        for k in self._cum:
            self._cum[k] += summ[k]
        if lat is not None:
            self._cum_lat = (
                lat.copy() if self._cum_lat is None else self._cum_lat + lat
            )
        if self._ring_chunks:
            self._ring.append(summ)
            while len(self._ring) > self._ring_chunks:
                self._ring.popleft()
        return summ

    def _window(self, rule: SloRule) -> tuple[dict, "np.ndarray | None", int]:
        """(counter sums, summed lat histogram | None, window ticks) for
        one rule's evaluation window."""
        if not rule.window_ticks:
            return self._cum, self._cum_lat, self._cum["ticks"]
        need = -(-rule.window_ticks // self.chunk)  # ceil → whole chunks
        chunks = list(self._ring)[-need:]
        sums = {
            k: sum(c[k] for c in chunks) for k in self._cum
        }
        lats = [c["lat"] for c in chunks if c["lat"] is not None]
        lat = np.sum(lats, axis=0) if lats else None
        return sums, lat, sums["ticks"]

    def _observe(self, rule: SloRule):
        """``(observed value, window ticks)`` for a rule — the value is
        None when the window holds no evidence (zero deliveries / zero
        sends / zero ticks).

        A windowed rule is not evaluated until the run has produced a
        FULL window of history (the Prometheus ``for``-clause rule): a
        1024-tick window assessed after the first 256-tick chunk would
        judge warmup noise — a joins-and-sync first chunk could fail a
        perfectly healthy soak. State metrics (crashed_fraction) are
        window-exempt and evaluate from the first chunk."""
        if (
            rule.window_ticks
            and rule.metric != "crashed_fraction"
            and self._cum["ticks"] < rule.window_ticks
        ):
            return None, 0
        sums, lat, ticks = self._window(rule)
        if rule.metric in _LATENCY_METRICS:
            if lat is None:
                return None, ticks
            if rule.group:
                gi = self.group_ids.index(rule.group)
                hist = lat[gi]
            else:
                hist = lat.sum(axis=0)
            if int(hist.sum()) == 0:
                return None, ticks
            q = _LATENCY_METRICS[rule.metric]
            # tick_ms=1.0 → the "_ms" value IS ticks (one estimator for
            # the journal percentiles and the SLO plane)
            pct = latency_percentiles(hist, 1.0, quantiles=(q,))
            return pct.get(f"p{int(q * 100)}_ms"), ticks
        if rule.metric == "delivered_per_tick":
            if ticks <= 0:
                return None, ticks
            return sums["delivered"] / ticks, ticks
        if rule.metric == "drop_rate":
            if sums["sent"] <= 0:
                return None, ticks
            return (
                (sums["dropped"] + sums["fault_dropped"]) / sums["sent"],
                ticks,
            )
        if rule.metric == "crashed_fraction":
            # state metric: cumulative regardless of window
            crashed = (
                self._cum["faults_crashed"] - self._cum["faults_restarted"]
            )
            return crashed / max(self.n_instances, 1), ticks
        raise AssertionError(f"unhandled metric {rule.metric}")

    def evaluate(self) -> list[dict]:
        """Run every rule against the just-folded chunk; returns the new
        breach records (empty when everything holds)."""
        self._fold_chunk()
        breaches: list[dict] = []
        for rule in self.plan.rules:
            observed, win_ticks = self._observe(rule)
            agg = self._agg[rule.name]
            if observed is None:
                continue
            agg["last_observed"] = float(observed)
            if SLO_OPS[rule.op](observed, rule.threshold):
                continue  # the assertion holds
            breach = {
                "rule": rule.name,
                "metric": rule.metric,
                "op": rule.op,
                "threshold": rule.threshold,
                "observed": float(observed),
                "severity": rule.severity,
                "group": rule.group,
                "tick": int(self._last_tick),
                # inclusive tick bounds of the evidence window (clamped
                # at 0: ticks are 0-based, a whole-run window starts at
                # the first tick)
                "window": [
                    max(int(self._last_tick) - int(win_ticks) + 1, 0),
                    int(self._last_tick),
                ],
            }
            breaches.append(breach)
            agg["breaches"] += 1
            agg.setdefault("first_tick", breach["tick"])
            agg["last_tick"] = breach["tick"]
            # "worst" = farthest past the threshold, by the comparator's
            # own direction
            worst = agg["worst"]
            if worst is None or (
                abs(observed - rule.threshold) > abs(worst - rule.threshold)
            ):
                agg["worst"] = float(observed)
            if len(self._records) < JOURNAL_RECORDS_CAP:
                self._records.append(breach)
            self._write(breach)
            if rule.severity == "fail" and self.fatal is None:
                self.fatal = breach
                if self._cancel is not None:
                    self._cancel.set()
        return breaches

    # ------------------------------------------------------------- outputs

    def _write(self, breach: dict) -> None:
        self.records_written += 1
        if self._f is None:
            return
        try:
            self._f.write(json.dumps({**self.ident, **breach}) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            self.path = None

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                self.path = None
            finally:
                self._f = None

    def journal(self) -> dict:
        """The journal ``slo`` block: rule verdicts + bounded breach
        records (the jsonl stream keeps every record)."""
        total = sum(a["breaches"] for a in self._agg.values())
        out: dict = {
            "rules": [
                {
                    "name": r.name,
                    "metric": r.metric,
                    "op": r.op,
                    "threshold": r.threshold,
                    "window_ticks": r.window_ticks,
                    "severity": r.severity,
                    **({"group": r.group} if r.group else {}),
                    "breaches": self._agg[r.name]["breaches"],
                    **(
                        {
                            "first_tick": self._agg[r.name]["first_tick"],
                            "last_tick": self._agg[r.name]["last_tick"],
                            "worst": self._agg[r.name]["worst"],
                        }
                        if self._agg[r.name]["breaches"]
                        else {}
                    ),
                    **(
                        {
                            "last_observed": self._agg[r.name][
                                "last_observed"
                            ]
                        }
                        if self._agg[r.name]["last_observed"] is not None
                        else {}
                    ),
                }
                for r in self.plan.rules
            ],
            "breaches": total,
        }
        if self.path is not None:
            out["file"] = SLO_FILE
        if self._records:
            out["records"] = list(self._records)
            if total > len(self._records):
                out["records_truncated"] = total - len(self._records)
        return out
