"""Multi-host execution for the ``sim:jax`` runner.

The reference scales past one host by scheduling containers on a cluster
(``pkg/runner/cluster_k8s.go``: one pod per instance, coordinated through
the sync service). The TPU-native analog is **multi-controller SPMD**
(SURVEY.md §2.6/§7-M5): every host joins one ``jax.distributed`` job over
DCN, the instance axis shards over the union of all hosts' devices, and
XLA's collectives carry cross-host message traffic over ICI within a slice
and DCN across slices — there is no NCCL/MPI layer to port.

Topology of a run:

- the **leader** (process 0) is the host whose engine executes the task;
  it broadcasts the job spec (plan, case, shapes, seed) to the cohort,
  runs the jitted program, gathers results, and owns outputs/journal;
- **followers** (``tg sim-worker``) join the coordinator, receive each
  job spec, execute the SAME program over the same global mesh (the
  multi-controller contract: identical computations in identical order),
  and loop for the next job.

Plan sources must be present on every host at the same plan name (the
cluster runners make the same assumption via the shared image), and
every host must expose the SAME local device count —
``jax.multihost_utils`` shapes its collectives as
``[num_processes, local_devices]``, so an asymmetric cohort fails with a
reshape error at the first broadcast (the reference makes the analogous
uniformity assumption across its worker nodes).
"""

from __future__ import annotations

import json

import numpy as np

__all__ = [
    "SPEC_BYTES",
    "broadcast_json",
    "global_mesh",
    "init_distributed",
    "is_leader",
    "to_host",
]

# Fixed wire size for the job-spec broadcast: multi-controller broadcasts
# need identical static shapes on every process. Public name: the
# executor prechecks a composition's spec against this bound BEFORE any
# cohort process spawns (executor._precheck_cohort_spec_size).
SPEC_BYTES = 65536
_SPEC_BYTES = SPEC_BYTES

_initialized = False


# error-text markers of a coordinator that is not (yet) reachable — the
# retryable class of initialize() failures (a worker racing the leader's
# startup, a transient DCN blip); everything else re-raises immediately
_CONNECT_MARKERS = (
    "deadline",
    "unavailable",
    "connection refused",
    "failed to connect",
    "timed out",
    "timeout",
    "connection reset",
)


def _is_connect_error(exc: BaseException) -> bool:
    return any(m in str(exc).lower() for m in _CONNECT_MARKERS)


def init_distributed(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    heartbeat_timeout_seconds: int = 20,
    connect_attempts: int = 3,
    connect_timeout_seconds: float = 60.0,
) -> None:
    """Join the jax.distributed cohort (idempotent). The coordinator is
    process 0's ``host:port`` — the DCN control endpoint.

    The heartbeat timeout is tightened from jax's 100 s default so a
    SIGKILLed member is declared dead (and every surviving process's
    runtime poisoned — see ``cohort.py``) well inside the reference's
    failure-detection envelope; the common mid-collective case is faster
    still (the transport notices the closed connection in ~1 s). The
    kwarg only exists on newer jax releases — on older ones the cohort
    joins with the default heartbeat rather than dying on a TypeError
    (member death is still detected, just slower in the SIGKILL case).

    Joining retries: a worker commonly races the leader's startup across
    hosts, so connect-class failures (refused / deadline / unavailable)
    are retried up to ``connect_attempts`` times with backoff inside a
    per-attempt ``connect_timeout_seconds`` budget (threaded to jax's
    ``initialization_timeout`` where supported) before failing with an
    error that names the coordinator address — the cross-host twin of
    the sync client's bounded reconnect (docs/CROSSHOST.md)."""
    global _initialized
    if _initialized:
        return
    import inspect
    import time

    import jax

    kwargs = dict(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    try:
        sig = inspect.signature(jax.distributed.initialize)
        if "heartbeat_timeout_seconds" in sig.parameters:
            kwargs["heartbeat_timeout_seconds"] = heartbeat_timeout_seconds
        if "initialization_timeout" in sig.parameters:
            kwargs["initialization_timeout"] = int(connect_timeout_seconds)
    except (TypeError, ValueError):  # unsignaturable shim — be safe
        pass
    attempts = max(1, int(connect_attempts))
    last: BaseException | None = None
    for attempt in range(1, attempts + 1):
        try:
            jax.distributed.initialize(**kwargs)
            _initialized = True
            return
        except RuntimeError as e:
            if "before" in str(e):
                # jax's constraint: distributed must precede backend
                # init. A warm engine (an earlier single-host run touched
                # devices) cannot join a cohort mid-life.
                raise RuntimeError(
                    "cannot join a multi-host cohort: this process already "
                    "initialized its jax backend (an earlier run?). "
                    "Multi-host jobs need a fresh engine process whose "
                    "FIRST sim run carries the coordinator_address config."
                ) from e
            if not _is_connect_error(e):
                raise  # not a join problem — keep the original diagnosis
            if attempt >= attempts:
                raise RuntimeError(
                    f"could not join cohort coordinator at "
                    f"{coordinator_address} after {attempts} attempt(s): {e}"
                ) from e
            last = e
        except Exception as e:  # noqa: BLE001 — jaxlib/grpc error types
            if not _is_connect_error(e) or attempt >= attempts:
                raise
            last = e
        time.sleep(min(5.0, 0.5 * (2 ** (attempt - 1))))
    raise RuntimeError(  # unreachable; loop raises on its last attempt
        f"could not join cohort coordinator at {coordinator_address}: {last}"
    )


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def is_leader() -> bool:
    import jax

    return jax.process_index() == 0


def global_mesh():
    """One mesh axis ``"i"`` over every device of every process — the
    instance axis shards across hosts exactly as it does across chips."""
    import jax

    return jax.sharding.Mesh(np.asarray(jax.devices()), ("i",))


def broadcast_json(obj: dict | None) -> dict:
    """Leader sends ``obj``; followers pass None and receive it. One
    fixed-size uint8 broadcast (multihost_utils.broadcast_one_to_all)."""
    from jax.experimental import multihost_utils

    if obj is not None:
        raw = json.dumps(obj).encode()
        if len(raw) + 8 > _SPEC_BYTES:
            raise ValueError(
                f"job spec too large for broadcast: {len(raw)} bytes"
            )
        buf = np.zeros((_SPEC_BYTES,), np.uint8)
        header = np.frombuffer(
            len(raw).to_bytes(8, "little"), dtype=np.uint8
        )
        buf[:8] = header
        buf[8 : 8 + len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    else:
        buf = np.zeros((_SPEC_BYTES,), np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    size = int.from_bytes(out[:8].tobytes(), "little")
    return json.loads(out[8 : 8 + size].tobytes().decode())


def cohort_agree(ok: bool) -> bool:
    """All-processes AND over a local readiness bit (one tiny allgather).
    Run after receiving a job spec: a host whose plans dir cannot satisfy
    the job votes False and EVERY process skips the job in lockstep —
    otherwise the dead worker would strand the cohort mid-collective."""
    from jax.experimental import multihost_utils

    votes = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([1 if ok else 0], np.uint8), tiled=True
        )
    )
    return bool(votes.min() == 1)


class CohortCancel:
    """Cancellation as a cohort decision: the leader broadcasts its local
    cancel state once per chunk and every process observes the same
    answer — a leader honoring a local Event alone would break out of the
    chunk loop and issue collectives the followers aren't running."""

    def __init__(self, local_event=None):
        self._local = local_event

    def set(self) -> None:
        """Mark the local half; the cohort observes it at the next
        ``is_set`` broadcast (the chunk-boundary vote). Lets the engine's
        stall watchdog treat cohort and plain Events uniformly."""
        if self._local is not None:
            self._local.set()

    def is_set(self) -> bool:
        from jax.experimental import multihost_utils

        flag = 1 if (self._local is not None and self._local.is_set()) else 0
        out = np.asarray(
            multihost_utils.broadcast_one_to_all(
                np.asarray([flag], np.uint8)
            )
        )
        return bool(out[0])


def broadcast_shutdown_if_leader() -> None:
    """Release any waiting sim-workers when a leader engine shuts down
    (their next broadcast receives the shutdown sentinel)."""
    if _initialized and is_leader() and is_multiprocess():
        broadcast_json({"shutdown": True})


def to_host(x) -> np.ndarray:
    """Materialize a (possibly cross-host-sharded) array on this host:
    ``process_allgather`` when multi-process, plain ``np.asarray``
    otherwise."""
    if is_multiprocess():
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)
