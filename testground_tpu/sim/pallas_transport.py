"""Hand-tiled Pallas transport kernels — the ``transport=pallas`` backend.

PERF.md's single-chip floor claim rests on XLA's lowering choices: 84% of
the sustained full-path tick is three gather/scatter ops (the stacking
base gather over the derived [L·N] fill table, and the payload +
src/occupancy plane scatters) that XLA:TPU lowers to ~6 ns/lane
scalar-core loops, each op re-walking its own 200k-entry index stream.
This module is the SURVEY §2.4.1 escalation ("implement the hot delivery
kernel in … Pallas where jnp ops are insufficient"): the same work
expressed as two hand-tiled kernels that walk the index stream ONCE.

**Calendar-commit kernel** (:func:`commit_calendar`) — replaces, for the
sorted slot path, everything downstream of the multi-operand sort.
Since ISSUE 14 the kernel is SEGMENTED: the sorted message stream is
never resident in VMEM as a whole — it streams through in fixed-size
tiles, which removes both the ~500k-instance whole-stream cap and the
storm-shape exclusion the ISSUE-5 kernel carried:

- the sort already orders messages by (bucket, dst), so bucket b's
  messages are one contiguous segment of the sorted stream. The host
  side cuts the stream at BOTH boundary families — the L+1 bucket
  starts (one ``searchsorted``) and the fixed tile starts k·T — and
  enumerates the resulting intervals in stream order. Each interval
  lies inside exactly one bucket AND one tile, so the static grid is
  one step per interval: ``K + L + 1`` steps for K tiles over L
  buckets, with the per-step (bucket, tile, lo, hi) tables handed to
  the kernel as scalar prefetch.
- per grid step, Pallas DMAs tile k of the stream operands ([1, T]
  blocks) and bucket b's occupancy/payload/etick rows ([1, N·SLOTS]
  blocks) into VMEM. Consecutive steps that share a tile or a bucket
  keep the block resident (no re-fetch), and the grid pipeline
  double-buffers the block DMAs, so tile k+1's fetch overlaps tile k's
  walk. The walk itself is unchanged: one index decode per message,
  storing EVERY plane's word — occupancy mark, W payload words,
  enqueue tick — at the message's slot position in one pass.
- slot assignment happens IN the kernel: a message's slot is its rank
  within its (bucket, dst) run plus the bucket's pre-tick fill, read as
  SLOTS scalar loads from the in-VMEM occupancy row at each run start
  (replacing the derived [L·N] fill table, its 200k-lane base gather,
  and the rank prefix-max). The (prev_key, next_slot) pair lives in
  SMEM scratch and persists across grid steps, so a (bucket, dst) run
  spanning a tile boundary keeps its rank exactly — the tile cut is
  invisible to the slot math. Fill reads stay PRE-update by
  construction: a bucket's input row block is fetched once, before the
  bucket's first interval, and all its intervals are consecutive.
- per-message survival (slot < SLOTS) is written through a tiled
  [1, m2] output (zeroed on each tile's first visit) so the flow
  counters and the flight recorder's fate plane stay exact.

**Delivery kernel** (:func:`pop_bucket`) — the tiled row pop over the
arriving bucket: one grid step DMAs bucket (t mod L)'s rows into VMEM,
emits the popped occupancy/payload rows for the inbox unpack, and
writes the zeroed occupancy row back in the same pass — fusing
``deliver``'s dynamic-slice read and clear-row write into one traversal.

Layout: the pallas backend keeps the 2-D ``[L, N·SLOTS]`` plane form
(``Calendar.flat=False``) even unsharded — the kernels block rows
directly, so the flat linear layout XLA's scatter lowering wants buys
nothing here. The N·SLOTS axis stays minor (the net.py layout rule).

Scope: the sorted enqueue path and ``deliver``. Direct slot mode keeps
its XLA scatter (one index per message, no sort — there is no bucket
ordering for the kernel to exploit).

**Mesh sharding** (ISSUE 20): on a mesh the SAME kernels run per chip
under ``shard_map``, each over its own destination-range shard of the
calendar planes (the free ``[L, SLOTS, N] → P(None, None, 'i')`` view
of the slot-major row axis). The cross-shard message exchange happens
BEFORE the kernel: net.py sorts the stream by a SHARD-major key
((dst_shard, bucket, local_dst) — same (bucket, dst) equivalence
classes, so slot assignment is bit-identical), and the sorted stream
enters every shard replicated — the implicit all-gather IS the
exchange stage, costed by the transport model as
``meshplan.cross_shard_bytes_est``. Inside each shard the keys are
rebased by −shard·L·n_loc (still ascending: earlier shards' messages
go negative, later shards' past the local window) and the interval
table clips the walk to the shard's own valid segment
[starts[0], starts[L]) — the kernel body is UNCHANGED, it just sees
n = n_loc. Per-shard survival tiles are zero outside the shard's
segment, so a sum over the shard axis reassembles the exact global
mask. ``SimProgram`` enforces the divisibility bound
(lane count % shards == 0). VMEM envelope (segmented): ~2·(3+W)·T words of
stream tiles plus ~2·2·(1+W+E) row blocks of N·SLOTS words (E = 1 with
the etick plane) — the m2 term is GONE, so the envelope no longer
depends on the message-stream length at all; only the per-bucket row
footprint bounds the instance count (~1M instances for the flagship
W=1, SLOTS=2 shape at the default T). The tile size T is the
``TG_TRANSPORT_TILE`` env knob (default :data:`DEFAULT_COMMIT_TILE`,
rounded to the 128-lane grain); see PERF.md "Pallas transport
kernels" for the full formula.

On non-TPU backends every kernel runs in interpret mode, so the CPU
test tier executes the real kernel logic bit-for-bit against the XLA
path (``tests/test_transport_pallas.py``, the fuzz suites); the real
chip is measured by ``tools/bench_pallas_transport.py`` and
``bench.py --transport pallas``.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp

__all__ = [
    "DEFAULT_COMMIT_TILE",
    "commit_calendar",
    "commit_tile_words",
    "pop_bucket",
    "pallas_interpret",
]

# Default stream-tile width in int32 words (the segmented commit
# kernel's T). 4096 keeps the double-buffered stream-side VMEM under
# ~256 KB at W=1 while amortizing the per-step grid overhead over
# thousands of messages; must be a multiple of the 128-lane grain.
# Override per process with TG_TRANSPORT_TILE (rounded down to the
# grain) — a TRACE-time knob: it changes the compiled kernel, so two
# processes with different values compile different programs.
DEFAULT_COMMIT_TILE = 4096


def pallas_interpret() -> bool:
    """Interpret-mode gate: anywhere but a real TPU backend, the kernels
    run under the Pallas interpreter — same semantics, executable on the
    CPU test tier (and on the 8-device virtual mesh's host platform)."""
    return jax.default_backend() != "tpu"


def commit_vmem_bytes(
    n_lanes: int,
    slots: int,
    width: int,
    occ_bool: bool = False,
    etick: bool = False,
    tile: int | None = None,
) -> int:
    """The segmented commit kernel's VMEM envelope estimate in bytes:
    double-buffered stream tiles ((2+W) inputs + the survival output at
    T words each) plus double-buffered row blocks in AND out ((1+W+E)
    planes of N·SLOTS words; the occupancy plane is 1 byte when bool).
    The m2 stream term of the ISSUE-5 kernel is gone by construction —
    what remains scales with N·SLOTS only (PERF.md "Pallas transport
    kernels" documents the formula and its remaining bound)."""
    t = commit_tile_words(tile)
    ns = n_lanes * slots
    stream = 2 * (2 + width + 1) * t * 4
    occ_b = 1 if occ_bool else 4
    row = ns * (occ_b + 4 * (width + (1 if etick else 0)))
    return stream + 4 * row  # rows: in + out, each double-buffered


def commit_tile_words(tile: int | None = None) -> int:
    """Resolve the commit kernel's stream-tile width: explicit arg wins,
    then the TG_TRANSPORT_TILE env knob, then the default — always
    rounded down to the 128-lane grain (floor 128)."""
    if tile is None:
        try:
            tile = int(os.environ.get("TG_TRANSPORT_TILE", "") or 0)
        except ValueError:
            tile = 0
        tile = tile or DEFAULT_COMMIT_TILE
    return max(128, (int(tile) // 128) * 128)


# Cache of built pallas_calls, keyed on the REDUCED static config: the
# engine traces one enqueue per program, but eager callers (the fuzz
# suites) hit this per tick, and the hypothesis suites sweep shapes.
# The key deliberately excludes anything the kernel body never reads
# (track_src rode along here until ISSUE 14 — a dead key axis: the
# kernel only cares about the occupancy dtype, which stays keyed), and
# the stream length enters as m2p — already padded UP to the tile
# grain — so nearby fuzz shapes share one entry. 256 bounds the worst
# hypothesis sweep (shape dims × {stacking, etick, occ dtype} ≈ low
# hundreds of distinct reduced configs) while each entry is only an
# untraced pallas_call closure.
@functools.lru_cache(maxsize=256)
def _commit_call(
    horizon: int,
    n: int,
    slots: int,
    width: int,
    m2p: int,
    tile: int,
    has_etick: bool,
    stacking: bool,
    occ_bool: bool,
    interpret: bool,
):
    """Build the segmented pallas_call for one static commit config.

    Grid = one step per (bucket, tile) intersection interval of the
    sorted stream (K + L + 1 static steps), walked in stream order with
    the per-step tables scalar-prefetched. Stream operands and the
    survival output are blocked [1, tile]; calendar rows [1, N·SLOTS].
    The (prev_key, next_slot) rank carry lives in SMEM scratch so runs
    spanning tile boundaries keep their slot rank."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ns = n * slots
    occ_dtype = jnp.bool_ if occ_bool else jnp.int32
    n_et = 1 if has_etick else 0
    k_tiles = m2p // tile
    n_steps = k_tiles + horizon + 1

    def kernel(*refs):
        # operand order (after the 5 scalar-prefetch refs): sorted
        # message stream tiles, then the input rows, then outputs,
        # then the SMEM rank-carry scratch
        sb_ref, st_ref, lo_ref, hi_ref, t_ref = refs[:5]
        sk_ref, occv_ref = refs[5], refs[6]
        pay_refs = refs[7 : 7 + width]
        occ_in = refs[7 + width]
        pay_in = refs[8 + width : 8 + 2 * width]
        et_in = refs[8 + 2 * width] if has_etick else None
        base = 8 + 2 * width + n_et
        surv_ref = refs[base]
        occ_out = refs[base + 1]
        pay_out = refs[base + 2 : base + 2 + width]
        et_out = refs[base + 2 + width] if has_etick else None
        carry_ref = refs[-1]  # the SMEM rank-carry scratch

        s = pl.program_id(0)
        b = sb_ref[s]
        k = st_ref[s]
        prev = jnp.maximum(s - 1, 0)
        new_row = (s == 0) | (b != sb_ref[prev])
        new_tile = (s == 0) | (k != st_ref[prev])

        @pl.when(s == 0)
        def _():
            # rank carry across ALL grid steps: no run is in flight yet
            carry_ref[0] = jnp.int32(-1)
            carry_ref[1] = jnp.int32(0)

        # the survival tile is shared by every interval inside tile k;
        # zero it once, on the tile's first (stream-ordered) visit
        @pl.when(new_tile)
        def _():
            surv_ref[:] = jnp.zeros_like(surv_ref)

        # pass the rows through on the bucket's FIRST interval only:
        # untouched cells must survive the write-back (the out block is
        # a fresh VMEM buffer), but later intervals of the same bucket
        # must not wipe earlier intervals' stores. The in block stays
        # resident (and PRE-update) across all of a bucket's intervals —
        # they are consecutive in stream order by construction.
        @pl.when(new_row)
        def _():
            occ_out[:] = occ_in[:]
            for w in range(width):
                pay_out[w][:] = pay_in[w][:]
            if has_etick:
                et_out[:] = et_in[:]

        off = k * tile
        lo = lo_ref[s] - off
        hi = hi_ref[s] - off
        tick = t_ref[0]

        def body(j, carry):
            prev_key, next_slot = carry
            key = sk_ref[0, j]
            dstj = key - b * n

            def fresh(_):
                # new (bucket, dst) run: rank restarts at the bucket's
                # pre-tick fill for this dst — read straight from the
                # PRE-update occupancy row (the in block), replacing the
                # XLA path's derived fill table + 200k-lane base gather
                if not stacking:
                    return jnp.int32(0)
                acc = jnp.int32(0)
                for sl in range(slots):
                    acc += (occ_in[0, sl * n + dstj] != 0).astype(
                        jnp.int32
                    )
                return acc

            slot = jax.lax.cond(
                key != prev_key, fresh, lambda _: next_slot, None
            )

            @pl.when(slot < slots)
            def _():
                # one traversal writes EVERY plane at this position —
                # the fusion the XLA path pays three scalar-core loops
                # for (positions are slot-major: pos = slot·N + dst)
                pos = slot * n + dstj
                if occ_bool:
                    occ_out[0, pos] = occv_ref[0, j] != 0
                else:
                    occ_out[0, pos] = occv_ref[0, j]
                for w in range(width):
                    pay_out[w][0, pos] = pay_refs[w][0, j]
                if has_etick:
                    et_out[0, pos] = tick
                surv_ref[0, j] = 1

            return key, slot + 1

        # resume the rank carry from scratch, walk this interval's
        # messages (tile-local indices), persist the carry for the next
        # interval — a run cut by the tile boundary continues exactly
        final_key, final_slot = jax.lax.fori_loop(
            lo, hi, body, (carry_ref[0], carry_ref[1])
        )
        carry_ref[0] = final_key
        carry_ref[1] = final_slot

    def stream_spec():
        return pl.BlockSpec((1, tile), lambda s, st_b, st_t, *_: (0, st_t[s]))

    def row_spec():
        return pl.BlockSpec((1, ns), lambda s, st_b, *_: (st_b[s], 0))

    n_rows = 1 + width + n_et
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_steps,),
        in_specs=[stream_spec() for _ in range(2 + width)]
        + [row_spec() for _ in range(n_rows)],
        out_specs=[stream_spec()] + [row_spec() for _ in range(n_rows)],
        scratch_shapes=[pltpu.SMEM((2,), jnp.int32)],
    )
    out_shape = [jax.ShapeDtypeStruct((1, m2p), jnp.int32)]
    out_shape.append(jax.ShapeDtypeStruct((horizon, ns), occ_dtype))
    out_shape += [
        jax.ShapeDtypeStruct((horizon, ns), jnp.int32) for _ in range(width)
    ]
    if has_etick:
        out_shape.append(jax.ShapeDtypeStruct((horizon, ns), jnp.int32))
    # operand index of the first plane input: 5 prefetch + (2 + W) stream
    first_plane = 7 + width
    aliases = {first_plane + i: 1 + i for i in range(n_rows)}
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )


def _interval_tables(
    sk: jax.Array, horizon: int, n: int, m2p: int, tile_w: int, k_tiles: int
):
    """The segmented kernel's per-grid-step scalar tables, from a sorted
    key stream: (bucket, tile, lo, hi) per interval.

    Bucket b's sorted segment is [starts[b], starts[b+1]); invalid
    messages carry key ≥ horizon·n and fall past starts[horizon]. The
    interval table cuts the stream at every bucket start AND every tile
    start: each interval lies in one bucket and one tile, and there are
    exactly K + L + 1 of them (the static grid).

    The walk bounds clamp to the VALID WINDOW [starts[0], starts[L]):
    unsharded, starts[0] is always 0 and only the invalid tail is
    clamped; per shard (keys rebased by −shard·L·n_loc, still
    ascending), earlier shards' messages sit below 0 and later shards'
    at/past L·n_loc, so the same clamp walks exactly the shard's own
    segment. The RAW interval still drives the tile index so every
    survival tile (the out-of-window spans included) is visited and
    zeroed."""
    starts = jnp.searchsorted(
        sk, jnp.arange(horizon + 1, dtype=jnp.int32) * jnp.int32(n)
    ).astype(jnp.int32)
    valid_begin = starts[0]
    valid_end = starts[horizon]
    bounds = jnp.sort(
        jnp.concatenate(
            [jnp.arange(k_tiles, dtype=jnp.int32) * jnp.int32(tile_w), starts]
        )
    )
    lo_raw = bounds
    hi_raw = jnp.concatenate(
        [bounds[1:], jnp.full((1,), m2p, jnp.int32)]
    )
    steps_lo = jnp.clip(lo_raw, valid_begin, valid_end)
    steps_hi = jnp.clip(hi_raw, valid_begin, valid_end)
    steps_tile = jnp.clip(lo_raw // tile_w, 0, k_tiles - 1).astype(
        jnp.int32
    )
    # bucket of the interval's first in-window message; out-of-window
    # intervals inherit the nearest in-window message's bucket so an
    # already-flushed row is never re-fetched (they do no row work —
    # the clamp only parks the block index on a real bucket)
    pos_b = jnp.clip(
        lo_raw, valid_begin, jnp.maximum(valid_end - 1, valid_begin)
    )
    steps_b = jnp.clip(
        jnp.searchsorted(starts, pos_b, side="right").astype(jnp.int32) - 1,
        0,
        horizon - 1,
    )
    return steps_b, steps_tile, steps_lo, steps_hi


def commit_calendar(
    cal,
    sk: jax.Array,  # [m2] int32, sorted keys (bucket·n + dst; big = invalid)
    occ_vals: jax.Array,  # [m2] int32 occupancy marks (src+1, or 1)
    pay_sorted,  # W × [m2] int32, sorted alongside sk
    t: jax.Array,
    *,
    stacking: bool = True,
    tile: int | None = None,
    mesh=None,
):
    """Commit one tick's sorted message stream into the calendar planes.

    Returns ``(cal', survived)`` with ``survived`` a [m2] int32 0/1 mask
    in SORTED order — 1 exactly where the XLA path's ``val_s`` (valid ∧
    rank < SLOTS) holds, so flow counters and fate mapping stay exact.
    Requires the 2-D plane layout (``cal.flat`` False).

    ``tile`` overrides the stream-tile width (tests use tiny tiles to
    pin the tile-boundary rank carry); default per
    :func:`commit_tile_words`. The stream is padded up to the tile
    grain with invalid keys — padding never survives and is sliced off
    the returned mask.

    ``mesh`` routes through the sharded variant: the same kernel per
    chip under ``shard_map``, each over its destination-range shard of
    the planes, with ``sk`` sorted by the SHARD-major key net.py builds
    on a mesh (see the module docstring's mesh section)."""
    if mesh is not None:
        return _commit_calendar_sharded(
            cal,
            sk,
            occ_vals,
            pay_sorted,
            t,
            stacking=stacking,
            tile=tile,
            mesh=mesh,
        )
    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    slots = cal.slots
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    n = ns // slots
    m2 = int(sk.shape[0])
    has_etick = cal.etick is not None
    if m2 == 0:  # degenerate direct call: nothing to commit
        return cal, jnp.zeros((0,), jnp.int32)

    tile_w = commit_tile_words(tile)
    m2p = -(-m2 // tile_w) * tile_w  # ceil to the tile grain
    k_tiles = m2p // tile_w
    pad = m2p - m2
    if pad:
        big_fill = jnp.full((pad,), horizon * n, jnp.int32)
        sk = jnp.concatenate([sk, big_fill])
        occ_vals = jnp.concatenate(
            [occ_vals, jnp.zeros((pad,), occ_vals.dtype)]
        )
        pay_sorted = [
            jnp.concatenate([p, jnp.zeros((pad,), p.dtype)])
            for p in pay_sorted
        ]

    steps_b, steps_tile, steps_lo, steps_hi = _interval_tables(
        sk, horizon, n, m2p, tile_w, k_tiles
    )
    tvec = jnp.reshape(jnp.asarray(t, jnp.int32), (1,))

    call = _commit_call(
        horizon,
        n,
        slots,
        width,
        m2p,
        tile_w,
        has_etick,
        bool(stacking),
        occ.dtype == jnp.bool_,
        pallas_interpret(),
    )
    # message-stream operands ride as [1, m2p] rows (TPU-friendly 2-D)
    args = [steps_b, steps_tile, steps_lo, steps_hi, tvec]
    args += [sk[None, :], occ_vals[None, :]]
    args += [p[None, :] for p in pay_sorted]
    args.append(occ)
    args += list(cal.payload)
    if has_etick:
        args.append(cal.etick)
    out = call(*args)
    survived = out[0][0, :m2]
    new_occ = out[1]
    new_payload = tuple(out[2 : 2 + width])
    new_etick = out[2 + width] if has_etick else None
    # provenance tracking only steers which Calendar field the updated
    # occupancy plane lands in — the kernel itself is identical either
    # way, which is exactly why track_src is NOT part of the call cache
    # key anymore
    track_src = cal.src is not None
    cal = dataclasses.replace(
        cal,
        payload=new_payload,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
        etick=new_etick,
    )
    return cal, survived


def _commit_calendar_sharded(
    cal,
    sk: jax.Array,  # [m2] int32, SHARD-major sorted keys (net.py on a mesh)
    occ_vals: jax.Array,
    pay_sorted,
    t: jax.Array,
    *,
    stacking: bool,
    tile: int | None,
    mesh,
):
    """The mesh variant of :func:`commit_calendar`: ``shard_map`` the
    UNCHANGED segmented kernel over each chip's destination-range shard
    of the calendar planes.

    The planes enter through the free ``[L, SLOTS, N]`` view with the
    lane axis sharded (``P(None, None, 'i')`` — slot-major rows make
    this a zero-copy reshape), so each shard holds a locally slot-major
    ``[L, SLOTS·n_loc]`` plane the kernel addresses with n = n_loc. The
    sorted stream enters REPLICATED (``P()``): that resharding is the
    cross-shard message exchange, in one collective, before commit.
    Inside each shard the keys are rebased by −shard·L·n_loc — still
    ascending — and :func:`_interval_tables` clips the walk to the
    shard's own contiguous segment. Per-shard survival tiles are zeroed
    everywhere and marked only inside the shard's segment, so summing
    the stacked per-shard masks reassembles the exact global mask the
    unsharded kernel would emit; the (bucket, dst) equivalence classes
    of the shard-major key equal the bucket-major key's, so slot
    assignment — and thus every plane write — is bit-identical."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    slots = cal.slots
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    n = ns // slots
    shards = int(mesh.shape["i"])
    assert n % shards == 0, (
        f"sharded pallas commit needs lane count {n} divisible by "
        f"{shards} shards (SimProgram enforces this)"
    )
    n_loc = n // shards
    ns_loc = n_loc * slots
    m2 = int(sk.shape[0])
    has_etick = cal.etick is not None
    if m2 == 0:  # degenerate direct call: nothing to commit
        return cal, jnp.zeros((0,), jnp.int32)

    tile_w = commit_tile_words(tile)
    m2p = -(-m2 // tile_w) * tile_w
    k_tiles = m2p // tile_w
    pad = m2p - m2
    if pad:
        # same invalid fill: big = horizon·n = shards·horizon·n_loc is
        # one past the max shard-major key, so padding sorts last here too
        big_fill = jnp.full((pad,), horizon * n, jnp.int32)
        sk = jnp.concatenate([sk, big_fill])
        occ_vals = jnp.concatenate(
            [occ_vals, jnp.zeros((pad,), occ_vals.dtype)]
        )
        pay_sorted = [
            jnp.concatenate([p, jnp.zeros((pad,), p.dtype)])
            for p in pay_sorted
        ]
    tvec = jnp.reshape(jnp.asarray(t, jnp.int32), (1,))

    call = _commit_call(
        horizon,
        n_loc,
        slots,
        width,
        m2p,
        tile_w,
        has_etick,
        bool(stacking),
        occ.dtype == jnp.bool_,
        pallas_interpret(),
    )
    seg = jnp.int32(horizon * n_loc)

    def shard_body(sk_r, occv_r, pays_r, tv, occ3, pays3, et3):
        s = jax.lax.axis_index("i").astype(jnp.int32)
        # rebase to the shard's local key space: the shard's own
        # messages land in [0, L·n_loc) encoded exactly as the
        # unsharded key (bucket·n_loc + local_dst); earlier shards'
        # go negative, later shards' and invalids past the window —
        # NO clamping here (it would break sortedness), the interval
        # tables clip the walk instead
        rk = sk_r - s * seg
        tables = _interval_tables(rk, horizon, n_loc, m2p, tile_w, k_tiles)
        occ_l = occ3.reshape(horizon, ns_loc)
        args = [*tables, tv, rk[None, :], occv_r[None, :]]
        args += [p[None, :] for p in pays_r]
        args.append(occ_l)
        args += [p.reshape(horizon, ns_loc) for p in pays3]
        if has_etick:
            args.append(et3.reshape(horizon, ns_loc))
        out = call(*args)
        surv = out[0]
        occ_out = out[1].reshape(horizon, slots, n_loc)
        pay_out = [
            p.reshape(horizon, slots, n_loc) for p in out[2 : 2 + width]
        ]
        et_out = (
            out[2 + width].reshape(horizon, slots, n_loc)
            if has_etick
            else jnp.zeros((0,), jnp.int32)
        )
        return surv, occ_out, pay_out, et_out

    plane3 = P(None, None, "i")
    et3_in = (
        cal.etick.reshape(horizon, slots, n)
        if has_etick
        else jnp.zeros((0,), jnp.int32)
    )
    surv_g, occ_g, pay_g, et_g = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(),
            P(),
            [P()] * width,
            P(),
            plane3,
            [plane3] * width,
            plane3 if has_etick else P(),
        ),
        out_specs=(
            # per-shard survival stacks on a leading shard axis (summed
            # below — avoids claiming replication for a psum'd output)
            P("i", None),
            plane3,
            [plane3] * width,
            plane3 if has_etick else P("i"),
        ),
        check_rep=False,
    )(
        sk,
        occ_vals,
        list(pay_sorted),
        tvec,
        occ.reshape(horizon, slots, n),
        [p.reshape(horizon, slots, n) for p in cal.payload],
        et3_in,
    )
    survived = jnp.sum(surv_g, axis=0)[:m2]
    new_occ = occ_g.reshape(horizon, ns)
    new_payload = tuple(p.reshape(horizon, ns) for p in pay_g)
    new_etick = et_g.reshape(horizon, ns) if has_etick else None
    track_src = cal.src is not None
    cal = dataclasses.replace(
        cal,
        payload=new_payload,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
        etick=new_etick,
    )
    return cal, survived


@functools.lru_cache(maxsize=64)
def _pop_call(
    horizon: int, ns: int, width: int, occ_bool: bool, interpret: bool
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    occ_dtype = jnp.bool_ if occ_bool else jnp.int32

    def kernel(*refs):
        # refs: b_ref, occ_in, pay_in×W, occ_out, row_occ, row_pay×W
        occ_in = refs[1]
        pay_in = refs[2 : 2 + width]
        occ_out = refs[2 + width]
        row_occ = refs[3 + width]
        row_pay = refs[4 + width : 4 + 2 * width]
        row = occ_in[:]
        row_occ[:] = row  # pop ...
        occ_out[:] = jnp.zeros_like(row)  # ... and clear, one traversal
        for w in range(width):
            row_pay[w][:] = pay_in[w][:]

    def row_spec():
        return pl.BlockSpec((1, ns), lambda i, b: (b[0], 0))

    full_row = pl.BlockSpec(memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[row_spec() for _ in range(1 + width)],
        out_specs=[row_spec()] + [full_row] * (1 + width),
    )
    out_shape = [jax.ShapeDtypeStruct((horizon, ns), occ_dtype)]
    out_shape.append(jax.ShapeDtypeStruct((1, ns), occ_dtype))
    out_shape += [
        jax.ShapeDtypeStruct((1, ns), jnp.int32) for _ in range(width)
    ]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases={1: 0},  # occupancy plane updated in place
        interpret=interpret,
    )


def pop_bucket(cal, t: jax.Array, mesh=None):
    """Pop the bucket arriving at tick ``t``: returns ``(cal', occ_row,
    pay_rows)`` with the rows as [N·SLOTS] vectors and the occupancy row
    cleared in the returned calendar (payload stays stale-but-masked,
    exactly like the XLA ``deliver``). ``mesh`` runs the same kernel
    per chip over its destination-range plane shard (the delivery pop
    is embarrassingly shard-local — no exchange stage)."""
    if mesh is not None:
        return _pop_bucket_sharded(cal, t, mesh)
    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    bvec = jnp.reshape(
        jnp.mod(jnp.asarray(t, jnp.int32), horizon), (1,)
    )
    call = _pop_call(
        horizon, ns, width, occ.dtype == jnp.bool_, pallas_interpret()
    )
    out = call(bvec, occ, *cal.payload)
    new_occ = out[0]
    occ_row = out[1][0]
    pay_rows = [r[0] for r in out[2 : 2 + width]]
    track_src = cal.src is not None
    cal = dataclasses.replace(
        cal,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
    )
    return cal, occ_row, pay_rows


def _pop_bucket_sharded(cal, t: jax.Array, mesh):
    """Mesh variant of :func:`pop_bucket`: the pop kernel per chip over
    its ``[L, SLOTS, n_loc]`` plane shard (same free view as the commit
    side). The popped [SLOTS, n_loc] rows reassemble along the lane
    axis into the global slot-major [N·SLOTS] row — delivery reads and
    clears only lane-local state, so no collective is needed at all."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    slots = cal.slots
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    n = ns // slots
    shards = int(mesh.shape["i"])
    assert n % shards == 0, (
        f"sharded pallas pop needs lane count {n} divisible by "
        f"{shards} shards (SimProgram enforces this)"
    )
    n_loc = n // shards
    ns_loc = n_loc * slots
    bvec = jnp.reshape(
        jnp.mod(jnp.asarray(t, jnp.int32), horizon), (1,)
    )
    call = _pop_call(
        horizon, ns_loc, width, occ.dtype == jnp.bool_, pallas_interpret()
    )

    def shard_body(bv, occ3, pays3):
        out = call(bv, occ3.reshape(horizon, ns_loc), *[
            p.reshape(horizon, ns_loc) for p in pays3
        ])
        new_occ = out[0].reshape(horizon, slots, n_loc)
        occ_row = out[1][0].reshape(slots, n_loc)
        pay_rows = [r[0].reshape(slots, n_loc) for r in out[2 : 2 + width]]
        return new_occ, occ_row, pay_rows

    plane3 = P(None, None, "i")
    row2 = P(None, "i")
    occ_g, row_g, pay_g = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), plane3, [plane3] * width),
        out_specs=(plane3, row2, [row2] * width),
        check_rep=False,
    )(
        bvec,
        occ.reshape(horizon, slots, n),
        [p.reshape(horizon, slots, n) for p in cal.payload],
    )
    new_occ = occ_g.reshape(horizon, ns)
    occ_row = row_g.reshape(ns)
    pay_rows = [r.reshape(ns) for r in pay_g]
    track_src = cal.src is not None
    cal = dataclasses.replace(
        cal,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
    )
    return cal, occ_row, pay_rows
