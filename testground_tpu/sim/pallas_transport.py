"""Hand-tiled Pallas transport kernels — the ``transport=pallas`` backend.

PERF.md's single-chip floor claim rests on XLA's lowering choices: 84% of
the sustained full-path tick is three gather/scatter ops (the stacking
base gather over the derived [L·N] fill table, and the payload +
src/occupancy plane scatters) that XLA:TPU lowers to ~6 ns/lane
scalar-core loops, each op re-walking its own 200k-entry index stream.
This module is the SURVEY §2.4.1 escalation ("implement the hot delivery
kernel in … Pallas where jnp ops are insufficient"): the same work
expressed as two hand-tiled kernels that walk the index stream ONCE.

**Calendar-commit kernel** (:func:`commit_calendar`) — replaces, for the
sorted slot path, everything downstream of the multi-operand sort:

- grid = one step per calendar bucket. The sort already orders messages
  by (bucket, dst), so bucket b's messages are one contiguous segment
  of the sorted stream; the segment bounds are a single ``searchsorted``
  of the L+1 bucket boundaries over the sorted keys, handed to the
  kernel as scalar prefetch (the index computation is known before the
  grid runs, so Pallas pipelines the row DMAs against it).
- each grid step holds bucket b's occupancy/payload/etick rows in VMEM
  (Pallas DMAs the [1, N·SLOTS] blocks HBM→VMEM and back around the
  step), walks the segment once, and for each message stores EVERY
  plane's word — occupancy mark, W payload words, enqueue tick — at the
  message's slot position in the same pass. One index decode per
  message, versus one scalar-core loop per plane per tick under XLA.
- slot assignment happens IN the kernel: a message's slot is its rank
  within its (bucket, dst) run — runs are contiguous in the sorted
  segment, so a sequential counter reproduces the XLA rank exactly —
  plus the bucket's pre-tick fill, read as SLOTS scalar loads from the
  in-VMEM occupancy row at each run start. That replaces the derived
  [L·N] fill table, its 200k-lane base gather (30% of the XLA tick),
  and the rank prefix-max entirely. Within-segment stores never affect
  the base reads: a (bucket, dst) run is visited once, and its fill is
  read from the PRE-update input block, exactly like the XLA path
  derives the fill table before the scatter.
- per-message survival (slot < SLOTS) is written to a [1, m] output so
  the flow counters and the flight recorder's fate plane stay exact.

**Delivery kernel** (:func:`pop_bucket`) — the tiled row pop over the
arriving bucket: one grid step DMAs bucket (t mod L)'s rows into VMEM,
emits the popped occupancy/payload rows for the inbox unpack, and
writes the zeroed occupancy row back in the same pass — fusing
``deliver``'s dynamic-slice read and clear-row write into one traversal.

Layout: the pallas backend keeps the 2-D ``[L, N·SLOTS]`` plane form
(``Calendar.flat=False``) even unsharded — the kernels block rows
directly, so the flat linear layout XLA's scatter lowering wants buys
nothing here. The N·SLOTS axis stays minor (the net.py layout rule).

Scope: the sorted enqueue path and ``deliver``. Direct slot mode keeps
its XLA scatter (one index per message, no sort — there is no bucket
ordering for the kernel to exploit), and mesh-sharded programs keep the
XLA path entirely (the cross-shard scatter IS the inter-chip traffic;
a single-device kernel cannot express it) — ``SimProgram`` enforces the
single-device bound. VMEM envelope: the whole sorted message stream
((3+W) × m2 int32) plus ~2(2+W) row blocks must fit in ~16 MB VMEM —
the flagship full path (m2 = 2N, W = 1, SLOTS = 2) fits to ~500k
instances; storm-shaped workloads (OUT_MSGS·IN_MSGS large) exceed it
well below 100k, which is part of what the A/B harness measures.

On non-TPU backends every kernel runs in interpret mode, so the CPU
test tier executes the real kernel logic bit-for-bit against the XLA
path (``tests/test_transport_pallas.py``, the fuzz suites); the real
chip is measured by ``tools/bench_pallas_transport.py`` and
``bench.py --transport pallas``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

__all__ = ["commit_calendar", "pop_bucket", "pallas_interpret"]


def pallas_interpret() -> bool:
    """Interpret-mode gate: anywhere but a real TPU backend, the kernels
    run under the Pallas interpreter — same semantics, executable on the
    CPU test tier (and on the 8-device virtual mesh's host platform)."""
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=64)
def _commit_call(
    horizon: int,
    n: int,
    slots: int,
    width: int,
    m2: int,
    track_src: bool,
    has_etick: bool,
    stacking: bool,
    occ_bool: bool,
    interpret: bool,
):
    """Build the pallas_call for one static commit configuration.

    Cached per program shape: the engine traces one enqueue per program,
    but eager callers (the fuzz suites) hit this per tick."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ns = n * slots
    occ_dtype = jnp.bool_ if occ_bool else jnp.int32
    n_et = 1 if has_etick else 0

    def kernel(*refs):
        # operand order (after the 2 scalar-prefetch refs): sorted
        # message stream, then the input rows, then outputs
        starts_ref, t_ref = refs[0], refs[1]
        sk_ref, occv_ref = refs[2], refs[3]
        pay_refs = refs[4 : 4 + width]
        occ_in = refs[4 + width]
        pay_in = refs[5 + width : 5 + 2 * width]
        et_in = refs[5 + 2 * width] if has_etick else None
        base = 5 + 2 * width + n_et
        surv_ref = refs[base]
        occ_out = refs[base + 1]
        pay_out = refs[base + 2 : base + 2 + width]
        et_out = refs[base + 2 + width] if has_etick else None

        b = pl.program_id(0)

        # the survival plane is revisited by every grid step (each step
        # writes its own segment); zero it once before the first
        @pl.when(b == 0)
        def _():
            surv_ref[:] = jnp.zeros_like(surv_ref)

        # pass the rows through: untouched cells must survive the write-
        # back (the out block is a fresh VMEM buffer, not the input)
        occ_out[:] = occ_in[:]
        for w in range(width):
            pay_out[w][:] = pay_in[w][:]
        if has_etick:
            et_out[:] = et_in[:]

        lo = starts_ref[b]
        hi = starts_ref[b + 1]
        tick = t_ref[0]

        def body(j, carry):
            prev_key, next_slot = carry
            key = sk_ref[0, j]
            dstj = key - b * n

            def fresh(_):
                # new (bucket, dst) run: rank restarts at the bucket's
                # pre-tick fill for this dst — read straight from the
                # PRE-update occupancy row (the in block), replacing the
                # XLA path's derived fill table + 200k-lane base gather
                if not stacking:
                    return jnp.int32(0)
                acc = jnp.int32(0)
                for s in range(slots):
                    acc += (occ_in[0, s * n + dstj] != 0).astype(jnp.int32)
                return acc

            slot = jax.lax.cond(
                key != prev_key, fresh, lambda _: next_slot, None
            )

            @pl.when(slot < slots)
            def _():
                # one traversal writes EVERY plane at this position —
                # the fusion the XLA path pays three scalar-core loops
                # for (positions are slot-major: pos = slot·N + dst)
                pos = slot * n + dstj
                if occ_bool:
                    occ_out[0, pos] = occv_ref[0, j] != 0
                else:
                    occ_out[0, pos] = occv_ref[0, j]
                for w in range(width):
                    pay_out[w][0, pos] = pay_refs[w][0, j]
                if has_etick:
                    et_out[0, pos] = tick
                surv_ref[0, j] = 1

            return key, slot + 1

        jax.lax.fori_loop(lo, hi, body, (jnp.int32(-1), jnp.int32(0)))

    stream_spec = pl.BlockSpec(memory_space=pltpu.VMEM)

    def row_spec():
        return pl.BlockSpec((1, ns), lambda b, *_: (b, 0))

    n_rows = 1 + width + n_et
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(horizon,),
        in_specs=[stream_spec] * (2 + width)
        + [row_spec() for _ in range(n_rows)],
        out_specs=[stream_spec] + [row_spec() for _ in range(n_rows)],
    )
    out_shape = [jax.ShapeDtypeStruct((1, m2), jnp.int32)]
    out_shape.append(jax.ShapeDtypeStruct((horizon, ns), occ_dtype))
    out_shape += [
        jax.ShapeDtypeStruct((horizon, ns), jnp.int32) for _ in range(width)
    ]
    if has_etick:
        out_shape.append(jax.ShapeDtypeStruct((horizon, ns), jnp.int32))
    # operand index of the first plane input: 2 prefetch + (2 + W) stream
    first_plane = 4 + width
    aliases = {first_plane + i: 1 + i for i in range(n_rows)}
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )


def commit_calendar(
    cal,
    sk: jax.Array,  # [m2] int32, sorted keys (bucket·n + dst; big = invalid)
    occ_vals: jax.Array,  # [m2] int32 occupancy marks (src+1, or 1)
    pay_sorted,  # W × [m2] int32, sorted alongside sk
    t: jax.Array,
    *,
    stacking: bool = True,
):
    """Commit one tick's sorted message stream into the calendar planes.

    Returns ``(cal', survived)`` with ``survived`` a [m2] int32 0/1 mask
    in SORTED order — 1 exactly where the XLA path's ``val_s`` (valid ∧
    rank < SLOTS) holds, so flow counters and fate mapping stay exact.
    Requires the 2-D plane layout (``cal.flat`` False)."""
    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    slots = cal.slots
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    n = ns // slots
    m2 = int(sk.shape[0])
    track_src = cal.src is not None
    has_etick = cal.etick is not None

    # bucket b's sorted segment is [starts[b], starts[b+1]); invalid
    # messages carry key = horizon·n and fall past starts[horizon]
    starts = jnp.searchsorted(
        sk, jnp.arange(horizon + 1, dtype=jnp.int32) * jnp.int32(n)
    ).astype(jnp.int32)
    tvec = jnp.reshape(jnp.asarray(t, jnp.int32), (1,))

    call = _commit_call(
        horizon,
        n,
        slots,
        width,
        m2,
        track_src,
        has_etick,
        bool(stacking),
        occ.dtype == jnp.bool_,
        pallas_interpret(),
    )
    # message-stream operands ride as [1, m2] rows (TPU-friendly 2-D)
    args = [starts, tvec, sk[None, :], occ_vals[None, :]]
    args += [p[None, :] for p in pay_sorted]
    args.append(occ)
    args += list(cal.payload)
    if has_etick:
        args.append(cal.etick)
    out = call(*args)
    survived = out[0][0]
    new_occ = out[1]
    new_payload = tuple(out[2 : 2 + width])
    new_etick = out[2 + width] if has_etick else None
    cal = dataclasses.replace(
        cal,
        payload=new_payload,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
        etick=new_etick,
    )
    return cal, survived


@functools.lru_cache(maxsize=64)
def _pop_call(
    horizon: int, ns: int, width: int, occ_bool: bool, interpret: bool
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    occ_dtype = jnp.bool_ if occ_bool else jnp.int32

    def kernel(*refs):
        # refs: b_ref, occ_in, pay_in×W, occ_out, row_occ, row_pay×W
        occ_in = refs[1]
        pay_in = refs[2 : 2 + width]
        occ_out = refs[2 + width]
        row_occ = refs[3 + width]
        row_pay = refs[4 + width : 4 + 2 * width]
        row = occ_in[:]
        row_occ[:] = row  # pop ...
        occ_out[:] = jnp.zeros_like(row)  # ... and clear, one traversal
        for w in range(width):
            row_pay[w][:] = pay_in[w][:]

    def row_spec():
        return pl.BlockSpec((1, ns), lambda i, b: (b[0], 0))

    full_row = pl.BlockSpec(memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[row_spec() for _ in range(1 + width)],
        out_specs=[row_spec()] + [full_row] * (1 + width),
    )
    out_shape = [jax.ShapeDtypeStruct((horizon, ns), occ_dtype)]
    out_shape.append(jax.ShapeDtypeStruct((1, ns), occ_dtype))
    out_shape += [
        jax.ShapeDtypeStruct((1, ns), jnp.int32) for _ in range(width)
    ]
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases={1: 0},  # occupancy plane updated in place
        interpret=interpret,
    )


def pop_bucket(cal, t: jax.Array):
    """Pop the bucket arriving at tick ``t``: returns ``(cal', occ_row,
    pay_rows)`` with the rows as [N·SLOTS] vectors and the occupancy row
    cleared in the returned calendar (payload stays stale-but-masked,
    exactly like the XLA ``deliver``)."""
    assert not cal.flat, "pallas transport requires 2-D calendar planes"
    width = cal.width
    occ = cal.occupancy_plane
    horizon, ns = occ.shape
    bvec = jnp.reshape(
        jnp.mod(jnp.asarray(t, jnp.int32), horizon), (1,)
    )
    call = _pop_call(
        horizon, ns, width, occ.dtype == jnp.bool_, pallas_interpret()
    )
    out = call(bvec, occ, *cal.payload)
    new_occ = out[0]
    occ_row = out[1][0]
    pay_rows = [r[0] for r in out[2 : 2 + width]]
    track_src = cal.src is not None
    cal = dataclasses.replace(
        cal,
        src=new_occ if track_src else None,
        valid=None if track_src else new_occ,
    )
    return cal, occ_row, pay_rows
