"""Sim telemetry plane: host-side half of the per-tick counter block.

The device-side half lives in the jitted tick (``sim/engine.py``): every
tick appends one fixed-shape int32 counter vector to the chunk's scan
output, so a CHUNK-tick dispatch returns a ``[chunk, K]`` block alongside
the carry and the ``done`` flag. The host flushes that block once per
chunk, piggybacking on the done-flag poll it already performs — the chunk
result is materialized by the time the done scalar is host-visible, so
reading the block is a device→host copy, **not** an additional blocking
sync (the ``engine._poll_done`` contract; tests count its calls).

This module owns everything about the block the host needs to agree on
with the device: the column schema, the row decoding, and the run-span
tracer that wraps the host-side phases (run → build → compile → chunk[i]
→ collect) in ``sdk/events.py``-style JSON lines.

Reference lineage: the counter rows are the sim analog of the runtime
metric batches the reference ships to InfluxDB (``pkg/metrics/viewer.go``
measurement tables); the span lines are the task-timeline events the
reference scatters across daemon logs, made structured.
"""

from __future__ import annotations

import json
import time
from typing import Any, Iterable

__all__ = [
    "LATENCY_BINS",
    "LATENCY_FILE",
    "NETMATRIX_FILE",
    "PERF_FILE",
    "PHASES_FILE",
    "SIM_SERIES_FILE",
    "SPAN_FILE",
    "TELEMETRY_FIXED_COLUMNS",
    "SpanTracer",
    "iter_jsonl",
    "latency_bin_edges",
    "latency_percentiles",
    "rows_from_blocks",
    "telemetry_totals",
]

# Per-run output file names (under <outputs>/<plan>/<run_id>/).
SIM_SERIES_FILE = "sim_timeseries.jsonl"
SPAN_FILE = "run_spans.jsonl"
# Per-group delivery-latency summary rows (viewer-shaped: run/plan/case/
# tick/group_id/name + count/mean/min/max) — the ``sim.latency.*``
# measurement family the dashboard and the Influx mirror consume.
LATENCY_FILE = "sim_latency.jsonl"
# Per-chunk performance-ledger rows (sim/perf.py: dispatch wall, ticks/s,
# peer·ticks/s, achieved FLOP/s and bytes/s, device bytes-in-use) — the
# ``sim.perf.*`` measurement family.
PERF_FILE = "sim_perf.jsonl"
# Per-phase tick attribution rows (sim/phases.py: per-phase XLA cost
# analysis + optional measured ms/tick, one row per phase plus the
# residual and whole-program rows) — the ``tg perf --phases`` backend.
PHASES_FILE = "sim_phases.jsonl"
# Per-chunk traffic-matrix deltas (sim/netmatrix.py: sparse nonzero
# src-group × dst-group cells per chunk) — the ``sim.netmatrix.*``
# measurement family and the ``tg netmap`` backend.
NETMATRIX_FILE = "sim_netmatrix.jsonl"

# Delivery-latency histogram schema, shared by the device accumulator
# (``sim/net.py::latency_histogram``) and every host-side consumer. Bins
# are log2-spaced in TICKS: bin b counts deliveries whose (delivery tick
# - enqueue tick) lies in [2^b, 2^(b+1)); the LAST bin is open-ended
# (delays past 2^(LATENCY_BINS-1) ticks clamp into it). Fixed and
# log-spaced so the device-side cost is a handful of compares per
# delivered message and the host can estimate stable p50/p95/p99 without
# per-message state — the shape every serving/training stack converges
# on for cheap always-on latency observability.
LATENCY_BINS = 12


def latency_bin_edges() -> tuple[int, ...]:
    """Lower edge (inclusive, in ticks) of each histogram bin."""
    return tuple(1 << b for b in range(LATENCY_BINS))


def latency_percentiles(
    hist, tick_ms: float, quantiles=(0.50, 0.95, 0.99)
) -> dict:
    """Estimate latency quantiles in milliseconds from one group's bin
    counts (``[LATENCY_BINS]`` ints). Linear interpolation inside the
    hit bin (the standard histogram-quantile estimator); the open last
    bin is valued at its lower edge, so a tail that escaped the bin
    range under-reports rather than inventing precision. Returns
    ``{count, p50_ms, p95_ms, p99_ms}`` (``count`` only when empty)."""
    counts = [int(c) for c in hist]
    total = sum(counts)
    out: dict = {"count": total}
    if total == 0:
        return out
    edges = latency_bin_edges()
    cum = 0
    targets = [(q, q * total) for q in quantiles]
    ti = 0
    for b, c in enumerate(counts):
        prev = cum
        cum += c
        while ti < len(targets) and cum >= targets[ti][1]:
            q, rank = targets[ti]
            lo = float(edges[b])
            hi = float(edges[b] * 2) if b < LATENCY_BINS - 1 else lo
            frac = (rank - prev) / c if c else 0.0
            ticks = lo + frac * (hi - lo)
            out[f"p{int(q * 100)}_ms"] = round(ticks * tick_ms, 6)
            ti += 1
        if ti >= len(targets):
            break
    return out

# Fixed leading columns of the device-side counter vector, in order.
# Columns after these are one live-instance count per group (schema key
# ``live`` in the decoded row, a {group_id: count} map). A padding row
# (ticks scanned after global completion) carries tick = -1 and is
# dropped by the decoder.
#
#   tick            the tick this row describes (scan-local, absolute)
#   delivered       messages popped from the calendar into inboxes
#   sent            outbox messages entering the transport (duplicate-
#                   shaping copies count: conservation must close)
#   enqueued        messages actually scattered into the calendar
#   dropped         sent - enqueued - rejected (loss, DROP filters,
#                   bandwidth, inbox-slot overflow, bad dst)
#   rejected        messages suppressed by REJECT filters (fed back to
#                   senders next tick)
#   bytes_enqueued  enqueued × MSG_BYTES — the bandwidth-accounting wire
#                   bytes admitted onto links this tick
#   cal_depth       in-flight messages in the calendar AFTER this tick
#                   (cumulative enqueued - delivered; no O(L·N) rescan)
#   sync_signals    Σ of all sync state counters (barrier occupancy)
#   sync_pubs       Σ of stored topic-stream entries (publish occupancy)
#   faults_crashed  instances crashed by the fault plane this tick
#   faults_restarted  instances revived by a scheduled restart this tick
#   fault_dropped   messages killed by faults this tick: send-time kills
#                   (partition/flap windows, loss bursts, dead targets)
#                   plus in-flight messages purged by a crash — the term
#                   that closes flow conservation under chaos (sent =
#                   delivered + in-flight + dropped + rejected + this).
#                   All three are constant 0 without a fault schedule.
TELEMETRY_FIXED_COLUMNS = (
    "tick",
    "delivered",
    "sent",
    "enqueued",
    "dropped",
    "rejected",
    "bytes_enqueued",
    "cal_depth",
    "sync_signals",
    "sync_pubs",
    "faults_crashed",
    "faults_restarted",
    "fault_dropped",
)


def iter_jsonl(path: str) -> Iterable[dict]:
    """Tolerant jsonl reader shared by every observability consumer
    (viewer, trace reader, influx re-read): blank lines and unparseable
    lines — e.g. the partially-written tail of a still-streaming file —
    are skipped, IO errors end the stream. One implementation, so a
    future hardening cannot drift across surfaces."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue
    except OSError:
        return


def rows_from_blocks(blocks: Iterable, group_ids: tuple) -> list[dict]:
    """Decode flushed ``[chunk, K]`` counter blocks into jsonl-ready row
    dicts (fixed columns flat, per-group live counts nested under
    ``live``). Padding rows (tick < 0) are dropped."""
    nfix = len(TELEMETRY_FIXED_COLUMNS)
    rows: list[dict] = []
    for block in blocks:
        for vec in block:
            tick = int(vec[0])
            if tick < 0:  # post-completion padding inside the chunk
                continue
            row: dict[str, Any] = {
                name: int(vec[i])
                for i, name in enumerate(TELEMETRY_FIXED_COLUMNS)
            }
            row["live"] = {
                gid: int(vec[nfix + gi]) for gi, gid in enumerate(group_ids)
            }
            rows.append(row)
    return rows


def telemetry_totals(rows: list[dict]) -> dict[str, int]:
    """Sum the per-tick flow counters — what must equal the run's final
    ``results()`` cumulative totals (the acceptance invariant the smoke
    target and tests check)."""
    return {
        k: sum(int(r.get(k, 0)) for r in rows)
        for k in (
            "delivered",
            "sent",
            "enqueued",
            "dropped",
            "rejected",
            "fault_dropped",
        )
    }


class SpanTracer:
    """Structured run-span events as ``sdk/events.py``-style JSON lines.

    Every line is ``{"ts": <ns>, "event": {"type": ..., "span": ...}}``
    so ``sdk.events.parse_event_line`` reads them back. Types:

    - ``span_start`` / ``span_end`` — a named phase; ``span_end`` carries
      ``wall_secs`` plus any attrs given at close (e.g. the build span
      ends with ``carry_bytes``)
    - ``point`` — an instant event (per-chunk progress, compile timing)

    A ``SpanTracer(None)`` is a no-op sink so call sites need no
    conditionals; failures are swallowed (observability must never fail
    the run it observes).

    Every row carries the lifecycle-trace vocabulary (tracectx.py):
    ``trace_id`` (the task's trace when ``ctx`` is given, else a fresh
    one), a per-span ``span_id``, ``parent_id`` (the innermost open
    span, or the context's parent — the supervisor's execute span — at
    top level), and ``wall_ns``, so run spans and the archive-time
    lifecycle spans merge into one Perfetto timeline without post-hoc
    clock alignment.
    """

    def __init__(self, path: str | None, ctx: dict | None = None):
        from testground_tpu.tracectx import new_trace_id

        ctx = ctx or {}
        self._path = path
        self._f = None
        self._trace_id = ctx.get("trace_id") or new_trace_id()
        self._root_parent = ctx.get("parent_id", "")
        # span name -> (monotonic t0, span_id, parent_id); plus a stack
        # of open span names so children parent to the innermost span
        self._open: dict[str, tuple[float, str, str]] = {}
        self._stack: list[str] = []
        if path is not None:
            try:
                self._f = open(path, "a", encoding="utf-8")
            except OSError:
                self._f = None

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def _emit(self, event: dict) -> None:
        if self._f is None:
            return
        try:
            self._f.write(
                json.dumps({"ts": time.time_ns(), "event": event}) + "\n"
            )
            self._f.flush()
        except (OSError, ValueError):
            pass

    def _parent(self) -> str:
        if self._stack:
            rec = self._open.get(self._stack[-1])
            if rec is not None:
                return rec[1]
        return self._root_parent

    def start(self, span: str, **attrs) -> None:
        from testground_tpu.tracectx import new_span_id

        # durations come from the monotonic clock — a wall-clock step
        # (NTP slew, operator date change) mid-span must not produce a
        # negative or wildly wrong wall_secs; the emitted line keeps the
        # wall-clock ts for cross-host correlation
        parent = self._parent()
        sid = new_span_id()
        self._open[span] = (time.monotonic(), sid, parent)
        self._stack.append(span)
        self._emit(
            {
                "type": "span_start",
                "span": span,
                "trace_id": self._trace_id,
                "span_id": sid,
                "parent_id": parent,
                "wall_ns": time.time_ns(),
                **attrs,
            }
        )

    def end(self, span: str, **attrs) -> None:
        rec = self._open.pop(span, None)
        sid = parent = ""
        if rec is not None:
            t0, sid, parent = rec
            attrs.setdefault(
                "wall_secs", round(time.monotonic() - t0, 6)
            )
            for i in range(len(self._stack) - 1, -1, -1):
                if self._stack[i] == span:
                    del self._stack[i]
                    break
        self._emit(
            {
                "type": "span_end",
                "span": span,
                "trace_id": self._trace_id,
                "span_id": sid,
                "parent_id": parent,
                "wall_ns": time.time_ns(),
                **attrs,
            }
        )

    def point(self, name: str, **attrs) -> None:
        from testground_tpu.tracectx import new_span_id

        self._emit(
            {
                "type": "point",
                "span": name,
                "trace_id": self._trace_id,
                "span_id": new_span_id(),
                "parent_id": self._parent(),
                "wall_ns": time.time_ns(),
                **attrs,
            }
        )

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None
