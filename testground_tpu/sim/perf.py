"""Run performance ledger: compile/memory/FLOP accounting and
throughput gauges (docs/OBSERVABILITY.md "Performance ledger").

The host-side half of the perf plane. The engine (``sim/engine.py``)
calls two hooks on a :class:`PerfLedger`:

- ``on_compile(lower_secs, compile_secs, compiled)`` — once, from the
  AOT lower/compile pass the run loop performs before its first
  dispatch when a ledger is attached. The split is the true
  trace/lower vs XLA-compile breakdown (the journal's ``compile_secs``
  lumps init + first dispatch), and the ``compiled`` object is
  harvested for ``cost_analysis()`` / ``memory_analysis()`` — the
  estimated FLOPs, bytes accessed, and peak/temp/argument bytes of one
  tick-chunk program.
- ``on_chunk(index, ticks, ticks_delta, wall_secs)`` — once per chunk
  dispatch, with the host-clock wall of that dispatch. Each call
  becomes one ``sim_perf.jsonl`` row (ticks/s, peer·ticks/s, achieved
  FLOP/s and bytes/s against the cost-analysis estimates, device
  bytes-in-use where the backend exposes memory stats).

Everything here is host-side bookkeeping riding state the run loop
already has: the ledger shapes NO part of the compiled program (pinned
by jaxpr equality in tests) and adds NO device→host syncs beyond the
per-chunk done poll (``engine._poll_done``; tests count calls). Like
every observability writer, the ledger never fails the run it observes.

``bench.py`` emits the same ledger schema (``compile``/``execute``
blocks) so ad-hoc bench runs and framework runs are directly
comparable, and ``perf_compare`` diffs a task's ledger against a
``BENCH_rNN.json`` line or a prior ``tg perf --json`` dump.
"""

from __future__ import annotations

import json
import math
from typing import Any

# the comparison codepath moved to the cross-run analysis plane
# (analysis/diff.py — stdlib-only, shared with `tg diff` and the bench
# sentinel); these names re-export so every historical import site
# (`from testground_tpu.sim.perf import perf_compare`) keeps working
# and there is exactly ONE implementation
from testground_tpu.analysis.diff import (  # noqa: F401 — re-exports
    extract_ledger_metrics as _extract_metrics,
    fmt_rate,
    num,
    perf_compare,
)

# the writer-owned file-name constant lives beside its siblings
# (SIM_SERIES_FILE / SPAN_FILE / LATENCY_FILE) in sim/telemetry.py
from .telemetry import PERF_FILE

__all__ = [
    "PERF_FILE",
    "PerfLedger",
    "compile_analysis",
    "cost_analysis_dict",
    "device_memory_stats",
    "fmt_rate",
    "memory_analysis_dict",
    "num",
    "perf_compare",
    "timed_lower_compile",
]


def device_memory_stats(device=None) -> dict:
    """The ONE device-memory probe (used by the runner healthcheck, the
    executor's capacity precheck, and the perf ledger's HBM sampling).

    Returns the backend's ``memory_stats()`` dict normalized to the keys
    consumers read — ``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit`` — keeping only those actually present (some
    platforms expose none, some a subset). Never raises: no backend, no
    device, or no stats all return ``{}``.
    """
    try:
        if device is None:
            import jax

            devs = jax.devices()
            if not devs:
                return {}
            device = devs[0]
        stats = getattr(device, "memory_stats", lambda: None)() or {}
        out = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            v = stats.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[key] = int(v)
        return out
    except Exception:  # noqa: BLE001 — observability never raises
        return {}


def cost_analysis_dict(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across jax versions and
    backends into ``{flops, bytes_accessed, transcendentals}`` (only the
    fields the backend actually estimates; XLA's keys carry spaces).
    Never raises; ``{}`` when the backend offers no estimate."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per module
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return {}
        out = {}
        for key, name in (
            ("flops", "flops"),
            ("bytes accessed", "bytes_accessed"),
            ("transcendentals", "transcendentals"),
        ):
            v = ca.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v) and v > 0:
                out[name] = float(v)
        return out
    except Exception:  # noqa: BLE001
        return {}


def memory_analysis_dict(compiled) -> dict:
    """Normalize ``compiled.memory_analysis()`` (a CompiledMemoryStats
    object, or None on some backends) into plain byte counts. Never
    raises; ``{}`` when unavailable."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return {}
        out = {}
        for attr, name in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes"),
        ):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)) and v >= 0:
                out[name] = int(v)
        if out:
            # the program's device-memory high-water estimate: arguments
            # + outputs + codegen + temporaries (what XLA reserves for
            # one execution, the per-program analog of the carry bytes)
            out["peak_bytes"] = (
                out.get("argument_bytes", 0)
                + out.get("output_bytes", 0)
                + out.get("temp_bytes", 0)
                + out.get("generated_code_bytes", 0)
            )
        return out
    except Exception:  # noqa: BLE001
        return {}


def compile_analysis(compiled) -> dict:
    """cost + memory analysis of one compiled chunk program, merged —
    the shared harvest used by the run ledger, the sim:plan precompile
    marker, and bench.py."""
    return {**cost_analysis_dict(compiled), **memory_analysis_dict(compiled)}


def timed_lower_compile(fn, *args) -> tuple:
    """Time ``fn.lower(*args)`` and ``.compile()`` separately; returns
    ``(lower_secs, compile_secs, compiled)`` — the argument order
    :meth:`PerfLedger.on_compile` takes. The ONE timed AOT accounting
    pass, shared by the run loop (``engine.run``), the ``sim:plan``
    precompile marker, and ``bench.py``'s warm-recompile split."""
    import time

    t0 = time.perf_counter()
    lowered = fn.lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    return t1 - t0, time.perf_counter() - t1, compiled


class PerfLedger:
    """Per-run performance ledger (see module docstring).

    Streams one jsonl row per chunk dispatch to ``path`` (``None`` only
    counts — the telemetry-writer rule), aggregates host-side, and
    renders the ``journal["sim"]["perf"]`` block via :meth:`summary`.
    ``aot=False`` skips the lower/compile pass entirely — the executor
    passes it when the persistent compile cache is disabled, where the
    AOT pass would force a full second XLA compile instead of a cache
    read.
    """

    def __init__(
        self,
        instances: int,
        chunk: int,
        ident: dict | None = None,
        path: str | None = None,
        aot: bool = True,
        warmup: int = 1,
        transport: str = "xla",
        bucket: int | None = None,
    ):
        # ``instances`` is the EXACT live count — never the padded
        # bucket size: every ticks/s → peer·ticks/s normalization below
        # divides real work done for real tenants, so a padded or
        # packed run can never report inflated throughput (the bucket
        # size rides beside it as an annotation).
        self.instances = int(instances)
        self.bucket = int(bucket) if bucket else None
        self.chunk = int(chunk)
        # per-backend tag (ISSUE 5): every jsonl row and the summary
        # name the transport backend the measured program compiled with,
        # so xla-vs-pallas A/B ledgers are never cross-attributed by
        # `tg perf --compare` or the bench trajectory
        self.transport = str(transport or "xla")
        # dispatches excluded from the steady_* window: the first carries
        # trace + compile everywhere; under a multi-device mesh the
        # SECOND retraces at the GSPMD sharding fixed point (see
        # engine.run's compile_secs comment), so the executor passes 2
        # there — otherwise that recompile lands in steady throughput
        # and `--compare` reports phantom regressions
        self.warmup = max(0, int(warmup))
        self.ident = dict(ident or {})
        self.path = path
        self.wants_aot = bool(aot)
        self.rows_written = 0
        self._compile: dict = {}
        self._chunk_walls: list[float] = []
        self._ticks = 0
        self._hbm_peak = 0
        self._hbm_limit = 0
        self._f = None
        if path is not None:
            try:
                self._f = open(path, "w")
            except OSError:  # observe best-effort, never fail the run
                self.path = None

    # ------------------------------------------------------------- hooks

    def on_compile(self, lower_secs: float, compile_secs: float, compiled) -> None:
        self._compile = {
            "lower_secs": round(float(lower_secs), 6),
            "compile_secs": round(float(compile_secs), 6),
            **compile_analysis(compiled),
        }

    def on_chunk(
        self, index: int, ticks: int, ticks_delta: int, wall_secs: float
    ) -> None:
        wall = max(float(wall_secs), 1e-9)
        self._chunk_walls.append(wall)
        self._ticks = int(ticks)
        row: dict[str, Any] = {
            "tick": int(ticks),
            "chunk": int(index),
            "transport": self.transport,
            "wall_secs": round(wall, 6),
            "ticks_per_sec": round(ticks_delta / wall, 3),
            "peer_ticks_per_sec": round(
                self.instances * ticks_delta / wall, 3
            ),
        }
        if self.bucket:
            row["bucket"] = self.bucket
        flops = self._compile.get("flops")
        if flops:
            # achieved rate of the ESTIMATED per-chunk work — how fast
            # the hardware retired what XLA predicted the chunk costs
            row["flops_per_sec"] = round(flops / wall, 3)
        bytes_acc = self._compile.get("bytes_accessed")
        if bytes_acc:
            row["bytes_per_sec"] = round(bytes_acc / wall, 3)
        mem = device_memory_stats()
        if "bytes_in_use" in mem:
            row["bytes_in_use"] = mem["bytes_in_use"]
        self._hbm_peak = max(
            self._hbm_peak,
            mem.get("peak_bytes_in_use", 0),
            mem.get("bytes_in_use", 0),
        )
        self._hbm_limit = mem.get("bytes_limit", self._hbm_limit)
        self.rows_written += 1
        if self._f is not None:
            try:
                self._f.write(json.dumps({**self.ident, **row}) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                self.path = None

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                self.path = None
            finally:
                self._f = None

    # ----------------------------------------------------------- summary

    def summary(self) -> dict:
        """The ``sim.perf`` journal block. ``execute.wall_secs`` is the
        sum of per-chunk dispatch walls (what the jsonl rows must sum
        to); ``steady_*`` excludes the ``warmup`` leading dispatches,
        which carry trace + compile (or the persistent-cache read) and,
        on a mesh, the sharding fixed-point retrace."""
        out: dict[str, Any] = {
            "instances": self.instances,
            "chunk": self.chunk,
            "transport": self.transport,
        }
        if self.bucket:
            out["bucket"] = self.bucket
        if self._compile:
            out["compile"] = dict(self._compile)
        if self._chunk_walls:
            wall = sum(self._chunk_walls)
            ex: dict[str, Any] = {
                "chunks": len(self._chunk_walls),
                "ticks": self._ticks,
                "wall_secs": round(wall, 6),
                "ticks_per_sec": round(self._ticks / wall, 3),
                "peer_ticks_per_sec": round(
                    self.instances * self._ticks / wall, 3
                ),
            }
            steady = self._chunk_walls[self.warmup :]
            if steady:
                s_wall = sum(steady)
                s_ticks = len(steady) * self.chunk
                ex["steady_chunks"] = len(steady)
                ex["steady_wall_secs"] = round(s_wall, 6)
                ex["steady_ticks_per_sec"] = round(s_ticks / s_wall, 3)
                ex["steady_peer_ticks_per_sec"] = round(
                    self.instances * s_ticks / s_wall, 3
                )
                flops = self._compile.get("flops")
                if flops:
                    ex["est_flops_per_sec"] = round(
                        flops * len(steady) / s_wall, 3
                    )
                bytes_acc = self._compile.get("bytes_accessed")
                if bytes_acc:
                    ex["est_bytes_per_sec"] = round(
                        bytes_acc * len(steady) / s_wall, 3
                    )
            out["execute"] = ex
        if self._hbm_peak:
            hbm = {"peak_bytes": self._hbm_peak}
            if self._hbm_limit:
                hbm["bytes_limit"] = self._hbm_limit
            out["hbm"] = hbm
        series: dict[str, Any] = {"rows": self.rows_written}
        if self.path is not None:
            series["file"] = PERF_FILE
        out["series"] = series
        return out


# --------------------------------------------------------------- compare
# `perf_compare` / `_extract_metrics` now live in analysis/diff.py
# (re-exported above): ONE comparison codepath shared with `tg diff`,
# output pinned compatible by tests/test_sim_perf.py.
