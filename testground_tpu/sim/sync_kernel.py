"""In-sim coordination: the sync service as on-device tensors.

TPU-native twin of the Redis-backed sync service (SURVEY.md §2.6): state
counters and topic streams are device arrays updated once per tick from the
vmapped step outputs — a barrier round-trip that costs a Redis RTT in the
reference costs one reduction here.

- ``SignalEntry(state)``  → counter += Σ signals; the 1-based sequence is the
  pre-tick count plus this instance's rank among same-tick signallers
  (``jnp.cumsum`` prefix over the instance axis — deterministic, matching
  the reference's atomic-increment ordering up to same-instant ties)
- ``Barrier/SignalAndWait`` → plans compare ``counts[state] >= target``
- ``Publish``             → append to a bounded per-topic stream in instance
  order (every subscriber sees every entry, in one global order)
- ``Subscribe``           → per-instance read cursors; the engine serves a
  SUB_K-entry window past the cursor each tick

Layout note (see ``net.py``): per-instance arrays keep the N axis minor —
``last_seq`` is [S, N], and [N, S]-shaped step outputs are transposed once
before the arithmetic so the hot reductions run on unpadded tiles.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = [
    "SyncState",
    "live_per_group",
    "make_sync_state",
    "make_sub_window",
    "sync_occupancy",
    "update_sync",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SyncState:
    """counts:    [S] int32 — per-state counter values
    last_seq:    [S, N] int32 — per-instance latest SignalEntry result
    stream:      [T, CAP, PW] int32 — per-topic append-only payload log
    stream_len:  [T] int32
    cursors:     [T, N] int32 — per-instance per-topic read positions
    dropped:     [T] int32 — publishes lost to a full stream (surfaced in
                 the run journal; the reference would instead grow Redis)
    """

    counts: jax.Array
    last_seq: jax.Array
    stream: jax.Array
    stream_len: jax.Array
    cursors: jax.Array
    dropped: jax.Array


def make_sync_state(
    n: int, n_states: int, n_topics: int, cap: int, pub_width: int
) -> SyncState:
    return SyncState(
        counts=jnp.zeros((n_states,), jnp.int32),
        last_seq=jnp.zeros((n_states, n), jnp.int32),
        stream=jnp.zeros((n_topics, cap, pub_width), jnp.int32),
        stream_len=jnp.zeros((n_topics,), jnp.int32),
        cursors=jnp.zeros((n_topics, n), jnp.int32),
        dropped=jnp.zeros((n_topics,), jnp.int32),
    )


def update_sync(
    sync: SyncState,
    signals: jax.Array,  # [S, N] int32 0/1 (plane layout)
    pub_payload: jax.Array,  # [T, PW, N] int32
    pub_valid: jax.Array,  # [T, N] bool
    sub_consume: jax.Array,  # [T, N] int32
) -> SyncState:
    n = signals.shape[1]
    n_topics, cap, pw = sync.stream.shape

    # --- SignalEntry: counters + per-signaller sequence numbers; prefix
    # scans run along the unpadded minor (instance) axis.
    sig = signals
    prefix = jnp.cumsum(sig, axis=1)  # inclusive prefix per state
    seq = sync.counts[:, None] + prefix  # 1-based rank for signallers
    last_seq = jnp.where(sig > 0, seq, sync.last_seq)
    counts = sync.counts + jnp.sum(sig, axis=1)

    # --- Publish: stable append in instance order
    if n_topics > 0:
        pv = pub_valid.astype(jnp.int32)  # [T, N]
        offsets = sync.stream_len[:, None] + jnp.cumsum(pv, axis=1) - pv
        # Flat scatter into [T·CAP, PW]; overflow/invalid entries get unique
        # out-of-range indices (duplicate scatter indices would force XLA's
        # slow sort-based lowering — see net.enqueue).
        in_range = pub_valid & (offsets < cap)
        oob = jnp.int32(n_topics * cap) + jnp.arange(
            n_topics * n, dtype=jnp.int32
        ).reshape(n_topics, n)
        flat_idx = jnp.where(
            in_range,
            jnp.arange(n_topics, dtype=jnp.int32)[:, None] * cap + offsets,
            oob,
        )
        # updates in publish order: [T, PW, N] → [T·N, PW]
        upd = jnp.transpose(pub_payload, (0, 2, 1)).reshape(-1, pw)
        stream = (
            sync.stream.reshape(-1, pw)
            .at[flat_idx.reshape(-1)]
            .set(upd, mode="drop", unique_indices=True)
            .reshape(n_topics, cap, pw)
        )
        published = jnp.sum(pv, axis=1)
        stored = jnp.sum(in_range.astype(jnp.int32), axis=1)
        stream_len = jnp.minimum(sync.stream_len + published, cap)
        dropped = sync.dropped + (published - stored)
        # --- Subscribe: advance cursors (clamped to what exists)
        cursors = jnp.minimum(
            sync.cursors + jnp.maximum(sub_consume, 0),
            stream_len[:, None],
        )
    else:
        stream, stream_len, dropped, cursors = (
            sync.stream,
            sync.stream_len,
            sync.dropped,
            sync.cursors,
        )

    return SyncState(
        counts=counts,
        last_seq=last_seq,
        stream=stream,
        stream_len=stream_len,
        cursors=cursors,
        dropped=dropped,
    )


def live_per_group(status: jax.Array, groups) -> jax.Array:
    """[G] int32 — RUNNING instances per group at this instant: the sync
    service's **live membership view**, the degraded-barrier denominator.

    The reference's Redis barriers wait on a fixed target and deadlock
    when a member dies mid-barrier; the cohort work taught the *host*
    side to fail fast on member death, and this extends the semantics
    into the sim's sync plane: the engine snapshots live counts at tick
    start (AFTER the tick's fault events fire) and serves them to every
    instance via ``SyncView.live``, so a plan writes its barrier as
    ``counts[s] >= jnp.sum(sync.live)`` and the target degrades the same
    tick an instance crashes — the run completes instead of hanging
    until ``max_ticks``. Instances that signalled before dying stay in
    ``counts`` (a Redis entry outlives its writer), which only makes the
    comparison easier to satisfy, never stuck. G small reductions over
    contiguous slices — safe every tick inside the jitted loop."""
    from .api import RUNNING

    return jnp.stack(
        [
            jnp.sum(
                (
                    status[g.offset : g.offset + g.count] == RUNNING
                ).astype(jnp.int32)
            )
            for g in groups
        ]
    )


def sync_occupancy(sync: SyncState) -> tuple[jax.Array, jax.Array]:
    """Scalar occupancy of the sync service for the telemetry plane:
    (Σ state counters — total signals ever fired, i.e. barrier
    occupancy; Σ stored topic-stream entries — publish occupancy).
    Two tiny reductions over [S] / [T] vectors, safe to take every tick
    inside the jitted loop."""
    return jnp.sum(sync.counts), jnp.sum(sync.stream_len)


def make_sub_window(
    sync: SyncState, sub_k: int
) -> tuple[jax.Array, jax.Array]:
    """Build each instance's next-SUB_K window into every topic stream.

    Returns (sub_payload [N, T, K, PW], sub_valid [N, T, K]).
    """
    n_topics, n = sync.cursors.shape
    _, cap, pw = sync.stream.shape
    if n_topics == 0:
        return (
            jnp.zeros((n, 0, sub_k, pw), jnp.int32),
            jnp.zeros((n, 0, sub_k), bool),
        )
    # idx [T, N, K]
    idx = sync.cursors[:, :, None] + jnp.arange(sub_k, dtype=jnp.int32)
    valid = idx < sync.stream_len[:, None, None]
    idx_c = jnp.clip(idx, 0, cap - 1)
    # gather stream[t, idx[t,n,k]] → [T, N, K, PW]
    payload = sync.stream[
        jnp.arange(n_topics, dtype=jnp.int32)[:, None, None], idx_c
    ]
    return (
        jnp.transpose(payload, (1, 0, 2, 3)),
        jnp.transpose(valid, (1, 0, 2)),
    )
