"""Mesh placement plane: THE one partition decision for sharded runs.

Every mesh gate in the serving plane — bucket resolution, pack
admission, the sharded Pallas commit, and the transport cost model —
used to make its own single-device-only call. This module replaces
those with ONE explicit rule table (the EasyLM ``match_partition_rules``
idiom: regex on a logical leaf path → :class:`PartitionSpec`), consumed
by all four:

* the engine's carry constraint (``SimProgram._constrain``) resolves
  every carry plane through :meth:`MeshPlan.spec_for`;
* ``resolve_buckets`` accepts a mesh exactly when every rung's padded
  group count divides across the ``i`` (peers) shards
  (:func:`indivisible_counts`);
* ``PackRunner`` maps the pack run axis per the table (replicated, or
  ``runs``-sharded on a 2-D mesh) via ``spec_for(..., lead=...)``;
* ``decide_transport`` scores mesh arms from
  :func:`cross_shard_bytes_est` instead of refusing, and the mesh
  layout string (:func:`layout_str`) keys its decision cache and the
  precompile BuildKey.

Axis conventions: the instance (padded lane) axis shards on mesh axis
``"i"`` — the name the engine has always used — and a 2-D mesh adds a
leading ``"runs"`` axis for the pack run dimension. ``parse_mesh_shape``
accepts ``"4"`` (1-D, 4 peer shards) or ``"2x4"`` (2 run shards × 4
peer shards).

The table is deliberately tiny and total: the LAST rule is a match-all
mapping to replicated, so scalars, per-group states, sync counters and
every future carry leaf stay replicated unless a rule says otherwise.
"""

from __future__ import annotations

import dataclasses
import re
from collections.abc import Mapping
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "MeshPlan",
    "DEFAULT_RULES",
    "parse_mesh_shape",
    "make_mesh",
    "plan_for",
    "layout_str",
    "peer_shards",
    "indivisible_counts",
    "cross_shard_bytes_est",
]

# The rule table. First match wins; paths are the engine's logical
# carry-plane names (NOT jax keystr output — the engine resolves each
# plane it constrains by name, so the table survives dataclass
# refactors). Axis position is encoded in the spec itself: a calendar
# plane is [L, slots*N] so the instance axis is axis 1; link rules are
# [R, F, N] so it is axis 2.
DEFAULT_RULES: tuple[tuple[str, str, P], ...] = (
    # per-lane status rows: [N_lanes]
    ("instance-rows", r"^(status|finished_at|rejected)$", P("i")),
    # calendar planes: [L, slots*N] (payload tuple members included)
    ("calendar-planes", r"^cal\.(payload(\.\d+)?|src|valid|etick)$", P(None, "i")),
    # link lane planes: [E, N] egress targets / filters
    ("link-lane-planes", r"^link\.(egress|filters)$", P(None, "i")),
    # link per-node rows: [N]
    ("link-node-rows", r"^link\.(region_of|backlog)$", P("i")),
    # link shaping rules: [R, F, N]
    ("link-rules", r"^link\.rules$", P(None, None, "i")),
    # everything else — scalars, per-group states, sync state, flow
    # accumulators, histograms — is replicated
    ("replicated", r".*", P()),
)


def parse_mesh_shape(text: str) -> tuple[int, ...]:
    """``"4"`` → ``(4,)``; ``"2x4"`` → ``(2, 4)``. 1-D is (peers,);
    2-D is (runs, peers). Anything else refuses loudly."""
    parts = str(text).lower().replace("×", "x").split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"mesh shape {text!r} is not N or AxB (e.g. '4' or '2x4')"
        ) from None
    if not (1 <= len(dims) <= 2) or any(d < 1 for d in dims):
        raise ValueError(
            f"mesh shape {text!r} must be 1-D (peers) or 2-D (runs x peers) "
            "with positive extents"
        )
    return dims


def mesh_axis_names(ndim: int) -> tuple[str, ...]:
    return ("i",) if ndim == 1 else ("runs", "i")


def make_mesh(
    shape: Sequence[int] | str | None = None,
    *,
    devices: Sequence[Any] | None = None,
) -> Mesh | None:
    """Build the serving mesh, or None for a single device.

    With ``shape=None`` every visible device lands on a 1-D ``("i",)``
    mesh (the historical ``shard=true`` behavior). An explicit shape
    must multiply out to a device count we actually have; fewer than
    all devices is fine (bench rungs pin 4 of 8 virtual devices).
    """
    if isinstance(shape, str):
        shape = parse_mesh_shape(shape)
    elif isinstance(shape, int):
        # `--run-cfg mesh=4` coalesces as a bare int (the run-config
        # layer does not coerce scalars to the declared field type)
        shape = (int(shape),)
    devs = list(devices if devices is not None else jax.devices())
    if shape is None:
        if len(devs) <= 1:
            return None
        return Mesh(np.asarray(devs), ("i",))
    need = int(np.prod(shape))
    if need == 1:
        return None
    if need > len(devs):
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {need} devices, "
            f"only {len(devs)} visible"
        )
    arr = np.asarray(devs[:need]).reshape(tuple(shape))
    return Mesh(arr, mesh_axis_names(len(shape)))


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the partition-rule table resolved against it.

    ``spec_for(path)`` is the ONE placement query: every consumer —
    engine constraint, pack stacking, pallas shard_map specs, journal
    rendering — resolves leaf placement through it.
    """

    mesh: Mesh
    rules: tuple[tuple[str, str, P], ...] = DEFAULT_RULES

    @property
    def shards(self) -> int:
        """Extent of the instance (``i``) axis."""
        return int(self.mesh.shape["i"])

    @property
    def runs(self) -> int:
        """Extent of the pack run axis (1 when the mesh is 1-D)."""
        return int(self.mesh.shape.get("runs", 1))

    @property
    def devices(self) -> int:
        return int(self.mesh.devices.size)

    def spec_for(
        self,
        path: str,
        *,
        lead: str | None = None,
        ndim: int | None = None,
    ) -> P:
        """Resolve a logical carry path to its PartitionSpec.

        ``lead`` prepends an axis for stacked (packed) carries: the
        pack run axis maps to the ``runs`` mesh axis when the mesh has
        one, else it is replicated — per the same table discipline, one
        decision for every stacked leaf. ``ndim`` clamps the spec to
        the leaf's actual rank (keeping the LEADING entries): a FLAT
        calendar plane folds [L, slots·N] into one axis whose slot-
        major positions admit no aligned instance slicing, so only the
        leading (run-axis) constraint survives and GSPMD places the
        rest.
        """
        for _name, pat, spec in self.rules:
            if re.match(pat, path):
                break
        else:  # unreachable: DEFAULT_RULES ends in a match-all
            spec = P()
        if lead is not None:
            lead_axis = lead if lead in self.mesh.shape else None
            spec = P(lead_axis, *tuple(spec))
        if ndim is not None and len(tuple(spec)) > ndim:
            spec = P(*tuple(spec)[:ndim])
        return spec

    def sharding_for(
        self,
        path: str,
        *,
        lead: str | None = None,
        ndim: int | None = None,
    ) -> NamedSharding:
        return NamedSharding(
            self.mesh, self.spec_for(path, lead=lead, ndim=ndim)
        )

    def layout_table(self) -> list[dict[str, str]]:
        """The rule table in journal form — stable, human-diffable."""
        return [
            {"rule": name, "path": pat, "spec": _spec_str(spec)}
            for name, pat, spec in self.rules
        ]


def _spec_str(spec: P) -> str:
    parts = []
    for ax in tuple(spec):
        if ax is None:
            parts.append("-")
        elif isinstance(ax, (tuple, list)):
            parts.append("+".join(str(a) for a in ax))
        else:
            parts.append(str(ax))
    return "(" + ",".join(parts) + ")" if parts else "replicated"


def plan_for(mesh: Mesh | None) -> MeshPlan | None:
    return None if mesh is None else MeshPlan(mesh)


def layout_str(mesh: Mesh | None) -> str:
    """Canonical mesh layout key — ``"1"`` single device, ``"4"`` 1-D,
    ``"2x4"`` 2-D — used by the transport decision cache, the
    precompile BuildKey, bench bank rows, and metric labels. The label
    space is bounded by real hardware topologies."""
    if mesh is None:
        return "1"
    shape = getattr(mesh, "shape", None)
    if not isinstance(shape, Mapping):  # `tg check` device-count stand-in
        return str(int(mesh.devices.size))
    if "runs" in shape:
        return f"{int(shape['runs'])}x{int(shape['i'])}"
    return str(int(shape["i"]))


def peer_shards(mesh: Any) -> int:
    """Extent of the instance (``i``) axis, duck-type safe: `tg check`
    probes the bucket gate with a stand-in object exposing only
    ``devices.size`` (a real Mesh is not constructible offline), so
    fall back to the device count — correct for every 1-D mesh, which
    is all a stand-in models."""
    if mesh is None:
        return 1
    shape = getattr(mesh, "shape", None)
    if isinstance(shape, Mapping) and "i" in shape:
        return int(shape["i"])
    return int(mesh.devices.size)


def indivisible_counts(
    counts: Sequence[int], shards: int
) -> tuple[int, ...]:
    """The padded group counts that do NOT divide across ``shards``
    peer shards — empty means the layout is supported. This is the
    whole divisibility contract: every sharded plane slices the padded
    instance axis into equal contiguous blocks, so each padded count
    (and their sum) must be a multiple of the shard count."""
    return tuple(int(c) for c in counts if int(c) % int(shards) != 0)


def cross_shard_bytes_est(
    *,
    stream_bytes: int,
    shards: int,
    payload_bytes_per_msg: int = 0,
) -> int:
    """Modeled per-commit ICI exchange traffic for the sharded Pallas
    commit: the sorted message stream is exchanged so every shard sees
    the messages addressed to its lane range (the all-gather IS the
    exchange stage — each shard receives the (shards-1)/shards fraction
    it does not already hold). ``payload_bytes_per_msg`` is already
    folded into ``stream_bytes`` by callers that know the width; the
    parameter exists so the transport model can itemize."""
    if shards <= 1:
        return 0
    del payload_bytes_per_msg  # itemization handled by callers
    return int(stream_bytes) * (int(shards) - 1) // int(shards)
