"""Measured cost model behind ``transport=auto`` (ISSUE 14 / ROADMAP
item 1): score xla-vs-pallas per program and pick the backend from data
instead of a hand-set knob.

``resolve_transport`` (``sim/executor.py``) stays THE one shared gate —
the executor, the sim-worker followers, the pack path, and the
``sim:plan`` precompile all call it — but since ISSUE 14 it delegates
here, so every consumer resolves ``auto`` identically and the decision
is a journaled, explainable record (``sim.transport {requested,
resolved, reason, scores}``) rather than a vibe. Evidence sources, in
strength order:

1. **banked chip verdicts** — ``tools/bench_pallas_transport.py`` JSON
   lines (``BENCH_PALLAS*.json`` beside the repo, or the
   ``TG_TRANSPORT_BANK`` file/dir) measured on THIS backend kind and
   THIS workload shape (plan/case, via the bench workload mapping). A
   real measurement of the real kernels beats any model; the nearest
   rung by instance count decides, with pallas flipping only past
   :data:`BANKED_RATIO_MARGIN` (one bench run carries real spread).
2. **opt-in measured probe** (``transport_probe = K`` in the runner
   config) — both candidate programs' transport phases (``deliver`` +
   ``net_commit``) jitted in isolation and timed K reps at the run's
   real shapes, the ``sim/phases.py`` calibration path. Off the hot
   path but costs two standalone compiles + 2K dispatches, so opt-in.
3. **static scoring** (the default) — the XLA arm's transport phases
   lowered standalone at the run's real shapes and their
   ``cost_analysis()`` bytes harvested (the phases-ledger machinery),
   against the segmented kernel's closed-form single-pass traffic
   model. Pallas wins only past :data:`PALLAS_BYTE_MARGIN` — the
   measured XLA bytes include the sort the pallas arm also pays, and
   the 1.08× chip margin history says a thin edge is one chip-lottery
   run from inverting, so the static path demands a wide one.

Hard gates precede all scoring: direct slot mode resolves to xla (no
sorted bucket ordering for the commit kernel to exploit), and a mesh
whose peer shards do NOT divide the lane count resolves to xla (the
sharded commit needs equal per-chip plane blocks — sim/meshplan.py).
A divisible mesh SCORES instead of refusing (ISSUE 20): the pallas arm
prices per-shard bytes plus the modeled ICI exchange traffic
(:func:`~testground_tpu.sim.meshplan.cross_shard_bytes_est` — the
sorted stream's all-gather before commit), the xla arm its per-shard
share of the measured scatter bytes. Banked verdicts and the measured
probe are single-device evidence, so mesh runs score statically until
meshed rungs are banked.

Decisions cache per build-key (the workload shape + every
program-shaping gate + backend + mesh layout), so the one-per-run
scoring cost is paid once per distinct program, like the precompile's
BuildKey.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os

__all__ = [
    "PALLAS_BYTE_MARGIN",
    "TRANSPORTS",
    "TransportContext",
    "TransportDecision",
    "clear_decision_cache",
    "decide_transport",
    "mesh_lanes_message",
]

TRANSPORTS = ("xla", "pallas", "auto")

# Static-scoring bar: pallas is chosen only when the XLA arm's measured
# transport bytes exceed the kernel's modeled single-pass traffic by
# this factor. The margin absorbs (a) the multi-operand sort, which the
# measured XLA phase includes but the pallas arm pays identically, and
# (b) model error headroom — PERF.md's 1.08× observation at 1M is the
# cautionary tale this knob exists for.
PALLAS_BYTE_MARGIN = 2.0

# Banked-verdict bar: a measured chip ratio flips the decision to
# pallas only past this factor — a single bench run carries the
# documented ±3-8% run-to-run spread (PERF.md), and the whole point of
# the data-driven gate is that a 1.0x-adjacent measurement is one
# chip-lottery run from inverting. Looser than the static margin
# because a real measurement of the real kernels is stronger evidence
# than a byte model.
BANKED_RATIO_MARGIN = 1.15

# the transport phases — the ops the kernels replace; everything else
# is identical between backends by construction
_TRANSPORT_PHASES = ("deliver", "net_commit")

# bench_pallas_transport workload name → the (plan, case) it measures:
# a banked verdict is only evidence for the workload SHAPE it was
# measured on (a sustained-pingpong win says nothing about storm's
# row-heavy fan-in)
_BENCH_WORKLOAD_PLANS = {
    "sustained": ("network", "pingpong-sustained"),
    "flood": ("benchmarks", "pingpong-flood"),
    "storm": ("benchmarks", "storm"),
}


@dataclasses.dataclass(frozen=True)
class TransportContext:
    """Workload context the cost model scores against — built by each
    gate call site AFTER specialization, so the statics are the run's
    real shapes. ``probe_reps`` > 0 opts into the measured probe."""

    testcase: object
    groups: tuple
    test_plan: str = "?"
    test_case: str = "?"
    tick_ms: float = 1.0
    chunk: int = 128
    telemetry: bool = False
    validate: bool = False
    hosts: tuple = ()
    probe_reps: int = 0


@dataclasses.dataclass
class TransportDecision:
    """One resolution of the transport knob: what was asked, what was
    chosen, why (human-readable — the ``tg stats`` pretty line), and
    the scores behind it (absent for explicit/forced choices)."""

    requested: str
    resolved: str
    reason: str
    scores: dict | None = None

    def block(self) -> dict:
        """The ``sim.transport`` journal block."""
        out = {
            "requested": self.requested,
            "resolved": self.resolved,
            "reason": self.reason,
        }
        if self.scores:
            out["scores"] = dict(self.scores)
        return out


_DECISION_CACHE: dict = {}


def clear_decision_cache() -> None:
    """Tests (and long-lived daemons that reload a plan) reset here."""
    _DECISION_CACHE.clear()


def _cache_key(context: TransportContext, backend: str, mesh=None):
    from .meshplan import layout_str

    cls = type(context.testcase)
    return (
        context.test_plan,
        context.test_case,
        tuple((g.id, g.count) for g in context.groups),
        cls.__name__,
        cls.OUT_MSGS,
        cls.IN_MSGS,
        cls.MSG_WIDTH,
        cls.MAX_LINK_TICKS,
        cls.SLOT_MODE,
        tuple(cls.SHAPING),
        bool(cls.CROSS_TICK_STACKING),
        int(context.chunk),
        bool(context.telemetry),
        bool(context.validate),
        tuple(context.hosts),
        int(context.probe_reps),
        backend,
        # the mesh layout shapes the sharded arms' costs AND the program
        # variant itself, so it keys the decision like the BuildKey
        layout_str(mesh),
    )


def mesh_lanes_message(requested: str, n_lanes: int, shards: int) -> str:
    """The indivisible-mesh fallback line — shared with the static
    checker (``sim/check.py`` reports it as
    ``transport.mesh-indivisible``), so the finding is the gate's warn
    string by construction."""
    return (
        f"transport={requested} on this mesh: {n_lanes} lane(s) do not "
        f"divide across {shards} peer shard(s) — the sharded commit "
        "needs equal per-chip plane blocks; resolving to the XLA "
        "transport (pad the instance counts, or pick a mesh whose "
        "shard count divides the lanes)"
    )


def decide_transport(cfg, mesh, context=None, warn=None) -> TransportDecision:
    """Resolve the runner-config ``transport`` knob into a backend.

    The single decision point behind ``resolve_transport``: validates
    the knob, applies the structural gates (indivisible mesh layout →
    xla, direct slots → xla), and for ``auto`` scores the candidates
    per the module docstring — on a divisible mesh the arms are priced
    per shard plus modeled ICI exchange traffic. ``warn`` is a
    ``(fmt, *args)`` callable for the loud fallbacks; ``context`` (a
    :class:`TransportContext`) is required for ``auto`` to score and
    for the mesh divisibility check — without one the gate falls back
    to xla (auto) or passes through to the engine's own divisibility
    backstop (explicit pallas), rather than guessing."""
    requested = str(getattr(cfg, "transport", "xla") or "xla").lower()
    if requested not in TRANSPORTS:
        raise ValueError(
            f"unknown transport {requested!r} in runner config: expected "
            "'xla', 'pallas', or 'auto' (--run-cfg transport=pallas)"
        )
    if requested == "xla":
        return TransportDecision(
            requested, "xla", "explicit runner-config choice (the default)"
        )
    if mesh is not None and context is not None:
        from .meshplan import peer_shards

        shards = peer_shards(mesh)
        n_lanes = _total_instances(context) + len(context.hosts)
        if shards > 1 and n_lanes % shards != 0:
            if warn is not None:
                warn("%s", mesh_lanes_message(requested, n_lanes, shards))
            return TransportDecision(
                requested,
                "xla",
                f"{n_lanes} lane(s) do not divide across {shards} peer "
                "shard(s) — the sharded commit needs equal per-chip "
                "plane blocks",
            )
    if requested == "pallas":
        return TransportDecision(
            requested, "pallas", "explicit runner-config choice"
        )

    # ------------------------------------------------------ transport=auto
    if context is None:
        if warn is not None:
            warn(
                "transport=auto needs workload context to score at this "
                "gate and none was provided — resolving to xla"
            )
        return TransportDecision(
            "auto", "xla", "no workload context at this gate"
        )
    import jax

    backend = jax.default_backend()
    key = _cache_key(context, backend, mesh)
    hit = _DECISION_CACHE.get(key)
    if hit is not None:
        return hit
    decision = _score(context, backend, mesh)
    _DECISION_CACHE[key] = decision
    return decision


# ---------------------------------------------------------------- scoring


def _score(
    context: TransportContext, backend: str, mesh=None
) -> TransportDecision:
    cls = type(context.testcase)
    if cls.SLOT_MODE != "sorted":
        return TransportDecision(
            "auto",
            "xla",
            "direct slot mode: no sorted bucket ordering for the commit "
            "kernel to exploit",
        )
    if mesh is not None:
        # banked verdicts and the probe measure the UNSHARDED arms —
        # under a mesh the static model is the only one that prices the
        # exchange stage, so score statically until meshed rungs bank
        return _static_decision(context, backend, mesh)

    banked = _banked_verdict(
        backend,
        _total_instances(context),
        context.test_plan,
        context.test_case,
    )
    if banked is not None:
        ratio = float(banked["pallas_vs_xla"])
        resolved = "pallas" if ratio >= BANKED_RATIO_MARGIN else "xla"
        return TransportDecision(
            "auto",
            resolved,
            "banked bench verdict: pallas_vs_xla "
            f"{ratio:.2f}x at {banked.get('instances', '?')} instances "
            f"on {backend} ({banked.get('file', '?')}; pallas needs "
            f">={BANKED_RATIO_MARGIN:g}x)",
            scores={
                "source": "banked",
                "margin": BANKED_RATIO_MARGIN,
                **banked,
            },
        )

    if int(context.probe_reps) > 0:
        return _measured_decision(context, backend)
    return _static_decision(context, backend)


def _total_instances(context: TransportContext) -> int:
    return sum(int(g.count) for g in context.groups)


def _build_candidate(context: TransportContext, transport: str):
    from .engine import SimProgram

    return SimProgram(
        context.testcase,
        context.groups,
        test_plan=context.test_plan,
        test_case=context.test_case,
        test_run="transport-auto",
        tick_ms=context.tick_ms,
        mesh=None,
        chunk=context.chunk,
        hosts=tuple(context.hosts),
        validate=bool(context.validate),
        telemetry=bool(context.telemetry),
        transport=transport,
    )


def _xla_transport_bytes(context: TransportContext) -> float | None:
    """Measured static cost of the ops the kernels replace: the XLA
    arm's ``deliver`` + ``net_commit`` phases lowered STANDALONE at the
    run's real shapes (``sim/phases.py`` machinery) and their
    cost-analysis bytes summed. None when the harvest yields nothing
    (backend without cost analysis) — the caller then refuses pallas
    rather than deciding on a zero."""
    from .phases import _phase_cost, phase_specs

    prog = _build_candidate(context, "xla")
    total = 0.0
    seen = False
    for name, fn, args in phase_specs(prog):
        if name not in _TRANSPORT_PHASES:
            continue
        cost = _phase_cost(fn, args)
        val = cost.get("bytes_accessed")
        if val:
            total += float(val)
            seen = True
    return total if seen else None


def _pallas_modeled_bytes(context: TransportContext) -> float:
    """Closed-form single-pass traffic of the segmented kernels at the
    run's shapes, in bytes/tick — the PERF.md envelope formula priced
    out: one streamed read of the (2+W)-plane sorted stream plus the
    survival write (tile-padded), worst-case every calendar bucket's
    row set read+written once by the commit, and the delivery pop's row
    traffic. Deliberately worst-case on the bucket count (every bucket
    touched every tick) so the model under-promises for pallas."""
    from .pallas_transport import commit_tile_words

    cls = type(context.testcase)
    n_lanes = _total_instances(context) + len(context.hosts)
    width = int(cls.MSG_WIDTH)
    slots = int(cls.IN_MSGS)
    horizon = int(cls.MAX_LINK_TICKS)
    etick = 1 if context.telemetry else 0
    m2 = cls.OUT_MSGS * n_lanes * (2 if "duplicate" in cls.SHAPING else 1)
    tile = commit_tile_words()
    m2p = -(-max(m2, 1) // tile) * tile
    ns = n_lanes * slots
    n_rows = 1 + width + etick
    commit_words = (2 + width) * m2p + m2p + horizon * n_rows * ns * 2
    pop_words = (3 + 2 * width) * ns
    return float((commit_words + pop_words) * 4)


def _stream_bytes_per_tick(context: TransportContext) -> int:
    """Bytes of the tile-padded sorted stream one commit consumes — the
    (2+W) int32 planes (key, occupancy value, payload words) the sharded
    arm all-gathers across peer shards before its per-shard walk. The
    input to :func:`~testground_tpu.sim.meshplan.cross_shard_bytes_est`."""
    from .pallas_transport import commit_tile_words

    cls = type(context.testcase)
    n_lanes = _total_instances(context) + len(context.hosts)
    m2 = cls.OUT_MSGS * n_lanes * (2 if "duplicate" in cls.SHAPING else 1)
    tile = commit_tile_words()
    m2p = -(-max(m2, 1) // tile) * tile
    return (2 + int(cls.MSG_WIDTH)) * m2p * 4


def _static_decision(
    context: TransportContext, backend: str, mesh=None
) -> TransportDecision:
    from .meshplan import cross_shard_bytes_est, layout_str, peer_shards

    xla_bytes = _xla_transport_bytes(context)
    if not xla_bytes:
        return TransportDecision(
            "auto",
            "xla",
            "no cost analysis available for the transport phases on "
            f"{backend} — keeping the XLA path",
            scores={"source": "static", "backend": backend},
        )
    pallas_bytes = _pallas_modeled_bytes(context)
    shards = peer_shards(mesh)
    exchange = 0
    if shards > 1:
        # mesh arms: both sides split their plane traffic across the
        # peer shards; the pallas arm additionally pays the modeled ICI
        # exchange (the sorted stream's all-gather before commit)
        exchange = cross_shard_bytes_est(
            stream_bytes=_stream_bytes_per_tick(context), shards=shards
        )
        xla_bytes = xla_bytes / shards
        pallas_bytes = pallas_bytes / shards + exchange
    ratio = xla_bytes / max(pallas_bytes, 1.0)
    resolved = "pallas" if ratio >= PALLAS_BYTE_MARGIN else "xla"
    reason = (
        f"commit+deliver bytes {ratio:.1f}x the single-pass kernel "
        f"estimate ({'clears' if resolved == 'pallas' else 'under'} the "
        f"{PALLAS_BYTE_MARGIN:g}x margin)"
        + (
            f" across {shards} peer shard(s), ICI exchange priced in"
            if shards > 1
            else ""
        )
    )
    scores = {
        "source": "static",
        "backend": backend,
        "xla_bytes_per_tick": round(xla_bytes, 1),
        "pallas_modeled_bytes_per_tick": round(pallas_bytes, 1),
        "ratio": round(ratio, 3),
        "margin": PALLAS_BYTE_MARGIN,
    }
    if shards > 1:
        scores["mesh"] = layout_str(mesh)
        scores["shards"] = shards
        scores["cross_shard_bytes_est"] = int(exchange)
    return TransportDecision("auto", resolved, reason, scores=scores)


def _measured_decision(
    context: TransportContext, backend: str
) -> TransportDecision:
    """The opt-in probe (``transport_probe = K``): time both arms'
    transport phases in isolation at the run's real shapes. On a
    non-TPU backend the pallas arm runs INTERPRETED — the measurement
    is then a functional gate, not a kernel cost, and the reason says
    so; the probe is meant for chip sessions. Probe decisions cache
    per build-key within a process; across processes (a build vs the
    run it warmed) a near-tie could time differently and resolve the
    other way — that costs a compile-cache miss, never a wrong
    program."""
    from .phases import _measure_phases, phase_specs

    reps = int(context.probe_reps)
    measured: dict[str, float] = {}
    for transport in ("xla", "pallas"):
        prog = _build_candidate(context, transport)
        specs = [
            s
            for s in phase_specs(prog, concrete=True)
            if s[0] in _TRANSPORT_PHASES
        ]
        ms = _measure_phases(specs, reps)
        if len(ms) != len(_TRANSPORT_PHASES):
            return TransportDecision(
                "auto",
                "xla",
                f"measured probe failed on the {transport} arm — "
                "keeping the XLA path",
                scores={"source": "measured", "backend": backend},
            )
        measured[transport] = sum(ms.values())
    interpreted = backend != "tpu"
    resolved = (
        "pallas" if measured["pallas"] < measured["xla"] else "xla"
    )
    return TransportDecision(
        "auto",
        resolved,
        f"measured probe: xla {measured['xla']:.3f} ms vs pallas "
        f"{measured['pallas']:.3f} ms per tick over {reps} rep(s) on "
        f"{backend}"
        + (" (pallas INTERPRETED — functional timing)" if interpreted else ""),
        scores={
            "source": "measured",
            "backend": backend,
            "xla_ms_per_tick": round(measured["xla"], 6),
            "pallas_ms_per_tick": round(measured["pallas"], 6),
            "reps": reps,
            "pallas_interpreted": interpreted,
        },
    )


# ----------------------------------------------------------- banked bank


def _bank_paths() -> list:
    """Candidate verdict files: the TG_TRANSPORT_BANK file/dir when
    set, else BENCH_PALLAS*.json beside the repo root (where the bench
    rounds already live)."""
    override = os.environ.get("TG_TRANSPORT_BANK", "")
    if override:
        if os.path.isdir(override):
            return sorted(glob.glob(os.path.join(override, "*.json")))
        return [override] if os.path.isfile(override) else []
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return sorted(glob.glob(os.path.join(root, "BENCH_PALLAS*.json")))


def _banked_verdict(
    backend: str, instances: int, plan: str, case: str
) -> dict | None:
    """Nearest applicable banked A/B verdict: a
    ``bench_pallas_transport`` JSON record measured on this backend
    KIND, on this workload SHAPE (the record's explicit plan/case, or
    its bench workload name mapped through
    :data:`_BENCH_WORKLOAD_PLANS` — foreign-shape verdicts are never
    evidence for this run), with the real kernels (interpreted rows
    are functional gates — skipped). Returns ``{pallas_vs_xla,
    instances, file}`` or None."""
    best = None
    for path in _bank_paths():
        try:
            with open(path) as f:
                records = [
                    json.loads(line)
                    for line in f
                    if line.strip().startswith("{")
                ]
        except (OSError, ValueError):
            continue
        for rec in records:
            rec_shape = _BENCH_WORKLOAD_PLANS.get(
                rec.get("workload", ""),
                (rec.get("plan"), rec.get("case")),
            )
            if rec_shape != (plan, case):
                continue
            rungs = rec.get("rungs") or [rec]
            for rung in rungs:
                if not isinstance(rung, dict):
                    continue
                ratio = rung.get("pallas_vs_xla", rec.get("pallas_vs_xla"))
                if ratio is None:
                    continue
                if rung.get("backend", rec.get("backend")) != backend:
                    continue
                if rung.get(
                    "pallas_interpreted", rec.get("pallas_interpreted")
                ):
                    continue
                inst = int(rung.get("instances", rec.get("instances", 0)))
                dist = abs(inst - instances)
                if best is None or dist < best[0]:
                    best = (
                        dist,
                        {
                            "pallas_vs_xla": float(ratio),
                            "instances": inst,
                            "file": os.path.basename(path),
                        },
                    )
    return best[1] if best else None
