"""Tick phase attribution plane: the per-phase device cost ledger
(docs/OBSERVABILITY.md "Phase attribution").

The jitted tick decomposes into named phases (``engine.SimProgram._tick``
wraps each in ``jax.named_scope("tg.<phase>")``): calendar delivery, the
latency-histogram accumulate, the vmapped user step, the transport
commit, the sync fold, fault point events, and the telemetry row. This
module turns that decomposition into a durable, regression-testable
attribution surface — the PERF.md "3 ops = 84%" table computed
programmatically, per transport backend, instead of hand-read profiler
sessions:

- **static attribution** — each phase method is lowered STANDALONE at
  the run's real shapes (``jax.eval_shape`` avals, no device
  allocation) and its compiled ``cost_analysis()`` harvested (flops,
  bytes accessed, transcendentals). The whole-program chunk cost is
  normalized per tick and an explicit **residual row** (whole − Σ
  phases) makes the coverage claim airtight by construction: fusion
  across phase boundaries, scan plumbing, and carry donation land in
  the residual, never silently inside a phase.
- **measured calibration** (opt-in, ``measure=K`` reps) — each phase is
  jitted in isolation and timed over K repetitions with concrete
  inputs, off the hot path, yielding measured ms/tick per phase — the
  per-op A/B evidence the Pallas chip verdict needs
  (``tools/bench_pallas_transport.py --phases``).

Like every observability plane: the ledger shapes NO part of the run's
program (the phase methods are re-lowered out-of-line; the run's chunk
program is untouched — pinned by jaxpr equality in tests) and building
it must never fail the run it measures (the executor wraps it
best-effort). Module import stays jax-free so the Prometheus exposition
and the console table can import the row helpers cheaply.
"""

from __future__ import annotations

import json
from typing import Any

from .perf import cost_analysis_dict, num
from .telemetry import LATENCY_BINS, PHASES_FILE

__all__ = [
    "PHASES_FILE",
    "TICK_PHASES",
    "build_phase_ledger",
    "phase_rows",
    "phase_specs",
    "write_phase_rows",
]

# Canonical phase order — the tick's dataflow order (engine._tick). A
# program variant compiles a subset: lat_hist/telemetry only under
# telemetry=true, faults only with an armed schedule.
TICK_PHASES = (
    "faults",
    "deliver",
    "lat_hist",
    "step",
    "sync",
    "net_commit",
    "telemetry",
)

# cost_analysis fields the ledger carries per phase (the keys
# cost_analysis_dict normalizes to)
_COST_KEYS = ("flops", "bytes_accessed", "transcendentals")


def phase_specs(prog, concrete: bool = False, seed: int = 0) -> list:
    """``[(name, fn, args), ...]`` for the phases compiled into ``prog``
    (an ``engine.SimProgram``), in :data:`TICK_PHASES` order.

    ``fn`` is a standalone jittable closure over the program's static
    config; ``args`` are its example inputs at the run's REAL shapes —
    ``jax.ShapeDtypeStruct`` avals by default (lowering/cost analysis
    allocates nothing), or concrete device values with
    ``concrete=True`` (the measured-calibration path; costs one carry
    init plus the derived intermediates)."""
    import jax
    import jax.numpy as jnp

    from .api import CRASH
    from .net import deliver, latency_histogram
    from .sync_kernel import update_sync

    # bucketed programs (sim/buckets.py) init against runtime live
    # counts — the phase specs then attribute the runtime-N program the
    # run actually compiled, translation included
    if getattr(prog, "live_counts", None) is not None:
        import numpy as _np

        _lc = _np.asarray(prog.live_counts, _np.int32)

        def _init():
            return prog.init_carry(seed, _lc)

    else:

        def _init():
            return prog.init_carry(seed)

    if concrete:
        carry = jax.jit(_init)()

        def derive(f, *args):
            return jax.jit(f)(*args)

    else:
        carry = jax.eval_shape(_init)
        derive = jax.eval_shape

    t = carry.t
    scalar = (
        jnp.int32(0)
        if concrete
        else jax.ShapeDtypeStruct((), jnp.int32)
    )

    def f_deliver(cal, t_):
        return deliver(cal, t_, transport=prog.transport)

    def f_lat_hist(cal, inbox, t_):
        return latency_histogram(
            cal,
            inbox,
            t_,
            prog._lat_group_of,
            len(prog.groups),
            LATENCY_BINS,
        )

    def f_step(carry_, inbox, t_):
        return prog._step_phase(carry_, inbox, t_)

    def f_sync(sync, signals, pub_payload, pub_valid, sub_consume):
        return update_sync(sync, signals, pub_payload, pub_valid, sub_consume)

    def f_faults(carry_, t_):
        return prog._fault_phase(carry_, t_)

    def f_net_commit(cal, link, step, t_, k_msg, dead, lc=None):
        return prog._net_commit_phase(
            cal, link, step, t_, k_msg, dead, virt=prog._virt(lc)
        )

    def f_telemetry(t_, status, sync, scalars):
        return prog._telemetry_phase(t_, status, sync, *scalars)

    # derived example inputs, chained exactly like the tick's dataflow
    _, inbox = derive(f_deliver, carry.cal, t)
    step = derive(f_step, carry, inbox, t)
    k_msg = derive(lambda k: jax.random.split(k)[1], carry.net_key)
    if prog.faults is not None:
        dead = derive(
            lambda c, t_: prog._fault_phase(c, t_)[4], carry, t
        )
        if dead is None:  # defensive: schedule without kill masks
            dead = derive(lambda s: s == CRASH, carry.status)
    else:
        dead = None

    specs: list = []
    if prog.faults is not None:
        specs.append(("faults", f_faults, (carry, t)))
    specs.append(("deliver", f_deliver, (carry.cal, t)))
    if prog.telemetry:
        specs.append(("lat_hist", f_lat_hist, (carry.cal, inbox, t)))
    specs.append(("step", f_step, (carry, inbox, t)))
    specs.append(
        (
            "sync",
            f_sync,
            (
                carry.sync,
                step["signals"],
                step["pub_payload"],
                step["pub_valid"],
                step["sub_consume"],
            ),
        )
    )
    specs.append(
        (
            "net_commit",
            f_net_commit,
            (
                carry.cal,
                carry.link,
                step,
                t,
                k_msg,
                dead,
                carry.live_counts,
            ),
        )
    )
    if prog.telemetry:
        specs.append(
            (
                "telemetry",
                f_telemetry,
                (t, step["status"], carry.sync, (scalar,) * 9),
            )
        )
    return specs


def _phase_cost(fn, args) -> dict:
    """Lower + compile one phase standalone and harvest its normalized
    cost analysis. Never raises (the cost harvest is already
    never-raising; a phase whose lowering fails contributes an empty
    row rather than killing the ledger)."""
    import jax

    try:
        compiled = jax.jit(fn).lower(*args).compile()
    except Exception:  # noqa: BLE001 — observability never raises
        return {}
    return cost_analysis_dict(compiled)


def _measure_phases(specs, reps: int) -> dict[str, float]:
    """Time each phase in isolation: jit, warm once (compile excluded),
    then ``reps`` back-to-back calls bracketed by one block — measured
    wall / reps = ms per call. Uses the concrete inputs
    ``phase_specs(concrete=True)`` built, so every phase runs the real
    shapes. A D2H read forces completion even on remotely-tunneled
    backends where block_until_ready may return early (the bench.py
    workaround)."""
    import time

    import jax
    import numpy as np

    out: dict[str, float] = {}
    for name, fn, args in specs:
        try:
            jfn = jax.jit(fn)
            res = jfn(*args)
            jax.block_until_ready(res)
            t0 = time.perf_counter()
            for _ in range(reps):
                res = jfn(*args)
            jax.block_until_ready(res)
            leaves = jax.tree.leaves(res)
            if leaves:
                np.asarray(leaves[0])
            out[name] = (time.perf_counter() - t0) * 1e3 / max(reps, 1)
        except Exception:  # noqa: BLE001 — calibration is best-effort
            continue
    return out


def build_phase_ledger(
    prog,
    whole: dict | None = None,
    measure: int = 0,
    seed: int = 0,
) -> dict:
    """Build the ``sim.phases`` journal block for one program.

    ``whole`` is an optional pre-harvested whole-program cost dict for
    one CHUNK dispatch (e.g. the perf ledger's ``compile`` block — it
    may carry extra keys; only the cost fields are read). When absent,
    the chunk program is lowered + compiled here (a persistent-cache
    read when the run already compiled it). ``measure > 0`` adds the
    measured ms/tick calibration at that many repetitions per phase.

    Block shape::

        {transport, chunk, instances,
         phases: [{phase, flops?, bytes_accessed?, transcendentals?,
                   flops_frac?, bytes_frac?, measured_ms?, measured_reps?}],
         whole_per_tick: {flops?, bytes_accessed?, transcendentals?},
         residual: {flops?, bytes_accessed?, transcendentals?},
         coverage: {flops_frac?, bytes_frac?}}

    The invariant consumers may rely on (and tests pin): for every cost
    field present in ``whole_per_tick``, Σ phases + residual ==
    whole_per_tick EXACTLY (the residual is defined as the difference,
    and may be negative — standalone phases lose cross-phase fusion the
    whole program has)."""
    import jax

    specs = phase_specs(prog)
    rows: list[dict[str, Any]] = []
    for name, fn, args in specs:
        rows.append({"phase": name, **_phase_cost(fn, args)})
    if not isinstance(whole, dict) or not any(
        num(whole.get(k)) for k in _COST_KEYS
    ):
        if getattr(prog, "live_counts", None) is not None:
            import numpy as _np

            _lc = _np.asarray(prog.live_counts, _np.int32)
            carry = jax.eval_shape(lambda: prog.init_carry(seed, _lc))
        else:
            carry = jax.eval_shape(lambda: prog.init_carry(seed))
        try:
            # same donation as the run's chunk program, so a warm
            # persistent cache serves this instead of a second compile
            whole = cost_analysis_dict(
                jax.jit(prog._chunk_step, donate_argnums=0)
                .lower(carry)
                .compile()
            )
        except Exception:  # noqa: BLE001 — observability never raises
            whole = {}
    chunk = max(int(prog.chunk), 1)
    whole_tick = {
        k: float(num(whole.get(k)) or 0.0) / chunk
        for k in _COST_KEYS
        if num(whole.get(k))
    }
    sums = {
        k: sum(float(r.get(k, 0.0) or 0.0) for r in rows) for k in _COST_KEYS
    }
    residual = {k: whole_tick[k] - sums[k] for k in whole_tick}
    for r in rows:
        for key, frac in (("flops", "flops_frac"), ("bytes_accessed", "bytes_frac")):
            if whole_tick.get(key) and r.get(key) is not None:
                r[frac] = round(float(r[key]) / whole_tick[key], 4)
    if measure > 0:
        measured = _measure_phases(
            phase_specs(prog, concrete=True, seed=seed), int(measure)
        )
        for r in rows:
            if r["phase"] in measured:
                r["measured_ms"] = round(measured[r["phase"]], 6)
                r["measured_reps"] = int(measure)
    coverage = {}
    for key, frac in (("flops", "flops_frac"), ("bytes_accessed", "bytes_frac")):
        if whole_tick.get(key):
            coverage[frac] = round(sums[key] / whole_tick[key], 4)
    return {
        "transport": prog.transport,
        "chunk": int(prog.chunk),
        "instances": int(prog.n),
        "phases": rows,
        "whole_per_tick": {k: round(v, 3) for k, v in whole_tick.items()},
        "residual": {k: round(v, 3) for k, v in residual.items()},
        "coverage": coverage,
    }


def phase_rows(block: dict) -> list[dict]:
    """Flatten a ``sim.phases`` block into uniform per-row dicts — one
    per phase, plus the synthesized ``residual`` and ``total`` rows —
    the ONE row shape behind the jsonl artifact, the ``tg_phase_*``
    Prometheus gauges, and the console table. Shape-tolerant: a foreign
    or truncated block yields what it holds, never raises."""
    if not isinstance(block, dict):
        return []
    rows: list[dict] = []
    transport = block.get("transport", "xla")
    for r in block.get("phases") or []:
        if isinstance(r, dict) and r.get("phase"):
            rows.append({"transport": transport, **r})
    for name, key in (("residual", "residual"), ("total", "whole_per_tick")):
        src = block.get(key)
        if isinstance(src, dict) and src:
            rows.append(
                {
                    "transport": transport,
                    "phase": name,
                    **{
                        k: v
                        for k, v in src.items()
                        if num(v) is not None
                    },
                }
            )
    return rows


def write_phase_rows(path: str, ident: dict, block: dict) -> int:
    """Write the block's rows as ``sim_phases.jsonl`` (one row per phase
    + residual + total, each carrying the run identity). Best-effort
    like every observability writer: IO failure writes nothing and
    returns 0 — the journal block remains the durable copy."""
    rows = phase_rows(block)
    if not rows:
        return 0
    try:
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps({**ident, **row}) + "\n")
    except (OSError, ValueError):
        return 0
    return len(rows)
