"""The sim engine: compiles a (testcase × groups) configuration into one
jitted tick program and steps it to completion.

This replaces the reference's entire execution substrate — container
scheduling, sidecar shaping, Redis sync (SURVEY.md §1 L1/L2) — with a
single SPMD program:

- every instance's ``step`` is lifted with ``jax.vmap`` (one vmap per
  group, so per-group params stay static and state pytrees may differ in
  shape across groups);
- a tick = deliver messages → vmapped steps → enqueue sends → fold sync
  counters/streams → apply network reconfigs; ``lax.scan`` runs CHUNK
  ticks per dispatch and the host polls a scalar ``done`` flag between
  chunks (no per-tick host sync);
- the instance axis shards over a ``jax.sharding.Mesh`` axis ``"i"``:
  states/status/link rows shard by instance, the calendar by destination,
  sync counters/streams stay replicated. XLA inserts the cross-shard
  collectives for message scatter — the ICI analog of the reference's
  data-network traffic.

Terminal instances are frozen: their state stops updating and their sends/
signals/publishes are masked, mirroring a container that has exited.

The deterministic fault-injection plane (``sim/faults.py``,
docs/FAULTS.md) hooks in here: scheduled crash/restart point events
apply at tick start (status flips, calendar purge, per-group re-init),
window faults ride into the transport with the enqueue call, and the
``done`` check waits out the schedule's last event. All of it is
compiled out when no schedule is declared.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from . import meshplan as _meshplan
from .api import (
    CRASH,
    RUNNING,
    GroupSpec,
    Inbox,
    Outbox,
    SimEnv,
    SimTestcase,
    StepOut,
    SyncView,
)
from .net import (
    MSG_BYTES,
    Calendar,
    LinkState,
    apply_net_updates,
    deliver,
    enqueue,
    latency_histogram,
    make_link_state,
    purge_dst,
    purge_dst_matrix,
)
from .netmatrix import NM_CHANNELS, NM_DELIVERED, NM_FAULT
from .sync_kernel import (
    SyncState,
    live_per_group,
    make_sub_window,
    make_sync_state,
    sync_occupancy,
    update_sync,
)
from .telemetry import LATENCY_BINS, TELEMETRY_FIXED_COLUMNS
from .trace import EV_DELIVER, EV_SEND, EV_SIGNAL, EV_STATUS

__all__ = [
    "MAX_FILTER_CELLS",
    "SimCarry",
    "SimProgram",
    "SimStallError",
    "build_groups",
]


class SimStallError(RuntimeError):
    """A device chunk dispatch exceeded the wall-clock watchdog (see
    ``SimJaxConfig.chunk_timeout_secs``): the worker thread is released
    with a diagnostic instead of hanging forever on the device poll."""

    def __init__(self, ticks: int, chunk_index: int, timeout: float):
        self.ticks = ticks
        self.chunk_index = chunk_index
        self.timeout = timeout
        super().__init__(
            f"sim chunk {chunk_index} did not complete within "
            f"{timeout:g}s wall (last completed tick {ticks}) — device "
            "hang or a pathologically slow dispatch; the cancel event "
            "was set and the dispatch abandoned"
        )

# Budget for the dense [R, N] per-region filter table, in int32 cells
# (2**28 = 1 GiB). See the N_REGIONS guard in SimProgram.__init__.
MAX_FILTER_CELLS = 2**28


# The cumulative flow counters accumulate in two int32 limbs (hi, lo)
# with a 30-bit spill: a single int32 would wrap after ~2^31 messages —
# about 21k ticks at the 100k-instance scale this engine targets — and
# jnp.int64 silently narrows to int32 without the x64 flag. Per-tick
# deltas are bounded far below 2^30 (≤ 2·OUT_MSGS·N messages), so the
# limb arithmetic is exact indefinitely.
_LIMB_BITS = 30
_LIMB_MASK = (1 << _LIMB_BITS) - 1


def _acc_zero() -> jax.Array:
    return jnp.zeros((2,), jnp.int32)


def _acc_add(acc: jax.Array, delta: jax.Array) -> jax.Array:
    lo = acc[1] + delta
    return jnp.stack(
        [acc[0] + jax.lax.shift_right_logical(lo, _LIMB_BITS), lo & _LIMB_MASK]
    )


def _acc_total(acc_host) -> int:
    return (int(acc_host[0]) << _LIMB_BITS) + int(acc_host[1])


def _check_carry_finite(carry, tick_lo: int, tick_hi: int) -> None:
    """Opt-in NaN/Inf guard (``SimJaxConfig.nan_guard``): scan every
    float leaf of the live carry and fail fast naming the first
    offending leaf and the tick range the chunk covered — turning a
    silent numeric corruption (which would otherwise surface ticks later
    as a wrong verdict) into an immediate, located failure."""
    flat, _ = jax.tree_util.tree_flatten_with_path(carry)
    for path, leaf in flat:
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        try:
            if not jnp.issubdtype(dtype, jnp.floating):
                continue
        except TypeError:  # extended dtypes (PRNG keys) are never float
            continue
        a = np.asarray(leaf)
        if not np.all(np.isfinite(a)):
            kind = "NaN" if np.isnan(a).any() else "Inf"
            raise FloatingPointError(
                f"nan_guard: {kind} in carry leaf "
                f"'carry{jax.tree_util.keystr(path)}' after ticks "
                f"({tick_lo}, {tick_hi}] — the plan's arithmetic (or a "
                "shaping input) produced a non-finite value in that "
                "tick range"
            )


def _poll_done(done) -> bool:
    """The single blocking device→host sync per chunk dispatch. D2H read,
    not block_until_ready — the latter may return early on remotely-
    tunneled backends (same workaround as bench.py). The telemetry plane
    piggybacks on this poll: once the done scalar is host-visible the
    chunk's counter block is already materialized, so reading it is a
    copy, not another sync. Tests monkeypatch this function to count
    syncs per chunk (telemetry on must equal telemetry off)."""
    return bool(np.asarray(done))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimCarry:
    """Everything that evolves across ticks (donated between chunks)."""

    states: tuple  # per-group state pytrees, leading axis = group count
    status: jax.Array  # [N] int32
    finished_at: jax.Array  # [N] int32 — tick of terminal status (-1 if none)
    cal: Calendar
    link: LinkState
    sync: SyncState
    rejected: jax.Array  # [N] int32 — REJECT feedback from last tick
    keys: jax.Array  # [N] per-instance PRNG keys
    net_key: jax.Array  # link-model PRNG key
    t: jax.Array  # int32 current tick
    # --- cumulative transport diagnostics (scalars; surfaced in results)
    clamped: jax.Array  # horizon-clamped deliveries (see NetFeedback)
    bw_dropped: jax.Array  # bandwidth_queue tail-drops
    # (src, tick) events where the shaped bandwidth changed under a
    # standing backlog — the regime where the HTB queue-occupancy bound
    # is approximate (net.py enqueue); counted so the divergence is loud
    bw_rate_changed: jax.Array
    collisions: jax.Array  # direct-mode slot collisions (validate runs)
    collision_where: jax.Array  # [2] (dst, slot) of the first collision
    # --- cumulative message-flow totals (always maintained — a few
    # scalar adds per tick; the telemetry plane's ground truth, which
    # the per-tick counter block must sum back to). Each is a [2] int32
    # (hi, lo) limb pair — see _acc_add — so totals stay exact past
    # int32 range without jax x64. cal_depth is the in-flight calendar
    # occupancy, tracked incrementally (enqueued - delivered) instead of
    # rescanning the O(L·N·SLOTS) planes; a plain int32 suffices — it is
    # bounded by the calendar's cell count, and a ≥2^31-cell calendar is
    # unallocatable anyway.
    msgs_delivered: jax.Array
    msgs_sent: jax.Array
    msgs_enqueued: jax.Array
    msgs_dropped: jax.Array
    msgs_rejected: jax.Array
    cal_depth: jax.Array
    # --- fault-injection plane (docs/FAULTS.md). Scalars stay zero (and
    # cost nothing) when no schedule is compiled in. fault_dropped is a
    # limb pair like the msgs_* totals: send-time fault kills PLUS
    # in-flight messages purged by crashes — the extra term that keeps
    # flow conservation exact under chaos (sent = delivered + in-flight
    # + dropped + rejected + fault_dropped).
    faults_crashed: jax.Array
    faults_restarted: jax.Array
    fault_dropped: jax.Array
    # --- delivery-latency histogram ([G, LATENCY_BINS] int32; None when
    # the telemetry plane is compiled out): per-receiver-group log2 bin
    # counts of (delivery tick - enqueue tick), accumulated per tick and
    # FLUSHED (read + zeroed) once per chunk by _chunk_step — the host
    # accumulates chunk deltas in python ints, so the device counter can
    # never wrap however long the run (same overflow discipline as the
    # limb-pair totals, without the limb arithmetic per bin).
    lat_hist: jax.Array | None = None
    # --- shape bucketing (sim/buckets.py; None when the run is not
    # bucketed): [G] int32 EXACT per-group instance counts, carried as
    # RUNTIME data so every composition in the same bucket shares one
    # compiled program — the whole point of the plane. Constant across
    # ticks (threaded through unchanged); the env virtualization, the
    # dst translation, and the PRNG derivation all read it.
    live_counts: jax.Array | None = None
    # --- traffic-matrix plane (sim/netmatrix.py; None when the plane is
    # compiled out): [NM_CHANNELS, GH, GH] int32 src-group × dst-group
    # flow counts (GH = groups + one hosts row when additional hosts are
    # attached), accumulated per tick and FLUSHED (read + zeroed) once
    # per chunk beside lat_hist — the host accumulates chunk deltas in
    # int64, so the device counter never wraps (a cell gains at most
    # chunk·O·N per flush, far under 2^31 at any plannable scale).
    net_mat: jax.Array | None = None
    # [GH] float32 per-src-group bandwidth-queue backlog high-water
    # (peak link busy-until horizon in ticks, the queue-depth shaping
    # observable). Monotone max — read once at results, never flushed.
    # None unless the matrix plane is on AND the plan declares the
    # bandwidth_queue shaping stage.
    net_bw_hiwater: jax.Array | None = None


def build_groups(run_groups, parameters_of=None) -> tuple[GroupSpec, ...]:
    """Lay groups out contiguously on the instance axis (the sim analog of
    the per-group container batches, ``local_docker.go:375-463``)."""
    specs = []
    off = 0
    for i, g in enumerate(run_groups):
        params = dict(g.parameters) if parameters_of is None else parameters_of(g)
        specs.append(
            GroupSpec(
                id=g.id, index=i, offset=off, count=g.instances, params=params
            )
        )
        off += g.instances
    return tuple(specs)


def constrain_carry(carry: "SimCarry", plan, lead: str | None = None):
    """Apply the ONE placement rule table (sim/meshplan.py) to every
    constrained carry plane. ``lead`` names the mesh axis a STACKED
    carry's leading run dimension maps to (the pack lift, sim/pack.py
    passes ``"runs"``) — same table, one decision for solo and packed
    carries alike. Rank-clamped per leaf (``ndim=``) so a pack's FLAT
    calendar planes keep only the constraints that align."""
    if plan is None:
        return carry
    wsc = jax.lax.with_sharding_constraint

    def sh(x, path):
        return wsc(x, plan.sharding_for(path, lead=lead, ndim=x.ndim))

    return dataclasses.replace(
        carry,
        status=sh(carry.status, "status"),
        finished_at=sh(carry.finished_at, "finished_at"),
        cal=dataclasses.replace(
            carry.cal,  # statics (slots/flat/horizon) survive
            payload=tuple(
                sh(p, f"cal.payload.{i}")
                for i, p in enumerate(carry.cal.payload)
            ),
            src=sh(carry.cal.src, "cal.src")
            if carry.cal.src is not None
            else None,
            valid=sh(carry.cal.valid, "cal.valid")
            if carry.cal.valid is not None
            else None,
            etick=sh(carry.cal.etick, "cal.etick")
            if carry.cal.etick is not None
            else None,
        ),
        link=LinkState(
            egress=sh(carry.link.egress, "link.egress"),
            filters=sh(carry.link.filters, "link.filters"),
            region_of=sh(carry.link.region_of, "link.region_of"),
            backlog=sh(carry.link.backlog, "link.backlog")
            if carry.link.backlog is not None
            else None,
            rules=sh(carry.link.rules, "link.rules")
            if carry.link.rules is not None
            else None,
        ),
        rejected=sh(carry.rejected, "rejected"),
    )


class SimProgram:
    def __init__(
        self,
        testcase: SimTestcase,
        groups: tuple[GroupSpec, ...],
        *,
        test_plan: str = "plan",
        test_case: str = "case",
        test_run: str = "run",
        tick_ms: float = 1.0,
        mesh: jax.sharding.Mesh | None = None,
        chunk: int = 128,
        hosts: tuple[str, ...] = (),
        validate: bool = False,
        telemetry: bool = False,
        faults=None,
        trace=None,
        transport: str = "xla",
        live_counts: tuple | None = None,
        netmatrix: bool = False,
    ):
        self.tc = testcase
        self.groups = groups
        self.n = sum(g.count for g in groups)
        # Shape bucketing (sim/buckets.py): ``groups`` is the PADDED
        # physical layout and ``live_counts`` the exact per-group sizes.
        # When set, the program becomes RUNTIME-N: exact counts ride the
        # carry (SimCarry.live_counts), plans see a virtualized env, and
        # any composition in the same bucket compiles the same HLO — the
        # persistent compile cache then serves every ``-i`` in the
        # bucket from one entry. None (default) compiles the identical
        # pre-bucket program (zero-overhead contract, pinned by tests).
        if live_counts is not None:
            live_counts = tuple(int(c) for c in live_counts)
            if len(live_counts) != len(groups):
                raise ValueError(
                    f"live_counts has {len(live_counts)} entries for "
                    f"{len(groups)} group(s) — the bucket plan must be "
                    "built from the same group layout"
                )
            for lc, g in zip(live_counts, groups):
                if not (0 < lc <= g.count):
                    raise ValueError(
                        f"group {g.id!r}: live count {lc} outside "
                        f"(0, {g.count}] — padding only ever adds lanes"
                    )
            if trace is not None:
                raise ValueError(
                    "the flight recorder is not supported with shape "
                    "bucketing (trace lanes are virtual-layout selectors "
                    "baked into the program) — run with bucket=off to "
                    "trace"
                )
            cls0 = type(testcase)
            if "filter_rules" in cls0.SHAPING and len(groups) > 1:
                raise ValueError(
                    "shape bucketing with multiple groups is incompatible "
                    "with 'filter_rules' shaping: rule ranges address the "
                    "exact (virtual) instance layout, and multi-group "
                    "padding shifts physical ids non-contiguously — run "
                    "with bucket=off or a single group"
                )
        self.live_counts = live_counts
        self.tick_ms = float(tick_ms)
        self.mesh = mesh
        self.chunk = int(chunk)
        self.meta = dict(
            test_plan=test_plan, test_case=test_case, test_run=test_run
        )
        cls = type(testcase)
        # Additional hosts: echo-service lanes appended past the instance
        # axis (the whitelisted-control-routes analog — see SimEnv.hosts).
        # Their traffic bypasses shaping/filters in the transport and they
        # never terminate, so they are excluded from the done check and
        # sliced out of results.
        self.hosts = tuple(hosts)
        self.n_lanes = self.n + len(self.hosts)
        self.validate = bool(validate)
        # Transport backend for the calendar hot path (ISSUE 5 / SURVEY
        # §2.4.1): "xla" compiles the scatter/gather path unchanged (the
        # zero-overhead default, pinned by jaxpr equality); "pallas"
        # swaps in the hand-tiled commit + delivery kernels
        # (sim/pallas_transport.py). A static program-shaping option
        # like telemetry/faults/trace: it must ride the cohort broadcast
        # and the precompile BuildKey.
        if transport not in ("xla", "pallas"):
            raise ValueError(
                f"unknown transport {transport!r}: expected 'xla' or "
                "'pallas'"
            )
        # Mesh placement rides the ONE rule table (sim/meshplan.py):
        # every constrained carry plane resolves its PartitionSpec
        # there, and the sharded Pallas commit/deliver kernels
        # (shard_map over per-chip lane ranges) require the lane axis
        # to divide across the peer shards.
        self.meshplan = _meshplan.plan_for(mesh)
        if transport == "pallas" and self.meshplan is not None:
            shards = self.meshplan.shards
            if self.n_lanes % shards != 0:
                raise ValueError(
                    f"transport=pallas on a mesh needs the lane count to "
                    f"divide across the peer shards: {self.n_lanes} "
                    f"lane(s) ({self.n} instances + {len(self.hosts)} "
                    f"host(s)) do not divide by {shards} — pad the "
                    "instance counts (shape bucketing does this), drop "
                    "the hosts, or use transport=xla"
                )
        self.transport = transport
        # Per-tick counter block (telemetry plane): when enabled, every
        # tick emits one K-vector through the scan's ys output and the
        # chunk returns a [chunk, K] block beside the done flag. A static
        # compile-time option — off, the block is compiled out entirely
        # (K = 0 and _chunk_step keeps its two-tuple shape).
        self.telemetry = bool(telemetry)
        self._tele_k = (
            len(TELEMETRY_FIXED_COLUMNS) + len(groups) if telemetry else 0
        )
        # Traffic-matrix plane (sim/netmatrix.py): [NM_CHANNELS, GH, GH]
        # src-group × dst-group flow counters in the carry, flushed once
        # per chunk beside the telemetry block. A static program-shaping
        # option like telemetry/faults/trace — off compiles the
        # identical pre-matrix program (zero-overhead contract, pinned
        # by jaxpr equality). GH appends one hosts row past the declared
        # groups so echo traffic is attributed, not lost, and the matrix
        # sums reconcile against the flow totals EXACTLY.
        self.netmatrix = bool(netmatrix)
        if self.netmatrix and not self.telemetry:
            raise ValueError(
                "the traffic-matrix plane rides the telemetry chunk "
                "flush: enable telemetry or drop netmatrix"
            )
        self._nm_gh = len(groups) + (1 if self.hosts else 0)
        # Fault-injection plane: a lowered FaultSchedule (sim/faults.py)
        # or None. A static program-shaping option like telemetry — the
        # schedule's event tensors bake into the traced tick, and None
        # compiles the identical pre-fault program (the zero-overhead
        # contract tests pin via jaxpr equality).
        self.faults = faults
        if faults is not None and faults.n != self.n:
            raise ValueError(
                f"fault schedule lowered for {faults.n} instance(s) but "
                f"the program has {self.n} — the schedule must be built "
                "from the same group layout"
            )
        # Flight recorder (sim/trace.py): a lowered TracePlan or None.
        # A static program-shaping option like telemetry/faults — the
        # traced lanes bake into the tick as gather indices, and None
        # compiles the identical no-trace program (zero-overhead
        # contract, pinned by jaxpr equality).
        self.trace = trace
        if trace is not None and trace.n != self.n:
            raise ValueError(
                f"trace plan lowered for {trace.n} instance(s) but the "
                f"program has {self.n} — the plan must be built from "
                "the same group layout"
            )
        if trace is not None:
            self._trace_lanes = jnp.asarray(trace.lanes)
            # post-host-merge outbox row count (the engine pads the
            # outbox planes up to the host echo slot count)
            o_rows = (
                max(cls.OUT_MSGS, cls.IN_MSGS) if hosts else cls.OUT_MSGS
            )
            self._trace_o_rows = o_rows
            self._trace_nrows = trace.count * (
                1 + len(cls.STATES) + o_rows + cls.IN_MSGS
            )
        else:
            self._trace_lanes = None
            self._trace_o_rows = 0
            self._trace_nrows = 0
        # Static horizon check: the plan's DEFAULT_LINK must be
        # deliverable within the calendar — shaped reconfigurations are
        # runtime data and get the clamp counter instead (NetFeedback).
        jitter_ms = (
            cls.DEFAULT_LINK[1] if "jitter" in cls.SHAPING else 0.0
        )  # the jitter plane is compiled out when undeclared
        base_ticks = int(
            np.ceil((cls.DEFAULT_LINK[0] + jitter_ms) / tick_ms)
        )
        if base_ticks > cls.MAX_LINK_TICKS - 1:
            raise ValueError(
                f"DEFAULT_LINK latency+jitter ({cls.DEFAULT_LINK[0]}+"
                f"{jitter_ms} ms = {base_ticks} ticks at "
                f"{tick_ms} ms/tick) exceeds the calendar horizon "
                f"MAX_LINK_TICKS-1 = {cls.MAX_LINK_TICKS - 1}; raise "
                "MAX_LINK_TICKS or the tick duration"
            )
        if "filter_rules" in cls.SHAPING:
            if "filters" in cls.SHAPING:
                raise ValueError(
                    "declare either 'filters' (dense per-dst-region "
                    "table) or 'filter_rules' (per-instance range-rule "
                    "lists), not both — two granularity models for the "
                    "same Accept/Reject/Drop semantics"
                )
            if cls.FILTER_RULES <= 0:
                raise ValueError(
                    "'filter_rules' shaping needs FILTER_RULES > 0 (the "
                    "max rules per instance)"
                )
        if "bandwidth_queue" in cls.SHAPING and "bandwidth" in cls.SHAPING:
            raise ValueError(
                "declare either 'bandwidth' (admission-cap drop) or "
                "'bandwidth_queue' (HTB queueing), not both — they are "
                "two semantics for the same LinkShape knob"
            )
        if "bandwidth_queue" in cls.SHAPING and cls.SLOT_MODE == "direct":
            raise ValueError(
                "bandwidth_queue is incompatible with SLOT_MODE='direct': "
                "queue deferral makes two sends from one outbox slot land "
                "on the same (receiver, slot, tick) and silently collide"
            )
        if "bandwidth_queue" in cls.SHAPING and "duplicate" in cls.SHAPING:
            raise ValueError(
                "bandwidth_queue is incompatible with duplicate shaping: "
                "second copies would bypass the egress queue (tc shapes "
                "netem duplicates through the HTB class; the transport "
                "creates copies after queue metering) — PARITY BOUND, "
                "use admission-cap 'bandwidth' with duplicate instead"
            )
        if not cls.CROSS_TICK_STACKING:
            # statically-detectable violations of the single-send-tick
            # bucket contract (see SimTestcase.CROSS_TICK_STACKING):
            # any compiled-in feature that makes per-message delay vary
            # breaks it, as do control lanes riding the 1-tick floor
            for feat, why in (
                ("duplicate", "second copies land one tick later"),
                ("jitter", "per-message delay varies with the jitter draw"),
                ("reorder", "reordered messages jump to the 1-tick floor"),
                (
                    "bandwidth_queue",
                    "queued messages defer by a backlog-dependent delay",
                ),
            ):
                if feat in cls.SHAPING:
                    raise ValueError(
                        f"CROSS_TICK_STACKING=False is incompatible with "
                        f"{feat} shaping ({why}, so one calendar bucket "
                        "fills from multiple send ticks)"
                    )
            if hosts:
                raise ValueError(
                    "CROSS_TICK_STACKING=False is incompatible with "
                    "additional_hosts (control lanes ride the 1-tick floor "
                    "while plan traffic rides the shaped latency)"
                )
        if self.hosts:
            if not cls.TRACK_SRC:
                raise ValueError(
                    "additional_hosts need TRACK_SRC=True (the echo replies "
                    "to the inbox src)"
                )
            if cls.SLOT_MODE == "direct":
                raise ValueError(
                    "additional_hosts need SLOT_MODE='sorted' (host fan-in "
                    "violates the direct mode contract)"
                )
        self.n_states = len(cls.STATES)
        self.n_topics = len(cls.TOPICS)
        self.n_regions = cls.N_REGIONS if cls.N_REGIONS > 0 else len(groups)
        # Static budget on the dense [R, N] filter table (VERDICT r4 #3):
        # a plan declaring N_REGIONS = N at large N would otherwise die
        # as an opaque XLA allocation error deep in tracing (100k × 100k
        # = a 40 GB table). Refuse loudly at program-build time instead —
        # the same failure class the clamp counters and collision
        # validation eliminated elsewhere. The budget is memory-shaped
        # (cells, i.e. int32 entries), distinct from the ~8k PERF parity
        # bound, which is about transport cost, not allocation.
        cells = self.n_regions * (self.n + len(hosts))
        if cells > MAX_FILTER_CELLS:
            raise ValueError(
                f"filter table [R={self.n_regions}, N={self.n}] needs "
                f"{cells:,} cells ({cells * 4 / 2**30:.1f} GiB int32), "
                f"over the MAX_FILTER_CELLS budget of {MAX_FILTER_CELLS:,} "
                f"({MAX_FILTER_CELLS * 4 / 2**30:.1f} GiB) — coarsen "
                "N_REGIONS (per-instance granularity is practical to ~8k "
                "instances, see PERF.md) or raise "
                "testground_tpu.sim.engine.MAX_FILTER_CELLS"
            )
        self._group_of = jnp.asarray(
            np.repeat(
                np.arange(len(groups), dtype=np.int32),
                [g.count for g in groups],
            )
        )
        # receiver lane → group map for the latency histogram: host echo
        # lanes map out of range so their control-route deliveries never
        # enter the plan-traffic latency stats
        self._lat_group_of = np.concatenate(
            [
                np.repeat(
                    np.arange(len(groups), dtype=np.int32),
                    [g.count for g in groups],
                ),
                np.full((len(self.hosts),), len(groups), np.int32),
            ]
        )
        # lane → matrix row for the traffic-matrix plane: identical map,
        # except host lanes land IN range (row len(groups) IS the hosts
        # row when GH = G+1) so echo traffic stays accounted
        self._nm_group_of = jnp.asarray(self._lat_group_of)
        self._chunk_fn: Callable | None = None

    # ------------------------------------------------------------ sharding

    def _pshard(self, path: str):
        """NamedSharding for a logical carry plane, resolved through
        the ONE placement rule table (sim/meshplan.py)."""
        if self.meshplan is None:
            return None
        return self.meshplan.sharding_for(path)

    def _constrain(self, carry: SimCarry) -> SimCarry:
        if self.mesh is None:
            return carry
        return constrain_carry(carry, self.meshplan)

    # ------------------------------------------------------------ buckets

    def _virt(self, live_counts):
        """Traced virtual-layout context under shape bucketing: exact
        per-group counts ``lc [G]``, virtual offsets ``voff [G+1]``, and
        the exact total ``ln`` — all derived from the carry's runtime
        ``live_counts`` leaf so they never bake into the program. None
        when the run is unbucketed."""
        if self.live_counts is None or live_counts is None:
            return None
        lc = jnp.asarray(live_counts, jnp.int32)
        voff = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(lc)]
        )
        return {"lc": lc, "voff": voff, "ln": voff[-1]}

    def _vgroups(self, virt):
        """Virtualized GroupSpec tuple: ids/params stay static, counts
        and offsets become traced scalars — what a bucketed plan's env
        must see so its behavior matches the exact-size run."""
        return tuple(
            GroupSpec(
                id=g.id,
                index=g.index,
                offset=virt["voff"][gi],
                count=virt["lc"][gi],
                params=g.params,
            )
            for gi, g in enumerate(self.groups)
        )

    def _derive_keys(self, inst_root, virt):
        """Per-lane PRNG keys under bucketing, bit-matching the unpadded
        run's ``jax.random.split(inst_root, live_n)``.

        ``split(key, n)`` lowers to ``threefry_2x32(key, iota(2n))``
        whose counter pairs are ``(k, k+n)`` (the iota is split in
        half), so element ``k`` of the flat key data is
        ``hash(k, k+n).a`` for ``k < n`` and ``hash(k-n, k).b`` past it
        — reproducible per index with ``n`` as a TRACED value (verified
        against jax.random.split by tests/test_sim_buckets.py). Real
        lane v therefore gets exactly the key the exact-size run's
        split gave it; dead pad lanes draw from a disjoint counter
        range (their keys are never observable — frozen from tick 0)."""
        from jax.extend import random as xrandom

        raw = jax.random.key_data(inst_root)
        impl = jax.random.key_impl(inst_root)
        ln = virt["ln"].astype(jnp.uint32)

        # physical lane → (virtual id, live?) from the static layout
        gseq = np.concatenate(
            [np.arange(g.count, dtype=np.int32) for g in self.groups]
        )
        gi_of = np.repeat(
            np.arange(len(self.groups), dtype=np.int32),
            [g.count for g in self.groups],
        )
        gseq = jnp.asarray(gseq)
        vid = (virt["voff"][jnp.asarray(gi_of)] + gseq).astype(jnp.uint32)
        live = gseq < virt["lc"][jnp.asarray(gi_of)]
        # pad lanes: unique counters past every live pair's range
        pad_vid = (
            ln + jnp.arange(self.n, dtype=jnp.uint32)
        )
        vid = jnp.where(live, vid, pad_vid)
        nn = jnp.where(live, ln, jnp.uint32(2 * self.n) + ln)

        def split_at(v, n_):
            def elem(k):
                a = xrandom.threefry_2x32(
                    raw, jnp.stack([k, k + n_]).astype(jnp.uint32)
                )
                b = xrandom.threefry_2x32(
                    raw, jnp.stack([k - n_, k]).astype(jnp.uint32)
                )
                return jnp.where(k < n_, a[0], b[1])

            return jnp.stack([elem(2 * v), elem(2 * v + 1)])

        data = jax.vmap(split_at)(vid, nn)
        return jax.random.wrap_key_data(data, impl=impl)

    def _translate_dst(self, dst, virt):
        """Plan-emitted VIRTUAL destinations → physical lanes: each
        virtual segment (every group's live span, then the host lanes)
        shifts by its own static physical offset; anything outside the
        virtual address space maps to -1 — the same out-of-range drop
        the exact-size run applies (net.enqueue bounds mask)."""
        phys = jnp.full_like(dst, -1)
        for gi, g in enumerate(self.groups):
            lo, hi = virt["voff"][gi], virt["voff"][gi + 1]
            in_seg = (dst >= lo) & (dst < hi)
            phys = jnp.where(in_seg, dst - lo + g.offset, phys)
        if self.hosts:
            ln = virt["ln"]
            in_h = (dst >= ln) & (dst < ln + len(self.hosts))
            phys = jnp.where(in_h, dst - ln + self.n, phys)
        return phys

    def _translate_src(self, src, virt):
        """Inverse map for delivered provenance: the calendar stores
        PHYSICAL sender lanes, but a bucketed plan must see the same
        ``inbox.src`` values the exact-size run serves (plans reply to
        them), so delivered src ids map back to virtual before the step
        phase. Cleared slots hold 0 and map to 0 (group 0's first lane
        in both layouts)."""
        v = src
        for gi, g in enumerate(self.groups):
            in_seg = (src >= g.offset) & (src < g.offset + g.count)
            v = jnp.where(in_seg, src - g.offset + virt["voff"][gi], v)
        if self.hosts:
            in_h = src >= self.n
            v = jnp.where(in_h, src - self.n + virt["ln"], v)
        return v

    def _virtual_midx(self, rows: int, virt):
        """Virtual message indices for the transport's shaping dice
        (net.enqueue ``midx``): the exact-size run hashes per-feature
        uniforms from the FLAT message index ``o·n_lanes + src``, so a
        bucketed run must feed the dice the virtual flat index or every
        stochastic shaping draw (loss, jitter, duplicate, chaos
        loss-bursts) diverges from the unpadded run. Pad lanes draw
        from past the virtual range (their messages are never valid)."""
        gseq = np.concatenate(
            [np.arange(g.count, dtype=np.int32) for g in self.groups]
        )
        gi_of = np.repeat(
            np.arange(len(self.groups), dtype=np.int32),
            [g.count for g in self.groups],
        )
        vsrc = (
            virt["voff"][jnp.asarray(gi_of)] + jnp.asarray(gseq)
        ).astype(jnp.int32)
        live = jnp.asarray(gseq) < virt["lc"][jnp.asarray(gi_of)]
        n_vlanes = virt["ln"] + jnp.int32(len(self.hosts))
        # dead pad lanes: indices past rows·n_vlanes, per-lane unique
        vsrc = jnp.where(
            live, vsrc, n_vlanes + jnp.arange(self.n, dtype=jnp.int32)
        )
        if self.hosts:
            vsrc = jnp.concatenate(
                [
                    vsrc,
                    virt["ln"]
                    + jnp.arange(len(self.hosts), dtype=jnp.int32),
                ]
            )
        o = jnp.arange(rows, dtype=jnp.int32)[:, None]
        return (o * n_vlanes + vsrc[None, :]).reshape(-1)

    # ---------------------------------------------------------------- init

    def _env_for(self, gspec: GroupSpec, gs, gseq, key, virt=None) -> SimEnv:
        if virt is not None:
            vgroups = self._vgroups(virt)
            return SimEnv(
                test_plan=self.meta["test_plan"],
                test_case=self.meta["test_case"],
                test_run=self.meta["test_run"],
                # exact values as TRACED scalars — the program stays
                # identical across every composition in the bucket
                test_instance_count=virt["ln"],
                tick_ms=self.tick_ms,
                groups=vgroups,
                group=vgroups[gspec.index],
                global_seq=virt["voff"][gspec.index] + gseq,
                group_seq=gseq,
                key=key,
                hosts=self.hosts,
            )
        return SimEnv(
            test_plan=self.meta["test_plan"],
            test_case=self.meta["test_case"],
            test_run=self.meta["test_run"],
            test_instance_count=self.n,
            tick_ms=self.tick_ms,
            groups=self.groups,
            group=gspec,
            global_seq=gs,
            group_seq=gseq,
            key=key,
            hosts=self.hosts,
        )

    def init_carry(self, seed: int = 0, live_counts=None) -> SimCarry:
        cls = type(self.tc)
        if (self.live_counts is not None) != (live_counts is not None):
            raise ValueError(
                "init_carry live_counts must be provided exactly when "
                "the program was built with a bucket plan"
            )
        root = jax.random.key(seed)
        net_key, inst_root = jax.random.split(root)
        virt = self._virt(live_counts)
        if virt is not None:
            keys = self._derive_keys(inst_root, virt)
        else:
            keys = jax.random.split(inst_root, self.n)

        states = []
        for g in self.groups:
            gs = jnp.arange(g.offset, g.offset + g.count, dtype=jnp.int32)
            gseq = jnp.arange(g.count, dtype=jnp.int32)
            gkeys = keys[g.offset : g.offset + g.count]

            def init_one(gs_, gseq_, k_, _g=g):
                return self.tc.init(
                    self._env_for(_g, gs_, gseq_, k_, virt=virt)
                )

            states.append(jax.vmap(init_one)(gs, gseq, gkeys))

        # host lanes sit past the instance axis: region 0 (their traffic
        # bypasses filters anyway), default egress, no sync participation
        region_of = jnp.minimum(self._group_of, self.n_regions - 1)
        if self.hosts:
            region_of = jnp.concatenate(
                [region_of, jnp.zeros((len(self.hosts),), jnp.int32)]
            )
        status0 = jnp.full((self.n_lanes,), RUNNING, jnp.int32)
        if virt is not None:
            # dead pad lanes: CRASH from tick 0, frozen by the engine's
            # terminal-instance masking — the live-lane machinery the
            # faults plane already exercises (docs/FAULTS.md). They
            # never step, send, signal, or gate the done check.
            gseq_all = jnp.concatenate(
                [
                    jnp.arange(g.count, dtype=jnp.int32)
                    for g in self.groups
                ]
            )
            live_mask = gseq_all < virt["lc"][self._group_of]
            if self.hosts:
                live_mask = jnp.concatenate(
                    [live_mask, jnp.ones((len(self.hosts),), bool)]
                )
            status0 = jnp.where(live_mask, status0, CRASH)
        carry = SimCarry(
            states=tuple(states),
            status=status0,
            finished_at=jnp.full((self.n_lanes,), -1, jnp.int32),
            cal=Calendar.empty(
                cls.MAX_LINK_TICKS,
                self.n_lanes,
                cls.IN_MSGS,
                cls.MSG_WIDTH,
                # the matrix plane forces the provenance plane on (it
                # attributes deliveries and crash purges to sender
                # groups); the plan-visible inbox src is re-zeroed in
                # _tick when the plan itself opted out, so results stay
                # bit-equal with the plane off
                track_src=cls.TRACK_SRC or self.netmatrix,
                # unsharded: flat planes in the scatters' linear layout
                # (see Calendar docstring); sharded: 2-D rows whose
                # N·SLOTS axis carries the instance-axis sharding. The
                # pallas backend keeps the 2-D form too — its kernels
                # block bucket rows directly, so the flat layout XLA's
                # scatter lowering wants buys nothing there
                flat=self.mesh is None and self.transport != "pallas",
                # the enqueue-tick plane feeds the delivery-latency
                # histograms — telemetry-gated like the counter block
                track_etick=self.telemetry,
            ),
            link=make_link_state(
                self.n_lanes,
                self.n_regions,
                cls.DEFAULT_LINK,
                # instances start in region = group index; plans with
                # N_REGIONS > len(groups) reassign via StepOut.region
                region_of=region_of,
                track_backlog="bandwidth_queue" in cls.SHAPING,
                n_rules=(
                    cls.FILTER_RULES
                    if "filter_rules" in cls.SHAPING
                    else 0
                ),
            ),
            sync=make_sync_state(
                self.n, self.n_states, self.n_topics, cls.TOPIC_CAP, cls.PUB_WIDTH
            ),
            rejected=jnp.zeros((self.n_lanes,), jnp.int32),
            keys=keys,
            net_key=net_key,
            t=jnp.int32(0),
            clamped=jnp.int32(0),
            bw_dropped=jnp.int32(0),
            bw_rate_changed=jnp.int32(0),
            collisions=jnp.int32(0),
            collision_where=jnp.zeros((2,), jnp.int32),
            msgs_delivered=_acc_zero(),
            msgs_sent=_acc_zero(),
            msgs_enqueued=_acc_zero(),
            msgs_dropped=_acc_zero(),
            msgs_rejected=_acc_zero(),
            cal_depth=jnp.int32(0),
            faults_crashed=jnp.int32(0),
            faults_restarted=jnp.int32(0),
            fault_dropped=_acc_zero(),
            lat_hist=(
                jnp.zeros((len(self.groups), LATENCY_BINS), jnp.int32)
                if self.telemetry
                else None
            ),
            live_counts=(
                jnp.asarray(live_counts, jnp.int32)
                if virt is not None
                else None
            ),
            net_mat=(
                jnp.zeros(
                    (NM_CHANNELS, self._nm_gh, self._nm_gh), jnp.int32
                )
                if self.netmatrix
                else None
            ),
            net_bw_hiwater=(
                jnp.zeros((self._nm_gh,), jnp.float32)
                if self.netmatrix and "bandwidth_queue" in cls.SHAPING
                else None
            ),
        )
        if self.mesh is not None:
            carry = jax.jit(self._constrain)(carry)
        return carry

    # ---------------------------------------------------------------- tick
    #
    # The tick decomposes into named phases — fault point events, calendar
    # delivery, the latency-histogram accumulate, the vmapped user step,
    # the transport commit, the sync fold, and the telemetry row — each a
    # method below and each executed under jax.named_scope("tg.<phase>").
    # The scopes are name-stack metadata only: the traced jaxpr is
    # unchanged (the zero-overhead pins stay green) and real-chip
    # XProf/Perfetto captures (--run-cfg profile=true) become legible per
    # phase and per transport backend. The same phase methods are lowered
    # STANDALONE by sim/phases.py to harvest per-phase cost_analysis()
    # into the run's PhaseLedger (docs/OBSERVABILITY.md "Phase
    # attribution") — keep _tick and the phase methods in lockstep.

    def _fault_phase(self, carry: SimCarry, t):
        """Fault-plane point events at tick START (docs/FAULTS.md):
        scheduled restarts revive CRASHED slots, then scheduled crashes
        flip status and purge the victims' in-flight calendar rows (a
        message in flight toward an instance crashing this tick is lost
        on the wire, never delivered posthumously). Compiled out
        entirely when no schedule is declared. Returns ``(carry,
        crashed_t, restarted_t, purged_t, dead)`` — ``dead`` is the
        post-event crashed-lane mask the transport uses to kill traffic
        to dead lanes (None without a schedule)."""
        crashed_t = jnp.int32(0)
        restarted_t = jnp.int32(0)
        purged_t = jnp.int32(0)
        faults = self.faults

        def _to_lanes(mask):  # [N] plan mask → [n_lanes] (hosts never fault)
            if not self.hosts:
                return mask
            return jnp.concatenate(
                [mask, jnp.zeros((len(self.hosts),), bool)]
            )

        if faults is not None and faults.has_restarts:
            # restart revives CRASHED slots (fault- or plan-crashed): the
            # container is rebooted with its identity — state re-runs
            # ``testcase.init`` under the instance's original PRNG key,
            # while its sync history (counts, last_seq, cursors) persists
            # exactly like Redis state outlives a process restart.
            revive = faults.restart_mask_at(t) & (
                carry.status[: self.n] == CRASH
            )
            restarted_t = jnp.sum(revive.astype(jnp.int32))

            def _revive(states):
                out = []
                virt = self._virt(carry.live_counts)
                for gi, g in enumerate(self.groups):
                    gs = jnp.arange(
                        g.offset, g.offset + g.count, dtype=jnp.int32
                    )
                    gseq = jnp.arange(g.count, dtype=jnp.int32)
                    gkeys = carry.keys[g.offset : g.offset + g.count]

                    def init_one(gs_, gseq_, k_, _g=g, _virt=virt):
                        return self.tc.init(
                            self._env_for(_g, gs_, gseq_, k_, virt=_virt)
                        )

                    fresh = jax.vmap(init_one)(gs, gseq, gkeys)
                    rv = revive[g.offset : g.offset + g.count]

                    def sel(new_leaf, old_leaf, _rv=rv):
                        a = _rv.reshape(
                            _rv.shape + (1,) * (new_leaf.ndim - 1)
                        )
                        return jnp.where(a, new_leaf, old_leaf)

                    out.append(jax.tree.map(sel, fresh, states[gi]))
                return tuple(out)

            # cond so restart-free ticks never pay the vmapped re-init
            states0 = jax.lax.cond(
                jnp.any(revive), _revive, lambda s: s, carry.states
            )
            revive_l = _to_lanes(revive)
            carry = dataclasses.replace(
                carry,
                states=states0,
                status=jnp.where(revive_l, RUNNING, carry.status),
                finished_at=jnp.where(revive_l, -1, carry.finished_at),
            )
        if faults is not None and faults.has_crashes:
            kill = faults.crash_mask_at(t) & (
                carry.status[: self.n] == RUNNING
            )
            crashed_t = jnp.sum(kill.astype(jnp.int32))
            kill_l = _to_lanes(kill)
            # purge the victims' in-flight calendar rows (cond-gated: the
            # O(L·N·SLOTS) sweep runs only on ticks a crash fires)
            if self.netmatrix:
                # matrix-attributing purge: the same sweep also charges
                # each killed message's (sender group, crashed receiver
                # group) cell so chaos losses land in the right cells —
                # accumulated straight into the carry to keep this
                # phase's return signature unchanged
                gh = self._nm_gh
                cal0, purged_t, pmat = jax.lax.cond(
                    jnp.any(kill),
                    lambda c: purge_dst_matrix(
                        c, kill_l, self._nm_group_of, gh
                    ),
                    lambda c: (
                        c,
                        jnp.int32(0),
                        jnp.zeros((gh, gh), jnp.int32),
                    ),
                    carry.cal,
                )
                net_mat0 = carry.net_mat.at[NM_FAULT].add(pmat)
            else:
                cal0, purged_t = jax.lax.cond(
                    jnp.any(kill),
                    lambda c: purge_dst(c, kill_l),
                    lambda c: (c, jnp.int32(0)),
                    carry.cal,
                )
                net_mat0 = carry.net_mat
            carry = dataclasses.replace(
                carry,
                cal=cal0,
                net_mat=net_mat0,
                status=jnp.where(kill_l, CRASH, carry.status),
                finished_at=jnp.where(kill_l, t, carry.finished_at),
            )
        # crashed lanes kill traffic addressed to (or somehow from) them
        # at send time — counted as fault_dropped in the transport
        dead = (carry.status == CRASH) if faults is not None else None
        return carry, crashed_t, restarted_t, purged_t, dead

    def _step_phase(self, carry: SimCarry, inbox_all, t) -> dict:
        """The vmapped user-step phase: per-group ``testcase.step`` under
        ``jax.vmap`` (one vmap per group, so per-group params stay
        static), terminal-instance freezing, the host-echo outbox merge,
        and the per-group output planes concatenated to the full
        instance axis (reconfig planes host-padded). Pure dataflow from
        ``(carry, inbox)`` to the merged planes — a standalone phase so
        ``sim/phases.py`` can lower and cost it in isolation."""
        cls = type(self.tc)
        # live membership snapshot served to every instance's SyncView
        # (see sync_kernel.live_per_group — the degraded-barrier target)
        live_g = live_per_group(carry.status, self.groups)
        sub_payload, sub_valid = make_sub_window(carry.sync, cls.SUB_K)
        env_keys = jax.vmap(jax.random.fold_in)(
            carry.keys, jnp.broadcast_to(t, (self.n,))
        )
        virt = self._virt(carry.live_counts)

        outs: list[StepOut] = []
        for gi, g in enumerate(self.groups):
            lo, hi = g.offset, g.offset + g.count
            gs = jnp.arange(lo, hi, dtype=jnp.int32)
            gseq = jnp.arange(g.count, dtype=jnp.int32)
            inbox_g = Inbox(
                payload=inbox_all.payload[:, :, lo:hi],
                src=inbox_all.src[:, lo:hi],
                valid=inbox_all.valid[:, lo:hi],
            )
            sync_g = SyncView(
                counts=carry.sync.counts,
                last_seq=carry.sync.last_seq[:, lo:hi],
                sub_payload=sub_payload[lo:hi],
                sub_valid=sub_valid[lo:hi],
                rejected=carry.rejected[lo:hi],
                dropped=carry.sync.dropped,
                live=live_g,
            )

            def step_one(gs_, gseq_, k_, state_, inbox_, syncv_, _g=g):
                env = self._env_for(_g, gs_, gseq_, k_, virt=virt)
                return self.tc.step(env, state_, inbox_, syncv_, t)

            # Outputs come back in plane layout (instance axis LAST via
            # out_axes=-1) so downstream kernels never touch an array whose
            # minor dim is a small message axis (see net.py layout rule).
            out = jax.vmap(
                step_one,
                in_axes=(
                    0,
                    0,
                    0,
                    0,
                    Inbox(payload=2, src=1, valid=1),
                    SyncView(
                        counts=None,
                        last_seq=1,  # stored [S, N]: instance axis is 1
                        sub_payload=0,
                        sub_valid=0,
                        rejected=0,
                        dropped=None,  # global per-topic totals
                        live=None,  # global per-group live counts
                    ),
                ),
                out_axes=StepOut(
                    state=0,
                    status=0,
                    outbox=Outbox(dst=-1, payload=-1, valid=-1),
                    signals=-1,
                    pub_payload=-1,
                    pub_valid=-1,
                    sub_consume=-1,
                    net_shape=-1,
                    net_shape_valid=0,
                    net_filters=-1,
                    net_filters_valid=0,
                    net_rules=-1,
                    net_rules_valid=0,
                    region=0,
                    region_valid=0,
                ),
            )(gs, gseq, env_keys[lo:hi], carry.states[gi], inbox_g, sync_g)
            outs.append(out)

        # --- merge per-group outputs along the instance axis, masking
        # instances that already terminated (frozen like exited containers).
        # (Host lanes past self.n have their own echo path below.)
        active = carry.status[: self.n] == RUNNING  # [N]

        def freeze(old_leaf, new_leaf, lo, hi):
            a = active[lo:hi]
            a = a.reshape(a.shape + (1,) * (new_leaf.ndim - 1))
            return jnp.where(a, new_leaf, old_leaf)

        new_states = tuple(
            jax.tree.map(
                partial(freeze, lo=g.offset, hi=g.offset + g.count),
                carry.states[gi],
                outs[gi].state,
            )
            for gi, g in enumerate(self.groups)
        )

        def cat0(getter):
            return jnp.concatenate([getter(o) for o in outs], axis=0)

        def catl(getter):  # plane fields: instance axis is last
            return jnp.concatenate([getter(o) for o in outs], axis=-1)

        status_new = cat0(lambda o: o.status)
        status = jnp.where(active, status_new, carry.status[: self.n])
        finished_at = jnp.where(
            active & (status_new != RUNNING), t, carry.finished_at[: self.n]
        )
        if self.hosts:
            status = jnp.concatenate([status, carry.status[self.n :]])
            finished_at = jnp.concatenate(
                [finished_at, carry.finished_at[self.n :]]
            )

        dst = catl(lambda o: o.outbox.dst)  # [O, N]
        payload = catl(lambda o: o.outbox.payload)  # [O, W, N]
        valid = catl(lambda o: o.outbox.valid) & active[None, :]

        if self.hosts:
            # Echo service: every message delivered to a host lane goes
            # straight back to its sender, payload verbatim, next tick —
            # the http-echo container behind a whitelisted control route.
            h_dst = inbox_all.src[:, self.n :]  # [SLOTS, H]
            h_val = inbox_all.valid[:, self.n :]
            h_pay = jnp.moveaxis(
                inbox_all.payload[:, :, self.n :], 0, 1
            )  # [SLOTS, W, H]
            rows = max(dst.shape[0], h_dst.shape[0])

            def pad_rows(x):
                if x.shape[0] >= rows:
                    return x
                pad = jnp.zeros((rows - x.shape[0],) + x.shape[1:], x.dtype)
                return jnp.concatenate([x, pad])

            dst = jnp.concatenate([pad_rows(dst), pad_rows(h_dst)], axis=-1)
            payload = jnp.concatenate(
                [pad_rows(payload), pad_rows(h_pay)], axis=-1
            )
            valid = jnp.concatenate([pad_rows(valid), pad_rows(h_val)], axis=-1)

        active_row = active[None, :]
        signals = catl(lambda o: o.signals) * active_row.astype(jnp.int32)
        pub_payload = catl(lambda o: o.pub_payload)  # [T, PW, N]
        pub_valid = catl(lambda o: o.pub_valid) & active_row
        sub_consume = catl(lambda o: o.sub_consume) * active_row.astype(
            jnp.int32
        )

        net_shape = catl(lambda o: o.net_shape)  # [7, N]
        net_shape_valid = cat0(lambda o: o.net_shape_valid) & active

        def merge_reconfig_plane(width, zero_shape, getter, vgetter):
            """Concat a per-group OPTIONAL reconfig plane along the
            instance axis: groups emitting the 0-width sentinel get a
            zero plane with valid=False so the concat stays rectangular;
            (None, None) when no group emits at all."""
            if width <= 0 or not any(
                getter(o).shape[0] == width for o in outs
            ):
                return None, None
            planes, valids = [], []
            for gi, o in enumerate(outs):
                count = self.groups[gi].count
                if getter(o).shape[0] == width:
                    planes.append(getter(o))
                    valids.append(vgetter(o))
                else:
                    planes.append(jnp.zeros(zero_shape(count), jnp.int32))
                    valids.append(jnp.zeros((count,), bool))
            return (
                jnp.concatenate(planes, axis=-1),
                jnp.concatenate(valids, axis=0) & active,
            )

        n_regions = self.n_regions
        net_filters, net_filters_valid = merge_reconfig_plane(
            n_regions,
            lambda c: (n_regions, c),
            lambda o: o.net_filters,
            lambda o: o.net_filters_valid,
        )
        if net_filters is None:  # no group drives filters
            net_filters = jnp.zeros((n_regions, self.n), jnp.int32)
            net_filters_valid = jnp.zeros((self.n,), bool)
        n_rules = cls.FILTER_RULES if "filter_rules" in cls.SHAPING else 0
        net_rules, net_rules_valid = merge_reconfig_plane(
            n_rules,
            lambda c: (n_rules, 3, c),
            lambda o: o.net_rules,
            lambda o: o.net_rules_valid,
        )
        net_region = cat0(lambda o: o.region)
        net_region_valid = cat0(lambda o: o.region_valid) & active
        if self.hosts:
            # host lanes never reconfigure: pad the update planes with
            # valid=False columns so shapes match the n_lanes link state
            h = len(self.hosts)

            def pad_cols(x, fill=0):
                pad = jnp.full(x.shape[:-1] + (h,), fill, x.dtype)
                return jnp.concatenate([x, pad], axis=-1)

            net_shape = pad_cols(net_shape)
            net_shape_valid = pad_cols(net_shape_valid, False)
            net_filters = pad_cols(net_filters)
            net_filters_valid = pad_cols(net_filters_valid, False)
            net_region = pad_cols(net_region)
            net_region_valid = pad_cols(net_region_valid, False)
            if net_rules is not None:
                net_rules = pad_cols(net_rules)
                net_rules_valid = pad_cols(net_rules_valid, False)
        return {
            "states": new_states,
            "status": status,
            "finished_at": finished_at,
            "dst": dst,
            "payload": payload,
            "valid": valid,
            "signals": signals,
            "pub_payload": pub_payload,
            "pub_valid": pub_valid,
            "sub_consume": sub_consume,
            "net_shape": net_shape,
            "net_shape_valid": net_shape_valid,
            "net_filters": net_filters,
            "net_filters_valid": net_filters_valid,
            "net_rules": net_rules,
            "net_rules_valid": net_rules_valid,
            "net_region": net_region,
            "net_region_valid": net_region_valid,
        }

    def _netmatrix_send(self, flow, dst) -> jax.Array:
        """Scatter one tick's per-message send-side fates into the
        [NM_CHANNELS, GH, GH] matrix delta. ``flow`` is the transport's
        [4, M] per-original-message counts (sent copies, enqueued
        copies, rejected, fault-killed — net.enqueue ``want_flow``) and
        ``dst`` the POST-translation [O, n_lanes] physical destination
        plane; message m's sender lane is ``m % n_lanes`` (the
        transport's flattening order), and an invalid destination is
        charged to its clipped lane's group — consistent on both the
        sent and dropped sides, so conservation closes cell-wise. The
        delivered channel is filled receiver-side (_netmatrix_delivered)
        and the crash-purge fault term in _fault_phase."""
        gh = self._nm_gh
        g = self._nm_group_of
        dst_f = dst.reshape(-1)
        rows = dst_f.shape[0] // self.n_lanes
        srcg = jnp.tile(g, rows)
        dstg = g[jnp.clip(dst_f, 0, self.n_lanes - 1)]
        cell = srcg * gh + dstg
        sent_m, enq_m, rej_m, fault_m = flow
        # per-message residual: copies that rolled the shaping dice and
        # lost (loss/partition/filter/duplicate-then-drop) — the same
        # identity the scalar dropped_t closes in _tick
        drop_m = sent_m - enq_m - rej_m - fault_m
        counts = jnp.stack([sent_m, enq_m, drop_m, rej_m, fault_m])
        chan = jnp.asarray([0, 1, 3, 4, 5], jnp.int32)  # 2 = delivered
        idx = chan[:, None] * (gh * gh) + cell[None, :]
        flat = (
            jnp.zeros((NM_CHANNELS * gh * gh,), jnp.int32)
            .at[idx.reshape(-1)]
            .add(counts.reshape(-1))
        )
        return flat.reshape(NM_CHANNELS, gh, gh)

    def _netmatrix_delivered(self, inbox) -> jax.Array:
        """[GH, GH] count of this tick's deliveries per (sender group,
        receiver group) cell, read off the popped inbox BEFORE any
        virtual-id translation: ``inbox.src`` holds PHYSICAL provenance
        lanes there (the matrix plane forces track_src on) and column j
        IS receiver lane j. Host echo deliveries land in the hosts
        row/column, so Σ cells == delivered_t exactly."""
        gh = self._nm_gh
        g = self._nm_group_of
        srcg = g[jnp.clip(inbox.src, 0, self.n_lanes - 1)]
        dstg = g[None, :]
        idx = jnp.where(inbox.valid, srcg * gh + dstg, jnp.int32(gh * gh))
        return (
            jnp.zeros((gh * gh,), jnp.int32)
            .at[idx.reshape(-1)]
            .add(1, mode="drop")
            .reshape(gh, gh)
        )

    def _net_commit_phase(self, cal, link, step: dict, t, k_msg, dead, virt=None):
        """Transport commit: enqueue this tick's sends into the calendar
        (the PERF.md hot path — three scatter/gather ops under xla, the
        hand-tiled kernels under pallas) and apply the plan-driven link
        reconfigurations. Returns ``(cal, fb, link, bw_changed_t,
        nm_send)`` — ``bw_changed_t`` is this tick's count of bandwidth
        changes under a standing backlog (the HTB bound-approximation
        counter), ``nm_send`` the traffic-matrix send-side delta (None
        when the matrix plane is compiled out). The matrix scatter reads
        the transport's already-materialized per-message flow tensor —
        OUTSIDE the pallas commit kernel — so both backends produce
        bit-equal matrices.

        Under shape bucketing (``virt``), plan-emitted VIRTUAL
        destinations translate to physical lanes here — one select per
        group over the already-materialized dst plane — and the
        transport's shaping dice hash VIRTUAL message indices, so every
        stochastic draw matches the unpadded run's."""
        cls = type(self.tc)
        dst = step["dst"]
        midx = None
        if virt is not None:
            dst = self._translate_dst(dst, virt)
            midx = self._virtual_midx(dst.shape[0], virt)
        cal, fb = enqueue(
            cal,
            link,
            dst,
            step["payload"],
            step["valid"],
            t,
            self.tick_ms,
            k_msg,
            slot_mode=cls.SLOT_MODE,
            features=tuple(cls.SHAPING),
            control_start=self.n if self.hosts else None,
            stacking=cls.CROSS_TICK_STACKING,
            bw_queue_cap=cls.BW_QUEUE_MSGS,
            validate=self.validate,
            faults=self.faults,
            dead=dead,
            # flight recorder: per-message transport fate for traced
            # send events (compiled out when no trace plan is declared)
            want_fate=self.trace is not None,
            # traffic matrix: per-message flow counts (same tensors the
            # fate plane reads, summed with .add instead of .max)
            want_flow=self.netmatrix,
            transport=self.transport,
            dice_idx=midx,
            mesh=self.mesh,
        )
        nm_send = None
        if self.netmatrix:
            with jax.named_scope("tg.netmatrix_send"):
                nm_send = self._netmatrix_send(fb.flow, dst)
        new_link = apply_net_updates(
            link,
            step["net_shape"],
            step["net_shape_valid"],
            step["net_filters"],
            step["net_filters_valid"],
            step["net_region"],
            step["net_region_valid"],
            step["net_rules"],
            step["net_rules_valid"],
        )
        bw_changed_t = jnp.int32(0)
        if fb.backlog is not None:  # HTB queue depths advance each tick
            new_link = dataclasses.replace(new_link, backlog=fb.backlog)
            # ADVICE r4: the queue-occupancy bound values standing busy
            # time at the CURRENT rate, so it is approximate exactly when
            # the rate changes under a nonzero backlog — count those
            # (src, tick) events and surface them (journal + warning)
            from .net import BANDWIDTH as _BW

            changed = (
                new_link.egress[_BW] != link.egress[_BW]
            ) & (fb.backlog > 0)
            bw_changed_t = jnp.sum(changed.astype(jnp.int32))
        return cal, fb, new_link, bw_changed_t, nm_send

    def _telemetry_phase(
        self,
        t,
        status,
        sync,
        delivered_t,
        sent_t,
        enqueued_t,
        dropped_t,
        rejected_t,
        cal_depth,
        crashed_t,
        restarted_t,
        fault_dropped_t,
    ) -> jax.Array:
        """Assemble the per-tick counter-block row
        (TELEMETRY_FIXED_COLUMNS order, then one live-instance count per
        group) — all scalar reductions over arrays the tick already
        materialized, so the block costs no extra memory traffic of the
        calendar's order."""
        sig_occ, pub_occ = sync_occupancy(sync)
        live = [
            jnp.sum(
                (status[g.offset : g.offset + g.count] == RUNNING).astype(
                    jnp.int32
                )
            )
            for g in self.groups
        ]
        return jnp.stack(
            [
                t,
                delivered_t,
                sent_t,
                enqueued_t,
                dropped_t,
                rejected_t,
                # int multiply: exact over the full int32 range (the
                # float32 detour would round above 2^24 bytes/tick); the
                # column wraps only past 2^31/MSG_BYTES ≈ 8.4M msgs/tick
                enqueued_t * jnp.int32(MSG_BYTES),
                cal_depth,
                sig_occ,
                pub_occ,
                crashed_t,
                restarted_t,
                fault_dropped_t,
                *live,
            ]
        ).astype(jnp.int32)

    def _tick(self, carry: SimCarry) -> tuple[SimCarry, jax.Array]:
        """One simulated tick. Returns (carry', telemetry vector, trace
        rows) — the vector is the per-tick counter block row ([K] int32,
        K = 0 when telemetry is compiled out; see
        telemetry.TELEMETRY_FIXED_COLUMNS for the column schema)."""
        t = carry.t
        # status snapshot BEFORE the fault plane touches it — the flight
        # recorder's status-transition events must capture scheduled
        # crashes/restarts as well as plan-driven terminals
        status_prev = carry.status

        with jax.named_scope("tg.faults"):
            carry, crashed_t, restarted_t, purged_t, dead = (
                self._fault_phase(carry, t)
            )

        virt = self._virt(carry.live_counts)
        with jax.named_scope("tg.deliver"):
            cal, inbox_all = deliver(
                carry.cal, t, transport=self.transport, mesh=self.mesh
            )
        nm_del = None
        if self.netmatrix:
            # receiver-side matrix capture on the PHYSICAL inbox (before
            # any virtual-id translation below)
            with jax.named_scope("tg.netmatrix_deliver"):
                nm_del = self._netmatrix_delivered(inbox_all)
            if not type(self.tc).TRACK_SRC:
                # the plan opted out of provenance but the matrix plane
                # forced the src plane on — hand the plan the all-zero
                # src values a valid-plane calendar serves (net.deliver
                # track_src=False contract), so plan behavior and
                # results stay bit-equal with the plane off
                inbox_all = Inbox(
                    payload=inbox_all.payload,
                    src=jnp.zeros_like(inbox_all.src),
                    valid=inbox_all.valid,
                )
        if virt is not None:
            # delivered provenance back to virtual ids (plans reply to
            # inbox.src — the values must match the unpadded run's)
            inbox_all = Inbox(
                payload=inbox_all.payload,
                src=self._translate_src(inbox_all.src, virt),
                valid=inbox_all.valid,
            )
        # delivery-latency histogram (telemetry plane): bin this tick's
        # deliveries by (t - enqueue tick) per receiver group. The etick
        # row survives deliver's occupancy clear (only the occupancy
        # plane is zeroed), so the pre-deliver calendar is read against
        # the popped inbox's validity; host echo lanes are excluded by
        # the out-of-range group map.
        if self.telemetry:
            with jax.named_scope("tg.lat_hist"):
                lat_hist_t = latency_histogram(
                    carry.cal,
                    inbox_all,
                    t,
                    self._lat_group_of,
                    len(self.groups),
                    LATENCY_BINS,
                )
        else:
            lat_hist_t = None
        # messages popped into inboxes this tick (incl. host echo lanes)
        delivered_t = jnp.sum(inbox_all.valid.astype(jnp.int32))

        with jax.named_scope("tg.step"):
            step = self._step_phase(carry, inbox_all, t)
        status = step["status"]

        net_key, k_msg = jax.random.split(carry.net_key)
        with jax.named_scope("tg.net_commit"):
            cal, fb, link, bw_changed_t, nm_send = self._net_commit_phase(
                cal, carry.link, step, t, k_msg, dead, virt=virt
            )
        with jax.named_scope("tg.sync"):
            sync = update_sync(
                carry.sync,
                step["signals"],
                step["pub_payload"],
                step["pub_valid"],
                step["sub_consume"],
            )

        # first collision wins: keep the earliest (dst, slot) for the error
        collision_where = jnp.where(
            (carry.collisions == 0) & (fb.collisions > 0),
            fb.collision_where,
            carry.collision_where,
        )

        # --- message-flow accounting: conservation closes per tick —
        # sent (incl. duplicate copies) = enqueued + rejected + dropped
        # + fault_dropped(send-side), so every loss (loss%, DROP filters,
        # bandwidth, slot overflow, bad dst, fault kills) lands in
        # exactly one counter. Crash purges remove already-enqueued
        # messages, so they move from the in-flight depth into
        # fault_dropped — cumulatively, sent = delivered + in-flight +
        # dropped + rejected + fault_dropped stays exact.
        rejected_t = jnp.sum(fb.rejected)
        dropped_t = fb.sent - fb.enqueued - rejected_t - fb.fault_dropped
        fault_dropped_t = fb.fault_dropped + purged_t
        cal_depth = carry.cal_depth + fb.enqueued - delivered_t - purged_t

        new_carry = self._constrain(
            SimCarry(
                states=step["states"],
                status=status,
                finished_at=step["finished_at"],
                cal=cal,
                link=link,
                sync=sync,
                rejected=fb.rejected,
                keys=carry.keys,
                net_key=net_key,
                t=t + 1,
                clamped=carry.clamped + fb.clamped,
                bw_dropped=carry.bw_dropped + fb.bw_dropped,
                bw_rate_changed=carry.bw_rate_changed + bw_changed_t,
                collisions=carry.collisions + fb.collisions,
                collision_where=collision_where,
                msgs_delivered=_acc_add(carry.msgs_delivered, delivered_t),
                msgs_sent=_acc_add(carry.msgs_sent, fb.sent),
                msgs_enqueued=_acc_add(carry.msgs_enqueued, fb.enqueued),
                msgs_dropped=_acc_add(carry.msgs_dropped, dropped_t),
                msgs_rejected=_acc_add(carry.msgs_rejected, rejected_t),
                cal_depth=cal_depth,
                faults_crashed=carry.faults_crashed + crashed_t,
                faults_restarted=carry.faults_restarted + restarted_t,
                fault_dropped=_acc_add(
                    carry.fault_dropped, fault_dropped_t
                ),
                lat_hist=(
                    carry.lat_hist + lat_hist_t
                    if self.telemetry
                    else None
                ),
                live_counts=carry.live_counts,
                # traffic matrix: the fault-phase purge term is already
                # inside carry.net_mat (accumulated there to keep the
                # phase signature stable); fold in this tick's send-side
                # channels and the receiver-side delivered cells
                net_mat=(
                    carry.net_mat + nm_send.at[NM_DELIVERED].add(nm_del)
                    if self.netmatrix
                    else None
                ),
                net_bw_hiwater=(
                    jnp.maximum(
                        carry.net_bw_hiwater,
                        jnp.zeros_like(carry.net_bw_hiwater)
                        .at[self._nm_group_of]
                        .max(link.backlog),
                    )
                    if carry.net_bw_hiwater is not None
                    else None
                ),
            )
        )
        # flight-recorder event rows for this tick ([R, 5] int32; R = 0
        # when no trace plan is compiled in)
        with jax.named_scope("tg.trace"):
            trows = self._trace_tick_rows(
                t,
                status_prev,
                status,
                step["signals"],
                step["dst"],
                step["valid"],
                fb.fate,
                inbox_all,
            )
        if not self.telemetry:
            return new_carry, jnp.zeros((0,), jnp.int32), trows
        with jax.named_scope("tg.telemetry"):
            tele = self._telemetry_phase(
                t,
                status,
                sync,
                delivered_t,
                fb.sent,
                fb.enqueued,
                dropped_t,
                rejected_t,
                cal_depth,
                crashed_t,
                restarted_t,
                fault_dropped_t,
            )
        return new_carry, tele, trows

    def _trace_tick_rows(
        self, t, status_prev, status_new, signals, dst, valid, fate, inbox
    ) -> jax.Array:
        """One tick's flight-recorder rows: ``[R, 5]`` int32 with columns
        ``(tick, lane, kind, a, b)``; unused slots carry kind = -1 (the
        host decoder drops them). R is static — per traced lane, one
        status slot, one per sync state, one per (host-padded) outbox
        row, one per inbox slot — so the rows ride the chunk scan's
        stacked ys like the counter block, with zero extra host syncs.
        Returns ``[0, 5]`` when no trace plan is compiled in."""
        if self.trace is None:
            return jnp.zeros((0, 5), jnp.int32)
        lanes = self._trace_lanes  # [L] int32, static

        def repl(x):
            """Pin a traced-lane gather to fully-replicated layout. The
            source arrays shard by instance; without the constraint the
            SPMD partitioner emits a partial-gather whose shard-wise
            combine corrupts the masked -1 slots (observed: row values
            summed across shards). Per-lane values are L-sized, so the
            forced all-gather is noise."""
            if self.mesh is None:
                return x
            return jax.lax.with_sharding_constraint(
                x,
                jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec()
                ),
            )

        def rows(hit, kind, a, b):
            """(…, L)-shaped event family → flattened [-1, 5] rows."""
            k = jnp.where(hit, jnp.int32(kind), jnp.int32(-1))
            shape = k.shape
            return jnp.stack(
                [
                    jnp.broadcast_to(t, shape),
                    jnp.broadcast_to(lanes, shape),
                    k,
                    jnp.broadcast_to(jnp.asarray(a, jnp.int32), shape),
                    jnp.broadcast_to(jnp.asarray(b, jnp.int32), shape),
                ],
                axis=-1,
            ).reshape(-1, 5)

        parts = []
        # status transitions — plan terminals AND scheduled crash/restart
        sp = repl(status_prev[lanes])
        sn = repl(status_new[lanes])
        parts.append(rows(sp != sn, EV_STATUS, sn, sp))
        # sync signals (barrier entry): one slot per declared state
        if signals.shape[0] > 0:
            sig = repl(signals[:, lanes])  # [S, L]
            sid = jnp.broadcast_to(
                jnp.arange(sig.shape[0], dtype=jnp.int32)[:, None],
                sig.shape,
            )
            parts.append(rows(sig > 0, EV_SIGNAL, sid, 0))
        # sends, with the transport fate in original outbox order
        f = repl(fate.reshape(dst.shape)[:, lanes])  # [O, L]
        parts.append(
            rows(repl(valid[:, lanes]), EV_SEND, repl(dst[:, lanes]), f)
        )
        # deliveries, with provenance (src reads 0 under TRACK_SRC=False)
        parts.append(
            rows(
                repl(inbox.valid[:, lanes]),
                EV_DELIVER,
                repl(inbox.src[:, lanes]),
                0,
            )
        )
        return jnp.concatenate(parts, axis=0)

    # ------------------------------------------------------------- sizing

    def estimate_carry_bytes(self) -> int:
        """Exact byte size of the run's device-resident carry (states,
        calendar planes, link tensors, sync state), computed WITHOUT
        allocating or compiling: ``jax.eval_shape`` traces ``init_carry``
        abstractly and the leaf shapes/dtypes are summed. The per-run
        capacity precheck (executor) compares a multiple of this against
        device memory — the analog of the reference's cluster capacity
        precheck (``pkg/runner/cluster_k8s.go:958-1012``)."""
        if self.live_counts is not None:
            shapes = jax.eval_shape(
                lambda: self.init_carry(
                    0, np.asarray(self.live_counts, np.int32)
                )
            )
        else:
            shapes = jax.eval_shape(lambda: self.init_carry(0))
        return sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree.leaves(shapes)
        )

    # ----------------------------------------------------------- execution

    def _chunk_step(self, carry: SimCarry):
        """Run up to `chunk` ticks; ticks after global completion no-op.

        Returns ``(carry, done)``, extended positionally by the compiled-
        in observability planes: with ``telemetry=True``, a ``[chunk, K]``
        per-tick counter block and the chunk's ``[G, LATENCY_BINS]``
        latency-histogram delta (read out of the carry and zeroed, so the
        device counter never wraps); with a trace plan, a
        ``[chunk, R, 5]`` flight-recorder block. Post-completion padding
        rows carry tick/kind = -1 and are dropped by the host decoders.
        Every block rides the scan's stacked ys (or the carry itself), so
        it reaches the host in the same dispatch result as the done flag
        — no extra device round-trip."""
        k = self._tele_k
        r = self._trace_nrows

        def all_done(c):
            # host lanes never terminate — only plan instances gate done.
            # With a fault schedule, the run must also outlive the last
            # scheduled event: an all-crashed fleet with a restart still
            # to come is paused, not finished.
            done = jnp.all(c.status[: self.n] != RUNNING)
            if self.faults is not None:
                done = done & (c.t > self.faults.last_event_tick)
            return done

        def body(c, _):
            c, tele, trows = jax.lax.cond(
                all_done(c),
                lambda x: (
                    x,
                    jnp.full((k,), -1, jnp.int32),
                    jnp.full((r, 5), -1, jnp.int32),
                ),
                self._tick,
                c,
            )
            return c, (tele, trows)

        carry, (tele, trows) = jax.lax.scan(
            body, carry, None, length=self.chunk
        )
        done = all_done(carry)
        out = [carry, done]
        if self.telemetry:
            # flush-and-zero the histogram delta: the host accumulates
            # chunk deltas in python ints (no int32 wrap, ever)
            out.append(tele)
            out.append(carry.lat_hist)
            carry = dataclasses.replace(
                carry, lat_hist=jnp.zeros_like(carry.lat_hist)
            )
            out[0] = carry
        if self.netmatrix:
            # flush-and-zero the traffic-matrix delta (same discipline:
            # the host accumulates chunk deltas in int64)
            out.append(carry.net_mat)
            carry = dataclasses.replace(
                carry, net_mat=jnp.zeros_like(carry.net_mat)
            )
            out[0] = carry
        if self.trace is not None:
            out.append(trows)
        return tuple(out)

    def compiled_chunk(self):
        if self._chunk_fn is None:
            self._chunk_fn = jax.jit(self._chunk_step, donate_argnums=0)
        return self._chunk_fn

    def telemetry_schema(self) -> tuple[str, ...]:
        """Column names of the per-tick counter block, in device order:
        the fixed flow/occupancy counters, then one ``live_<group id>``
        column per group."""
        return TELEMETRY_FIXED_COLUMNS + tuple(
            f"live_{g.id}" for g in self.groups
        )

    def _dispatch_watched(
        self, fn, carry, ticks: int, timeout: float, cancel, on_stall
    ):
        """Run one chunk dispatch + done poll under a wall-clock watchdog.

        The device poll is the only place the host can hang indefinitely
        (a wedged device, a deadlocked cross-host collective): the
        dispatch runs in a daemon thread joined with ``timeout``, and on
        expiry the cancel event is set, ``on_stall(last_tick, chunk)``
        fires for journaling, and :class:`SimStallError` releases the
        worker thread — the abandoned dispatch thread dies with the
        process. Only sim-time ``max_ticks`` bounded a run before this."""
        import threading as _threading

        box: dict[str, Any] = {}

        def work():
            try:
                out = fn(carry)
                box["out"] = out
                box["done"] = _poll_done(out[1])
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["err"] = e

        th = _threading.Thread(
            target=work, daemon=True, name="sim-chunk-dispatch"
        )
        th.start()
        th.join(timeout)
        if th.is_alive():
            chunk_index = ticks // self.chunk
            if cancel is not None:
                cancel.set()
            if on_stall is not None:
                try:
                    on_stall(ticks, chunk_index)
                except Exception:  # noqa: BLE001 — diagnostics only
                    pass
            raise SimStallError(ticks, chunk_index, timeout)
        if "err" in box:
            raise box["err"]
        return box["out"], box["done"]

    def run(
        self,
        seed: int = 0,
        max_ticks: int = 10_000,
        cancel=None,
        on_chunk: Callable[[int], None] | None = None,
        observer: Callable[[int, "SimCarry"], None] | None = None,
        telemetry_cb: Callable[[np.ndarray], None] | None = None,
        lat_hist_cb: Callable[[np.ndarray], None] | None = None,
        trace_cb: Callable[[np.ndarray], None] | None = None,
        netmatrix_cb: Callable[[np.ndarray], None] | None = None,
        chunk_timeout: float = 0.0,
        chunk_sleep_ms: float = 0.0,
        on_stall: Callable[[int, int], None] | None = None,
        nan_guard: bool = False,
        perf=None,
        resume_carry=None,
        resume_ticks: int = 0,
        lat_hist_init=None,
        net_mat_init=None,
        live_counts=None,
    ) -> dict[str, Any]:
        """Step to completion. Returns host-side results:

        status [N], finished_at [N], ticks run, final per-group states,
        sync counters and journal counters.

        ``observer(ticks, carry)`` is called after every chunk with the live
        device carry — the periodic metrics-sampling hook (reading the carry
        forces a device sync, so observers should sample on a cadence, not
        every call).

        ``telemetry_cb(block)`` receives each chunk's ``[chunk, K]``
        per-tick counter block as host numpy (programs built with
        ``telemetry=True`` only). The read piggybacks on the done-flag
        poll: by the time the done scalar is host-visible the block is
        materialized, so this is a copy, not an extra blocking sync.
        The same applies to ``trace_cb(block)`` — each chunk's
        ``[chunk, R, 5]`` flight-recorder block (trace-plan programs
        only) — and to the per-chunk latency-histogram deltas, which the
        loop accumulates into ``results()['lat_hist']`` and hands to
        ``lat_hist_cb(delta)`` (the run health plane's per-chunk feed,
        ``sim/slo.py``) as host numpy: the delta was already read for
        the accumulator, so the callback adds no device traffic.

        ``chunk_timeout`` > 0 arms the per-chunk wall-clock watchdog
        (see :meth:`_dispatch_watched`); ``on_stall(last_tick, chunk)``
        is its journaling hook. ``chunk_sleep_ms`` > 0 sleeps host-side
        inside each chunk's timed window — the deterministic synthetic
        slowdown behind ``SimJaxConfig.debug_chunk_sleep_ms`` (the
        comparison plane's test knob; never program-shaping). ``nan_guard`` scans every float leaf of
        the carry after each chunk and fails fast naming the offending
        leaf and tick range — a debug flag (each scan is a device→host
        read of the whole carry).

        ``netmatrix_cb(delta)`` receives each chunk's traffic-matrix
        delta ([NM_CHANNELS, GH, GH] host int64; netmatrix programs
        only) under the same piggyback contract — the loop already read
        it for the ``results()['net_matrix']`` accumulator, so the
        callback adds no device traffic and no extra syncs.

        ``resume_carry`` seeds the loop with an already-device-resident
        carry instead of ``init_carry(seed)`` — the checkpoint plane's
        restore path (``sim/checkpoint.py``): ``resume_ticks`` fast-
        forwards the tick counter to the snapshot's chunk boundary and
        ``lat_hist_init`` / ``net_mat_init`` re-seed the host-side
        latency-histogram and traffic-matrix accumulators, so a resumed
        run's results are leaf-for-leaf those of an uninterrupted one
        (pinned by ``tests/test_sim_checkpoint.py``).

        ``perf`` is a performance-ledger hook object (``sim/perf.py``):
        ``on_compile(lower_secs, compile_secs, compiled)`` fires once
        from an AOT lower/compile pass before the first dispatch (only
        when ``perf.wants_aot`` — with the persistent compile cache
        warm, the loop's own first dispatch then reads the cache entry
        this pass wrote), and ``on_chunk(index, ticks, delta, wall)``
        fires per dispatch with its host-clock wall. The ledger shapes
        NO part of the program and adds NO device syncs — both pinned
        by tests.
        """
        import time as _time

        # init is traceable; jit it so construction is one dispatch rather
        # than hundreds of eager ops (matters on remote-tunneled devices).
        t0 = _time.perf_counter()
        if self.live_counts is not None and live_counts is None:
            live_counts = self.live_counts
        if resume_carry is not None:
            carry = resume_carry
        elif live_counts is not None:
            # bucketed init: the exact counts AND the seed are RUNTIME
            # inputs, so every composition in the bucket traces (and
            # caches) the same init program too
            carry = jax.jit(lambda s, lc: self.init_carry(s, lc))(
                np.int32(seed), np.asarray(live_counts, np.int32)
            )
        else:
            carry = jax.jit(lambda: self.init_carry(seed))()
        fn = self.compiled_chunk()
        if perf is not None and getattr(perf, "wants_aot", False):
            # AOT accounting pass: lower + compile the chunk program
            # out-of-line so the ledger records the true trace/lower vs
            # XLA-compile split and can harvest cost/memory analysis.
            # The compile lands in the persistent cache, so the loop's
            # first dispatch below re-traces but reads the cache entry
            # instead of compiling again. Best-effort: the ledger must
            # never fail the run it measures.
            try:
                from .perf import timed_lower_compile

                perf.on_compile(*timed_lower_compile(fn, carry))
            except Exception:  # noqa: BLE001 — accounting only
                pass
        ticks = int(resume_ticks) if resume_carry is not None else 0
        start_ticks = ticks
        compile_secs = 0.0
        # host-side accumulator for the per-chunk histogram deltas —
        # python/int64 arithmetic, so the totals never wrap; a resumed
        # run re-seeds it from the snapshot so the final histogram
        # equals an uninterrupted run's
        lat_hist_acc = None
        if self.telemetry:
            lat_hist_acc = (
                np.asarray(lat_hist_init, np.int64).copy()
                if lat_hist_init is not None
                else np.zeros((len(self.groups), LATENCY_BINS), np.int64)
            )
        net_mat_acc = None
        if self.netmatrix:
            gh = self._nm_gh
            net_mat_acc = (
                np.asarray(net_mat_init, np.int64).copy()
                if net_mat_init is not None
                else np.zeros((NM_CHANNELS, gh, gh), np.int64)
            )
        while ticks < max_ticks:
            # the first dispatch includes trace + XLA compile (and under
            # a mesh the second recompiles at the sharding fixed point —
            # see the compile_secs note below), so the watchdog budget —
            # sized for steady-state chunks — only arms from the third
            # dispatch on (counted from the resume point: a resumed
            # run's first dispatch pays compile again); a hang during
            # compile is bounded by the engine-level task controls
            watch = chunk_timeout and chunk_timeout > 0 and (
                ticks >= start_ticks + 2 * self.chunk
            )
            t_chunk = _time.perf_counter()
            if watch:
                out, done_host = self._dispatch_watched(
                    fn, carry, ticks, chunk_timeout, cancel, on_stall
                )
                carry = out[0]
                ticks += self.chunk
            else:
                out = fn(carry)
                carry, done = out[0], out[1]
                ticks += self.chunk
                # THE one blocking device→host sync per chunk (tests
                # count _poll_done calls to pin the telemetry plane's
                # zero-extra-syncs contract).
                done_host = _poll_done(done)
            if chunk_sleep_ms > 0:
                # debug slowdown (SimJaxConfig.debug_chunk_sleep_ms):
                # inside the timed window on purpose, so the ledger's
                # per-chunk walls — and everything judged from them —
                # see a deterministic synthetic regression
                _time.sleep(chunk_sleep_ms / 1000.0)
            if perf is not None:
                # host-clock wall of this dispatch + done poll — no
                # device reads beyond the poll the loop already paid
                perf.on_chunk(
                    ticks // self.chunk - 1,
                    ticks,
                    self.chunk,
                    _time.perf_counter() - t_chunk,
                )
            if nan_guard:
                _check_carry_finite(carry, ticks - self.chunk, ticks)
            if compile_secs == 0.0:
                # init + first chunk = trace/lower + XLA compile (or a
                # persistent-cache read — see utils/compile_cache) + one
                # chunk's execution; the honest over-count direction, same
                # convention as bench.py's compile_secs. Under a mesh the
                # SECOND dispatch recompiles once more: XLA assigns the
                # unconstrained per-group state leaves GSPMD shardings, so
                # the chunk retraces at that fixed point (stable from then
                # on — verified). That cost lands in run wall; the
                # sim:plan precompile warms BOTH variants.
                compile_secs = _time.perf_counter() - t0
            block_idx = 2
            if self.telemetry:
                if telemetry_cb is not None:
                    telemetry_cb(np.asarray(out[2]))
                delta = np.asarray(out[3], dtype=np.int64)
                lat_hist_acc += delta
                if lat_hist_cb is not None:
                    lat_hist_cb(delta)
                block_idx = 4
            if self.netmatrix:
                nm_delta = np.asarray(out[block_idx], dtype=np.int64)
                net_mat_acc += nm_delta
                if netmatrix_cb is not None:
                    netmatrix_cb(nm_delta)
                block_idx += 1
            if self.trace is not None and trace_cb is not None:
                trace_cb(np.asarray(out[block_idx]))
            if on_chunk is not None:
                on_chunk(ticks)
            if observer is not None:
                observer(ticks, carry)
            if done_host:
                break
            if cancel is not None and cancel.is_set():
                break
        res = self.results(carry, ticks, live_counts=live_counts)
        res["compile_secs"] = compile_secs
        if lat_hist_acc is not None:
            # per-receiver-group delivery-latency bin counts (see
            # telemetry.LATENCY_BINS) — Σ over bins == delivered plan
            # messages, exactly (host lanes excluded)
            res["lat_hist"] = lat_hist_acc.tolist()
        if net_mat_acc is not None:
            # cumulative src-group × dst-group traffic matrix
            # ([NM_CHANNELS, GH, GH]; sim/netmatrix.py channel order) —
            # per channel, Σ cells equals the flow total exactly
            res["net_matrix"] = net_mat_acc.tolist()
        return res

    def virtual_groups(self, live_counts=None) -> tuple[GroupSpec, ...]:
        """The EXACT (virtual) group layout of a bucketed program —
        static python ints, the layout every host-side reporting surface
        works in. ``live_counts`` overrides the construction-time plan
        (run packing re-uses one program across members whose exact
        sizes differ within the bucket)."""
        live = tuple(
            int(c) for c in (live_counts or self.live_counts or ())
        )
        out, off = [], 0
        for gi, g in enumerate(self.groups):
            out.append(
                GroupSpec(
                    id=g.id,
                    index=gi,
                    offset=off,
                    count=live[gi],
                    params=g.params,
                )
            )
            off += live[gi]
        return tuple(out)

    def results(
        self, carry: SimCarry, ticks: int, live_counts=None
    ) -> dict[str, Any]:
        # to_host assembles cross-host shards when the mesh spans multiple
        # processes (a collective — every process must call results());
        # single-process it is a plain device→host copy
        from .distributed import to_host

        if self.live_counts is not None:
            # bucketed run: demux the padded physical arrays back to the
            # EXACT layout — telemetry/results/callers never see a dead
            # lane, and the returned groups carry exact counts/offsets
            live = tuple(
                int(c) for c in (live_counts or self.live_counts)
            )
            vgroups = self.virtual_groups(live)
            status_h = to_host(carry.status)
            fin_h = to_host(carry.finished_at)
            segs = [
                (g.offset, g.offset + lv)
                for g, lv in zip(self.groups, live)
            ]
            status_x = np.concatenate(
                [status_h[lo:hi] for lo, hi in segs]
            )
            fin_x = np.concatenate([fin_h[lo:hi] for lo, hi in segs])
            states_x = tuple(
                jax.tree.map(
                    lambda leaf, _lv=lv: to_host(leaf)[:_lv],
                    carry.states[gi],
                )
                for gi, (g, lv) in enumerate(zip(self.groups, live))
            )
            base = self._results_tail(carry, ticks)
            base.update(
                status=status_x,
                finished_at=fin_x,
                states=states_x,
                groups=vgroups,
            )
            return base

        base = self._results_tail(carry, ticks)
        base.update(
            # host lanes are internal plumbing — plan instances only
            status=to_host(carry.status)[: self.n],
            finished_at=to_host(carry.finished_at)[: self.n],
            states=jax.tree.map(to_host, carry.states),
            groups=self.groups,
        )
        return base

    def _results_tail(self, carry: SimCarry, ticks: int) -> dict[str, Any]:
        """The layout-independent part of :meth:`results`: sync state,
        flow totals, fault counters, footprint — identical between the
        exact and the bucket-demuxed paths (dead lanes contribute
        nothing to any of these by construction)."""
        from .distributed import to_host

        return {
            "ticks": ticks,
            "tick_ms": self.tick_ms,
            "sync_counts": to_host(carry.sync.counts),
            "pub_dropped": to_host(carry.sync.dropped),
            "latency_clamped": int(to_host(carry.clamped)),
            "bw_queue_dropped": int(to_host(carry.bw_dropped)),
            "bw_rate_change_backlogged": int(to_host(carry.bw_rate_changed)),
            "collisions": int(to_host(carry.collisions)),
            "collision_where": to_host(carry.collision_where).tolist(),
            # cumulative message-flow totals — the per-tick telemetry
            # rows must sum exactly to these (conservation: sent =
            # enqueued + dropped + rejected; cal_depth = in-flight)
            "msgs_delivered": _acc_total(to_host(carry.msgs_delivered)),
            "msgs_sent": _acc_total(to_host(carry.msgs_sent)),
            "msgs_enqueued": _acc_total(to_host(carry.msgs_enqueued)),
            "msgs_dropped": _acc_total(to_host(carry.msgs_dropped)),
            "msgs_rejected": _acc_total(to_host(carry.msgs_rejected)),
            "cal_depth": int(to_host(carry.cal_depth)),
            # fault-injection plane (zeros when no schedule compiled in);
            # fault_dropped closes the chaos conservation identity:
            # sent = delivered + in-flight + dropped + rejected + it
            "faults_crashed": int(to_host(carry.faults_crashed)),
            "faults_restarted": int(to_host(carry.faults_restarted)),
            "fault_dropped": _acc_total(to_host(carry.fault_dropped)),
            # bandwidth-queue depth high-water per src group (matrix
            # plane + bandwidth_queue shaping only — monotone max, read
            # once here rather than flushed per chunk)
            **(
                {
                    "net_bw_hiwater": to_host(
                        carry.net_bw_hiwater
                    ).tolist()
                }
                if carry.net_bw_hiwater is not None
                else {}
            ),
            # device-resident carry footprint (eval_shape — no compile):
            # always reported so memory is part of every run's record
            "carry_bytes": self.estimate_carry_bytes(),
        }
