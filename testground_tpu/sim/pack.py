"""Run packing: many small concurrent compositions batched into ONE
device program via a leading run axis (PERF.md "Serving: buckets +
packing", ROADMAP item 2).

The engine queue used to serialize small runs one dispatch at a time
while the device sat mostly idle — a 8-instance composition costs the
same dispatch latency as a 100k one. This module lifts the jitted tick
over a RUN axis with ``jax.vmap`` (the same lift the engine already
uses for instances) so R compatible runs execute as one program and one
dispatch per chunk:

- **PackRunner** owns the device half: a vmapped ``init_carry`` over
  per-run ``(seed, live_counts)`` inputs and a vmapped ``_chunk_step``
  loop. The run-axis width is padded to a power of two (bounded by
  ``pack_max``) with DEAD dummy runs — status CRASH from tick 0, the
  same masking the shape-bucket plane uses for lanes — so every pack
  width in a ladder compiles (and caches) one program, with live
  membership as runtime data.
- **Straggler rule**: a run whose instances all terminated no-ops its
  ticks inside the vmapped ``lax.cond`` (select) instead of blocking
  the pack; its carry freezes, so its end-of-pack slice IS its
  result at its own finish tick. A canceled member (operator kill, SLO
  fail) is snapshotted at the chunk boundary it stopped caring at.
- **Host demux**: per-run telemetry blocks / latency-histogram deltas /
  SLO evaluation / perf rows split off the ``[R, ...]`` device blocks
  each chunk; each member's results are ``SimProgram.results`` over its
  run slice — bit-equal per run to an isolated run of the same seed
  (pinned by tests/test_sim_pack.py).

Compatibility (what may share a pack) is decided by the engine-side
admission key (``engine/pack.py``): same plan/case/params, same padded
bucket layout, same program gates (transport/telemetry/validate/chunk/
max_ticks), no faults/trace/hosts/cohort/checkpoint. Seeds and exact
live sizes are per-run runtime inputs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import numpy as np

__all__ = [
    "PACK_MIN_MEMBERS",
    "PackMember",
    "PackRunner",
    "pack_width",
]

# a pack of one is just a run — the admission layer never builds one
PACK_MIN_MEMBERS = 2


def pack_width(members: int, pack_max: int) -> int:
    """Canonical vmapped run-axis width: the smallest power of two
    holding ``members``, clamped to ``pack_max`` — a small ladder of
    widths, so the packed program compiles (and caches) once per width
    rather than once per membership count."""
    members = max(1, int(members))
    w = 1
    while w < members:
        w *= 2
    return max(PACK_MIN_MEMBERS, min(w, max(int(pack_max), members)))


@dataclasses.dataclass
class PackMember:
    """One run riding the pack: its runtime inputs and host-side hooks.
    Callbacks mirror ``SimProgram.run``'s, already demuxed to this
    member's slice."""

    seed: int
    live_counts: tuple | None = None  # exact per-group counts (bucketed)
    max_ticks: int = 10_000
    telemetry_cb: Callable | None = None
    lat_hist_cb: Callable | None = None
    on_chunk: Callable | None = None  # on_chunk(ticks)
    # polled each chunk: True stops THIS member (operator cancel or an
    # SLO fail) — the pack continues for everyone else
    cancel_check: Callable[[], bool] | None = None
    perf: Any = None  # PerfLedger hook (on_chunk only — no AOT in packs)

    # --- filled by PackRunner.run
    ticks: int = 0
    canceled: bool = False
    done: bool = False


class PackRunner:
    """Vmapped executor for N compatible runs over ONE SimProgram.

    The program must be trace-free and fault-free (the admission key
    guarantees it). ``prog.live_counts`` decides whether members carry
    per-run exact counts (shape bucketing) — when set, every member's
    ``live_counts`` must be provided.

    **Mesh placement** (ISSUE 20): the inner program is ALWAYS built
    unmeshed — a ``with_sharding_constraint`` under the run-axis vmap
    would pin per-member layouts at trace time — and the pack's real
    mesh arrives here instead. PackRunner places the STACKED ``[R,
    ...]`` carry through the one rule table (sim/meshplan.py) outside
    the vmap: instance-axis planes shard on the ``i`` peers axis, the
    run axis maps to a 2-D mesh's ``runs`` axis (replicated on a 1-D
    mesh). Packs therefore compile once per (width, mesh layout), and
    members still demux/snapshot/cancel independently."""

    def __init__(self, prog, width: int, mesh=None):
        from . import meshplan as _meshplan

        self.prog = prog
        self.width = int(width)
        if prog.trace is not None or prog.faults is not None:
            raise ValueError(
                "run packing requires a trace-free, fault-free program "
                "(pack admission must refuse these compositions)"
            )
        if prog.mesh is not None:
            raise ValueError(
                "the pack's inner program must be built unmeshed "
                "(mesh=None): PackRunner places the stacked carry "
                "through the rule table outside the vmap — pass the "
                "mesh to PackRunner instead"
            )
        self.meshplan = _meshplan.plan_for(mesh)
        if self.meshplan is not None and prog.transport == "pallas":
            raise ValueError(
                "a packed mesh run cannot use transport=pallas (the "
                "vmapped single-device kernels do not partition over "
                "the mesh; the shard_map variant is the solo path) — "
                "the transport gate resolves this to xla"
            )
        self._init_fn = None
        self._chunk_fn = None

    # ------------------------------------------------------------- device

    def _packed_init(self, seeds, lcs, live_run):
        """Traced: per-run carries stacked on the leading run axis, dead
        dummy runs (live_run False) forced all-CRASH so they are done
        from tick 0 and never contribute a message or a counter."""
        import jax
        import jax.numpy as jnp

        from .api import CRASH

        if self.prog.live_counts is not None:
            carry = jax.vmap(
                lambda s, lc: self.prog.init_carry(s, lc)
            )(seeds, lcs)
        else:
            carry = jax.vmap(lambda s: self.prog.init_carry(s))(seeds)
        status = jnp.where(
            live_run[:, None], carry.status, jnp.int32(CRASH)
        )
        carry = dataclasses.replace(carry, status=status)
        return self._constrain_stacked(carry)

    def _constrain_stacked(self, carry):
        """Place the stacked carry per the rule table — OUTSIDE the
        vmap, so the constraint sees the real [R, ...] leaves."""
        if self.meshplan is None:
            return carry
        from .engine import constrain_carry

        return constrain_carry(carry, self.meshplan, lead="runs")

    def packed_init(self):
        if self._init_fn is None:
            import jax

            self._init_fn = jax.jit(self._packed_init)
        return self._init_fn

    def packed_chunk(self):
        if self._chunk_fn is None:
            import jax

            vstep = jax.vmap(self.prog._chunk_step)
            if self.meshplan is None:
                step = vstep
            else:

                def step(carry):
                    out = vstep(carry)
                    return (self._constrain_stacked(out[0]),) + tuple(
                        out[1:]
                    )

            self._chunk_fn = jax.jit(step, donate_argnums=0)
        return self._chunk_fn

    # --------------------------------------------------------------- run

    def run(self, members: list[PackMember]) -> list[dict]:
        """Step every member to completion (or cancel/budget) in one
        vmapped loop — ONE dispatch per chunk for the whole pack — and
        return per-member results dicts (the ``SimProgram.run`` shape).
        """
        import jax

        from .engine import _poll_done

        if not (0 < len(members) <= self.width):
            raise ValueError(
                f"{len(members)} member(s) for a width-{self.width} pack"
            )
        prog = self.prog
        chunk = prog.chunk
        n_live = len(members)
        width = self.width

        t0 = time.perf_counter()
        seeds = np.asarray(
            [m.seed for m in members] + [0] * (width - n_live), np.int32
        )
        live_run = np.asarray(
            [True] * n_live + [False] * (width - n_live), bool
        )
        if prog.live_counts is not None:
            for m in members:
                if m.live_counts is None:
                    raise ValueError(
                        "bucketed pack members must carry live_counts"
                    )
            fill = members[0].live_counts
            lcs = np.asarray(
                [m.live_counts for m in members]
                + [fill] * (width - n_live),
                np.int32,
            )
        else:
            lcs = np.zeros((width, 1), np.int32)  # unused traced input
        carry = self.packed_init()(seeds, lcs, live_run)
        fn = self.packed_chunk()

        max_ticks = max(m.max_ticks for m in members)
        ticks = 0
        compile_secs = 0.0
        # host-side latency accumulators (python ints — no wrap)
        lat_acc = None
        if prog.telemetry:
            from .telemetry import LATENCY_BINS

            lat_acc = np.zeros(
                (width, len(prog.groups), LATENCY_BINS), np.int64
            )
        active = [True] * n_live  # still watching (not done/canceled)
        stashes: list[Any] = [None] * n_live

        def _stash(i: int, carry_now) -> None:
            """Freeze member i's observable state at THIS chunk
            boundary: its lanes keep ticking on device after a cancel,
            and results must reflect the boundary it stopped at. The
            slice materializes NEW device buffers (a gather), so the
            next dispatch's donation cannot invalidate it; PRNG-key
            leaves slice typed, never through numpy."""
            stashes[i] = jax.tree.map(lambda x: x[i], carry_now)

        while ticks < max_ticks and any(active):
            t_chunk = time.perf_counter()
            out = fn(carry)
            carry, done = out[0], out[1]
            ticks += chunk
            done_host = np.asarray(done)  # the one device sync per chunk
            _poll_done(done_host[0])  # same barrier discipline as run()
            wall = time.perf_counter() - t_chunk
            if compile_secs == 0.0:
                compile_secs = time.perf_counter() - t0
            tele_host = None
            if prog.telemetry:
                tele_host = np.asarray(out[2])  # [R, chunk, K]
                lat_delta = np.asarray(out[3], dtype=np.int64)
                # accumulate ONLY members still being watched: a
                # canceled/budget-stashed member's lanes keep ticking
                # (and delivering) on device, and its journaled
                # histogram must stop at the boundary its snapshot
                # froze at — exactly where an isolated run stopped.
                # (A DONE member's deltas are zero anyway.)
                for i in range(n_live):
                    if active[i]:
                        lat_acc[i] += lat_delta[i]
            for i, m in enumerate(members):
                if not active[i]:
                    continue
                if m.perf is not None:
                    m.perf.on_chunk(
                        ticks // chunk - 1, ticks, chunk, wall
                    )
                if prog.telemetry:
                    if m.telemetry_cb is not None:
                        m.telemetry_cb(tele_host[i])
                    if m.lat_hist_cb is not None:
                        m.lat_hist_cb(lat_delta[i])
                if m.on_chunk is not None:
                    m.on_chunk(ticks)
                if bool(done_host[i]):
                    # finished: the member's carry freezes from here
                    # (every lane terminal → the vmapped cond no-ops),
                    # so its end-of-pack slice is its result — record
                    # its OWN finish tick and stop demuxing
                    m.done = True
                    m.ticks = ticks
                    active[i] = False
                elif ticks >= m.max_ticks:
                    # this member's own budget is spent (another member
                    # may run longer): snapshot — its lanes would keep
                    # evolving past the budget an isolated run enforces
                    m.ticks = ticks
                    active[i] = False
                    _stash(i, carry)
                elif m.cancel_check is not None and m.cancel_check():
                    m.canceled = True
                    m.ticks = ticks
                    active[i] = False
                    _stash(i, carry)

        for i, m in enumerate(members):
            if active[i]:  # pack budget exhausted while still running
                m.ticks = ticks
                active[i] = False

        results: list[dict] = []
        for i, m in enumerate(members):
            src = (
                stashes[i]
                if stashes[i] is not None
                else jax.tree.map(
                    lambda x, _i=i: x[_i]
                    if hasattr(x, "__getitem__")
                    else x,
                    carry,
                )
            )
            res = prog.results(
                src, m.ticks, live_counts=m.live_counts
            )
            res["compile_secs"] = compile_secs
            if lat_acc is not None:
                res["lat_hist"] = lat_acc[i].tolist()
            results.append(res)
        return results
