"""The ``sim:jax`` execution substrate: a vectorized discrete-event network
simulation on TPU.

This package replaces the reference's runner/sidecar data plane (real
containers + tc/netem shaping, SURVEY.md §2.5) with a single compiled
program: each instance's main loop is a traceable state machine lifted over
the instance axis with ``jax.vmap``; sync primitives (Signal/Barrier/
Publish) lower to counter tensors updated with ``psum``/``cumsum``; link
shaping (latency/jitter/bandwidth/loss + subnet filters) is arithmetic on
per-instance egress state and bounded rule tables; and the whole tick loop
runs under ``jit`` sharded over a ``jax.sharding.Mesh``.

Import layering: this package's submodules import jax; the package root and
``runner`` stay import-light so the control plane can load without jax.
"""

__all__ = [
    "api",
    "net",
    "sync_kernel",
    "engine",
    "executor",
    "phases",
    "runner",
]
