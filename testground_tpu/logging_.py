"""Logging singleton (twin of ``pkg/logging/log.go``): a process-wide
structured logger with an adjustable level and console-style output."""

from __future__ import annotations

import logging
import sys

__all__ = ["S", "set_level"]

_logger: logging.Logger | None = None


def _build() -> logging.Logger:
    logger = logging.getLogger("testground_tpu")
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            logging.Formatter(
                "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def S() -> logging.Logger:
    """The process-wide logger (``logging.S()`` in the reference)."""
    global _logger
    if _logger is None:
        _logger = _build()
    return _logger


def set_level(level: str) -> None:
    S().setLevel(getattr(logging, level.upper(), logging.INFO))
