"""Environment configuration: ``$TESTGROUND_HOME`` layout, ``.env.toml``
loading, and config coalescing. Twin of the reference's ``pkg/config``."""

from .coalescing import CoalescedConfig
from .dirs import Directories
from .env import (
    DEFAULT_LISTEN_ADDR,
    DEFAULT_QUEUE_SIZE,
    DEFAULT_TASK_REPO_TYPE,
    DEFAULT_WORKERS,
    RUNNER_DISABLED_FLAG,
    ClientConfig,
    DaemonConfig,
    EnvConfig,
    SchedulerConfig,
)

__all__ = [
    "CoalescedConfig",
    "ClientConfig",
    "DaemonConfig",
    "DEFAULT_LISTEN_ADDR",
    "DEFAULT_QUEUE_SIZE",
    "DEFAULT_TASK_REPO_TYPE",
    "DEFAULT_WORKERS",
    "Directories",
    "EnvConfig",
    "RUNNER_DISABLED_FLAG",
    "SchedulerConfig",
]
