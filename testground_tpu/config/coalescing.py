"""Config coalescing (``pkg/config/coalescing.go``).

Layers of untyped config maps merge left-to-right (later layers win) and
coalesce into a typed config object. The reference round-trips through TOML to
get typed decoding; here dataclass field introspection gives the same effect
without serialization.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Type, TypeVar

T = TypeVar("T")


class CoalescedConfig:
    """An ordered stack of config maps; later appends take precedence."""

    def __init__(self, *layers: dict[str, Any] | None):
        self._layers: list[dict[str, Any]] = [l for l in layers if l]

    def append(self, layer: dict[str, Any] | None) -> "CoalescedConfig":
        c = CoalescedConfig()
        c._layers = list(self._layers)
        if layer:
            c._layers.append(layer)
        return c

    def flatten(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for layer in self._layers:
            out.update(layer)
        return out

    def coalesce_into(self, typ: Type[T]) -> T:
        """Build a ``typ`` dataclass from the flattened map; unknown keys are
        ignored and nested dataclass fields are constructed recursively
        (mirrors TOML round-trip decoding semantics of ``CoalesceIntoType``,
        ``coalescing.go:11-39``)."""
        return _into_dataclass(typ, self.flatten())


def _into_dataclass(typ: Type[T], data: dict[str, Any]) -> T:
    if not dataclasses.is_dataclass(typ):
        raise TypeError(f"{typ} is not a dataclass")
    # Resolve string annotations (PEP 563 modules) to real types.
    try:
        hints = typing.get_type_hints(typ)
    except Exception:
        hints = {}
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(typ):
        if f.name not in data:
            continue
        v = data[f.name]
        ftype = hints.get(f.name, f.type if isinstance(f.type, type) else None)
        if ftype is not None and dataclasses.is_dataclass(ftype) and isinstance(v, dict):
            v = _into_dataclass(ftype, v)
        kwargs[f.name] = v
    return typ(**kwargs)
