"""Environment configuration (``pkg/config/env.go`` + ``loader.go``).

Populated by coalescing, in descending precedence:
1. environment variables (``TESTGROUND_HOME``),
2. ``$TESTGROUND_HOME/.env.toml``,
3. defaults.
"""

from __future__ import annotations

import os
from testground_tpu.utils.compat import tomllib
from dataclasses import dataclass, field

from .dirs import Directories

ENV_TESTGROUND_HOME = "TESTGROUND_HOME"

DEFAULT_LISTEN_ADDR = "localhost:8042"
DEFAULT_CLIENT_URL = f"http://{DEFAULT_LISTEN_ADDR}"
DEFAULT_TASK_REPO_TYPE = "memory"
DEFAULT_WORKERS = 2
DEFAULT_QUEUE_SIZE = 100
DEFAULT_TASK_TIMEOUT_MIN = 10

# Config flag marking a runner disabled in .env.toml
# (``pkg/config/env.go:63``, enforced by the supervisor).
RUNNER_DISABLED_FLAG = "disabled"


@dataclass
class SchedulerConfig:
    workers: int = 0
    queue_size: int = 0
    task_repo_type: str = ""
    task_timeout_min: int = 0


@dataclass
class DaemonConfig:
    listen: str = ""
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    tokens: list[str] = field(default_factory=list)
    slack_webhook_url: str = ""
    github_repo_status_token: str = ""
    root_url: str = ""
    influxdb_endpoint: str = ""
    # per-scrape task-label cardinality bound for GET /metrics (0 = the
    # daemon's built-in default); truncation is reported via the
    # tg_scrape_tasks_total/_elided gauges, never silent
    metrics_task_limit: int = 0


@dataclass
class ClientConfig:
    endpoint: str = ""
    token: str = ""
    user: str = ""


@dataclass
class EnvConfig:
    builders: dict[str, dict] = field(default_factory=dict)
    runners: dict[str, dict] = field(default_factory=dict)
    daemon: DaemonConfig = field(default_factory=DaemonConfig)
    client: ClientConfig = field(default_factory=ClientConfig)
    dirs: Directories = field(default_factory=lambda: Directories(""))
    # whether .env.toml explicitly chose a task repo type; the in-process
    # CLI upgrades the "memory" default to "disk" so task state survives
    # across invocations (the reference's daemon is long-lived, ours isn't)
    task_repo_explicit: bool = False

    @classmethod
    def load(
        cls, home: str | None = None, ensure_dirs: bool = True
    ) -> "EnvConfig":
        """Resolve the home dir, read ``.env.toml`` when present, apply
        defaults, and ensure the directory layout exists
        (``pkg/config/loader.go:32-110``). ``ensure_dirs=False`` skips the
        layout creation — for healthchecks, which must observe the
        environment rather than repair it as a side effect."""
        e = cls()
        if home is None:
            home = os.environ.get(ENV_TESTGROUND_HOME) or os.path.join(
                os.path.expanduser("~"), "testground"
            )
        e.dirs = Directories(home)

        env_toml = os.path.join(home, ".env.toml")
        if os.path.isfile(env_toml):
            try:
                with open(env_toml, "rb") as f:
                    e._apply_toml(tomllib.load(f))
            except tomllib.TOMLDecodeError as err:
                raise ValueError(
                    f"found .env.toml at {env_toml}, but failed to parse: {err}"
                ) from err

        e._ensure_minimal()
        if ensure_dirs:
            for d in e.dirs.all():
                os.makedirs(d, exist_ok=True)
        return e

    def _apply_toml(self, d: dict) -> None:
        self.builders.update(d.get("builders", {}))
        self.runners.update(d.get("runners", {}))
        dm = d.get("daemon", {})
        self.daemon.listen = dm.get("listen", self.daemon.listen)
        self.daemon.tokens = list(dm.get("tokens", self.daemon.tokens))
        self.daemon.slack_webhook_url = dm.get(
            "slack_webhook_url", self.daemon.slack_webhook_url
        )
        self.daemon.github_repo_status_token = dm.get(
            "github_repo_status_token", self.daemon.github_repo_status_token
        )
        self.daemon.root_url = dm.get("root_url", self.daemon.root_url)
        self.daemon.influxdb_endpoint = dm.get(
            "influxdb_endpoint", self.daemon.influxdb_endpoint
        )
        # clamp: a negative limit would slice tasks[:-n] and export the
        # OLDEST tasks — treat anything < 1 as "use the built-in default"
        self.daemon.metrics_task_limit = max(
            0,
            int(
                dm.get("metrics_task_limit", self.daemon.metrics_task_limit)
            ),
        )
        sch = dm.get("scheduler", {})
        self.daemon.scheduler.workers = int(sch.get("workers", 0))
        self.daemon.scheduler.queue_size = int(sch.get("queue_size", 0))
        self.daemon.scheduler.task_repo_type = sch.get("task_repo_type", "")
        self.daemon.scheduler.task_timeout_min = int(sch.get("task_timeout_min", 0))
        if sch.get("task_repo_type"):
            self.task_repo_explicit = True
        cl = d.get("client", {})
        self.client.endpoint = cl.get("endpoint", self.client.endpoint)
        self.client.token = cl.get("token", self.client.token)
        self.client.user = cl.get("user", self.client.user)

    def _ensure_minimal(self) -> None:
        """Apply fallback defaults (``pkg/config/loader.go:55-63``).

        Deviation: the reference defaults ``client.endpoint`` to
        localhost:8042 because its CLI can only talk to a daemon; here the
        CLI runs an in-process engine unless an endpoint is configured, so
        the endpoint stays empty (``DEFAULT_CLIENT_URL`` remains the
        suggestion printed by ``tg daemon``)."""
        self.daemon.listen = self.daemon.listen or DEFAULT_LISTEN_ADDR
        sch = self.daemon.scheduler
        sch.workers = sch.workers or DEFAULT_WORKERS
        sch.queue_size = sch.queue_size or DEFAULT_QUEUE_SIZE
        sch.task_repo_type = sch.task_repo_type or DEFAULT_TASK_REPO_TYPE
        sch.task_timeout_min = sch.task_timeout_min or DEFAULT_TASK_TIMEOUT_MIN

    def runner_config(self, runner_id: str) -> dict:
        """The raw .env.toml config map for a runner (``{}`` when absent)
        — the layer healthchecks read to probe the CONFIGURED
        environment (e.g. the sync bind host) rather than defaults."""
        cfg = self.runners.get(runner_id, {})
        return dict(cfg) if isinstance(cfg, dict) else {}

    def runner_is_disabled(self, runner_id: str) -> bool:
        """Whether .env.toml marks the runner disabled
        (``pkg/engine/supervisor.go:568-571`` semantics)."""
        cfg = self.runners.get(runner_id, {})
        return bool(cfg.get(RUNNER_DISABLED_FLAG, False))
