"""``$TESTGROUND_HOME`` directory layout (``pkg/config/dirs.go``)."""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Directories:
    home: str

    def plans(self) -> str:
        return os.path.join(self.home, "plans")

    def sdks(self) -> str:
        return os.path.join(self.home, "sdks")

    def work(self) -> str:
        return os.path.join(self.home, "data", "work")

    def outputs(self) -> str:
        return os.path.join(self.home, "data", "outputs")

    def daemon(self) -> str:
        return os.path.join(self.home, "data", "daemon")

    def compile_cache(self) -> str:
        """Persistent XLA compilation cache — the build-artifact cache
        analog of the reference's go-build cache image
        (``pkg/build/docker_go.go:266-283``)."""
        return os.path.join(self.home, "data", "compile-cache")

    def all(self) -> list[str]:
        return [
            self.home,
            self.plans(),
            self.sdks(),
            self.work(),
            self.outputs(),
            self.daemon(),
        ]
