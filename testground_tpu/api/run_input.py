"""Framed inputs/outputs exchanged between the engine and builders/runners.

Behavioral twin of the reference's ``pkg/api/runner.go:36-109`` and
``pkg/api/builder.go:29-75``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .composition import Resources

__all__ = [
    "BuildInput",
    "BuildOutput",
    "CollectionInput",
    "RunGroup",
    "RunInput",
    "RunOutput",
]


@dataclass
class RunGroup:
    """One group's slice of a run (``pkg/api/runner.go:65-85``)."""

    id: str
    instances: int
    artifact_path: str = ""
    # builder that produced the artifact — runners dispatch execution on
    # this (e.g. exec:py → interpreter, exec:bin → direct exec), never on
    # filename conventions
    builder: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    profiles: dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    # declarative fault schedule for this group's slice of the run
    # ([[groups.run.faults]] — raw tables; the sim:jax runner lowers and
    # validates them, other runners ignore them)
    faults: list = field(default_factory=list)
    # flight-recorder sampling table for this group's slice
    # ([groups.run.trace] — raw table, lowered by the sim:jax runner)
    trace: dict = field(default_factory=dict)
    # SLO assertion tables for this group's slice ([[groups.run.slo]] —
    # raw tables, lowered by the sim:jax runner)
    slo: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "instances": self.instances,
            "artifact_path": self.artifact_path,
            "builder": self.builder,
            "parameters": dict(self.parameters),
            "profiles": dict(self.profiles),
            "resources": self.resources.to_dict(),
            "faults": [dict(f) for f in self.faults],
            "trace": dict(self.trace),
            "slo": [dict(s) for s in self.slo],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RunGroup":
        return cls(
            id=d["id"],
            instances=int(d["instances"]),
            artifact_path=d.get("artifact_path", ""),
            builder=d.get("builder", ""),
            parameters=dict(d.get("parameters", {})),
            profiles=dict(d.get("profiles", {})),
            resources=Resources.from_dict(d.get("resources", {})),
            faults=[dict(f) for f in d.get("faults", [])],
            trace=dict(d.get("trace", {})),
            slo=[dict(s) for s in d.get("slo", [])],
        )


@dataclass
class RunInput:
    """Input options for running one test run (``pkg/api/runner.go:36-63``)."""

    run_id: str
    test_plan: str
    test_case: str
    total_instances: int
    groups: list[RunGroup] = field(default_factory=list)
    runner_config: Any = None
    disable_metrics: bool = False
    # run-global fault schedule ([[global.run.faults]]): events whose
    # default target is the WHOLE run — group-scoped declarations ride
    # on their RunGroup instead
    faults: list = field(default_factory=list)
    # run-global flight-recorder table ([global.run.trace]): selectors
    # whose default target is the WHOLE run
    trace: dict = field(default_factory=dict)
    # run-global SLO assertions ([[global.run.slo]]): rules evaluated
    # against the whole run's metric stream
    slo: list = field(default_factory=list)
    # lifecycle trace context (tracectx.py): {"trace_id", "parent_id",
    # "traceparent"} threaded by the supervisor so executor spans and
    # sync hello attribution join the task's tree. Distinct from
    # ``trace`` above, which is the flight-recorder sampling table.
    trace_ctx: dict = field(default_factory=dict)
    # EnvConfig equivalent is attached by the engine at dispatch time.
    env: Any = None
    # preemption signal (engine/controller.py): a threading.Event the
    # supervisor arms so the fleet controller can stop this run at a
    # chunk boundary for live migration. Process-local — never
    # serialized (to_dict excludes it, like env).
    preempt: Any = None

    def to_dict(self) -> dict:
        return {
            "run_id": self.run_id,
            "test_plan": self.test_plan,
            "test_case": self.test_case,
            "total_instances": self.total_instances,
            "groups": [g.to_dict() for g in self.groups],
            "disable_metrics": self.disable_metrics,
            "faults": [dict(f) for f in self.faults],
            "trace": dict(self.trace),
            "slo": [dict(s) for s in self.slo],
            "trace_ctx": dict(self.trace_ctx),
        }


@dataclass
class RunOutput:
    """Output from a run (``pkg/api/runner.go:87-102``)."""

    run_id: str
    composition: Any = None
    result: Any = None


@dataclass
class CollectionInput:
    """Input for collecting a run's outputs (``pkg/api/runner.go:104-114``)."""

    run_id: str
    runner_id: str
    runner_config: Any = None
    env: Any = None


@dataclass
class BuildInput:
    """Input options for building a test plan (``pkg/api/builder.go:29-58``)."""

    build_id: str
    test_plan: str
    unpacked_plan_dir: str = ""
    unpacked_sdk_dir: str = ""
    selectors: list[str] = field(default_factory=list)
    dependencies: dict[str, tuple[str, str]] = field(default_factory=dict)
    build_config: Any = None
    env: Any = None


@dataclass
class BuildOutput:
    """Output from a build (``pkg/api/builder.go:60-75``)."""

    builder_id: str
    artifact_path: str
    dependencies: dict[str, str] = field(default_factory=dict)
