"""Composition preparation pipeline.

Applies manifest/global defaults, synthesizes the default run, resolves
instance counts, and bounds-checks against the test case's constraints.
Behavioral twin of the reference's ``pkg/api/composition_preparation.go``.
All functions return prepared *clones*; inputs are never mutated.
"""

from __future__ import annotations

from .composition import (
    Composition,
    CompositionRunGroup,
    Run,
    apply_dependency_defaults,
)
from .manifest import TestPlanManifest
from .template import compile_composition_template

__all__ = [
    "generate_default_run",
    "load_composition",
    "prepare_for_build",
    "prepare_for_run",
]


def load_composition(path) -> Composition:
    """Render a composition file through the template engine, parse it, and
    synthesize the default run when no ``[[runs]]`` are declared — the entry
    point CLI/load paths use, mirroring ``pkg/cmd/template.go:88-107``
    (template → parse → GenerateDefaultRun). Validation requires runs to
    exist, so loading and validating compose cleanly."""
    text = compile_composition_template(path)
    return generate_default_run(Composition.from_toml(text))


def prepare_for_build(
    c: Composition, manifest: TestPlanManifest
) -> Composition:
    """Verify builder compatibility and trickle down build configuration
    (``composition_preparation.go:63-89`` + per-group ``:16-56``).

    Precedence for each group's build_config key: group > global > manifest
    builder defaults. The global ``[global.build]`` selectors/dependencies
    fill in where the group sets none.
    """
    c = c.clone()
    # The server doesn't care about client-local plan paths; the manifest name
    # is canonical (composition_preparation.go:64-68).
    c.global_.plan = manifest.name

    if not manifest.builders:
        raise ValueError("plan supports no builders; review the manifest")

    for g in c.groups:
        if not g.builder:
            g.builder = c.global_.builder
        if not manifest.has_builder(g.builder):
            raise ValueError(
                f"plan does not support builder '{g.builder}'; "
                f"supported: {manifest.supported_builders()}"
            )
        for k, v in c.global_.build_config.items():
            g.build_config.setdefault(k, v)
        for k, v in manifest.builders.get(g.builder, {}).items():
            g.build_config.setdefault(k, v)
        if c.global_.build is not None:
            g.build.dependencies = apply_dependency_defaults(
                g.build.dependencies, c.global_.build.dependencies
            )
            if not g.build.selectors:
                g.build.selectors = list(c.global_.build.selectors)
    return c


def generate_default_run(c: Composition) -> Composition:
    """Synthesize a single ``default`` run covering all groups when the
    composition declares no ``[[runs]]``
    (``composition_preparation.go:93-110``)."""
    c = c.clone()
    if not c.runs:
        run = Run(
            id="default",
            total_instances=c.global_.total_instances,
            groups=[g.default_run_group() for g in c.groups],
        )
        c.runs = [run]
    return c


def _prepare_run_group(
    g: CompositionRunGroup,
    run: Run,
    c: Composition,
    manifest: TestPlanManifest,
) -> None:
    """Merge order for a run group's test params (missing-key fill at each
    step, so earlier sources win): run group > run > backing group > global
    run defaults > testcase defaults
    (``composition_preparation.go:232-281``)."""
    for k, v in run.test_params.items():
        g.test_params.setdefault(k, v)
    g.merge_group(c.get_group(g.effective_group_id()))
    if c.global_.run is not None:
        g.merge_run(c.global_.run)
        for k, v in c.global_.run.test_params.items():
            g.test_params.setdefault(k, v)
    for k, v in manifest.default_parameters(c.global_.case).items():
        g.test_params.setdefault(k, v)


def prepare_for_run(c: Composition, manifest: TestPlanManifest) -> Composition:
    """Full run preparation (``composition_preparation.go:118-169``):
    default-run synthesis, test-case existence, runner support, manifest
    runner config fill-in, per-run group merges, instance count resolution and
    bounds checks."""
    c = generate_default_run(c)
    c.global_.plan = manifest.name

    tcase = manifest.testcase_by_name(c.global_.case)
    if tcase is None:
        raise ValueError(
            f"test case {c.global_.case} not found in plan {manifest.name}"
        )
    if not manifest.runners:
        raise ValueError("plan supports no runners; review the manifest")
    if not manifest.has_runner(c.global_.runner):
        raise ValueError(
            f"plan does not support runner '{c.global_.runner}'; "
            f"supported: {manifest.supported_runners()}"
        )

    for k, v in manifest.runners.get(c.global_.runner, {}).items():
        c.global_.run_config.setdefault(k, v)

    for run in c.runs:
        for g in run.groups:
            _prepare_run_group(g, run, c, manifest)
        run.recalculate_instance_counts()
        t = run.total_instances
        if t < tcase.instances.minimum or t > tcase.instances.maximum:
            raise ValueError(
                f"total instance count ({t}) outside of allowable range "
                f"[{tcase.instances.minimum}, {tcase.instances.maximum}] "
                f"for test case {tcase.name}"
            )
    return c
