"""Test-plan manifest types.

A plan's ``manifest.toml`` declares which builders and runners it supports
and its test cases with typed parameters and instance bounds. Behavioral twin
of the reference's ``pkg/api/manifest.go:14-162``; reference manifests parse
unchanged (same table/key names, including the ``instances = {min, max,
default}`` inline table).
"""

from __future__ import annotations

import json
from testground_tpu.utils.compat import tomllib
from dataclasses import dataclass, field
from typing import Any

__all__ = ["InstanceConstraints", "Parameter", "TestCase", "TestPlanManifest"]


@dataclass
class InstanceConstraints:
    """How many instances a test case may run
    (``pkg/api/manifest.go:45-49`` + the ``default`` key reference manifests
    carry, e.g. ``plans/placebo/manifest.toml``)."""

    minimum: int = 0
    maximum: int = 0
    default: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "InstanceConstraints":
        return cls(
            minimum=int(d.get("min", 0)),
            maximum=int(d.get("max", 0)),
            default=int(d.get("default", 0)),
        )

    def to_dict(self) -> dict:
        return {"min": self.minimum, "max": self.maximum, "default": self.default}


@dataclass
class Parameter:
    """Metadata about a test-case parameter (``pkg/api/manifest.go:37-43``)."""

    type: str = ""
    description: str = ""
    unit: str = ""
    default: Any = None

    @classmethod
    def from_dict(cls, d: dict) -> "Parameter":
        return cls(
            type=d.get("type", ""),
            description=d.get("desc", ""),
            unit=d.get("unit", ""),
            default=d.get("default"),
        )

    def to_dict(self) -> dict:
        out = {"type": self.type, "desc": self.description, "unit": self.unit}
        if self.default is not None:
            out["default"] = self.default
        return out


@dataclass
class TestCase:
    """A test case declared by a plan (``pkg/api/manifest.go:29-35``)."""

    __test__ = False  # not a pytest class despite the name

    name: str = ""
    instances: InstanceConstraints = field(default_factory=InstanceConstraints)
    parameters: dict[str, Parameter] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "TestCase":
        return cls(
            name=d.get("name", ""),
            instances=InstanceConstraints.from_dict(d.get("instances", {})),
            parameters={
                k: Parameter.from_dict(v) for k, v in d.get("params", {}).items()
            },
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "instances": self.instances.to_dict(),
            "params": {k: p.to_dict() for k, p in self.parameters.items()},
        }

    def describe(self) -> str:
        lines = [
            f"- Test case: {self.name}",
            "  Instances:",
            f"    minimum: {self.instances.minimum}",
            f"    maximum: {self.instances.maximum}",
            "  Parameters:",
        ]
        for name, p in self.parameters.items():
            lines.append(
                f"    {name} | {p.type} | {p.description} | {p.unit} "
                f"| default: {p.default}"
            )
        return "\n".join(lines) + "\n"


@dataclass
class TestPlanManifest:
    """A test plan known to the system (``pkg/api/manifest.go:14-27``)."""

    __test__ = False  # not a pytest class despite the name

    name: str = ""
    builders: dict[str, dict] = field(default_factory=dict)
    runners: dict[str, dict] = field(default_factory=dict)
    testcases: list[TestCase] = field(default_factory=list)
    extra_sources: dict[str, list[str]] = field(default_factory=dict)
    # Reference manifests carry a [defaults] table (builder/runner) used by
    # `testground run single` and plan templates (plans/placebo/manifest.toml).
    defaults: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "TestPlanManifest":
        return cls(
            name=d.get("name", ""),
            builders=dict(d.get("builders", {})),
            runners=dict(d.get("runners", {})),
            testcases=[TestCase.from_dict(x) for x in d.get("testcases", [])],
            extra_sources={
                k: list(v) for k, v in d.get("extra_sources", {}).items()
            },
            defaults=dict(d.get("defaults", {})),
        )

    @classmethod
    def from_toml(cls, text: str) -> "TestPlanManifest":
        return cls.from_dict(tomllib.loads(text))

    @classmethod
    def load_file(cls, path) -> "TestPlanManifest":
        with open(path, "rb") as f:
            return cls.from_dict(tomllib.load(f))

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "builders": dict(self.builders),
            "runners": dict(self.runners),
            "testcases": [tc.to_dict() for tc in self.testcases],
            "extra_sources": dict(self.extra_sources),
            "defaults": dict(self.defaults),
        }

    def testcase_by_name(self, name: str) -> TestCase | None:
        """(``pkg/api/manifest.go:52-59``)."""
        for tc in self.testcases:
            if tc.name == name:
                return tc
        return None

    def default_parameters(self, testcase_name: str) -> dict[str, str]:
        """Default test params for a case, JSON-encoding non-string defaults
        (``pkg/api/manifest.go:61-84``)."""
        tc = self.testcase_by_name(testcase_name)
        if tc is None:
            raise KeyError(f"test case {testcase_name} not found")
        out: dict[str, str] = {}
        for n, p in tc.parameters.items():
            if p.default is None:
                continue
            if isinstance(p.default, str):
                out[n] = p.default
            else:
                out[n] = json.dumps(p.default)
        return out

    def has_builder(self, name: str) -> bool:
        return name in self.builders

    def has_runner(self, name: str) -> bool:
        return name in self.runners

    def supported_builders(self) -> list[str]:
        return list(self.builders)

    def supported_runners(self) -> list[str]:
        return list(self.runners)

    def describe(self) -> str:
        """Human description (``pkg/api/manifest.go:120-146``)."""
        return (
            f'This test plan is called "{self.name}".\n\n'
            f"It can be built with strategies: {self.supported_builders()}.\n\n"
            f"It can be run with strategies: {self.supported_runners()}.\n\n"
            f"It has {len(self.testcases)} test cases.\n"
        )
