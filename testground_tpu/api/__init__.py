"""Spec types shared across the framework: compositions, manifests, run
input/output frames.

Behavioral twin of the reference's ``pkg/api`` package (composition.go,
manifest.go, composition_preparation.go, composition_validation.go,
runner.go, builder.go) re-expressed as Python dataclasses.
"""

from .composition import (
    Build,
    Composition,
    CompositionRunGroup,
    Dependency,
    Global,
    Group,
    Instances,
    Metadata,
    Resources,
    Run,
    RunParams,
)
from .manifest import InstanceConstraints, Parameter, TestCase, TestPlanManifest
from .preparation import (
    generate_default_run,
    load_composition,
    prepare_for_build,
    prepare_for_run,
)
from .template import (
    TemplateError,
    compile_composition_template,
    render_template,
)
from .run_input import (
    BuildInput,
    BuildOutput,
    CollectionInput,
    RunGroup,
    RunInput,
    RunOutput,
)
from .validation import CompositionError, validate_for_build, validate_for_run

__all__ = [
    "Build",
    "BuildInput",
    "BuildOutput",
    "CollectionInput",
    "Composition",
    "generate_default_run",
    "prepare_for_build",
    "prepare_for_run",
    "RunOutput",
    "CompositionError",
    "CompositionRunGroup",
    "Dependency",
    "Global",
    "Group",
    "Instances",
    "InstanceConstraints",
    "Metadata",
    "Parameter",
    "Resources",
    "Run",
    "RunGroup",
    "RunInput",
    "RunParams",
    "TemplateError",
    "TestCase",
    "TestPlanManifest",
    "compile_composition_template",
    "render_template",
    "validate_for_build",
    "validate_for_run",
]
