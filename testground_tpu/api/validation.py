"""Composition validation.

Behavioral twin of ``pkg/api/composition_validation.go``: structural checks
(required fields), group/run uniqueness and cross-references, the
count-XOR-percentage rule, and instance-count recalculation for runs.
"""

from __future__ import annotations

from .composition import Composition, Instances

__all__ = ["CompositionError", "validate_for_build", "validate_for_run"]


class CompositionError(ValueError):
    """Raised when a composition fails validation."""


def _validate_instances(inst: Instances, where: str) -> None:
    """Either count or percentage must be provided, not both
    (``composition_validation.go:114-123``)."""
    ok = (inst.count == 0 or inst.percentage == 0) and (
        float(inst.count) + inst.percentage > 0
    )
    if not ok:
        raise CompositionError(
            f"{where}: exactly one of instances.count / instances.percentage "
            f"must be set (got count={inst.count}, "
            f"percentage={inst.percentage})"
        )


def _validate_groups(c: Composition) -> None:
    """(``composition_validation.go:15-33``)."""
    seen: set[str] = set()
    for g in c.groups:
        if g.id in seen:
            raise CompositionError(
                f"group ids not unique; found duplicate: {g.id}"
            )
        seen.add(g.id)
    for g in c.groups:
        if not g.builder and not c.global_.builder:
            raise CompositionError(f"group {g.id} is missing a builder")


def _validate_runs(c: Composition) -> None:
    """(``composition_validation.go:35-75``)."""
    seen: set[str] = set()
    for r in c.runs:
        if r.id in seen:
            raise CompositionError(f"runs ids not unique; found duplicate: {r.id}")
        seen.add(r.id)
    for r in c.runs:
        for g in r.groups:
            try:
                c.get_group(g.effective_group_id())
            except KeyError:
                raise CompositionError(
                    f"run {r.id}:{g.id} references non-existent group "
                    f"{g.effective_group_id()}"
                ) from None
        run_group_ids: set[str] = set()
        for g in r.groups:
            if g.id in run_group_ids:
                raise CompositionError(
                    f"group ids not unique; found duplicate: {r.id}:{g.id}"
                )
            run_group_ids.add(g.id)
    for r in c.runs:
        for g in r.groups:
            # Zero instances is the inherit-from-backing-group pattern; the
            # merge during prepare_for_run fills it in. The reference's
            # Runs.Validate applies no per-run-group instances check at all.
            if not g.instances.is_zero():
                _validate_instances(g.instances, f"run {r.id} group {g.id}")
        try:
            r.recalculate_instance_counts()
        except ValueError as e:
            raise CompositionError(str(e)) from None


def validate_for_build(c: Composition) -> None:
    """Validate for a build: plan + groups required; case/runner/runs exempt
    (``composition_validation.go:78-90``)."""
    if not c.global_.plan:
        raise CompositionError("composition is missing global.plan")
    if not c.groups:
        raise CompositionError("composition has no groups")
    for g in c.groups:
        if not g.instances.is_zero():
            _validate_instances(g.instances, f"group {g.id}")
    _validate_groups(c)


def validate_for_run(c: Composition) -> None:
    """Validate for a run: everything, including runs
    (``composition_validation.go:93-110``)."""
    if not c.global_.plan:
        raise CompositionError("composition is missing global.plan")
    if not c.global_.case:
        raise CompositionError("composition is missing global.case")
    if not c.global_.runner:
        raise CompositionError("composition is missing global.runner")
    if not c.groups:
        raise CompositionError("composition has no groups")
    for g in c.groups:
        if not g.instances.is_zero():
            _validate_instances(g.instances, f"group {g.id}")
    _validate_groups(c)
    if not c.runs:
        raise CompositionError("composition has no runs")
    _validate_runs(c)
