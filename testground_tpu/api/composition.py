"""Composition spec types.

A composition describes *what to run*: the test plan and case, the instance
groups that participate (with build and run configuration), and one or more
runs combining those groups. Behavioral twin of the reference's
``pkg/api/composition.go:18-503``; the TOML schema (table names, key names,
trickle-down semantics) is preserved so reference compositions parse
unchanged.
"""

from __future__ import annotations

import json
from testground_tpu.utils.compat import tomllib
from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "Build",
    "Composition",
    "CompositionRunGroup",
    "Dependency",
    "Global",
    "Group",
    "Instances",
    "Metadata",
    "Resources",
    "Run",
    "RunParams",
]


def _merge_missing(dst: dict, src: dict | None) -> dict:
    """Fill keys absent from ``dst`` with values from ``src`` (non-destructive
    merge — the semantics the reference gets from mergo.Merge on maps)."""
    if src:
        for k, v in src.items():
            if k not in dst:
                dst[k] = v
    return dst


@dataclass
class Metadata:
    """Optional composition metadata (``pkg/api/composition.go:77-83``)."""

    name: str = ""
    author: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Metadata":
        return cls(name=d.get("name", ""), author=d.get("author", ""))

    def to_dict(self) -> dict:
        return {"name": self.name, "author": self.author}


@dataclass
class Resources:
    """Per-instance resource requests, honored by cluster runners
    (``pkg/api/composition.go:85-88``)."""

    memory: str = ""
    cpu: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Resources":
        return cls(memory=d.get("memory", ""), cpu=d.get("cpu", ""))

    def to_dict(self) -> dict:
        return {"memory": self.memory, "cpu": self.cpu}

    def merge_from(self, other: "Resources") -> None:
        if not self.memory:
            self.memory = other.memory
        if not self.cpu:
            self.cpu = other.cpu


@dataclass
class Instances:
    """Instance count for a group: exact ``count`` XOR fraction
    ``percentage`` of the run's total (``pkg/api/composition.go:169-180``)."""

    count: int = 0
    percentage: float = 0.0

    @classmethod
    def from_dict(cls, d: dict) -> "Instances":
        return cls(
            count=int(d.get("count", 0)),
            percentage=float(d.get("percentage", 0.0)),
        )

    def to_dict(self) -> dict:
        return {"count": self.count, "percentage": self.percentage}

    def is_zero(self) -> bool:
        return self.count == 0 and self.percentage == 0.0

    def merge_from(self, other: "Instances") -> None:
        if self.count == 0:
            self.count = other.count
        if self.percentage == 0.0:
            self.percentage = other.percentage


@dataclass
class Dependency:
    """Upstream dependency override for a build
    (``pkg/api/composition.go:302-311``)."""

    module: str
    version: str
    target: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Dependency":
        return cls(
            module=d.get("module", ""),
            version=d.get("version", ""),
            target=d.get("target", ""),
        )

    def to_dict(self) -> dict:
        return {"module": self.module, "version": self.version, "target": self.target}


def apply_dependency_defaults(
    deps: list[Dependency], defaults: list[Dependency]
) -> list[Dependency]:
    """Append default dependency overrides for modules not explicitly set
    (``pkg/api/composition.go:251-273``). If no explicit overrides exist, the
    defaults are used as-is."""
    if not deps:
        return list(defaults)
    have = {d.module for d in deps}
    out = list(deps)
    for d in defaults:
        if d.module not in have:
            out.append(Dependency(module=d.module, version=d.version, target=d.target))
    return out


@dataclass
class Build:
    """Build directives: source selectors (build tags for Go; extras markers
    for Python plans) and dependency overrides
    (``pkg/api/composition.go:184-192``)."""

    selectors: list[str] = field(default_factory=list)
    dependencies: list[Dependency] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "Build":
        return cls(
            selectors=list(d.get("selectors", [])),
            dependencies=[Dependency.from_dict(x) for x in d.get("dependencies", [])],
        )

    def to_dict(self) -> dict:
        return {
            "selectors": list(self.selectors),
            "dependencies": [d.to_dict() for d in self.dependencies],
        }

    def build_key(self) -> str:
        """Canonical key over sorted selectors + sorted dependency overrides
        (``pkg/api/composition.go:220-241``; deviation: the reference keys
        only module:version, so two groups overriding the same module at
        different local targets would wrongly share one artifact — we
        include the target)."""
        selectors = ",".join(sorted(self.selectors))
        deps = sorted(self.dependencies, key=lambda d: d.module)
        # target is part of the key: two groups overriding the same
        # module at different local paths must NOT share an artifact
        # (the runner consumes targets from the built snapshot's
        # deps.json at launch time)
        dep_str = "".join(
            f"{d.module}:{d.version}:{d.target}|" for d in deps
        )
        return f"selectors={selectors};dependencies={dep_str}"


@dataclass
class RunParams:
    """Run directives for a group: a pre-built artifact to reuse, test
    parameters, profile capture spec (``pkg/api/composition.go:282-300``),
    and — beyond the reference — a declarative fault schedule
    (``[[groups.run.faults]]`` / ``[[global.run.faults]]``): a list of
    chaos events the ``sim:jax`` runner lowers into its deterministic
    fault-injection plane (docs/FAULTS.md), plus a flight-recorder
    sampling table (``[groups.run.trace]`` / ``[global.run.trace]``,
    docs/OBSERVABILITY.md) selecting which instances the sim engine
    records per-tick lifecycle events for, and run-health SLO
    assertions (``[[groups.run.slo]]`` / ``[[global.run.slo]]``,
    docs/OBSERVABILITY.md "Run health plane"): metric/comparator/
    threshold rules the sim engine evaluates per chunk while the run is
    in flight. Entries are kept as raw tables here; validation happens
    at lowering, where the group layout is known."""

    artifact: str = ""
    test_params: dict[str, str] = field(default_factory=dict)
    profiles: dict[str, str] = field(default_factory=dict)
    faults: list = field(default_factory=list)
    trace: dict = field(default_factory=dict)
    slo: list = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "RunParams":
        return cls(
            artifact=d.get("artifact", ""),
            test_params={str(k): str(v) for k, v in d.get("test_params", {}).items()},
            profiles=dict(d.get("profiles", {})),
            faults=[dict(f) for f in d.get("faults", [])],
            trace=dict(d.get("trace", {})),
            slo=[dict(s) for s in d.get("slo", [])],
        )

    def to_dict(self) -> dict:
        out = {
            "artifact": self.artifact,
            "test_params": dict(self.test_params),
            "profiles": dict(self.profiles),
        }
        # omit when empty: keeps serialized compositions byte-stable for
        # the (vast) majority that declare no chaos schedule, trace, or
        # SLO rules
        if self.faults:
            out["faults"] = [dict(f) for f in self.faults]
        if self.trace:
            out["trace"] = dict(self.trace)
        if self.slo:
            out["slo"] = [dict(s) for s in self.slo]
        return out


@dataclass
class Global:
    """Composition-wide defaults that trickle down to groups
    (``pkg/api/composition.go:33-75``)."""

    plan: str = ""
    case: str = ""
    total_instances: int = 0
    concurrent_builds: int = 0
    builder: str = ""
    build_config: dict[str, Any] = field(default_factory=dict)
    build: Build | None = None
    runner: str = ""
    run_config: dict[str, Any] = field(default_factory=dict)
    run: RunParams | None = None
    disable_metrics: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "Global":
        return cls(
            plan=d.get("plan", ""),
            case=d.get("case", ""),
            total_instances=int(d.get("total_instances", 0)),
            concurrent_builds=int(d.get("concurrent_builds", 0)),
            builder=d.get("builder", ""),
            build_config=dict(d.get("build_config", {})),
            build=Build.from_dict(d["build"]) if "build" in d else None,
            runner=d.get("runner", ""),
            run_config=dict(d.get("run_config", {})),
            run=RunParams.from_dict(d["run"]) if "run" in d else None,
            disable_metrics=bool(d.get("disable_metrics", False)),
        )

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "plan": self.plan,
            "case": self.case,
            "total_instances": self.total_instances,
            "concurrent_builds": self.concurrent_builds,
            "builder": self.builder,
            "build_config": dict(self.build_config),
            "runner": self.runner,
            "run_config": dict(self.run_config),
            "disable_metrics": self.disable_metrics,
        }
        if self.build is not None:
            out["build"] = self.build.to_dict()
        if self.run is not None:
            out["run"] = self.run.to_dict()
        return out


@dataclass
class Group:
    """An instance group: who builds it, how many instances, what params
    (``pkg/api/composition.go:90-115``)."""

    id: str = ""
    builder: str = ""
    build_config: dict[str, Any] = field(default_factory=dict)
    build: Build = field(default_factory=Build)
    resources: Resources = field(default_factory=Resources)
    instances: Instances = field(default_factory=Instances)
    run: RunParams = field(default_factory=RunParams)
    # cached by recalculate_instance_counts; mirrors calculatedInstanceCnt.
    calculated_instance_count: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "Group":
        return cls(
            id=d.get("id", ""),
            builder=d.get("builder", ""),
            build_config=dict(d.get("build_config", {})),
            build=Build.from_dict(d.get("build", {})),
            resources=Resources.from_dict(d.get("resources", {})),
            instances=Instances.from_dict(d.get("instances", {})),
            run=RunParams.from_dict(d.get("run", {})),
        )

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "builder": self.builder,
            "build_config": dict(self.build_config),
            "build": self.build.to_dict(),
            "resources": self.resources.to_dict(),
            "instances": self.instances.to_dict(),
            "run": self.run.to_dict(),
        }

    def build_key(self) -> str:
        """Composite key identifying this build for deduplication
        (``pkg/api/composition.go:196-216``). Requires a prepared group (the
        builder must have trickled down already)."""
        if not self.builder:
            raise ValueError("group must have a builder (composition not prepared)")
        data = {
            "builder": self.builder,
            "build_config": self.build_config,
            "build_as_key": self.build.build_key(),
        }
        return json.dumps(data, sort_keys=True, separators=(",", ":"))

    def default_run_group(self) -> "CompositionRunGroup":
        """Synthesize the run group used when a composition has no explicit
        ``[[runs]]`` (``pkg/api/composition.go:461-470``)."""
        return CompositionRunGroup(
            id=self.id,
            group_id=self.id,
            resources=Resources(**self.resources.to_dict()),
            instances=Instances(**self.instances.to_dict()),
            test_params=dict(self.run.test_params),
            profiles=dict(self.run.profiles),
            faults=[dict(f) for f in self.run.faults],
            trace=dict(self.run.trace),
            slo=[dict(s) for s in self.run.slo],
        )


@dataclass
class CompositionRunGroup:
    """A group's participation in one run (``pkg/api/composition.go:135-167``)."""

    id: str = ""
    group_id: str = ""
    resources: Resources = field(default_factory=Resources)
    instances: Instances = field(default_factory=Instances)
    test_params: dict[str, str] = field(default_factory=dict)
    profiles: dict[str, str] = field(default_factory=dict)
    # fault schedule for this group's slice of the run (see RunParams):
    # declared inline on the run group, or inherited from the backing
    # group's [[groups.run.faults]] when unset
    faults: list = field(default_factory=list)
    # flight-recorder sampling table, same inheritance rule as faults
    trace: dict = field(default_factory=dict)
    # SLO assertion tables, same inheritance rule as faults
    slo: list = field(default_factory=list)
    calculated_instance_count: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "CompositionRunGroup":
        return cls(
            id=d.get("id", ""),
            group_id=d.get("group_id", ""),
            resources=Resources.from_dict(d.get("resources", {})),
            instances=Instances.from_dict(d.get("instances", {})),
            test_params={str(k): str(v) for k, v in d.get("test_params", {}).items()},
            profiles=dict(d.get("profiles", {})),
            faults=[dict(f) for f in d.get("faults", [])],
            trace=dict(d.get("trace", {})),
            slo=[dict(s) for s in d.get("slo", [])],
        )

    def to_dict(self) -> dict:
        out = {
            "id": self.id,
            "group_id": self.group_id,
            "resources": self.resources.to_dict(),
            "instances": self.instances.to_dict(),
            "test_params": dict(self.test_params),
            "profiles": dict(self.profiles),
        }
        if self.faults:
            out["faults"] = [dict(f) for f in self.faults]
        if self.trace:
            out["trace"] = dict(self.trace)
        if self.slo:
            out["slo"] = [dict(s) for s in self.slo]
        return out

    def effective_group_id(self) -> str:
        """``group_id`` when set, else ``id`` (``pkg/api/composition.go:275-280``)."""
        return self.group_id or self.id

    def merge_group(self, g: Group) -> None:
        """Fill unset fields from the backing group
        (``pkg/api/composition.go:472-489``). The fault schedule fills
        only when this run group declares none of its own (fill-if-empty,
        like the artifact field) — list concatenation would double-fire
        events when preparation runs more than once — and fills ONLY from
        the backing group, never from ``Global.run``: run-global faults
        stay on the global and reach the runner as ``RunInput.faults``,
        scoped to the whole run rather than copied into every group."""
        self.resources.merge_from(g.resources)
        self.instances.merge_from(g.instances)
        self.merge_run(g.run)
        if not self.faults and g.run.faults:
            self.faults = [dict(f) for f in g.run.faults]
        # trace follows the faults rule exactly: fill-if-empty from the
        # backing group; [global.run.trace] reaches the runner as
        # RunInput.trace, scoped to the whole run
        if not self.trace and g.run.trace:
            self.trace = dict(g.run.trace)
        # slo follows the same rule: fill-if-empty from the backing
        # group; [[global.run.slo]] reaches the runner as RunInput.slo
        if not self.slo and g.run.slo:
            self.slo = [dict(s) for s in g.run.slo]

    def merge_run(self, rp: RunParams) -> None:
        """Fill missing test params / profiles from ``rp``
        (``pkg/api/composition.go:491-503``)."""
        _merge_missing(self.test_params, rp.test_params)
        _merge_missing(self.profiles, rp.profiles)


@dataclass
class Run:
    """One run of the composition: a total instance budget plus per-run group
    overrides (``pkg/api/composition.go:117-131``)."""

    id: str = ""
    test_params: dict[str, str] = field(default_factory=dict)
    total_instances: int = 0
    groups: list[CompositionRunGroup] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "Run":
        return cls(
            id=d.get("id", ""),
            test_params={str(k): str(v) for k, v in d.get("test_params", {}).items()},
            total_instances=int(d.get("total_instances", 0)),
            groups=[CompositionRunGroup.from_dict(x) for x in d.get("groups", [])],
        )

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "test_params": dict(self.test_params),
            "total_instances": self.total_instances,
            "groups": [g.to_dict() for g in self.groups],
        }

    def recalculate_instance_counts(self) -> None:
        """Resolve count/percentage per group and reconcile against the run
        total (``pkg/api/composition_preparation.go:172-196``).

        Percentages require an explicit total; an explicit total must match
        the computed sum exactly.
        """
        has_total = self.total_instances != 0
        computed = 0
        for g in self.groups:
            if g.instances.percentage > 0 and not has_total:
                raise ValueError(
                    "groups count percentage requires a total_instance configuration"
                )
            cnt = g.instances.count
            if cnt == 0:
                # Go math.Round: half away from zero. round() in Python is
                # banker's rounding, so do it explicitly.
                x = g.instances.percentage * float(self.total_instances)
                cnt = int(x + 0.5)
            g.calculated_instance_count = cnt
            computed += cnt
        if has_total and computed != self.total_instances:
            raise ValueError(
                f"total instances mismatch: computed: {computed} != "
                f"configured: {self.total_instances}"
            )
        self.total_instances = computed


@dataclass
class Composition:
    """The full run description (``pkg/api/composition.go:18-31``)."""

    metadata: Metadata = field(default_factory=Metadata)
    global_: Global = field(default_factory=Global)
    groups: list[Group] = field(default_factory=list)
    runs: list[Run] = field(default_factory=list)

    # ------------------------------------------------------------------ I/O

    @classmethod
    def from_dict(cls, d: dict) -> "Composition":
        return cls(
            metadata=Metadata.from_dict(d.get("metadata", {})),
            global_=Global.from_dict(d.get("global", {})),
            groups=[Group.from_dict(x) for x in d.get("groups", [])],
            runs=[Run.from_dict(x) for x in d.get("runs", [])],
        )

    @classmethod
    def from_toml(cls, text: str) -> "Composition":
        return cls.from_dict(tomllib.loads(text))

    @classmethod
    def load_file(cls, path) -> "Composition":
        with open(path, "rb") as f:
            return cls.from_dict(tomllib.load(f))

    def to_dict(self) -> dict:
        return {
            "metadata": self.metadata.to_dict(),
            "global": self.global_.to_dict(),
            "groups": [g.to_dict() for g in self.groups],
            "runs": [r.to_dict() for r in self.runs],
        }

    def to_toml(self) -> str:
        from testground_tpu.utils.toml_writer import dumps

        return dumps(self.to_dict())

    def write_file(self, path) -> None:
        """Persist as TOML (``pkg/api/composition.go:440-459``)."""
        with open(path, "w") as f:
            f.write(self.to_toml())

    def clone(self) -> "Composition":
        return Composition.from_dict(self.to_dict())

    # ------------------------------------------------------------- accessors

    def list_builders(self) -> list[str]:
        """Distinct builders used by groups, with the global default standing
        in for unset ones (``pkg/api/composition.go:313-332``)."""
        builders = set()
        for g in self.groups:
            builders.add(g.builder or self.global_.builder)
        return sorted(builders)

    def get_group(self, group_id: str) -> Group:
        for g in self.groups:
            if g.id == group_id:
                return g
        raise KeyError(f"unknown group id {group_id}")

    def get_run(self, run_id: str) -> Run:
        for r in self.runs:
            if r.id == run_id:
                return r
        raise KeyError(f"unknown run id {run_id}")

    def list_run_ids(self) -> list[str]:
        return sorted(r.id for r in self.runs)

    def list_group_ids(self) -> list[str]:
        return sorted(g.id for g in self.groups)

    def pick_groups(self, *indices: int) -> "Composition":
        """Clone retaining only the given group indices
        (``pkg/api/composition.go:335-350``)."""
        for i in indices:
            if i < 0 or i >= len(self.groups):
                raise IndexError(f"invalid group index {i}")
        c = self.clone()
        c.groups = [c.groups[i] for i in indices]
        return c

    def frame_for_runs(self, *run_ids: str) -> "Composition":
        """Clone retaining only the given runs and the groups they reference
        (``pkg/api/composition.go:353-388``)."""
        c = self.clone()
        runs = []
        required: dict[str, bool] = {}
        for rid in run_ids:
            r = c.get_run(rid)
            for g in r.groups:
                required[g.effective_group_id()] = True
            runs.append(r)
        c.groups = [c.get_group(gid) for gid in required]
        c.runs = runs
        return c
