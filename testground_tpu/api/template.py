"""Composition templating: a Go text/template subset for composition TOML.

The reference renders composition files through Go's ``text/template`` with a
six-function map before TOML-decoding them (``pkg/cmd/template.go:25-60``;
entry point ``loadComposition`` at ``template.go:88-107``). This module is
the behavioral twin: the same ``{{ ... }}`` action syntax — pipelines,
``with``/``range``/``if`` blocks, ``define``/``template`` partials, ``-``
whitespace-trim markers — and the same function map: ``pick``, ``toml``,
``withEnv``, ``split``, ``atoi``, ``load_resource``, plus the Go builtin
``index``. Python is the host language, so this is a compact recursive
interpreter over the action grammar, not a port of Go's template package;
only the surface real compositions use is implemented (no variable
assignment, no comparison builtins).

Rendering is client-side (CLI loading path), exactly like the reference:
the daemon only ever sees rendered TOML.
"""

from __future__ import annotations

import os
import re
from testground_tpu.utils.compat import tomllib

from ..utils.toml_writer import dumps as _toml_dumps

__all__ = [
    "TemplateError",
    "compile_composition_template",
    "render_template",
]


class TemplateError(Exception):
    """Parse or evaluation failure inside a composition template."""


_UNSET = object()

_ACTION_RE = re.compile(r"\{\{(-)?((?:[^}]|\}(?!\}))*?)(-)?\}\}", re.DOTALL)

_TOKEN_RE = re.compile(
    r"""
      (?P<ws>\s+)
    | (?P<pipe>\|)
    | (?P<lparen>\()
    | (?P<rparen>\))
    | (?P<string>"(?:[^"\\]|\\.)*"|`[^`]*`)
    | (?P<number>-?\d+(?:\.\d+)?)
    | (?P<var>\$[A-Za-z0-9_.]*)
    | (?P<field>\.[A-Za-z0-9_.]*)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    """,
    re.VERBOSE,
)


# --------------------------------------------------------------------------
# Lexing: split source into text segments and {{ action }} segments, applying
# the `-` trim markers to adjacent text (text/template semantics).


def _lex(src: str):
    segs = []  # ("text", s) | ["action", content, ltrim, rtrim]
    pos = 0
    for m in _ACTION_RE.finditer(src):
        segs.append(["text", src[pos : m.start()]])
        segs.append(["action", m.group(2).strip(), bool(m.group(1)), bool(m.group(3))])
        pos = m.end()
    segs.append(["text", src[pos:]])
    for i, s in enumerate(segs):
        if s[0] == "action":
            if s[2] and segs[i - 1][0] == "text":
                segs[i - 1][1] = segs[i - 1][1].rstrip()
            if s[3] and i + 1 < len(segs) and segs[i + 1][0] == "text":
                segs[i + 1][1] = segs[i + 1][1].lstrip()
    return segs


# --------------------------------------------------------------------------
# Pipeline parsing. Grammar:  pipeline := cmd ('|' cmd)* ;  cmd := operand+ ;
# operand := field | var | string | number | ident | '(' pipeline ')'


def _tokenize_action(content: str):
    toks, pos = [], 0
    while pos < len(content):
        m = _TOKEN_RE.match(content, pos)
        if m is None:
            raise TemplateError(f"bad token at {content[pos:pos+20]!r}")
        kind = m.lastgroup
        if kind != "ws":
            toks.append((kind, m.group(), pos))
        pos = m.end()
    return toks


def _parse_pipeline(content: str):
    toks = _tokenize_action(content)
    pipe, i = _parse_pipe_toks(toks, 0)
    if i != len(toks):
        raise TemplateError(f"trailing tokens in action: {content!r}")
    return pipe


def _parse_pipe_toks(toks, i):
    cmds = []
    while True:
        cmd, i = _parse_cmd(toks, i)
        cmds.append(cmd)
        if i < len(toks) and toks[i][0] == "pipe":
            i += 1
            continue
        return cmds, i


def _parse_cmd(toks, i):
    operands = []
    while i < len(toks) and toks[i][0] not in ("pipe", "rparen"):
        kind, text = toks[i][0], toks[i][1]
        if kind == "lparen":
            inner, i = _parse_pipe_toks(toks, i + 1)
            if i >= len(toks) or toks[i][0] != "rparen":
                raise TemplateError("missing )")
            node = ("paren", inner)
            rparen_end = toks[i][2] + 1
            i += 1
            # `(expr).field` — a field token adjacent to the closing paren
            # chains onto the expression's result (text/template semantics);
            # a space-separated `.field` is a distinct argument.
            if (
                i < len(toks)
                and toks[i][0] == "field"
                and toks[i][2] == rparen_end
            ):
                parts = [p for p in toks[i][1][1:].split(".") if p]
                node = ("chain", inner, parts)
                i += 1
            operands.append(node)
        elif kind == "string":
            operands.append(("str", _unquote(text)))
            i += 1
        elif kind == "number":
            operands.append(("num", float(text) if "." in text else int(text)))
            i += 1
        elif kind == "field":
            parts = [p for p in text[1:].split(".") if p]
            operands.append(("field", parts))
            i += 1
        elif kind == "var":
            parts = [p for p in text[1:].split(".") if p]
            operands.append(("var", parts))
            i += 1
        else:  # ident → function reference
            operands.append(("fn", text))
            i += 1
    if not operands:
        raise TemplateError("empty command in pipeline")
    return operands, i


def _unquote(text: str) -> str:
    if text.startswith("`"):
        return text[1:-1]
    body = text[1:-1]
    return re.sub(
        r"\\(.)",
        lambda m: {"n": "\n", "t": "\t", "r": "\r"}.get(m.group(1), m.group(1)),
        body,
    )


# --------------------------------------------------------------------------
# Block parsing: text/action segments → node tree + named templates.


def _first_word(content: str) -> str:
    return content.split(None, 1)[0] if content else ""


def _parse(segs):
    templates: dict[str, list] = {}

    def parse_else_tail(j, kind, pipe, body):
        """segs[j] is the `end`/`else`/`else if ...` action closing a block;
        build the node, recursing through `else if` chains. Returns the node
        and the index of the final `end` segment."""
        content = segs[j][1]
        if _first_word(content) == "else":
            rest = content[len("else") :].strip()
            if rest:
                if _first_word(rest) != "if":
                    raise TemplateError(f"expected 'else if', got {content!r}")
                pipe2 = _parse_pipeline(rest[len("if") :].strip())
                body2, j2 = parse_block(j + 1, {"end", "else"})
                inner, j3 = parse_else_tail(j2, "if", pipe2, body2)
                return (kind, pipe, body, [inner]), j3
            else_body, j2 = parse_block(j + 1, {"end"})
            return (kind, pipe, body, else_body), j2
        return (kind, pipe, body, []), j

    def parse_block(i, terminators):
        nodes = []
        while i < len(segs):
            seg = segs[i]
            if seg[0] == "text":
                if seg[1]:
                    nodes.append(("text", seg[1]))
                i += 1
                continue
            content = seg[1]
            if content.startswith("/*"):
                # {{/* comment */}} — consumed, emits nothing.
                if not content.endswith("*/"):
                    raise TemplateError("unclosed template comment")
                i += 1
                continue
            word = _first_word(content)
            if word in terminators:
                return nodes, i
            i += 1
            if word == "define":
                name = _expect_string(content[len("define") :].strip())
                body, j = parse_block(i, {"end"})
                templates[name] = body
                i = j + 1
            elif word in ("with", "range", "if"):
                pipe = _parse_pipeline(content[len(word) :].strip())
                body, j = parse_block(i, {"end", "else"})
                node, j = parse_else_tail(j, word, pipe, body)
                nodes.append(node)
                i = j + 1
            elif word == "template":
                rest = content[len("template") :].strip()
                name, remainder = _scan_string(rest)
                pipe = _parse_pipeline(remainder) if remainder.strip() else None
                nodes.append(("template", name, pipe))
            elif word in ("end", "else"):
                raise TemplateError(f"unexpected {{{{{word}}}}}")
            else:
                nodes.append(("pipe", _parse_pipeline(content)))
        if terminators:
            raise TemplateError(f"unterminated block; expected {terminators}")
        return nodes, i

    nodes, _ = parse_block(0, set())
    return nodes, templates


def _expect_string(text: str) -> str:
    name, rest = _scan_string(text)
    if rest.strip():
        raise TemplateError(f"trailing content after name: {text!r}")
    return name


def _scan_string(text: str):
    toks = _tokenize_action(text)
    if not toks or toks[0][0] != "string":
        raise TemplateError(f"expected quoted name in {text!r}")
    name = _unquote(toks[0][1])
    consumed = text.index(toks[0][1]) + len(toks[0][1])
    return name, text[consumed:]


# --------------------------------------------------------------------------
# Evaluation.


def _field_get(base, parts):
    for p in parts:
        if isinstance(base, dict):
            base = base.get(p)
        elif base is None:
            return None
        else:
            raise TemplateError(f"cannot access field {p!r} on {type(base).__name__}")
    return base


def _eval_operand(op, dot, root, funcs):
    kind = op[0]
    if kind == "str" or kind == "num":
        return op[1]
    if kind == "field":
        return _field_get(dot, op[1])
    if kind == "var":
        return _field_get(root, op[1])
    if kind == "paren":
        return _eval_pipe(op[1], dot, root, funcs)
    if kind == "chain":
        return _field_get(_eval_pipe(op[1], dot, root, funcs), op[2])
    if kind == "fn":
        raise TemplateError(f"function {op[1]!r} used as a value")
    raise TemplateError(f"bad operand {op!r}")


def _eval_cmd(cmd, dot, root, funcs, piped):
    head = cmd[0]
    args = [_eval_operand(a, dot, root, funcs) for a in cmd[1:]]
    if piped is not _UNSET:
        args.append(piped)
    if head[0] == "fn":
        fn = funcs.get(head[1])
        if fn is None:
            raise TemplateError(f"unknown function {head[1]!r}")
        try:
            return fn(*args)
        except TemplateError:
            raise
        except Exception as err:  # atoi/load_resource failures surface as-is
            raise TemplateError(f"{head[1]}: {err}") from err
    value = _eval_operand(head, dot, root, funcs)
    if args:
        raise TemplateError(f"cannot call non-function {head!r} with arguments")
    return value


def _eval_pipe(pipe, dot, root, funcs):
    val = _UNSET
    for cmd in pipe:
        val = _eval_cmd(cmd, dot, root, funcs, val)
    return val


def _to_str(v) -> str:
    if v is None:
        return "<no value>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return v
    return str(v)


def _exec_nodes(nodes, dot, root, funcs, templates, out):
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "pipe":
            out.append(_to_str(_eval_pipe(node[1], dot, root, funcs)))
        elif kind == "with":
            val = _eval_pipe(node[1], dot, root, funcs)
            if val:
                _exec_nodes(node[2], val, root, funcs, templates, out)
            else:
                _exec_nodes(node[3], dot, root, funcs, templates, out)
        elif kind == "if":
            val = _eval_pipe(node[1], dot, root, funcs)
            branch = node[2] if val else node[3]
            _exec_nodes(branch, dot, root, funcs, templates, out)
        elif kind == "range":
            val = _eval_pipe(node[1], dot, root, funcs)
            items = list(val.values()) if isinstance(val, dict) else (val or [])
            if items:
                for item in items:
                    _exec_nodes(node[2], item, root, funcs, templates, out)
            else:
                _exec_nodes(node[3], dot, root, funcs, templates, out)
        elif kind == "template":
            body = templates.get(node[1])
            if body is None:
                raise TemplateError(f"undefined template {node[1]!r}")
            arg = (
                _eval_pipe(node[2], dot, root, funcs)
                if node[2] is not None
                else None
            )
            # Inside an invoked template both `.` and `$` bind to the argument
            # (text/template semantics).
            _exec_nodes(body, arg, arg, funcs, templates, out)
        else:
            raise TemplateError(f"bad node {kind!r}")


# --------------------------------------------------------------------------
# Function map (template.go:25-60) + the Go builtin `index`.


def _index(collection, *keys):
    for k in keys:
        if isinstance(collection, dict):
            collection = collection.get(k)
        else:
            collection = collection[int(k)]
    return collection


def _make_funcs(template_dir: str, env: dict):
    def load_resource(p):
        # Client-side rendering: like the reference, paths resolve relative to
        # the template's own directory with no sandboxing (template.go:50-52).
        full = os.path.join(template_dir, p)
        with open(full, "rb") as f:
            return tomllib.load(f)

    def with_env(value):
        if not isinstance(value, dict):
            raise TemplateError("withEnv expects a table")
        return {**value, "Env": env}

    def pick(v, key):
        if not isinstance(v, dict):
            raise TemplateError("pick expects a table")
        return {key: v.get(key)}

    return {
        "pick": pick,
        "toml": _toml_dumps,
        "withEnv": with_env,
        "split": lambda s: s.split(","),
        "atoi": lambda s: int(str(s).strip()),
        "load_resource": load_resource,
        "index": _index,
    }


# --------------------------------------------------------------------------
# Public API.


def render_template(text: str, env: dict | None = None, template_dir: str = ".") -> str:
    """Render template ``text`` with ``{"Env": env}`` as the data, matching
    ``compositionData`` (``template.go:17-19``)."""
    env = dict(env) if env is not None else dict(os.environ)
    nodes, templates = _parse(_lex(text))
    data = {"Env": env}
    out: list[str] = []
    _exec_nodes(nodes, data, data, _make_funcs(template_dir, env), templates, out)
    return "".join(out)


def compile_composition_template(path, env: dict | None = None) -> str:
    """Read + render a composition file; the rendered TOML string is what gets
    decoded into a Composition (``template.go:88-107``)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    return render_template(text, env=env, template_dir=os.path.dirname(os.path.abspath(path)))
