"""HTTP client for the daemon — the L6 tier (``pkg/client/client.go``).

Two layers:

- :class:`Client` — thin typed wrappers over the daemon routes
  (``Client.Run/Build/Tasks/Status/Logs/CollectOutputs/Terminate/
  Healthcheck``, ``client.go:43-513``), stdlib ``http.client`` only, with
  bearer-token auth and streaming reads for /logs and /outputs.
- :class:`RemoteEngine` — an adapter exposing the subset of the Engine
  surface the CLI uses, so every ``tg`` verb works identically against
  ``--endpoint`` (the reference's client↔daemon hop is transport, not
  semantics — SURVEY.md §7 M2).
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Iterator
from urllib.parse import urlparse

from testground_tpu.engine import Task
from testground_tpu.healthcheck.report import CheckResult, Report

__all__ = ["Client", "RemoteEngine"]


class DaemonError(RuntimeError):
    pass


class Client:
    def __init__(self, endpoint: str, token: str = ""):
        if "//" not in endpoint:
            endpoint = "http://" + endpoint
        u = urlparse(endpoint)
        self.host = u.hostname or "localhost"
        self.port = u.port or 8042
        self.token = token

    # ------------------------------------------------------------ transport

    def _conn(self):
        import http.client

        return http.client.HTTPConnection(self.host, self.port, timeout=600)

    def _headers(self, content_type="application/json"):
        h = {"Content-Type": content_type}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _post(self, route: str, body: dict):
        """POST a JSON body; return the http response (caller reads)."""
        conn = self._conn()
        conn.request("POST", route, json.dumps(body), self._headers())
        resp = conn.getresponse()
        return conn, resp

    @staticmethod
    def _read_json_response(conn, resp) -> dict:
        """Read a JSON body; raise DaemonError on HTTP errors (including
        non-JSON error bodies)."""
        try:
            data = resp.read()
            try:
                obj = json.loads(data or b"{}")
            except ValueError:
                obj = {"error": data.decode(errors="replace")[:500]}
            if resp.status >= 400:
                raise DaemonError(obj.get("error") or f"HTTP {resp.status}")
            return obj
        finally:
            conn.close()

    def _post_json(self, route: str, body: dict) -> dict:
        conn, resp = self._post(route, body)
        return self._read_json_response(conn, resp)

    def _post_stream(self, route: str, body: dict) -> Iterator[str]:
        """POST; yield response lines (chunked ndjson streams)."""
        conn, resp = self._post(route, body)
        yield from self._read_stream(conn, resp)

    @staticmethod
    def _read_stream(conn, resp) -> Iterator[str]:
        """Yield a chunked response's complete lines — the ONE reader
        behind both streaming verbs (error decode + line split)."""
        try:
            if resp.status >= 400:
                data = resp.read()
                try:
                    msg = json.loads(data).get("error")
                except Exception:  # noqa: BLE001
                    msg = data.decode(errors="replace")
                raise DaemonError(msg or f"HTTP {resp.status}")
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    yield line.decode(errors="replace") + "\n"
            if buf:
                yield buf.decode(errors="replace")
        finally:
            conn.close()

    def _get_json(self, route: str, params: dict) -> dict:
        from urllib.parse import urlencode

        conn = self._conn()
        conn.request(
            "GET", f"{route}?{urlencode(params)}", headers=self._headers()
        )
        return self._read_json_response(conn, conn.getresponse())

    def _get_stream(self, route: str, params: dict) -> Iterator[str]:
        """GET; yield response lines (chunked ndjson streams — the GET
        twin of :meth:`_post_stream`)."""
        from urllib.parse import urlencode

        conn = self._conn()
        conn.request(
            "GET", f"{route}?{urlencode(params)}", headers=self._headers()
        )
        yield from self._read_stream(conn, conn.getresponse())

    # -------------------------------------------------------------- verbs

    def _queue(
        self,
        route: str,
        composition: dict,
        priority: int = 0,
        created_by: dict | None = None,
        trace_parent: str = "",
    ) -> str:
        """POST /run or /build; parse the chunked rpc response for the
        task id (``ParseRunResponse``, ``client.go:402``). A non-empty
        ``trace_parent`` rides the standard ``traceparent`` header so
        the daemon roots the task's lifecycle span tree at the
        submitter's span (tracectx.py)."""
        from testground_tpu.rpc import Chunk

        body = {"composition": composition, "priority": priority}
        if created_by:
            body["created_by"] = created_by
        task_id = ""
        conn = self._conn()
        headers = self._headers()
        if trace_parent:
            headers["traceparent"] = trace_parent
        conn.request("POST", route, json.dumps(body), headers)
        for line in self._read_stream(conn, conn.getresponse()):
            try:
                c = Chunk.from_json(line)
            except Exception:  # noqa: BLE001 — ignore non-chunk noise
                continue
            if c.type == "e" and c.error:
                raise DaemonError(c.error)
            if c.type == "r" and isinstance(c.payload, dict):
                task_id = c.payload.get("task_id", "")
        if not task_id:
            raise DaemonError(f"daemon {route} returned no task id")
        return task_id

    def run(
        self,
        composition: dict,
        priority: int = 0,
        created_by: dict | None = None,
        trace_parent: str = "",
    ) -> str:
        return self._queue(
            "/run", composition, priority, created_by, trace_parent
        )

    def build(
        self,
        composition: dict,
        priority: int = 0,
        created_by: dict | None = None,
        trace_parent: str = "",
    ) -> str:
        return self._queue(
            "/build", composition, priority, created_by, trace_parent
        )

    def tasks(
        self, states=None, types=None, before=None, after=None, limit=0
    ) -> list[dict]:
        return self._post_json(
            "/tasks",
            {
                "states": states,
                "types": types,
                "before": before,
                "after": after,
                "limit": limit,
            },
        )["tasks"]

    def status(self, task_id: str) -> dict:
        return self._post_json("/status", {"task_id": task_id})["task"]

    def stats(self, task_id: str) -> dict:
        """GET /stats — a task's sim telemetry summary (the ``tg stats``
        backend): identity + the journal's sim/telemetry/events sections."""
        return self._get_json("/stats", {"task_id": task_id})

    def perf(self, task_id: str) -> dict:
        """GET /perf — a task's performance-ledger payload (the ``tg
        perf`` backend): identity + the journal's sim block + the
        sim.perf ledger + task-level queue/runner timings."""
        return self._get_json("/perf", {"task_id": task_id})

    def diff(self, a: str, b: str, planes=None) -> dict:
        """GET /diff — the differential run analysis of two tasks (the
        ``tg diff`` backend; docs/OBSERVABILITY.md "Run diff"): exact
        counter comparison + noise-aware throughput verdicts, built
        daemon-side so archived tasks diff over HTTP."""
        params = {"a": a, "b": b}
        if planes:
            params["planes"] = (
                planes if isinstance(planes, str) else ",".join(planes)
            )
        return self._get_json("/diff", params)

    def metrics(self) -> str:
        """GET /metrics — the daemon's Prometheus text exposition
        (task gauges, flow counters, perf gauges)."""
        conn = self._conn()
        conn.request("GET", "/metrics", headers=self._headers())
        resp = conn.getresponse()
        try:
            data = resp.read()
            if resp.status >= 400:
                raise DaemonError(
                    data.decode(errors="replace")[:500]
                    or f"HTTP {resp.status}"
                )
            return data.decode(errors="replace")
        finally:
            conn.close()

    def fleet(self) -> dict:
        """GET /fleet — the daemon's live fleet snapshot (the ``tg top``
        backend): per-state counts over the FULL task store, queue
        depth by priority, worker occupancy, and live task rows."""
        return self._get_json("/fleet", {})

    def events(self, since: int = 0, follow: bool = False) -> Iterator[dict]:
        """GET /events — tail the daemon's control-plane event journal
        (``daemon_events.jsonl``) as ndjson dicts. One-shot by default
        (the server appends a ``{"type": "_tail", "offset": N}`` trailer
        for resume); ``follow=True`` keeps the stream open."""
        params = {"since": str(since), "follow": "1" if follow else "0"}
        for line in self._get_stream("/events", params):
            line = line.strip()
            if not line:
                continue  # follow-mode heartbeat
            try:
                yield json.loads(line)
            except ValueError:
                continue  # tolerant-reader rule: skip foreign noise

    def artifact(self, task_id: str, name: str, run: str = "") -> bytes:
        """GET /artifact — fetch one whitelisted run-outputs file (e.g.
        ``task_spans.jsonl`` for ``tg trace --lifecycle`` against a
        remote daemon) as raw bytes."""
        from urllib.parse import urlencode

        params = {"task_id": task_id, "name": name}
        if run:
            params["run"] = run
        conn = self._conn()
        conn.request(
            "GET", f"/artifact?{urlencode(params)}", headers=self._headers()
        )
        resp = conn.getresponse()
        try:
            data = resp.read()
            if resp.status >= 400:
                try:
                    msg = json.loads(data).get("error")
                except Exception:  # noqa: BLE001
                    msg = data.decode(errors="replace")[:500]
                raise DaemonError(msg or f"HTTP {resp.status}")
            return data
        finally:
            conn.close()

    def trace(self, task_id: str, limit: int = 0) -> dict:
        """GET /trace — a task's flight-recorder events (the ``tg trace``
        backend): the journal's trace summary plus the recorded
        ``sim_trace.jsonl`` events (``limit`` > 0 truncates)."""
        params = {"task_id": task_id}
        if limit:
            params["limit"] = str(limit)
        return self._get_json("/trace", params)

    def stream(
        self, task_id: str, follow: bool = True, families=None
    ) -> Iterator[dict]:
        """GET /stream — follow a task's live observability rows
        (telemetry / perf / SLO breaches / run spans) as ndjson: the
        ``tg watch`` backend (docs/OBSERVABILITY.md "Run health
        plane"). Yields one dict per row; the stream closes when the
        task finishes (an already-finished task replays its history,
        then closes)."""
        params: dict = {"task_id": task_id, "follow": "1" if follow else "0"}
        if families:
            params["families"] = ",".join(families)
        for line in self._get_stream("/stream", params):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # tolerant-reader rule: skip foreign noise

    def logs(self, task_id: str, follow: bool = False) -> Iterator[str]:
        return self._post_stream(
            "/logs", {"task_id": task_id, "follow": follow}
        )

    def collect_outputs(self, runner: str, run_id: str, sink) -> None:
        conn, resp = self._post("/outputs", {"runner": runner, "run_id": run_id})
        try:
            if resp.status >= 400:
                data = resp.read()
                try:
                    msg = json.loads(data).get("error")
                except Exception:  # noqa: BLE001
                    msg = data.decode(errors="replace")
                raise DaemonError(msg or f"HTTP {resp.status}")
            while True:
                chunk = resp.read1(1 << 16)
                if not chunk:
                    break
                sink.write(chunk)
        finally:
            conn.close()

    def terminate(self, runner: str = "", builder: str = "") -> str:
        body = {"builder": builder} if builder else {"runner": runner}
        return self._post_json("/terminate", body)["output"]

    def healthcheck(self, runner: str, fix: bool = False) -> tuple[Report, str]:
        obj = self._post_json("/healthcheck", {"runner": runner, "fix": fix})
        rep = Report(
            checks=[CheckResult(**c) for c in obj["report"].get("checks", [])],
            fixes=[CheckResult(**f) for f in obj["report"].get("fixes", [])],
        )
        return rep, obj.get("output", "")

    def kill(self, task_id: str) -> bool:
        return bool(self._post_json("/kill", {"task_id": task_id})["killed"])

    def preempt(self, task_id: str) -> dict:
        """POST /preempt — checkpoint-and-requeue a running task (the
        fleet controller's live-migration verb, docs/FLEET.md)."""
        return self._post_json("/preempt", {"task_id": task_id})

    def drain(self, timeout_secs: float = 30.0) -> dict:
        """POST /drain — gracefully drain the daemon: stop claiming,
        checkpoint + requeue running runs, then shut down."""
        return self._post_json("/drain", {"timeout_secs": timeout_secs})

    def delete(self, task_id: str) -> bool:
        """Delete a finished task's record + log (``daemon.go:88``)."""
        return bool(
            self._post_json("/delete", {"task_id": task_id})["deleted"]
        )

    def describe_plan(self, plan: str):
        """Fetch a daemon-hosted plan's manifest (GET /describe)."""
        from testground_tpu.api import TestPlanManifest

        obj = self._get_json("/describe", {"plan": plan})
        return TestPlanManifest.from_dict(obj["manifest"])

    def build_purge(self, builder: str, testplan: str = "") -> str:
        return self._post_json(
            "/build/purge", {"builder": builder, "testplan": testplan}
        )["output"]

    def import_plan(self, source_dir: str, name: str = "") -> str:
        """Tar.gz the plan dir and POST it (the reference ships sources
        as tars inside /run requests, ``client.go:84-228``)."""
        buf = io.BytesIO()
        base = os.path.basename(os.path.abspath(source_dir).rstrip("/"))
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            tar.add(
                source_dir,
                arcname=base,
                filter=lambda ti: None
                if "__pycache__" in ti.name or "/.git" in ti.name
                else ti,
            )
        conn = self._conn()
        route = "/plan/import" + (f"?name={name}" if name else "")
        conn.request(
            "POST",
            route,
            buf.getvalue(),
            self._headers("application/gzip"),
        )
        obj = self._read_json_response(conn, conn.getresponse())
        return obj["imported"]


class _RemoteReport(Report):
    pass


class RemoteEngine:
    """Engine-shaped facade over :class:`Client` for the CLI."""

    def __init__(self, client: Client, env):
        self.client = client
        self.env = env

    # -- queueing: manifest/sources resolve on the daemon side
    def queue_run(
        self, comp, manifest=None, sources_dir="", priority=0,
        created_by=None, trace_parent="", **_,
    ):
        return self.client.run(
            comp.to_dict(), priority,
            created_by.to_dict() if created_by else None,
            trace_parent=trace_parent,
        )

    def queue_build(
        self, comp, manifest=None, sources_dir="", priority=0,
        created_by=None, trace_parent="", **_,
    ):
        return self.client.build(
            comp.to_dict(), priority,
            created_by.to_dict() if created_by else None,
            trace_parent=trace_parent,
        )

    def get_task(self, task_id: str) -> Task | None:
        try:
            return Task.from_dict(self.client.status(task_id))
        except DaemonError:
            return None

    def task_stats(self, task_id: str) -> dict:
        """One round trip to the daemon's /stats route (the remote half
        of ``tg stats``; in-process engines assemble the same payload
        via Task.stats_payload)."""
        return self.client.stats(task_id)

    def task_perf(self, task_id: str) -> dict:
        """One round trip to the daemon's /perf route (the remote half
        of ``tg perf``; in-process engines assemble the same payload
        via Task.perf_payload)."""
        return self.client.perf(task_id)

    def task_trace(self, task_id: str, limit: int = 0) -> dict:
        """One round trip to the daemon's /trace route (the remote half
        of ``tg trace``; in-process engines read the run outputs via
        sim.trace.read_trace_events)."""
        return self.client.trace(task_id, limit=limit)

    def diff_tasks(self, a: str, b: str, planes=None) -> dict:
        """One round trip to the daemon's /diff route, named like
        Engine.diff_tasks so ``tg diff`` works identically in-process
        and remote (the document is built daemon-side by the same
        engine method)."""
        return self.client.diff(a, b, planes=planes)

    def fleet_payload(self) -> dict:
        """The daemon's /fleet route, shaped like Engine.fleet_payload
        so ``tg top`` works identically in-process and remote."""
        return self.client.fleet()

    def event_rows(self, since: int = 0, follow: bool = False):
        """The daemon's /events route (control-plane journal tail)."""
        return self.client.events(since=since, follow=follow)

    def task_artifact(self, task_id: str, name: str, run: str = "") -> bytes:
        """One whitelisted run-outputs file as raw bytes (the remote
        half of ``tg trace --lifecycle``; in-process engines read the
        outputs dir directly)."""
        return self.client.artifact(task_id, name, run=run)

    def stream_rows(
        self, task_id: str, follow: bool = True, cancel=None, families=None
    ) -> Iterator[dict]:
        """The daemon's /stream route, shaped like Engine.stream_rows so
        ``tg watch`` / ``-f`` followers work identically in-process and
        remote."""
        return self.client.stream(task_id, follow=follow, families=families)

    def tasks(
        self, states=None, types=None, before=None, after=None, limit=0, **_
    ) -> list[Task]:
        return [
            Task.from_dict(d)
            for d in self.client.tasks(
                states=states,
                types=types,
                before=before,
                after=after,
                limit=limit,
            )
        ]

    def logs(self, task_id: str, follow: bool = False, **_) -> Iterator[str]:
        return self.client.logs(task_id, follow=follow)

    def do_collect_outputs(self, runner_id, run_id, w, ow) -> None:
        self.client.collect_outputs(runner_id, run_id, w)

    def do_terminate(self, ref, ow, ctype: str = "runner") -> None:
        if ctype == "builder":
            out = self.client.terminate(builder=ref)
        else:
            out = self.client.terminate(runner=ref)
        if out:
            print(out, end="")

    def do_healthcheck(self, runner_id, fix, ow):
        report, out = self.client.healthcheck(runner_id, fix)
        if out:
            print(out, end="")
        return report

    def do_build_purge(self, builder_id, testplan, ow) -> None:
        out = self.client.build_purge(builder_id, testplan)
        if out:
            print(out, end="")

    def kill(self, task_id: str) -> bool:
        return self.client.kill(task_id)

    def preempt(self, task_id: str) -> dict:
        return self.client.preempt(task_id)

    def drain(self, timeout_secs: float = 30.0) -> dict:
        return self.client.drain(timeout_secs=timeout_secs)

    def delete_task(self, task_id: str) -> bool:
        return self.client.delete(task_id)

    def stop(self) -> None:  # no engine owned client-side
        pass
