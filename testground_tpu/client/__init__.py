"""HTTP client for the testground-tpu daemon (``pkg/client``)."""

from .client import Client, DaemonError, RemoteEngine

__all__ = ["Client", "DaemonError", "RemoteEngine"]
