"""Causal trace context for the control plane.

A task's journey — ``tg run`` submit → daemon HTTP → queue → supervisor
claim → executor run loop → sync service — crosses four processes and
two wire protocols. This module is the one shared vocabulary for the ids
that tie that journey together: a 128-bit ``trace_id`` minted once at
submit, and a 64-bit ``span_id`` per lifecycle phase, carried between
processes as a W3C-traceparent-shaped header string
(``00-<32 hex trace>-<16 hex span>-01``).

Deliberately tiny and stdlib-only: no propagation framework, no
sampling, no baggage. The daemon stores the ids on the ``Task`` row,
the supervisor threads them into ``RunInput.trace_ctx``, the executor's
``SpanTracer`` stamps them onto every ``run_spans.jsonl`` row, and the
sync client sends the task id in ``hello`` — everything else (tree
assembly, Perfetto export) happens at archive time from those ids.

Reference lineage: W3C Trace Context (traceparent) for the wire shape;
the reference testground daemon has no causal ids at all — task logs
are correlated by grep — which is precisely the gap this closes.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

__all__ = [
    "TraceContext",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
]

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def new_trace_id() -> str:
    """128-bit random trace id as 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


def parse_traceparent(header: str) -> tuple[str, str] | None:
    """Parse a traceparent header into ``(trace_id, span_id)``.

    Returns ``None`` for anything malformed (wrong field count, bad hex,
    all-zero ids) — an invalid incoming header means "start a new
    trace", never an error, per the W3C spec's restart semantics.
    """
    m = _TRACEPARENT_RE.match((header or "").strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


@dataclass
class TraceContext:
    """One node's view of a trace: the shared trace id plus this
    process's current span. ``child()`` mints the next hop."""

    trace_id: str = field(default_factory=new_trace_id)
    span_id: str = field(default_factory=new_span_id)
    parent_id: str = ""

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh root context (new trace, root span, no parent)."""
        return cls()

    @classmethod
    def from_traceparent(cls, header: str) -> "TraceContext | None":
        """Adopt an incoming traceparent: same trace, the header's span
        becomes this context's span (i.e. the parent for children minted
        here). ``None`` if the header is absent or malformed."""
        parsed = parse_traceparent(header)
        if parsed is None:
            return None
        trace_id, span_id = parsed
        return cls(trace_id=trace_id, span_id=span_id)

    def child(self) -> "TraceContext":
        """A new span in the same trace, parented to this one."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=new_span_id(),
            parent_id=self.span_id,
        )

    def to_traceparent(self) -> str:
        """The W3C wire form: version 00, sampled flag set."""
        return f"00-{self.trace_id}-{self.span_id}-01"
