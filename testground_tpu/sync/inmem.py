"""In-memory sync service.

Semantics (matching the reference sync service as used by
``plans/network/pingpong.go``, ``plans/example/sync.go``,
``plans/benchmarks/benchmarks.go``):

- ``signal_entry(state) -> seq``: atomic counter increment returning the
  1-based sequence number of this signaller.
- ``barrier(state, target)``: block until the state's counter >= target.
- ``signal_and_wait(state, target)``: both, returning the seq.
- ``publish(topic, payload) -> seq``: append to an ordered topic stream.
- ``subscribe(topic)``: iterator over ALL entries of the topic from the
  beginning — every subscriber sees every entry, in order.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Iterator

__all__ = ["InMemSyncService"]

# FIFO bound on remembered idempotency tokens: only a reconnecting
# client's unacked window (seconds of traffic) ever replays, so the cap
# bounds memory over week-long runs without a realistic double-apply.
MAX_TOKENS = 65536


class InMemSyncService:
    """Thread-safe coordination state for one or more runs.

    Keys are namespaced by run id by callers (the SDK prefixes
    ``run:<run_id>:``), matching the reference's key scoping.
    """

    def __init__(self):
        self._lock = threading.Condition()
        # optional sync-plane stats sink (sync/stats.py SyncStats): the
        # TCP server wires it so dedup hits, pubsub depth and barrier
        # lifecycle are accounted at the layer that owns the semantics;
        # None (the default) keeps this class dependency- and cost-free
        self.stats = None
        self._counters: dict[str, int] = {}
        self._topics: dict[str, list[Any]] = {}
        # idempotency tokens: a reconnecting client re-sends unacked
        # mutations with the token of the original attempt, and the
        # service answers with the original result instead of mutating
        # twice (at-least-once wire delivery → exactly-once effect);
        # FIFO-bounded at MAX_TOKENS entries each
        self._sig_tokens: dict[tuple[str, str], int] = {}
        self._sig_token_order: deque[tuple[str, str]] = deque()
        self._pub_tokens: dict[tuple[str, str], int] = {}
        self._pub_token_order: deque[tuple[str, str]] = deque()

    @staticmethod
    def _remember(tokens: dict, order: deque, key: tuple, seq: int) -> None:
        if key in tokens:
            return
        tokens[key] = seq
        order.append(key)
        while len(order) > MAX_TOKENS:
            tokens.pop(order.popleft(), None)

    # ------------------------------------------------------------- signals

    def signal_entry(self, state: str, token: str | None = None) -> int:
        with self._lock:
            if token is not None:
                prev = self._sig_tokens.get((state, token))
                if prev is not None:
                    if self.stats is not None:
                        self.stats.dedup_hit("signal")
                    return prev
            self._counters[state] = self._counters.get(state, 0) + 1
            seq = self._counters[state]
            if token is not None:
                self._remember(
                    self._sig_tokens, self._sig_token_order, (state, token), seq
                )
            self._lock.notify_all()
            return seq

    def counter(self, state: str) -> int:
        with self._lock:
            return self._counters.get(state, 0)

    def counters_snapshot(self, states) -> dict[str, int]:
        """Batched counter read for the event-loop server's coalesced
        release pass: after a drain touches many states, ONE lock
        acquisition answers all of them (the release decision then fans
        out every satisfiable waiter in one sweep)."""
        with self._lock:
            get = self._counters.get
            return {s: get(s, 0) for s in states}

    def barrier(
        self,
        state: str,
        target: int,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ) -> None:
        """Block until ``counter(state) >= target``."""
        st = self.stats
        if st is not None:
            st.barrier_parked(state, target)
        with self._lock:
            ok = self._lock.wait_for(
                lambda: self._counters.get(state, 0) >= target
                or (cancel is not None and cancel.is_set()),
                timeout=timeout,
            )
        if cancel is not None and cancel.is_set():
            if st is not None:
                st.barrier_canceled(state, target)
            raise InterruptedError(f"barrier {state} canceled")
        if not ok:
            if st is not None:
                st.barrier_timed_out(state, target)
            raise TimeoutError(f"barrier {state} (target {target}) timed out")
        if st is not None:
            st.barrier_released(state, target)

    def signal_and_wait(
        self,
        state: str,
        target: int,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
        token: str | None = None,
    ) -> int:
        seq = self.signal_entry(state, token=token)
        self.barrier(state, target, timeout=timeout, cancel=cancel)
        return seq

    # -------------------------------------------------------------- pub/sub

    def publish(self, topic: str, payload: Any, token: str | None = None) -> int:
        with self._lock:
            if token is not None:
                prev = self._pub_tokens.get((topic, token))
                if prev is not None:
                    if self.stats is not None:
                        self.stats.dedup_hit("publish")
                    return prev
            entries = self._topics.setdefault(topic, [])
            entries.append(payload)
            if self.stats is not None:
                self.stats.pubsub_published(len(entries))
            if token is not None:
                self._remember(
                    self._pub_tokens,
                    self._pub_token_order,
                    (topic, token),
                    len(entries),
                )
            self._lock.notify_all()
            return len(entries)

    def topic_len(self, topic: str) -> int:
        with self._lock:
            return len(self._topics.get(topic, []))

    def pubsub_gauges(self) -> tuple[int, int]:
        """Live (non-empty topics, total entries) for ``sync_stats`` v2.
        Non-empty so both backends agree: the C++ server's topic map
        grows an empty record on subscribe, this one does not."""
        with self._lock:
            nonempty = sum(1 for v in self._topics.values() if v)
            entries = sum(len(v) for v in self._topics.values())
        return nonempty, entries

    def get_entries(self, topic: str, start: int = 0) -> list[Any]:
        with self._lock:
            return list(self._topics.get(topic, [])[start:])

    def entries_since(self, topic: str, start: int) -> tuple[int, list[Any]]:
        """(topic length, entries[start:]) in one lock acquisition — the
        event-loop server's fanout pass reads each touched topic once
        per drain and distributes to every subscriber cursor from it."""
        with self._lock:
            entries = self._topics.get(topic)
            if not entries:
                return 0, []
            return len(entries), list(entries[start:])

    def subscribe(
        self,
        topic: str,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ) -> Iterator[Any]:
        """Yield every entry of the topic from the beginning, then block for
        new ones. Terminates when ``cancel`` is set (or ``timeout`` elapses
        between entries)."""
        cursor = 0
        while True:
            with self._lock:
                ok = self._lock.wait_for(
                    lambda: len(self._topics.get(topic, [])) > cursor
                    or (cancel is not None and cancel.is_set()),
                    timeout=timeout,
                )
                if cancel is not None and cancel.is_set():
                    return
                if not ok:
                    raise TimeoutError(f"subscribe {topic} timed out")
                entries = self._topics[topic][cursor:]
                cursor = len(self._topics[topic])
            yield from entries

    def publish_subscribe(
        self,
        topic: str,
        payload: Any,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ) -> tuple[int, Iterator[Any]]:
        seq = self.publish(topic, payload)
        return seq, self.subscribe(topic, timeout=timeout, cancel=cancel)

    # --------------------------------------------------------------- admin

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._topics.clear()
            self._sig_tokens.clear()
            self._sig_token_order.clear()
            self._pub_tokens.clear()
            self._pub_token_order.clear()
            self._lock.notify_all()
